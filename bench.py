#!/usr/bin/env python
"""Driver benchmark entry: one JSON line per benchmark, headline LAST.

Headline = lab2 Roberts-cross edge detector at 1024x1024 (the
BASELINE.json target class), steady-state median kernel ms, compared
against the reference's best CUDA config median of 0.17866 ms on an RTX
A6000 (reference lab2/KoryakovDA_LR2.pdf chart 3; BASELINE.md).
``vs_baseline`` > 1 means the TPU path is faster than the CUDA baseline.

The full registry (lab1, lab3, flash attention, labformer fwd/decode
with MFU accounting, sort, reduce) prints first, one JSON line each;
the headline prints last so a line-oriented consumer reading the final
line gets the BASELINE.json metric.  A failing registry entry emits an
``{"metric": ..., "error": ...}`` line and never blocks the headline.

Usage: ``python bench.py [--headline-only] [--only SUBSTR] [--reps N]``
"""

from __future__ import annotations

import argparse
import json
import sys


def _backend_alive(timeout_s: int = 240) -> str | None:
    """Probe jax backend init in a THROWAWAY subprocess.

    On the tunneled-TPU environment a dead relay makes backend init
    block indefinitely at the chip claim — inside this process that
    would mean zero output for the driver to record.  A subprocess probe
    converts the hang into an error string.  (The kill can orphan a
    pending claim, but the relay is already unhealthy in that branch.)
    """
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if r.returncode == 0 and "ok" in r.stdout:
            return None
        return (r.stderr.strip().splitlines() or ["backend init failed"])[-1][:300]
    except subprocess.TimeoutExpired:
        return f"backend init exceeded {timeout_s}s (TPU relay unreachable?)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--headline-only", action="store_true", help="skip the registry lines"
    )
    ap.add_argument("--only", default=None, help="substring filter for the registry")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--skip-probe", action="store_true",
                    help="skip the backend-liveness subprocess probe")
    args = ap.parse_args(argv)

    if not args.skip_probe:
        err = _backend_alive()
        if err:
            print(json.dumps({
                "metric": "lab2_roberts_1024x1024_median_ms",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "error": err,
            }), flush=True)
            return 0

    from tpulab.bench_image import bench_lab2

    if not args.headline_only:
        from tpulab.bench import run_benchmarks

        for extra in run_benchmarks(only=args.only, reps=args.reps):
            m = str(extra.get("metric", ""))
            if not ("lab2" in m and "1024x1024" in m):  # headline prints last
                print(json.dumps(extra), flush=True)

    # headline last: 11 outer trials + reported min/IQR tame the
    # run-to-run variance of a ~24 us kernel (VERDICT round 2, weak #4)
    row = bench_lab2(size=1024, reps=args.reps)
    headline = {
        "metric": row["metric"],
        "value": row["value"],
        "unit": row["unit"],
        "vs_baseline": row["vs_baseline"],
    }
    for k in ("min_ms", "p25_ms", "p75_ms", "iqr_ms", "n_trials"):
        if k in row:
            headline[k] = row[k]
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
