#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line for the headline metric.

Headline = lab2 Roberts-cross edge detector at 1024x1024 (the BASELINE.json
target class), steady-state median kernel ms, compared against the
reference's best CUDA config median of 0.17866 ms on an RTX A6000
(reference lab2/KoryakovDA_LR2.pdf chart 3; BASELINE.md).
``vs_baseline`` > 1 means the TPU path is faster than the CUDA baseline.

Usage: ``python bench.py [--all] [--only SUBSTR] [--reps N]``
(``--all`` prints every registered benchmark as extra JSON lines AFTER the
headline line; the driver only reads line one.)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--all", action="store_true", help="print every benchmark")
    ap.add_argument("--only", default=None, help="substring filter (with --all)")
    ap.add_argument("--reps", type=int, default=30)
    args = ap.parse_args(argv)

    from tpulab.bench_image import bench_lab2

    row = bench_lab2(size=1024, reps=args.reps)
    headline = {
        "metric": row["metric"],
        "value": row["value"],
        "unit": row["unit"],
        "vs_baseline": row["vs_baseline"],
    }
    print(json.dumps(headline), flush=True)

    if args.all:
        from tpulab.bench import run_benchmarks

        for extra in run_benchmarks(only=args.only, reps=args.reps):
            if extra["metric"] != row["metric"]:
                print(json.dumps(extra), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
