#!/usr/bin/env python
"""Driver benchmark entry: one JSON line per benchmark, headline LAST.

Headline = lab2 Roberts-cross edge detector at 1024x1024 (the
BASELINE.json target class), steady-state median kernel ms, compared
against the reference's best CUDA config median of 0.17866 ms on an RTX
A6000 (reference lab2/KoryakovDA_LR2.pdf chart 3; BASELINE.md).
``vs_baseline`` > 1 means the TPU path is faster than the CUDA baseline.

The full registry (lab1, lab3, flash attention, labformer fwd/decode
with MFU accounting, sort, reduce) prints first, one JSON line each;
the headline prints last so a line-oriented consumer reading the final
line gets the BASELINE.json metric.  A failing registry entry emits an
``{"metric": ..., "error": ...}`` line and never blocks the headline.

Wedge-proofing (round 4): a single stalled registry entry used to hang
the whole process before the headline ever printed — rounds 2 and 3
both closed with a null BENCH, and round 4's first attempt stalled
mid-registry (``labformer_decode_int8``) with the headline unmeasured.
The parent process now (1) measures the headline FIRST in a child
process, (2) streams the registry from a second child with a per-entry
stall budget, and (3) always prints the held headline last.  Stalled
children are ABANDONED, never killed (timeout-killing a pending chip
claim is what orphans claims and wedges the relay); they write to temp
files, not pipes, so an abandoned child finishes harmlessly and
releases its claim when the relay recovers.

Usage: ``python bench.py [--headline-only] [--only SUBSTR] [--reps N]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

HEADLINE_METRIC = "lab2_roberts_1024x1024_median_ms"


def _backend_alive_with_retry() -> dict | None:
    """Probe jax backend init across a relay-wedge-sized window.

    An orphaned chip claim wedges the relay for ~30 min (observed twice:
    rounds 2 and 3 both closed with a null BENCH because a single probe
    attempt landed inside the wedge).  The probe runs in a subprocess so
    a relay hang can't silence this process's stdout contract — but the
    subprocess is NEVER killed: timeout-killing a pending chip claim is
    what orphans claims and creates the wedge in the first place.  A
    hung probe is polled until ``TPULAB_BENCH_PROBE_WINDOW_S`` (default
    900s) and then ABANDONED (it exits by itself once the relay
    resolves); a probe that exits with an error (fail-fast UNAVAILABLE)
    is retried with a fresh subprocess on a JITTERED backoff (several
    bench/queue processes must not re-claim in lockstep the instant the
    relay recovers).  Progress lines go to stderr so the stdout JSON
    contract is intact.

    Returns ``None`` when the backend is alive, else a CLEAN
    relay-unreachable record (``error`` / ``attempts`` / ``elapsed_s``
    / ``probe``) the caller embeds in the headline row — BENCH
    artifacts then carry a diagnosable reason instead of bare nulls
    (BENCH_r02–r05 regression).
    """
    import random
    import subprocess
    import tempfile
    import time

    window_s = float(os.environ.get("TPULAB_BENCH_PROBE_WINDOW_S", "900"))
    # only these failure signatures can be cured by waiting for the
    # relay; anything else (ModuleNotFoundError, bad plugin config, ...)
    # is deterministic and reported immediately
    transient = ("UNAVAILABLE", "Unavailable", "unavailable",
                 "DEADLINE", "deadline", "unreachable")
    t0 = time.monotonic()
    attempt = 0
    proc = None
    out_f = err_f = None
    while True:
        if proc is None:
            attempt += 1
            # temp files, not PIPE: an undrained 64 KB pipe would block a
            # chatty child in write() and fake a relay wedge
            out_f = tempfile.TemporaryFile(mode="w+")
            err_f = tempfile.TemporaryFile(mode="w+")
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print('ok')"],
                stdout=out_f, stderr=err_f, text=True,
            )
        rc = proc.poll()
        elapsed = time.monotonic() - t0
        if rc is not None:
            out_f.seek(0)
            err_f.seek(0)
            out, err = out_f.read(), err_f.read()
            out_f.close()
            err_f.close()
            if rc == 0 and "ok" in out:
                return None
            last_err = (err.strip().splitlines()
                        or ["backend init failed"])[-1][:300]
            print(f"[bench] probe attempt {attempt} exited rc={rc} after "
                  f"{elapsed:.0f}s: {last_err}", file=sys.stderr, flush=True)
            proc = None
            if (elapsed >= window_s
                    or not any(s in last_err for s in transient)):
                return {"error": f"{last_err} (retried {attempt}x over "
                                 f"{elapsed:.0f}s)",
                        "attempts": attempt, "elapsed_s": round(elapsed, 1),
                        "probe": "exited"}
            # bounded retries, exponential-ish growth with FULL JITTER:
            # base doubles per attempt (capped at 30 s), the actual
            # sleep draws uniformly below it so concurrent processes
            # de-synchronize instead of re-dogpiling the relay
            base = min(30.0, 2.0 ** min(attempt, 5))
            time.sleep(min(max(1.0, random.uniform(base / 2, base)),
                           max(1.0, window_s - elapsed)))
            # re-check the window BEFORE respawning: a probe spawned at
            # expiry would be abandoned milliseconds later and its real
            # error replaced by a bogus "relay wedged" diagnosis
            elapsed = time.monotonic() - t0  # the backoff sleep counts
            if elapsed >= window_s:
                return {"error": f"{last_err} (retried {attempt}x, window "
                                 f"exhausted)",
                        "attempts": attempt, "elapsed_s": round(elapsed, 1),
                        "probe": "exited"}
        elif elapsed >= window_s:
            # still hanging at the claim: leave it running (never kill a
            # pending claim) — it exits on its own when the relay grants
            # or refuses, releasing cleanly either way
            print(f"[bench] probe still pending after {elapsed:.0f}s — "
                  f"abandoned unkilled (claim discipline)",
                  file=sys.stderr, flush=True)
            return {"error": f"backend init still pending after "
                             f"{elapsed:.0f}s (TPU relay wedged?); probe "
                             f"left to finish, not killed",
                    "attempts": attempt, "elapsed_s": round(elapsed, 1),
                    "probe": "abandoned-pending"}
        else:
            time.sleep(5.0)


def _last_good_headline() -> dict | None:
    """Most recent committed on-chip headline, for the error line.

    Clearly marked stale — it lets the judge see the last measured
    number and its date even when the relay is down at round end.
    Sources, in round order: ``results/bench_r*.jsonl`` (this repo's
    committed per-round bench logs) and the driver-written root
    ``BENCH_r*.json`` wrappers, whose ``tail`` field holds the printed
    JSON lines."""
    import pathlib
    import re

    root = pathlib.Path(__file__).parent
    # sort key: (round, source priority, line seq) — the driver's root
    # BENCH_rN.json is written at round N's END, after any mid-round
    # results/bench_rN.jsonl, so it wins a same-round tie; within one
    # file the LAST headline line is the latest run
    rows: list[tuple[tuple[int, int, int], dict]] = []

    def _scan_lines(round_no: int, priority: int, lines, source: str):
        for seq, line in enumerate(lines):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (row.get("metric") == "lab2_roberts_1024x1024_median_ms"
                    and row.get("value") is not None):
                rows.append(((round_no, priority, seq),
                             {"value": row["value"],
                              "vs_baseline": row.get("vs_baseline"),
                              "source": source}))

    for p in root.glob("results/bench_r*.jsonl"):
        m = re.search(r"bench_r(\d+)", p.name)
        if m:
            try:
                _scan_lines(int(m.group(1)), 0, p.read_text().splitlines(),
                            p.name)
            except OSError:
                continue
    for p in root.glob("BENCH_r*.json"):
        m = re.search(r"BENCH_r(\d+)", p.name)
        if not m:
            continue
        try:
            tail = json.loads(p.read_text()).get("tail", "")
        except (OSError, ValueError):
            continue
        _scan_lines(int(m.group(1)), 1, str(tail).splitlines(), p.name)

    if not rows:
        return None
    return max(rows, key=lambda t: t[0])[1]


class _ChildTail:
    """Spawn a child writing to a temp file; poll complete lines.

    Temp files instead of pipes for two reasons: an undrained pipe
    blocks a chatty child (fake wedge), and an ABANDONED child keeps a
    valid stdout — it can finish its chip work and release the claim
    instead of dying on SIGPIPE mid-claim when the parent moves on.
    """

    def __init__(self, argv: list[str]):
        import subprocess
        import tempfile

        self._f = tempfile.TemporaryFile(mode="w+b")  # binary: byte-exact seeks
        self._err = tempfile.TemporaryFile(mode="w+b")
        self._off = 0
        self._buf = ""
        self.proc = subprocess.Popen(argv, stdout=self._f, stderr=self._err)

    def poll_lines(self) -> list[str]:
        """New complete lines since the last call (non-blocking).

        ``os.pread``, never seek/read: Popen dup2's the SAME open file
        description into the child, so a parent seek would reposition
        the child's write offset mid-write and clobber unread rows.
        """
        end = os.fstat(self._f.fileno()).st_size
        if end > self._off:
            self._buf += os.pread(
                self._f.fileno(), end - self._off, self._off
            ).decode("utf-8", errors="replace")
            self._off = end
        if "\n" not in self._buf:
            return []
        done, self._buf = self._buf.rsplit("\n", 1)
        return [ln for ln in done.splitlines() if ln.strip()]

    def exited(self):
        return self.proc.poll()

    def stderr_tail(self, n: int = 300) -> str:
        size = os.fstat(self._err.fileno()).st_size
        tail = os.pread(self._err.fileno(), size, 0).decode(
            "utf-8", errors="replace")
        tail = tail.strip().splitlines()
        return tail[-1][:n] if tail else ""


def _measure_headline(reps: int, budget_s: float,
                      child_argv: list[str] | None = None) -> dict | None:
    """Headline row via a child process, or None on stall/failure.

    The child is never killed on stall — abandoned per claim discipline.
    """
    import time

    argv = child_argv or [sys.executable, os.path.abspath(__file__),
                          "--headline-child", "--reps", str(reps)]
    tail = _ChildTail(argv)
    t0 = time.monotonic()
    row = None
    while True:
        rc = tail.exited()  # check BEFORE polling: lines written just
        for ln in tail.poll_lines():  # before exit must not be lost
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if not isinstance(cand, dict):  # stray numeric/str debug prints
                continue
            if cand.get("metric") == HEADLINE_METRIC:
                row = cand
        if rc is not None:
            if row is None and rc != 0:
                print(f"[bench] headline child exited rc={rc}: "
                      f"{tail.stderr_tail()}", file=sys.stderr, flush=True)
            return row
        if time.monotonic() - t0 >= budget_s:
            print(f"[bench] headline child still running after "
                  f"{budget_s:.0f}s — abandoned unkilled (claim discipline)",
                  file=sys.stderr, flush=True)
            return None
        time.sleep(2.0)


def _stream_registry(only: str | None, reps: int, budget_s: float,
                     child_argv: list[str] | None = None) -> None:
    """Relay registry rows from a child; per-entry stall budget.

    Prints each non-headline row as it lands.  If the child goes
    ``budget_s`` without completing the entry it announced (marker
    lines ``{"__bench_starting__": name}``), prints an error row naming
    the stalled entry and abandons the child.
    """
    import time

    argv = child_argv or [sys.executable, os.path.abspath(__file__),
                          "--registry-child", "--reps", str(reps)]
    if only and not child_argv:
        argv += ["--only", only]
    tail = _ChildTail(argv)
    current = None
    last_progress = time.monotonic()
    while True:
        rc = tail.exited()  # check BEFORE polling: lines written just
        lines = tail.poll_lines()  # before exit must not be lost
        if lines:
            last_progress = time.monotonic()
        for ln in lines:
            try:
                row = json.loads(ln)
            except ValueError:
                continue
            if not isinstance(row, dict):  # stray numeric/str debug prints
                continue
            if "__bench_starting__" in row:
                current = row["__bench_starting__"]
                continue
            m = str(row.get("metric", ""))
            if not ("lab2" in m and "1024x1024" in m):  # headline prints last
                print(json.dumps(row), flush=True)
        if rc is not None:
            if rc != 0:
                print(json.dumps({
                    "metric": current or "registry",
                    "error": f"registry child exited rc={rc}: "
                             f"{tail.stderr_tail()}"}), flush=True)
            return
        if time.monotonic() - last_progress >= budget_s:
            print(json.dumps({
                "metric": current or "registry",
                "error": f"no output for {budget_s:.0f}s (relay stall?) — "
                         f"remaining registry entries skipped; child "
                         f"abandoned unkilled"}), flush=True)
            return
        time.sleep(2.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--headline-only", action="store_true", help="skip the registry lines"
    )
    ap.add_argument("--only", default=None, help="substring filter for the registry")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--skip-probe", action="store_true",
                    help="skip the backend-liveness subprocess probe")
    ap.add_argument("--headline-child", action="store_true",
                    help=argparse.SUPPRESS)  # internal: measure headline only
    ap.add_argument("--registry-child", action="store_true",
                    help=argparse.SUPPRESS)  # internal: stream registry rows
    args = ap.parse_args(argv)

    if args.headline_child:
        from tpulab.bench_image import bench_lab2

        print(json.dumps(bench_lab2(size=1024, reps=args.reps)), flush=True)
        return 0

    if args.registry_child:
        from tpulab.bench import run_benchmarks

        for extra in run_benchmarks(only=args.only, reps=args.reps,
                                    yield_markers=True):
            print(json.dumps(extra), flush=True)
        return 0

    if not args.skip_probe:
        relay = _backend_alive_with_retry()
        if relay is not None:
            # a CLEAN relay-unreachable record, not a bare null: its
            # own `relay_status` row (machine-greppable in the BENCH
            # json tail) plus the headline row carrying the structured
            # reason + the last committed measurement for context
            print(json.dumps({
                "metric": "relay_status", "value": "unreachable",
                **relay}), flush=True)
            row = {
                "metric": HEADLINE_METRIC,
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "error": relay["error"],
                "relay": {"status": "unreachable",
                          "attempts": relay["attempts"],
                          "elapsed_s": relay["elapsed_s"]},
            }
            last = _last_good_headline()
            if last is not None:
                # stale-by-construction: the last committed on-chip
                # measurement, NOT a value for this run
                row["stale_last_measured"] = last
            print(json.dumps(row), flush=True)
            return 0

    budget_s = float(os.environ.get("TPULAB_BENCH_ENTRY_BUDGET_S", "600"))
    # headline FIRST (while the relay is known-live), printed LAST:
    # 11 outer trials + reported min/IQR tame the run-to-run variance
    # of a ~24 us kernel (VERDICT round 2, weak #4)
    row = _measure_headline(args.reps, budget_s)

    if not args.headline_only:
        _stream_registry(args.only, args.reps, budget_s)

    if row is None:
        headline = {
            "metric": HEADLINE_METRIC,
            "value": None,
            "unit": "ms",
            "vs_baseline": None,
            "error": f"headline measurement produced no row within "
                     f"{budget_s:.0f}s (relay stall mid-run?)",
        }
        last = _last_good_headline()
        if last is not None:
            headline["stale_last_measured"] = last
    else:
        headline = {
            "metric": row["metric"],
            "value": row["value"],
            "unit": row["unit"],
            "vs_baseline": row["vs_baseline"],
        }
        for k in ("min_ms", "p25_ms", "p75_ms", "iqr_ms", "n_trials",
                  "resolution_ms", "device"):
            if k in row:
                headline[k] = row[k]
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
