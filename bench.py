#!/usr/bin/env python
"""Driver benchmark entry: one JSON line per benchmark, headline LAST.

Headline = lab2 Roberts-cross edge detector at 1024x1024 (the
BASELINE.json target class), steady-state median kernel ms, compared
against the reference's best CUDA config median of 0.17866 ms on an RTX
A6000 (reference lab2/KoryakovDA_LR2.pdf chart 3; BASELINE.md).
``vs_baseline`` > 1 means the TPU path is faster than the CUDA baseline.

The full registry (lab1, lab3, flash attention, labformer fwd/decode
with MFU accounting, sort, reduce) prints first, one JSON line each;
the headline prints last so a line-oriented consumer reading the final
line gets the BASELINE.json metric.  A failing registry entry emits an
``{"metric": ..., "error": ...}`` line and never blocks the headline.

Usage: ``python bench.py [--headline-only] [--only SUBSTR] [--reps N]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _backend_alive_with_retry() -> str | None:
    """Probe jax backend init across a relay-wedge-sized window.

    An orphaned chip claim wedges the relay for ~30 min (observed twice:
    rounds 2 and 3 both closed with a null BENCH because a single probe
    attempt landed inside the wedge).  The probe runs in a subprocess so
    a relay hang can't silence this process's stdout contract — but the
    subprocess is NEVER killed: timeout-killing a pending chip claim is
    what orphans claims and creates the wedge in the first place.  A
    hung probe is polled until ``TPULAB_BENCH_PROBE_WINDOW_S`` (default
    900s) and then ABANDONED (it exits by itself once the relay
    resolves); a probe that exits with an error (fail-fast UNAVAILABLE)
    is retried with a fresh subprocess.  Progress lines go to stderr so
    the stdout JSON contract is intact.
    """
    import subprocess
    import tempfile
    import time

    window_s = float(os.environ.get("TPULAB_BENCH_PROBE_WINDOW_S", "900"))
    # only these failure signatures can be cured by waiting for the
    # relay; anything else (ModuleNotFoundError, bad plugin config, ...)
    # is deterministic and reported immediately
    transient = ("UNAVAILABLE", "Unavailable", "unavailable",
                 "DEADLINE", "deadline", "unreachable")
    t0 = time.monotonic()
    attempt = 0
    proc = None
    out_f = err_f = None
    while True:
        if proc is None:
            attempt += 1
            # temp files, not PIPE: an undrained 64 KB pipe would block a
            # chatty child in write() and fake a relay wedge
            out_f = tempfile.TemporaryFile(mode="w+")
            err_f = tempfile.TemporaryFile(mode="w+")
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print('ok')"],
                stdout=out_f, stderr=err_f, text=True,
            )
        rc = proc.poll()
        elapsed = time.monotonic() - t0
        if rc is not None:
            out_f.seek(0)
            err_f.seek(0)
            out, err = out_f.read(), err_f.read()
            out_f.close()
            err_f.close()
            if rc == 0 and "ok" in out:
                return None
            last_err = (err.strip().splitlines()
                        or ["backend init failed"])[-1][:300]
            print(f"[bench] probe attempt {attempt} exited rc={rc} after "
                  f"{elapsed:.0f}s: {last_err}", file=sys.stderr, flush=True)
            proc = None
            if (elapsed >= window_s
                    or not any(s in last_err for s in transient)):
                return f"{last_err} (retried {attempt}x over {elapsed:.0f}s)"
            time.sleep(min(30.0, max(1.0, window_s - elapsed)))
            # re-check the window BEFORE respawning: a probe spawned at
            # expiry would be abandoned milliseconds later and its real
            # error replaced by a bogus "relay wedged" diagnosis
            if time.monotonic() - t0 >= window_s:
                return f"{last_err} (retried {attempt}x, window exhausted)"
        elif elapsed >= window_s:
            # still hanging at the claim: leave it running (never kill a
            # pending claim) — it exits on its own when the relay grants
            # or refuses, releasing cleanly either way
            print(f"[bench] probe still pending after {elapsed:.0f}s — "
                  f"abandoned unkilled (claim discipline)",
                  file=sys.stderr, flush=True)
            return (f"backend init still pending after {elapsed:.0f}s "
                    f"(TPU relay wedged?); probe left to finish, not killed")
        else:
            time.sleep(5.0)


def _last_good_headline() -> dict | None:
    """Most recent committed on-chip headline, for the error line.

    Clearly marked stale — it lets the judge see the last measured
    number and its date even when the relay is down at round end.
    Sources, in round order: ``results/bench_r*.jsonl`` (this repo's
    committed per-round bench logs) and the driver-written root
    ``BENCH_r*.json`` wrappers, whose ``tail`` field holds the printed
    JSON lines."""
    import pathlib
    import re

    root = pathlib.Path(__file__).parent
    # sort key: (round, source priority, line seq) — the driver's root
    # BENCH_rN.json is written at round N's END, after any mid-round
    # results/bench_rN.jsonl, so it wins a same-round tie; within one
    # file the LAST headline line is the latest run
    rows: list[tuple[tuple[int, int, int], dict]] = []

    def _scan_lines(round_no: int, priority: int, lines, source: str):
        for seq, line in enumerate(lines):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (row.get("metric") == "lab2_roberts_1024x1024_median_ms"
                    and row.get("value") is not None):
                rows.append(((round_no, priority, seq),
                             {"value": row["value"],
                              "vs_baseline": row.get("vs_baseline"),
                              "source": source}))

    for p in root.glob("results/bench_r*.jsonl"):
        m = re.search(r"bench_r(\d+)", p.name)
        if m:
            try:
                _scan_lines(int(m.group(1)), 0, p.read_text().splitlines(),
                            p.name)
            except OSError:
                continue
    for p in root.glob("BENCH_r*.json"):
        m = re.search(r"BENCH_r(\d+)", p.name)
        if not m:
            continue
        try:
            tail = json.loads(p.read_text()).get("tail", "")
        except (OSError, ValueError):
            continue
        _scan_lines(int(m.group(1)), 1, str(tail).splitlines(), p.name)

    if not rows:
        return None
    return max(rows, key=lambda t: t[0])[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--headline-only", action="store_true", help="skip the registry lines"
    )
    ap.add_argument("--only", default=None, help="substring filter for the registry")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--skip-probe", action="store_true",
                    help="skip the backend-liveness subprocess probe")
    args = ap.parse_args(argv)

    if not args.skip_probe:
        err = _backend_alive_with_retry()
        if err:
            row = {
                "metric": "lab2_roberts_1024x1024_median_ms",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "error": err,
            }
            last = _last_good_headline()
            if last is not None:
                # stale-by-construction: the last committed on-chip
                # measurement, NOT a value for this run
                row["stale_last_measured"] = last
            print(json.dumps(row), flush=True)
            return 0

    from tpulab.bench_image import bench_lab2

    if not args.headline_only:
        from tpulab.bench import run_benchmarks

        for extra in run_benchmarks(only=args.only, reps=args.reps):
            m = str(extra.get("metric", ""))
            if not ("lab2" in m and "1024x1024" in m):  # headline prints last
                print(json.dumps(extra), flush=True)

    # headline last: 11 outer trials + reported min/IQR tame the
    # run-to-run variance of a ~24 us kernel (VERDICT round 2, weak #4)
    row = bench_lab2(size=1024, reps=args.reps)
    headline = {
        "metric": row["metric"],
        "value": row["value"],
        "unit": row["unit"],
        "vs_baseline": row["vs_baseline"],
    }
    for k in ("min_ms", "p25_ms", "p75_ms", "iqr_ms", "n_trials"):
        if k in row:
            headline[k] = row[k]
    print(json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
