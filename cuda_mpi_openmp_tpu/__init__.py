"""Compatibility alias: ``cuda_mpi_openmp_tpu`` re-exports :mod:`tpulab`.

The framework's import name is ``tpulab``; this alias mirrors the
reference repository's name for discoverability.
"""

import sys

import tpulab
from tpulab import *  # noqa: F401,F403

# Make ``import cuda_mpi_openmp_tpu.ops`` style submodule imports resolve
# to the tpulab subpackages.
for _sub in ("io", "ops", "labs", "parallel", "models", "harness", "runtime", "utils", "cli"):
    try:
        _mod = __import__(f"tpulab.{_sub}", fromlist=[_sub])
        sys.modules[f"{__name__}.{_sub}"] = _mod
        globals()[_sub] = _mod
    except ImportError:
        pass

__version__ = tpulab.__version__
