// tpulab_client — native thin client for the tpulab warm-runtime daemon.
//
// The compiled counterpart of the reference suite's per-lab native
// binaries (reference lab*/src/*.cu stdin contract): reads the workload
// payload from stdin, prints the "<DEVICE> execution time: <T ms>" line
// and payload to stdout.  Compute happens in the persistent JAX daemon
// (tpulab/daemon.py) reached over a unix socket, so the harness's
// subprocess-per-run model (reference tester.py:126) costs a socket
// round-trip instead of TPU runtime init + XLA compile per run.
//
// Usage:  tpulab_client <lab> [--to-plot] [--backend B] [--key value ...]
// Socket: $TPULAB_DAEMON_SOCKET (default /tmp/tpulab.sock).  If the
// daemon is unreachable, falls back to exec'ing `python -m tpulab run`,
// preserving the contract (cold, but correct).
//
// Wire protocol: see tpulab/daemon.py docstring.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <csignal>

#include <sys/socket.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

std::string read_all_stdin() {
  std::string buf;
  char chunk[1 << 16];
  size_t n;
  while ((n = fread(chunk, 1, sizeof(chunk), stdin)) > 0) buf.append(chunk, n);
  return buf;
}

// Minimal JSON string escaping (keys/values are shell words; no control
// characters expected, but escape to stay valid).
std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default: out += c;
    }
  }
  return out;
}

// Strict JSON number grammar (RFC 8259): -?(0|[1-9][0-9]*)(\.[0-9]+)?
// ([eE][+-]?[0-9]+)?.  Looser sniffing ("007", "1.", "-", ".") would
// emit invalid JSON the daemon's json.loads rejects; anything failing
// this grammar is forwarded as a quoted string instead.
bool is_json_number(const std::string& v) {
  size_t i = 0, n = v.size();
  auto digit = [&](size_t j) { return j < n && v[j] >= '0' && v[j] <= '9'; };
  if (i < n && v[i] == '-') ++i;
  if (!digit(i)) return false;
  if (v[i] == '0') ++i;
  else while (digit(i)) ++i;
  if (i < n && v[i] == '.') {
    ++i;
    if (!digit(i)) return false;
    while (digit(i)) ++i;
  }
  if (i < n && (v[i] == 'e' || v[i] == 'E')) {
    ++i;
    if (i < n && (v[i] == '+' || v[i] == '-')) ++i;
    if (!digit(i)) return false;
    while (digit(i)) ++i;
  }
  return i == n;
}

// --key value pairs -> JSON object with bool/number passthrough (the
// daemon's workload kwargs are type-coerced Python-side as well; numbers
// are forwarded unquoted so e.g. --reps 5 arrives as an int).
std::string config_json(const std::vector<std::pair<std::string, std::string>>& kv) {
  std::string out = "{";
  bool first = true;
  for (const auto& [k, v] : kv) {
    if (!first) out += ",";
    first = false;
    out += "\"" + json_escape(k) + "\":";
    if (v == "true" || v == "false" || is_json_number(v))
      out += v;
    else
      out += "\"" + json_escape(v) + "\"";
  }
  return out + "}";
}

bool send_exact(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_exact(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

[[noreturn]] void exec_python_cli(int argc, char** argv) {
  // cold path: python -m tpulab run <lab> [--to-plot] [--backend B] [extras]
  std::vector<char*> args;
  static char py[] = "python3";
  static char dash_m[] = "-m";
  static char mod[] = "tpulab";
  static char run[] = "run";
  args = {py, dash_m, mod, run};
  for (int i = 1; i < argc; ++i) args.push_back(argv[i]);
  args.push_back(nullptr);
  execvp("python3", args.data());
  // try plain `python` if python3 is absent
  static char py2[] = "python";
  args[0] = py2;
  execvp("python", args.data());
  perror("tpulab_client: exec python fallback failed");
  exit(127);
}

[[noreturn]] void fallback_with_payload(int argc, char** argv,
                                        const std::string& payload) {
  // stdin is already consumed into `payload` (read before connecting so
  // the daemon's handler slot isn't held during stdin ingestion), so a
  // plain re-exec would hand the CLI an empty stdin — feed the captured
  // payload through a pipe instead.
  int fds[2];
  if (pipe(fds) != 0) {
    perror("tpulab_client: pipe for fallback failed");
    exit(127);
  }
  pid_t pid = fork();
  if (pid < 0) {
    perror("tpulab_client: fork for fallback failed");
    exit(127);
  }
  if (pid == 0) {
    close(fds[1]);
    if (dup2(fds[0], 0) < 0) _exit(127);
    close(fds[0]);
    exec_python_cli(argc, argv);
  }
  close(fds[0]);
  // child may exit before draining (e.g. bad args): a SIGPIPE here must
  // not kill us before we can report its exit status
  signal(SIGPIPE, SIG_IGN);
  size_t off = 0;
  while (off < payload.size()) {
    ssize_t w = write(fds[1], payload.data() + off, payload.size() - off);
    if (w < 0) {
      if (errno == EINTR) continue;
      break;  // child gone; its status is the answer
    }
    off += static_cast<size_t>(w);
  }
  close(fds[1]);
  int st = 0;
  waitpid(pid, &st, 0);
  exit(WIFEXITED(st) ? WEXITSTATUS(st) : 128 + WTERMSIG(st));
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <lab> [--to-plot] [--backend B] [--key value ...]\n", argv[0]);
    return 2;
  }
  std::string lab = argv[1];
  bool sweep = false;
  std::string backend;
  std::vector<std::pair<std::string, std::string>> cfg;
  for (int i = 2; i < argc; ++i) {
    std::string a = argv[i];
    if (a == "--to-plot" || a == "--to_plot") {
      sweep = true;
    } else if (a == "--backend" && i + 1 < argc) {
      backend = argv[++i];
    } else if (a.rfind("--", 0) == 0 && i + 1 < argc) {
      cfg.emplace_back(a.substr(2), argv[++i]);
    } else {
      fprintf(stderr, "tpulab_client: unrecognized arg %s\n", a.c_str());
      return 2;
    }
  }

  const char* sock_env = getenv("TPULAB_DAEMON_SOCKET");
  std::string sock_path = sock_env && *sock_env ? sock_env : "/tmp/tpulab.sock";
  if (sock_path.size() >= sizeof(sockaddr_un{}.sun_path)) {
    exec_python_cli(argc, argv);  // unusable socket path: cold path
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, sock_path.c_str(), sizeof(addr.sun_path) - 1);

  // Cheap daemon-presence check WITHOUT connecting: the common
  // no-daemon cold path must keep handing python an untouched streaming
  // stdin (no double-buffering of multi-hundred-MB payloads), and a
  // throwaway probe connection would both churn a daemon handler slot
  // and double-count against --max-requests.  A stale socket file
  // (daemon crashed) is rare and still correct: we buffer stdin, the
  // real connect below fails, and fallback_with_payload pipes the
  // captured bytes to the python CLI.
  if (access(sock_path.c_str(), F_OK) != 0) {
    exec_python_cli(argc, argv);
  }

  // Socket file exists: slurp stdin BEFORE the real connect — from
  // connect() on, the daemon holds a bounded handler slot with an
  // eviction deadline (tpulab/daemon.py RECV_TIMEOUT_S), and time spent
  // by a slow upstream producer must not count against it.
  std::string payload = read_all_stdin();

  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) {
    fallback_with_payload(argc, argv, payload);
  }
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
    std::string header = "{\"lab\":\"" + json_escape(lab) + "\"";
    header += ",\"sweep\":" + std::string(sweep ? "true" : "false");
    header += ",\"backend\":" +
              (backend.empty() ? std::string("null")
                               : "\"" + json_escape(backend) + "\"");
    header += ",\"config\":" + config_json(cfg) + "}";

    uint32_t hlen = static_cast<uint32_t>(header.size());
    uint64_t plen = payload.size();
    bool ok = send_exact(fd, &hlen, 4) && send_exact(fd, header.data(), hlen) &&
              send_exact(fd, &plen, 8) && send_exact(fd, payload.data(), plen);
    // Frame loop: status-2 CHUNK frames (streaming generate) print as
    // they arrive; the terminal frame (0 ok / 1 error) ends the
    // request.  After streamed chunks the terminal body is suppressed
    // on stdout — it repeats the full output for non-streaming readers.
    bool streamed = false;
    while (ok) {
      uint8_t status = 255;
      uint64_t rlen = 0;
      if (!recv_exact(fd, &status, 1) || !recv_exact(fd, &rlen, 8)) break;
      std::string out(rlen, '\0');
      if (!recv_exact(fd, out.data(), rlen)) break;
      if (status == 2) {
        fwrite(out.data(), 1, out.size(), stdout);
        fflush(stdout);
        streamed = true;
        continue;
      }
      close(fd);
      if (status == 0) {
        if (!streamed) fwrite(out.data(), 1, out.size(), stdout);
        return 0;
      }
      fwrite(out.data(), 1, out.size(), stderr);
      return 1;
    }
    if (streamed) {
      // partial output already reached stdout: a fallback rerun would
      // duplicate it — report the broken stream instead
      fprintf(stderr, "tpulab_client: stream broken mid-response\n");
      close(fd);
      return 1;
    }
    fprintf(stderr, "tpulab_client: daemon protocol error, falling back\n");
    close(fd);
    fallback_with_payload(argc, argv, payload);
  }
  close(fd);
  // stale socket file or refused connect: the daemon is gone — pipe the
  // already-captured payload through the python CLI
  fallback_with_payload(argc, argv, payload);
}
