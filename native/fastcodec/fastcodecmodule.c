/* fastcodec — C implementations of the lab suite's hot codec loops.
 *
 * Native counterpart of the reference's per-pixel Python loops in
 * utils/converter.py:84-113 (the profiled harness hotspot, SURVEY.md
 * section 3.1).  Exposed functions:
 *
 *   hex_encode(data: bytes, group: int = 8) -> str
 *       lowercase hex, space-separated fixed-size groups (one group =
 *       one little-endian u32 word = one RGBA pixel or header int).
 *   hex_decode(text: str) -> bytes
 *       whitespace-tolerant hex -> raw bytes.
 *
 * Built with the stdlib CPython C API (no pybind11 in the image); see
 * tools/build_native.py.  tpulab.io.imagefile auto-uses it when
 * importable and falls back to binascii otherwise.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

static const char HEXDIGITS[] = "0123456789abcdef";

static PyObject *fastcodec_hex_encode(PyObject *self, PyObject *args) {
  Py_buffer buf;
  Py_ssize_t group = 8;
  if (!PyArg_ParseTuple(args, "y*|n", &buf, &group)) return NULL;
  if (group <= 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "group must be positive");
    return NULL;
  }
  const uint8_t *src = (const uint8_t *)buf.buf;
  Py_ssize_t n = buf.len;
  Py_ssize_t hex_len = n * 2;
  Py_ssize_t n_groups = hex_len ? (hex_len + group - 1) / group : 0;
  Py_ssize_t total = hex_len + (n_groups > 0 ? n_groups - 1 : 0);

  PyObject *out = PyUnicode_New(total, 127);
  if (!out) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  Py_UCS1 *dst = PyUnicode_1BYTE_DATA(out);
  Py_ssize_t written = 0, in_group = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    uint8_t b = src[i];
    for (int half = 0; half < 2; half++) {
      if (in_group == group) {
        dst[written++] = ' ';
        in_group = 0;
      }
      dst[written++] = (uint8_t)HEXDIGITS[half ? (b & 0xF) : (b >> 4)];
      in_group++;
    }
  }
  PyBuffer_Release(&buf);
  return out;
}

static int hex_val(uint32_t c) {
  if (c >= '0' && c <= '9') return (int)(c - '0');
  if (c >= 'a' && c <= 'f') return (int)(c - 'a' + 10);
  if (c >= 'A' && c <= 'F') return (int)(c - 'A' + 10);
  return -1;
}

static PyObject *fastcodec_hex_decode(PyObject *self, PyObject *args) {
  PyObject *text;
  if (!PyArg_ParseTuple(args, "U", &text)) return NULL;
  if (PyUnicode_READY(text) < 0) return NULL;
  Py_ssize_t len = PyUnicode_GET_LENGTH(text);
  int kind = PyUnicode_KIND(text);
  const void *data = PyUnicode_DATA(text);

  uint8_t *tmp = (uint8_t *)PyMem_Malloc(len ? (size_t)len / 2 + 1 : 1);
  if (!tmp) return PyErr_NoMemory();

  Py_ssize_t out_len = 0;
  int have_hi = 0, hi = 0;
  for (Py_ssize_t i = 0; i < len; i++) {
    uint32_t c = PyUnicode_READ(kind, data, i);
    if (c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\f' || c == 0x0B)
      continue;
    int v = hex_val(c);
    if (v < 0) {
      PyMem_Free(tmp);
      /* format the ordinal directly: %R on a fresh PyUnicode_FromOrdinal
       * would leak the temporary (PyErr_Format does not steal it).
       * lowercase %04x: uppercase %X only exists from CPython 3.12 */
      PyErr_Format(PyExc_ValueError, "non-hex character U+%04x at index %zd",
                   (unsigned)c, i);
      return NULL;
    }
    if (have_hi) {
      tmp[out_len++] = (uint8_t)((hi << 4) | v);
      have_hi = 0;
    } else {
      hi = v;
      have_hi = 1;
    }
  }
  if (have_hi) {
    PyMem_Free(tmp);
    PyErr_SetString(PyExc_ValueError, "odd number of hex digits");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize((const char *)tmp, out_len);
  PyMem_Free(tmp);
  return out;
}

static PyMethodDef fastcodec_methods[] = {
    {"hex_encode", fastcodec_hex_encode, METH_VARARGS,
     "hex_encode(data, group=8) -> grouped lowercase hex string"},
    {"hex_decode", fastcodec_hex_decode, METH_VARARGS,
     "hex_decode(text) -> bytes (whitespace tolerant)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef fastcodec_module = {
    PyModuleDef_HEAD_INIT, "_tpulab_fastcodec",
    "C codec loops for the tpulab image formats", -1, fastcodec_methods};

PyMODINIT_FUNC PyInit__tpulab_fastcodec(void) {
  return PyModule_Create(&fastcodec_module);
}
