// tpulab native data loader: threaded, deterministic, step-ordered.
//
// The training driver consumes (batch, row_tokens) int32 batches of
// byte-level tokens streamed from arbitrary files (the byte LM treats
// any file as training data).  Worker threads claim step numbers with
// an atomic counter, synthesize their batch with pread (no shared file
// offsets), and publish into an ordered buffer; the consumer always
// receives step k before step k+1, so a run is bit-reproducible for a
// given (files, seed, start_step) regardless of thread count — the
// property the reference world's CUDA pipelines get from single-stream
// loaders, kept here under real prefetch concurrency.
//
// Row sampling is stateless: row r of step s reads file/offset derived
// from splitmix64(seed, s, r).  Resume == reopen with start_step.
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   tl_open(paths, n, batch, row_tokens, prefetch, threads, seed,
//           start_step, err, errlen) -> handle | NULL
//   tl_next(handle, out) -> step number delivered, or -1 after close
//   tl_short_reads(handle) -> rows zero-padded by IO failure so far
//   tl_close(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct File {
  int fd;
  int64_t size;
};

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Loader {
  std::vector<File> files;
  int batch = 0;
  int row_tokens = 0;
  int prefetch = 0;
  uint64_t seed = 0;

  std::vector<std::thread> workers;
  std::atomic<uint64_t> claim{0};   // next step a worker takes
  std::atomic<bool> stop{false};
  // rows zero-padded because pread failed or the file shrank; exposed
  // via tl_short_reads so the consumer can detect corrupted training
  // rows instead of silently learning token 0 (round-2 advisor)
  mutable std::atomic<uint64_t> short_reads{0};

  std::mutex mu;
  std::condition_variable cv_room;  // producers: buffer has room
  std::condition_variable cv_data;  // consumer: next step is present
  std::map<uint64_t, std::vector<int32_t>> ready;
  uint64_t next_out = 0;            // step the consumer needs next

  ~Loader() {
    {
      // store+notify under mu: without the lock a worker between its
      // predicate check and blocking would miss the wakeup (lost
      // notify) and t.join() below would hang forever
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_room.notify_all();
    cv_data.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    for (auto& f : files) close(f.fd);
  }

  void fill_batch(uint64_t step, std::vector<int32_t>& out) const {
    const int64_t row_bytes = row_tokens;
    std::vector<unsigned char> buf(row_bytes);
    for (int r = 0; r < batch; ++r) {
      uint64_t h = splitmix64(seed ^ splitmix64(step * 0x10001ULL + r));
      const File& f = files[h % files.size()];
      int64_t span = f.size - row_bytes;
      int64_t off = span > 0 ? (int64_t)(splitmix64(h) % (uint64_t)(span + 1)) : 0;
      int64_t got = 0;
      while (got < row_bytes) {
        ssize_t n = pread(f.fd, buf.data() + got, row_bytes - got, off + got);
        if (n <= 0) {  // unexpected shrink: zero-fill rather than hang
          std::memset(buf.data() + got, 0, row_bytes - got);
          short_reads.fetch_add(1);
          break;
        }
        got += n;
      }
      int32_t* dst = out.data() + (size_t)r * row_tokens;
      for (int64_t i = 0; i < row_bytes; ++i) dst[i] = buf[i];
    }
  }

  void worker() {
    while (!stop.load()) {
      uint64_t step = claim.fetch_add(1);
      std::vector<int32_t> out((size_t)batch * row_tokens);
      fill_batch(step, out);
      std::unique_lock<std::mutex> lk(mu);
      // bounded: don't run more than `prefetch` steps past the consumer
      cv_room.wait(lk, [&] {
        return stop.load() || step < next_out + (uint64_t)prefetch;
      });
      if (stop.load()) return;
      ready.emplace(step, std::move(out));
      cv_data.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* tl_open(const char** paths, int n_files, int batch, int row_tokens,
              int prefetch, int threads, uint64_t seed, uint64_t start_step,
              char* err, int errlen) {
  auto fail = [&](const std::string& m) -> void* {
    if (err && errlen > 0) {
      std::snprintf(err, errlen, "%s", m.c_str());
    }
    return nullptr;
  };
  if (n_files <= 0) return fail("no input files");
  if (batch <= 0 || row_tokens <= 0) return fail("batch/row_tokens must be > 0");
  auto ld = new Loader();
  ld->batch = batch;
  ld->row_tokens = row_tokens;
  ld->prefetch = prefetch > 0 ? prefetch : 2;
  ld->seed = seed;
  ld->claim.store(start_step);
  ld->next_out = start_step;
  for (int i = 0; i < n_files; ++i) {
    int fd = open(paths[i], O_RDONLY);
    if (fd < 0) {
      delete ld;
      return fail(std::string("cannot open ") + paths[i]);
    }
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < row_tokens) {
      close(fd);
      continue;  // too small to yield one full row
    }
    ld->files.push_back({fd, (int64_t)st.st_size});
  }
  if (ld->files.empty()) {
    delete ld;
    return fail("no file holds a full row of row_tokens bytes");
  }
  int nt = threads > 0 ? threads : 2;
  for (int i = 0; i < nt; ++i)
    ld->workers.emplace_back([ld] { ld->worker(); });
  return ld;
}

long long tl_next(void* handle, int32_t* out) {
  auto ld = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_data.wait(lk, [&] {
    return ld->stop.load() || ld->ready.count(ld->next_out) > 0;
  });
  if (ld->stop.load()) return -1;
  auto it = ld->ready.find(ld->next_out);
  std::memcpy(out, it->second.data(), it->second.size() * sizeof(int32_t));
  uint64_t step = it->first;
  ld->ready.erase(it);
  ld->next_out = step + 1;
  ld->cv_room.notify_all();
  return (long long)step;
}

unsigned long long tl_short_reads(void* handle) {
  return (unsigned long long)
      static_cast<Loader*>(handle)->short_reads.load();
}

void tl_close(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
