"""Test configuration.

Forces the CPU backend with 8 virtual devices BEFORE jax initializes, so
multi-device sharding/collective tests run without TPU hardware (the
equivalent of the reference suite's golden-file tier, which runs against
whatever device is present — see SURVEY.md section 4).
"""

import os
import pathlib as _pathlib

# Hard override: the container environment pins JAX_PLATFORMS=axon (real
# TPU tunnel); tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# Blank (not unset) so child processes — subprocess-target tests spawn
# `python -m tpulab` — skip the sitecustomize axon TPU claim: a test run
# killed mid-claim wedges the relay for every later python startup.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache (.jax_cache/, gitignored): on this
# one-core box the suite's wall time is dominated by recompiling the
# same small programs every run — warm-cache runs cut minutes off every
# verification loop.  Env vars (not config calls) so the subprocess
# targets (`python -m tpulab ...`) share the cache too.
_cache_dir = _pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(_cache_dir))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
# The AOT loader logs a full machine-feature dump at E level for every
# cache hit (XLA records pseudo-features like +prefer-no-scatter that
# host detection never reports — same machine, cosmetic mismatch);
# silence the C++ log stream or cached runs drown the pytest output.
# Level 3 is the MINIMUM that works: the spam is emitted at ERROR level
# (cpu_aot_loader.cc:210, two ~2KB lines per loaded executable —
# verified 2026-07-30: TF_CPP_MIN_LOG_LEVEL=2 still prints it), and no
# env knob filters a single C++ module's ERROR stream.  Cost: genuine
# XLA ERROR logs are also hidden — FATALs still abort loudly, and
# Python-side exceptions are unaffected.
#
# This must be a FORCED assignment: the axon sitecustomize pins
# TF_CPP_MIN_LOG_LEVEL=1 into os.environ at interpreter start, so a
# setdefault here silently loses (verified 2026-07-30 — the "silenced"
# spam was in fact flowing the whole time, and once the AOT cache grew
# past ~32 loaded executables per daemon it deadlocked the module-
# scoped daemon fixture by filling its undrained 64 KB stdout pipe).
# Debug escape hatch: TPULAB_TEST_TF_LOG=0 pytest ... restores the full
# C++ stream (parent AND `python -m tpulab` subprocess targets).
os.environ["TF_CPP_MIN_LOG_LEVEL"] = os.environ.get("TPULAB_TEST_TF_LOG", "3")

# The container's sitecustomize registers the axon PJRT plugin at
# interpreter startup and calls jax.config.update("jax_platforms",
# "axon,cpu"), which takes precedence over the env var — override the
# config itself before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")
# sitecustomize imported jax BEFORE this conftest set the cache env
# vars, so the in-process config never saw them — set it explicitly,
# from the POST-setdefault env values so a caller's own
# JAX_COMPILATION_CACHE_DIR keeps parent and subprocess targets on one
# cache (children get the env vars at startup, before their jax import)
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs",
                  float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                  int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]))

import pathlib

import numpy as np
import pytest

REFERENCE_ROOT = pathlib.Path("/root/reference")


@pytest.fixture(scope="session")
def reference_root():
    if not REFERENCE_ROOT.exists():
        pytest.skip("reference snapshot not mounted")
    return REFERENCE_ROOT


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def trained_small_cfg():
    from tpulab.models.labformer import LabformerConfig

    return LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                           max_seq=128)


@pytest.fixture(scope="session")
def trained_small(trained_small_cfg):
    """ONE sharp-logit small labformer shared by the serving-tier
    suites (beam/paged/speculative/distill): untrained argmax ties flip
    under benign numeric reorderings, so cross-implementation token
    equality needs real margins — and training the same model four
    times per run is pure waste.  Config must match each module's CFG:
    d32 / h4 / L2 / ff64 / max_seq 128 (consumers assert equality via
    trained_small_cfg so drift fails loudly)."""
    from tpulab.models.labformer import init_train_state

    params, opt, step = init_train_state(trained_small_cfg, None, seed=0)
    tok = np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))
    for _ in range(80):
        params, opt, _ = step(params, opt, tok)
    return jax.device_get(params)
