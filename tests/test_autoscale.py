"""Elastic fleet: the round-17 autoscaling / brownout / preemption suite.

The daemon's fleet gains a telemetry-driven control loop
(``tpulab/autoscale.py`` policy, ``tpulab/daemon.py`` mechanics):

  * :class:`AutoscalePolicy` moves an integer replica target one step
    at a time inside ``[min, max]`` on consecutive-evidence streaks,
    with per-direction cooldowns and a scale-in hold after the last
    scale-out — certified here tick-by-tick with a caller-owned clock;
  * :class:`BrownoutLadder` engages its degradation rungs in order
    (hedging_off -> spec_off -> token_cap -> deadline_tight) under
    sustained pressure and releases them in REVERSE order as pressure
    decays, one rung per tick — so the fleet always unwinds through
    the exact states it climbed;
  * scale-in drains the chosen replica, migrates its in-flight
    requests over the round-13 path (greedy streams BIT-IDENTICAL),
    releases the engine, and refuses to drop below one serving
    replica; a scale-out revives the retired slot through the rebuild
    lifecycle, replaying anything a preemption parked there;
  * spot preemption is a first-class drill: a ``replica.preempt``
    fault rule is the cloud's preemption notice — the replica drains
    what its deadline allows, parks the stragglers, and releases with
    NO serving floor (the cloud does not ask);
  * observability: the elastic counters/gauges are registered AND
    documented, the ``fleet`` response carries target-vs-actual and
    ladder state, and the ops console renders both;
  * ``--autoscale-min``/``--autoscale-max`` bounds are validated at
    daemon startup with parseable errors.
"""

import json
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import tpulab.daemon as daemon_mod
from tpulab import autoscale, faults, obs
from tpulab.autoscale import LADDER, AutoscalePolicy, BrownoutLadder, Signals
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs import render

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent

HOT = Signals(active_replicas=1, load_per_replica=10.0)
COLD = Signals(active_replicas=1, load_per_replica=0.0)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _mk_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("n_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_seq", 64)
    return PagedEngine(params, CFG, **kw)


def _mk_fleet(params, n, **eng_kw):
    def builder():
        return _mk_engine(params, **eng_kw), None

    return daemon_mod._make_fleet(builder, n)


def _no_leaks(eng):
    cache_blocks = {b for blocks in eng.prefix_cache.values()
                    for b in blocks}
    assert len(eng.free) + len(cache_blocks) == eng.n_usable_blocks, (
        len(eng.free), sorted(cache_blocks), eng.n_usable_blocks)
    assert len(set(eng.free)) == len(eng.free), "double-freed block"
    assert all(eng.block_refs[b] == 0 for b in eng.free)


def _live_replicas(fleet):
    with fleet.cv:
        return [r for r in fleet.replicas if not r.retired]


def _wait_healthy(svc, replica, timeout=120):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        row = svc.replica_status(replica)
        if row["health"] == "healthy" and not row["retired"]:
            return row
        time.sleep(0.02)
    raise AssertionError(f"replica{replica.index} never came healthy")


# -------------------------------------------------------- policy units
def test_policy_validates_bounds():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(0, 3)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy(3, 2)
    with pytest.raises(ValueError, match="load_low"):
        AutoscalePolicy(1, 3, load_low=5.0, load_high=4.0)
    with pytest.raises(ValueError, match="out_after"):
        AutoscalePolicy(1, 3, out_after=0)


def test_policy_overload_underload_classification():
    pol = AutoscalePolicy(1, 3, load_high=4.0, load_low=1.0,
                          queue_wait_high_s=0.5)
    # any single overload signal trips the hot classification
    assert pol.overloaded(Signals(1, alerts_firing=1))
    assert pol.overloaded(Signals(1, shed_rate=0.2))
    assert pol.overloaded(Signals(1, queue_wait_p99_s=0.5))
    assert pol.overloaded(Signals(1, load_per_replica=4.0))
    assert not pol.overloaded(Signals(1, load_per_replica=3.9))
    # underload requires EVERY signal calm...
    assert pol.underloaded(Signals(1, load_per_replica=1.0))
    assert pol.underloaded(Signals(1, load_per_replica=0.5,
                                   queue_wait_p99_s=0.1))
    # ...and a firing alert / sheds / warm queue-wait all veto it
    assert not pol.underloaded(Signals(1, alerts_firing=1))
    assert not pol.underloaded(Signals(1, shed_rate=0.1))
    assert not pol.underloaded(Signals(1, load_per_replica=0.0,
                                       queue_wait_p99_s=0.25))
    assert not pol.underloaded(Signals(1, load_per_replica=1.1))


def test_policy_scale_out_streak_bounds_cooldown():
    pol = AutoscalePolicy(1, 3, out_after=2, out_cooldown_s=10.0)
    assert pol.observe(0.0, HOT) == 1      # streak 1: no move yet
    assert pol.observe(1.0, HOT) == 2      # streak 2: raise
    assert pol.raises == 1
    # streak restarts after a move; cooldown then blocks the next one
    assert pol.observe(2.0, HOT) == 2
    assert pol.observe(3.0, HOT) == 2      # streak 2 again, but <10s
    assert pol.observe(11.0, HOT) == 3     # cooldown expired
    # bounded: the ceiling holds no matter how hot it stays
    for t in (30.0, 40.0, 50.0):
        assert pol.observe(t, HOT) == 3
    assert pol.snapshot()["target"] == 3


def test_policy_scale_in_floor_and_hold_after_out():
    pol = AutoscalePolicy(1, 3, out_after=1, in_after=2,
                          out_cooldown_s=0.0, in_cooldown_s=5.0)
    assert pol.observe(0.0, HOT) == 2
    # capacity the burst just demanded is not returned on the first
    # quiet ticks: scale-in held within in_cooldown_s of the scale-out
    assert pol.observe(1.0, COLD) == 2
    assert pol.observe(2.0, COLD) == 2     # streak satisfied, held
    assert pol.observe(6.0, COLD) == 1     # hold expired: lower
    assert pol.lowers == 1
    # floor: never below min_replicas
    for t in (20.0, 30.0, 40.0):
        assert pol.observe(t, COLD) == 1


def test_policy_ambiguous_tick_resets_both_streaks():
    pol = AutoscalePolicy(1, 3, out_after=2, out_cooldown_s=0.0)
    mid = Signals(1, load_per_replica=2.0)  # between low and high
    assert pol.observe(0.0, HOT) == 1
    assert pol.observe(1.0, mid) == 1       # resets the hot streak
    assert pol.observe(2.0, HOT) == 1       # back to streak 1
    assert pol.observe(3.0, HOT) == 2       # clean streak completes
    assert pol.snapshot()["hot_streak"] == 0


# -------------------------------------------------------- ladder units
def test_ladder_validates_params():
    with pytest.raises(ValueError, match="engage_after"):
        BrownoutLadder(engage_after=0)
    with pytest.raises(ValueError, match="token_cap"):
        BrownoutLadder(token_cap=0)
    with pytest.raises(ValueError, match="deadline_slack"):
        BrownoutLadder(deadline_slack=1.5)


def test_ladder_engages_in_order_releases_in_reverse():
    lad = BrownoutLadder(engage_after=1, release_after=1,
                         step_cooldown_s=0.0)
    t = iter(range(100))
    engaged = [lad.observe(float(next(t)), True) for _ in range(5)]
    assert engaged == [f"engage:{r}" for r in LADDER] + [None]
    assert lad.level == len(LADDER)
    released = [lad.observe(float(next(t)), False) for _ in range(5)]
    assert released == [f"release:{r}" for r in reversed(LADDER)] + [None]
    assert lad.level == 0
    assert lad.engages == lad.releases == len(LADDER)


def test_ladder_hysteresis_and_flap_guard():
    lad = BrownoutLadder(engage_after=2, release_after=2,
                         step_cooldown_s=5.0)
    assert lad.observe(0.0, True) is None
    assert lad.observe(1.0, True) == "engage:hedging_off"
    # a one-tick pressure gap must not flap the rung back off: the
    # calm streak is reset by the next hot tick...
    assert lad.observe(2.0, False) is None
    assert lad.observe(3.0, True) is None
    # ...and even a full calm streak is held inside step_cooldown_s of
    # the engage
    assert lad.observe(4.0, False) is None
    assert lad.observe(5.0, False) is None
    assert lad.level == 1
    assert lad.observe(7.0, False) == "release:hedging_off"
    assert lad.level == 0


def test_ladder_rung_effects_per_level():
    lad = BrownoutLadder(engage_after=1, release_after=1,
                         step_cooldown_s=0.0, token_cap=16)
    assert not lad.hedging_disabled and not lad.spec_disabled
    assert lad.cap_steps(100) == 100
    assert lad.tighten_deadline_ms(1000.0) == 1000.0
    lad.observe(0.0, True)                   # level 1: hedging_off
    assert lad.hedging_disabled and not lad.spec_disabled
    lad.observe(1.0, True)                   # level 2: spec_off
    assert lad.spec_disabled
    assert lad.cap_steps(100) == 100         # rung 3 not engaged yet
    lad.observe(2.0, True)                   # level 3: token_cap
    assert lad.cap_steps(100) == 16
    assert lad.cap_steps(8) == 8             # never raises a request
    assert lad.tighten_deadline_ms(1000.0) == 1000.0
    lad.observe(3.0, True)                   # level 4: deadline_tight
    assert lad.tighten_deadline_ms(1000.0) == 500.0
    # deadline-free requests opted out of shedding; brownout must not
    # opt them in
    assert lad.tighten_deadline_ms(None) is None
    assert lad.snapshot()["rungs"] == list(LADDER)


# ------------------------------------------------------- fleet elastic
def test_scale_in_under_load_migrates_bit_identical(trained):
    """The tentpole's scale-in half: retiring a LOADED replica drains
    it through the migration path — the in-flight greedy stream lands
    on the peer bit-identical to an undisturbed run, the engine is
    released, blocks balance on the survivor — and a scale-out later
    revives the slot through the rebuild lifecycle."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    s0 = daemon_mod._C_SCALE_INS.value
    o0 = daemon_mod._C_SCALE_OUTS.value
    hold = {}
    t = threading.Thread(target=lambda: hold.setdefault(
        "out", svc.generate(fleet, _cycle_prompt(4), 24)))
    t.start()
    victim = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and victim is None:
        for r in fleet.replicas:
            with r.cond:
                if r.engine is not None and any(
                        a is not None for a in r.engine.active):
                    victim = r.index
                    break
        time.sleep(0.005)
    assert victim is not None, "request never became active"
    assert fleet.retire_replica(index=victim) == victim
    assert daemon_mod._C_SCALE_INS.value == s0 + 1
    t.join(timeout=60)
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=24,
                    temperature=0.0)[0]
    assert np.array_equal(hold["out"], want)
    st = svc.fleet_status(fleet)
    assert st["active"] == 1
    assert st["replica"][victim]["retired"]
    assert st["replica"][victim]["health"] == "retired"
    assert st["replica"][victim]["parked"] == 0
    survivor = fleet.replicas[1 - victim]
    with survivor.cond:
        _no_leaks(survivor.engine)
    # scale-out revives the retired slot (generation advances)
    assert fleet.add_replica() == victim
    assert daemon_mod._C_SCALE_OUTS.value == o0 + 1
    row = _wait_healthy(svc, fleet.replicas[victim])
    assert row["generation"] >= 1
    assert svc.fleet_status(fleet)["active"] == 2
    out = svc.generate(fleet, _cycle_prompt(4), 4)
    assert len(out) == 4


def test_scale_in_refuses_last_serving_replica(trained):
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 1)
    assert fleet.retire_replica() is None
    assert fleet.retire_replica(index=0) is None
    out = svc.generate(fleet, _cycle_prompt(4), 4)  # still serving
    assert len(out) == 4


def test_scale_in_picks_least_loaded_highest_index(trained):
    """An idle 2-replica fleet scales in replica 1, not replica 0 —
    ties go to the HIGHEST index so replica 0 stays the fleet's
    stable anchor."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    svc.generate(fleet, _cycle_prompt(4), 2)
    assert fleet.retire_replica() == 1
    assert [r.index for r in _live_replicas(fleet)] == [0]


def test_preempt_drill_migrates_and_scale_out_revives(trained):
    """The spot-preemption drill: a deterministic ``replica.preempt``
    rule delivers the notice mid-generation; the replica drains into
    its peer inside the deadline (stream bit-identical), releases its
    engine with the preemption counted, and the next scale-out
    revives the slot."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    p0 = daemon_mod._C_SPOT_PREEMPTIONS.value
    with faults.active([{"site": "replica.preempt@replica0",
                         "kind": "preempt", "at": 4, "arg": 5000.0}]):
        out = svc.generate(fleet, _cycle_prompt(4), 16)
        assert faults.INJECTOR.fired() == {"replica.preempt@replica0": 1}
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=16,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    assert daemon_mod._C_SPOT_PREEMPTIONS.value == p0 + 1
    st = svc.fleet_status(fleet)
    assert st["active"] == 1 and st["replica"][0]["retired"]
    with fleet.replicas[1].cond:
        _no_leaks(fleet.replicas[1].engine)
    assert fleet.add_replica() == 0
    _wait_healthy(svc, fleet.replicas[0])


def test_preempt_no_peer_parks_then_revival_replays(trained):
    """A preempted SOLO replica has nowhere to migrate: unlike
    scale-in there is no serving floor (the cloud does not ask), so
    the in-flight request PARKS on the slot and the scale-out
    revival replays it — the waiter's stream completes bit-identical
    across the preemption."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 1)
    hold = {}
    with faults.active([{"site": "replica.preempt@replica0",
                         "kind": "preempt", "at": 4, "arg": 500.0}]):
        t = threading.Thread(target=lambda: hold.setdefault(
            "out", svc.generate(fleet, _cycle_prompt(4), 12)))
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with fleet.cv:
                if fleet.replicas[0].retired:
                    parked = len(fleet.replicas[0].parked)
                    break
            time.sleep(0.01)
        else:
            raise AssertionError("preemption never retired the replica")
    assert parked == 1, "straggler did not park on the retired slot"
    assert fleet.add_replica() == 0          # revival replays the park
    t.join(timeout=120)
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                    temperature=0.0)[0]
    assert np.array_equal(hold["out"], want)
    _wait_healthy(svc, fleet.replicas[0])
    with fleet.replicas[0].cond:
        _no_leaks(fleet.replicas[0].engine)


def test_fleet_status_elastic_shape(trained):
    """An ARMED fleet's status carries target-vs-actual and ladder
    state; a disarmed fleet (the default) carries neither — the
    pre-elastic response shape is unchanged."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    st = svc.fleet_status(fleet)
    assert "autoscale" not in st and "brownout" not in st
    fleet.autoscaler = AutoscalePolicy(1, 3)
    fleet.brownout = BrownoutLadder()
    st = svc.fleet_status(fleet)
    assert st["active"] == 2
    assert st["autoscale"]["target"] == 1
    assert st["autoscale"]["min"] == 1 and st["autoscale"]["max"] == 3
    assert st["brownout"]["level"] == 0 and st["brownout"]["rungs"] == []
    for row in st["replica"]:
        assert row["retired"] is False


def test_brownout_token_cap_bounds_admission(trained):
    """Rung 3 end-to-end through the daemon's admission path: with
    ``token_cap`` engaged a generate request's output is capped; after
    the ladder fully releases, the same request runs full-length."""
    fleet = _mk_fleet(trained, 1)
    fleet.brownout = BrownoutLadder(engage_after=1, release_after=1,
                                    step_cooldown_s=0.0, token_cap=6)
    key = (None, "gather", "native", 1, 0, "")
    daemon_mod._FLEETS[key] = (None, fleet)
    try:
        for i in range(3):                   # climb to token_cap
            fleet.brownout.observe(float(i), True)
        out = daemon_mod._handle_generate(
            {"config": {"steps": 20, "prefill_chunk": 0}}, b"hi")
        assert len(out) == 6
        for i in range(3, 6):                # fully release
            fleet.brownout.observe(float(i), False)
        assert fleet.brownout.level == 0
        out = daemon_mod._handle_generate(
            {"config": {"steps": 20, "prefill_chunk": 0}}, b"hi")
        assert len(out) == 20
    finally:
        daemon_mod._FLEETS.pop(key, None)


# --------------------------------------------------------- observability
def test_elastic_counters_registered_and_documented():
    """The round-13 lint, elastic surface: every scaling counter and
    gauge is a registered metric AND has a docs entry."""
    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("daemon_scale_outs", "daemon_scale_ins",
                 "daemon_spot_preemptions", "daemon_brownout_steps",
                 "daemon_brownout_reversals", "fleet_target_replicas",
                 "daemon_brownout_level"):
        assert obs.REGISTRY.get(name) is not None, name
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"
    # the drill surface and the ladder are documented too
    for needle in ("replica.preempt", "hedging_off", "deadline_tight"):
        assert needle in docs, needle


def test_render_fleet_elastic_surface():
    fleet = {
        "replicas": 3, "active": 2,
        "autoscale": {"target": 2, "min": 1, "max": 3,
                      "raises": 4, "lowers": 3},
        "brownout": {"level": 2, "rungs": ["hedging_off", "spec_off"],
                     "engages": 5, "releases": 3},
        "replica": [
            {"replica": 0, "health": "healthy", "pending": 0,
             "active": 1, "requests_done": 7},
            {"replica": 1, "health": "healthy", "pending": 2,
             "active": 1, "requests_done": 3},
            {"replica": 2, "health": "retired", "retired": True,
             "dead": True},
        ],
    }
    text = render.format_fleet(fleet)
    assert "2/3 serving, target 2 [1..3]" in text
    assert "scale-outs=4 scale-ins=3" in text
    assert "brownout: level 2 [hedging_off > spec_off]" in text
    assert "engages=5 releases=3" in text
    # a retired replica renders "retired" (not "dead") in its flags
    line2 = [ln for ln in text.splitlines() if "replica2" in ln][0]
    assert "retired" in line2 and "dead" not in line2


# ------------------------------------------------------ startup bounds
def test_daemon_validates_autoscale_bounds(tmp_path):
    """Bad ``--replicas``/autoscale bounds die at STARTUP with a
    parseable error naming the offending values — not after an hour of
    traffic."""
    cases = [
        (["--autoscale-max", "-1"], "--autoscale-max"),
        (["--autoscale-min", "0", "--autoscale-max", "2"],
         "--autoscale-min"),
        (["--autoscale-min", "3", "--autoscale-max", "2"],
         "--autoscale-min"),
        (["--replicas", "5", "--autoscale-max", "3"], "--replicas"),
        (["--autoscale-max", "2", "--metrics-interval", "0"],
         "sampler"),
    ]
    for extra, needle in cases:
        proc = subprocess.run(
            [sys.executable, "-m", "tpulab.daemon",
             "--socket", str(tmp_path / "x.sock")] + extra,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 2, (extra, proc.stderr)
        assert needle in proc.stderr, (extra, proc.stderr)
