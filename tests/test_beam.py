"""Beam search (tpulab.models.beam).

Pinned: beams=1 == greedy, wider beams never score worse than greedy
(the property beam search exists for), backtracking self-consistency
(the returned sequence's log-prob under the model equals the reported
score), and input validation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.beam import beam_search
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig, forward

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _seq_logprob(params, prompt, cont):
    """Total log P(cont | prompt) under the model, f32."""
    full = np.concatenate([prompt, cont])[None, :]
    logits = np.asarray(
        forward(params, jnp.asarray(full, jnp.int32), CFG)
    ).astype(np.float64)
    lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    lp = np.asarray(lp)[0]
    p = len(prompt)
    # token at absolute position p+i is predicted by logits at p+i-1
    return float(sum(lp[p - 1 + i, cont[i]] for i in range(len(cont))))


def test_beam1_equals_greedy(trained):
    prompt = (np.arange(5) % 7).astype(np.int32)
    seq, score = beam_search(trained, prompt, CFG, steps=8, beams=1)
    want = generate(trained, prompt[None, :], CFG, steps=8, temperature=0.0)[0]
    assert np.array_equal(seq, want)
    assert np.isfinite(score)


def test_wider_beam_never_scores_worse(trained):
    # an adversarial-ish prompt off the trained cycle makes greedy
    # suboptimal more often; regardless, beam-k >= greedy must hold
    for prompt in [(np.arange(5) % 7), np.array([6, 2, 5, 1])]:
        prompt = prompt.astype(np.int32)
        greedy = generate(trained, prompt[None, :], CFG, steps=10,
                          temperature=0.0)[0]
        g_lp = _seq_logprob(trained, prompt, greedy)
        seq, score = beam_search(trained, prompt, CFG, steps=10, beams=4)
        assert score >= g_lp - 1e-4, (score, g_lp)


def test_score_matches_model_logprob(trained):
    prompt = (np.arange(6) % 7).astype(np.int32)
    seq, score = beam_search(trained, prompt, CFG, steps=7, beams=3)
    # the reported score must equal the model's own log-prob of the
    # returned sequence (backtracking reconstructed the right lineage)
    assert abs(score - _seq_logprob(trained, prompt, seq)) < 1e-3


def test_validation():
    from tpulab.models.labformer import init_params

    params = init_params(CFG, seed=0)
    with pytest.raises(ValueError, match="steps"):
        beam_search(params, np.zeros(3, np.int32), CFG, steps=0)
    with pytest.raises(ValueError, match="beams"):
        beam_search(params, np.zeros(3, np.int32), CFG, steps=4, beams=0)


def test_single_step(trained):
    prompt = (np.arange(4) % 7).astype(np.int32)
    seq, score = beam_search(trained, prompt, CFG, steps=1, beams=3)
    want = generate(trained, prompt[None, :], CFG, steps=1, temperature=0.0)[0]
    assert np.array_equal(seq, want)  # one step: beam == greedy argmax
