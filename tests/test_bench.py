"""Bench accounting units: analytic labformer FLOPs + MFU fields.

Timing benchmarks themselves are hardware-bound (see RESULTS.md /
BENCH_r*.json); what is testable hermetically is the accounting — the
analytic FLOPs formula (used because XLA's cost model counts a
``lax.scan`` body once regardless of trip count) and the MFU math.
"""

import numpy as np

from tpulab.bench import _mfu_fields, labformer_fwd_flops


class _Cfg:
    d_model = 4
    d_ff = 8
    n_layers = 2
    vocab = 16


def test_labformer_fwd_flops_hand_computed():
    # per token: 2 * 2 layers * (4*4*4 + 2*4*8) + 2*4*16 = 4*(64+64)+128 = 640
    # attention: 2 layers * 4*s*s*d / 2 (causal) with s=3, d=4 = 2*4*9*4/2 = 144
    # batch 5: 5 * (3*640 + 144) = 5 * 2064 = 10320
    assert labformer_fwd_flops(_Cfg, b=5, s=3) == 10320
    # non-causal doubles only the attention term
    assert labformer_fwd_flops(_Cfg, b=5, s=3, causal=False) == 5 * (3 * 640 + 288)


def test_labformer_fwd_flops_matches_real_config_scale():
    from tpulab.models.labformer import LabformerConfig

    cfg = LabformerConfig(d_model=512, n_heads=8, n_layers=8, d_ff=2048, max_seq=512)
    got = labformer_fwd_flops(cfg, b=8, s=512)
    # 2*params*tokens dominates: params ~ 8*(4*512^2 + 2*512*2048) = 25.2M
    approx = 2 * 25_165_824 * 8 * 512
    assert 1.0 < got / approx < 1.15  # logits + causal attention on top


class _Dev:
    device_kind = "TPU v5 lite"


def test_mfu_fields_math():
    # 197 TFLOP/s peak (v5 lite table): 98.5 TFLOP/s achieved = 50%
    f = _mfu_fields(98.5e9, 1.0, _Dev())  # 98.5 GFLOP in 1 ms
    assert f["achieved_tflops"] == 98.5
    assert f["mfu_pct_of_bf16_peak"] == 50.0
    assert f["peak_tflops"] == 197


def test_mfu_fields_empty_without_peak_or_flops():
    class Unknown:
        device_kind = "host"

    assert _mfu_fields(1e9, 1.0, Unknown()) == {}
    assert _mfu_fields(0, 1.0, _Dev()) == {}


def test_variance_fields_summary():
    from tpulab.bench import variance_fields

    f = variance_fields([3.0, 1.0, 2.0, 4.0, 5.0])
    assert f["median_ms"] == 3.0
    assert f["min_ms"] == 1.0
    assert f["p25_ms"] == 2.0 and f["p75_ms"] == 4.0
    assert f["iqr_ms"] == 2.0
    assert f["n_trials"] == 5
    assert variance_fields([]) == {}


def test_measure_collects_samples():
    """The collect hook feeds variance_fields: samples arrive in ms and
    match the reported outer-trial count."""
    import jax.numpy as jnp

    from tpulab.runtime.timing import measure_ms

    samples = []
    ms, _ = measure_ms(lambda x: x + 1, (jnp.float32(1.0),), warmup=1,
                       reps=2, outer=4, collect=samples)
    import statistics

    assert len(samples) == 4
    assert min(samples) > 0
    assert ms == statistics.median(samples)


def test_run_benchmarks_isolates_failures(monkeypatch):
    """One broken bench becomes an error row; the rest still run."""
    import tpulab.bench as tb

    def boom(**kw):
        raise RuntimeError("synthetic failure")

    monkeypatch.setattr(tb, "bench_sort", boom)
    rows = list(tb.run_benchmarks(only="hw2_sort"))
    assert rows == [{"metric": "hw2_sort", "error": "RuntimeError: synthetic failure"}]
