"""Bench accounting units: analytic labformer FLOPs + MFU fields.

Timing benchmarks themselves are hardware-bound (see RESULTS.md /
BENCH_r*.json); what is testable hermetically is the accounting — the
analytic FLOPs formula (used because XLA's cost model counts a
``lax.scan`` body once regardless of trip count) and the MFU math.
"""

import numpy as np

from tpulab.bench import _mfu_fields, labformer_fwd_flops


class _Cfg:
    d_model = 4
    d_ff = 8
    n_layers = 2
    vocab = 16


def test_labformer_fwd_flops_hand_computed():
    # per token: 2 * 2 layers * (4*4*4 + 2*4*8) + 2*4*16 = 4*(64+64)+128 = 640
    # attention: 2 layers * 4*s*s*d / 2 (causal) with s=3, d=4 = 2*4*9*4/2 = 144
    # batch 5: 5 * (3*640 + 144) = 5 * 2064 = 10320
    assert labformer_fwd_flops(_Cfg, b=5, s=3) == 10320
    # non-causal doubles only the attention term
    assert labformer_fwd_flops(_Cfg, b=5, s=3, causal=False) == 5 * (3 * 640 + 288)


def test_labformer_fwd_flops_matches_real_config_scale():
    from tpulab.models.labformer import LabformerConfig

    cfg = LabformerConfig(d_model=512, n_heads=8, n_layers=8, d_ff=2048, max_seq=512)
    got = labformer_fwd_flops(cfg, b=8, s=512)
    # 2*params*tokens dominates: params ~ 8*(4*512^2 + 2*512*2048) = 25.2M
    approx = 2 * 25_165_824 * 8 * 512
    assert 1.0 < got / approx < 1.15  # logits + causal attention on top


class _Dev:
    device_kind = "TPU v5 lite"


def test_mfu_fields_math():
    # 197 TFLOP/s peak (v5 lite table): 98.5 TFLOP/s achieved = 50%
    f = _mfu_fields(98.5e9, 1.0, _Dev())  # 98.5 GFLOP in 1 ms
    assert f["achieved_tflops"] == 98.5
    assert f["mfu_pct_of_bf16_peak"] == 50.0
    assert f["peak_tflops"] == 197


def test_mfu_fields_empty_without_peak_or_flops():
    class Unknown:
        device_kind = "host"

    assert _mfu_fields(1e9, 1.0, Unknown()) == {}
    assert _mfu_fields(0, 1.0, _Dev()) == {}


def test_variance_fields_summary():
    from tpulab.bench import variance_fields

    f = variance_fields([3.0, 1.0, 2.0, 4.0, 5.0])
    assert f["median_ms"] == 3.0
    assert f["min_ms"] == 1.0
    assert f["p25_ms"] == 2.0 and f["p75_ms"] == 4.0
    assert f["iqr_ms"] == 2.0
    assert f["n_trials"] == 5
    assert variance_fields([]) == {}


def test_variance_fields_never_prints_zero_min():
    """Round-4 verdict weak #4: BENCH_r04's lab1-f32 row printed
    ``min_ms: 0.0`` — sub-resolution samples must clamp to the method's
    resolution bound and carry it, and significant-digit rounding must
    never flatten a real nonzero floor to 0.0."""
    from tpulab.bench import variance_fields

    # (a) resolution clamp: samples below the floor report the floor
    f = variance_fields([2e-7, 3e-7, 1e-2], meta={"resolution_ms": 5e-4})
    assert f["min_ms"] == 5e-4
    assert f["resolution_ms"] == 5e-4
    assert f["p25_ms"] >= 5e-4
    # (b) rounding: a real 2e-7 floor survives 6-SIGNIFICANT-digit
    # rounding (the old round(v, 6) printed it as 0.0)
    g = variance_fields([2e-7, 3e-7, 4e-7])
    assert g["min_ms"] > 0
    assert all(v > 0 for k, v in g.items()
               if k.endswith("_ms") and isinstance(v, float))


def test_measure_reports_resolution_and_clamps(monkeypatch):
    """measure_* write resolution_ms into meta and no collected sample
    sits below it — the no-0.0-minima contract at the source."""
    import jax.numpy as jnp

    from tpulab.runtime.timing import (measure_kernel_ms, measure_ms,
                                       measurement_resolution_ms)

    samples: list = []
    meta: dict = {}
    measure_ms(lambda x: x + 1, (jnp.float32(1.0),), warmup=1, reps=4,
               outer=3, collect=samples, meta=meta)
    res = meta["resolution_ms"]
    assert res > 0 and res == measurement_resolution_ms("cpu", 4)
    assert all(s >= res for s in samples)

    samples2: list = []
    meta2: dict = {}
    measure_kernel_ms(lambda x: x + 1, (jnp.ones((8,), jnp.float32),),
                      iters=1000, outer=2, collect=samples2, meta=meta2)
    assert meta2["resolution_ms"] > 0
    assert min(samples2) >= meta2["resolution_ms"]


def test_measure_collects_samples():
    """The collect hook feeds variance_fields: samples arrive in ms and
    match the reported outer-trial count."""
    import jax.numpy as jnp

    from tpulab.runtime.timing import measure_ms

    samples = []
    ms, _ = measure_ms(lambda x: x + 1, (jnp.float32(1.0),), warmup=1,
                       reps=2, outer=4, collect=samples)
    import statistics

    assert len(samples) == 4
    assert min(samples) > 0
    assert ms == statistics.median(samples)


def test_run_benchmarks_isolates_failures(monkeypatch):
    """One broken bench becomes an error row; the rest still run."""
    import tpulab.bench as tb

    def boom(**kw):
        raise RuntimeError("synthetic failure")

    monkeypatch.setattr(tb, "bench_sort", boom)
    rows = list(tb.run_benchmarks(only="hw2_sort"))
    assert rows == [{"metric": "hw2_sort", "error": "RuntimeError: synthetic failure"}]


def test_run_benchmarks_markers(monkeypatch):
    """yield_markers announces each entry before it runs, so the stall
    watchdog can name the entry a wedge swallowed."""
    import tpulab.bench as tb

    monkeypatch.setattr(tb, "bench_sort", lambda **kw: {"metric": "s", "value": 1})
    rows = list(tb.run_benchmarks(only="hw2_sort", yield_markers=True))
    assert rows == [{"__bench_starting__": "hw2_sort"},
                    {"metric": "s", "value": 1}]


# --- wedge-proof parent logic (bench.py at repo root) ---------------------

def _load_root_bench():
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("root_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_measure_headline_returns_row():
    import sys

    bench = _load_root_bench()
    code = ("import json; print(json.dumps({'metric':"
            " 'lab2_roberts_1024x1024_median_ms', 'value': 0.03,"
            " 'unit': 'ms', 'vs_baseline': 5.9}))")
    got = bench._measure_headline(1, budget_s=30,
                                  child_argv=[sys.executable, "-c", code])
    assert got is not None and got["value"] == 0.03


def test_measure_headline_stall_abandons_unkilled():
    import sys
    import time

    bench = _load_root_bench()
    t0 = time.monotonic()
    got = bench._measure_headline(
        1, budget_s=2,
        child_argv=[sys.executable, "-c", "import time; time.sleep(6)"])
    assert got is None
    assert time.monotonic() - t0 < 5  # gave up at the budget, didn't wait out


def test_stream_registry_relays_and_reports_stall(capsys):
    import json as _json
    import sys

    bench = _load_root_bench()
    # marker -> good row -> marker -> sleep past budget: the error row
    # must name the SECOND entry and the good row must have been relayed
    code = (
        "import json, time, sys\n"
        "print(json.dumps({'__bench_starting__': 'fast_one'}), flush=True)\n"
        "print(json.dumps({'metric': 'fast_one', 'value': 1}), flush=True)\n"
        "print(json.dumps({'__bench_starting__': 'wedged_one'}), flush=True)\n"
        "time.sleep(8)\n"
    )
    bench._stream_registry(None, 1, budget_s=2,
                           child_argv=[sys.executable, "-c", code])
    out = [_json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert {"metric": "fast_one", "value": 1} in out
    assert any(r.get("metric") == "wedged_one" and "relay stall" in r.get("error", "")
               for r in out)


def test_stream_registry_suppresses_headline_row(capsys):
    import json as _json
    import sys

    bench = _load_root_bench()
    code = (
        "import json\n"
        "print(json.dumps({'metric': 'lab2_roberts_1024x1024_median_ms',"
        " 'value': 9}), flush=True)\n"
        "print(json.dumps({'metric': 'other', 'value': 2}), flush=True)\n"
    )
    bench._stream_registry(None, 1, budget_s=30,
                           child_argv=[sys.executable, "-c", code])
    out = [_json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert {"metric": "other", "value": 2} in out
    assert not any(r.get("metric", "").startswith("lab2_roberts") for r in out)


def test_bench_cli_streams_rows(monkeypatch, capsys):
    """`tpulab bench --only X` coerces kwargs and streams JSON rows."""
    import tpulab.bench as tb
    from tpulab.cli.bench import run_bench_cli

    monkeypatch.setattr(tb, "bench_sort",
                        lambda reps=0, **kw: {"metric": "s", "value": reps})
    rc = run_bench_cli(["--only", "hw2_sort", "--reps", "3"])
    import json as _json

    rows = [_json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert rc == 0 and rows == [{"metric": "s", "value": 3}]
