"""BPE tokenizer: training, coding, persistence, and the train/generate
integration (``--tokenizer``).

Claims under test:
  * lossless round-trips for ANY bytes (trained-on or not) — ids
    0..255 are the raw bytes, so coverage is total;
  * training is deterministic and actually compresses repetitive text;
  * encode applies merges in learned priority order (GPT-2 scheme);
  * save/load round-trips and foreign files are refused loudly;
  * tpulab train --tokenizer sets the model vocab from the merge table
    and learns from the encoded corpus; generate --tokenizer decodes.
"""

import json

import numpy as np
import pytest

from tpulab.io.bpe import BPETokenizer, corpus_from_dir, train_bpe


def test_roundtrip_lossless_any_bytes():
    tok = train_bpe(b"abcabcabc" * 50, vocab=300)
    for data in (b"abcabc", b"zzz \x00\xff binary \x80", bytes(range(256))):
        assert tok.decode(tok.encode(data)) == data


def test_training_deterministic_and_compresses():
    corpus = (b"the quick brown fox jumps over the lazy dog. " * 200)
    t1 = train_bpe(corpus, vocab=400)
    t2 = train_bpe(corpus, vocab=400)
    assert t1.merges == t2.merges
    n = len(t1.encode(corpus))
    assert n < len(corpus) / 2, (n, len(corpus))


def test_encode_prefilter_matches_naive_pass_per_merge():
    """The membership pre-filter (skip merges whose ids are absent) must
    be a pure optimization: output identical to one _apply_merge pass
    per learned merge in rank order, on bytes the tokenizer never saw."""
    from tpulab.io.bpe import _apply_merge

    tok = train_bpe(b"the quick brown fox. " * 300 + b"abcabc" * 100,
                    vocab=360)
    rng = np.random.default_rng(3)
    for data in (b"the fox abc", rng.integers(0, 256, 500,
                                              dtype=np.uint8).tobytes(),
                 b"", b"q", b"the quick brown fox. " * 7):
        naive = np.frombuffer(data, np.uint8).astype(np.int32)
        for rank, (a, b) in enumerate(tok.merges):
            if len(naive) < 2:
                break
            naive = _apply_merge(naive, a, b, 256 + rank)
        np.testing.assert_array_equal(tok.encode(data), naive)


def test_heap_encode_matches_pass_encode(monkeypatch):
    """The rank-priority-queue encode (large-vocab path) must produce
    the identical segmentation as the per-merge pass encode — including
    overlapping runs ('aaaa'), ties, and bytes never seen in training —
    and the threshold dispatch must route through it transparently."""
    rng = np.random.default_rng(7)
    tok = train_bpe(b"the quick brown fox. " * 300 + b"aaaa" * 100
                    + b"abcabc" * 100, vocab=380)
    cases = (b"", b"a", b"aaaaaaa", b"the fox aaaa abc",
             rng.integers(0, 256, 2000, dtype=np.uint8).tobytes(),
             b"the quick brown fox. " * 9)
    for data in cases:
        np.testing.assert_array_equal(tok._encode_heap(data),
                                      tok.encode(data))
    # threshold dispatch: force every vocab through the heap path and
    # confirm the public surface (encode -> decode roundtrip) holds
    monkeypatch.setattr(BPETokenizer, "_HEAP_ENCODE_FROM", 1)
    for data in cases:
        assert tok.decode(tok.encode(data)) == data


def test_merge_priority_order():
    # 'ab' dominates, then 'abab' (as merged-id pairs): encode must
    # apply the earlier merge everywhere before later ones
    tok = train_bpe(b"ab" * 100, vocab=280)
    assert tok.merges[0] == (ord("a"), ord("b"))
    ids = tok.encode(b"abab")
    # both 'ab' pairs merge to 256, then (256, 256) merges if learned
    assert 256 not in ids or len(ids) == 1 or all(i >= 256 for i in ids)
    assert tok.decode(ids) == b"abab"


def test_no_merges_below_frequency_two():
    tok = train_bpe(b"abcdefgh", vocab=1000)  # nothing repeats
    assert tok.merges == []
    assert tok.vocab == 256


def test_max_token_bytes_caps_memorization():
    """Long exact repeats must not collapse into corpus-scale tokens."""
    corpus = b"def roberts(img): return edges(img)\n" * 400
    tok = train_bpe(corpus, vocab=320)
    assert max(len(tok.decode([i])) for i in range(256, tok.vocab)) <= 32
    # the corpus still encodes to hundreds of word-scale tokens, not a
    # handful of memorized lines
    assert len(tok.encode(corpus)) >= len(corpus) / 32


def test_vocab_bounds():
    with pytest.raises(ValueError, match=">= 256"):
        train_bpe(b"xx", vocab=100)
    with pytest.raises(ValueError, match="65536"):
        train_bpe(b"xx", vocab=1 << 17)


def test_save_load_roundtrip(tmp_path):
    tok = train_bpe(b"hello world " * 100, vocab=300)
    p = str(tmp_path / "tok.json")
    tok.save(p)
    back = BPETokenizer.load(p)
    assert back.merges == tok.merges
    data = b"hello there"
    assert np.array_equal(back.encode(data), tok.encode(data))


def test_load_refuses_foreign_files(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"something": "else"}))
    with pytest.raises(ValueError, match="not a tpulab-bpe"):
        BPETokenizer.load(str(p))


def test_decode_rejects_out_of_vocab():
    tok = train_bpe(b"aa" * 10, vocab=257)
    with pytest.raises(ValueError, match="outside vocab"):
        tok.decode([tok.vocab])


def test_corpus_from_dir_ordered_and_limited(tmp_path):
    (tmp_path / "b.txt").write_bytes(b"BBBB")
    (tmp_path / "a.txt").write_bytes(b"AAAA")
    assert corpus_from_dir(str(tmp_path)) == b"AAAABBBB"
    assert corpus_from_dir(str(tmp_path), limit_bytes=6) == b"AAAABB"
    with pytest.raises(FileNotFoundError):
        corpus_from_dir(str(tmp_path / "missing"))


def test_tokenizer_cli_train_info(tmp_path, capsys):
    from tpulab.io.bpe import main as bpe_main

    (tmp_path / "c.txt").write_bytes(b"spam and eggs and spam " * 100)
    out = str(tmp_path / "tok.json")
    rc = bpe_main(["train", "--data-dir", str(tmp_path), "--vocab", "300",
                   "--out", out])
    assert rc == 0
    row = json.loads(capsys.readouterr().out)
    assert row["vocab"] <= 300 and row["merges"] == row["vocab"] - 256
    assert row["compression_sample_64k"] > 1.5
    rc = bpe_main(["info", out])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["vocab"] == row["vocab"]


def test_train_with_tokenizer_end_to_end(tmp_path):
    """tpulab train --tokenizer: vocab comes from the merge table, the
    loss is over encoded tokens, eval rides the held-out tail."""
    from tpulab.train import train

    data = tmp_path / "data"
    data.mkdir()
    (data / "c.txt").write_bytes(
        b"def roberts(img): return edges(img)\n" * 400)
    tok = train_bpe((data / "c.txt").read_bytes(), vocab=320)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)

    logs = []
    step, loss = train(steps=6, batch=2, seq=32, data_dir=str(data),
                       tokenizer=tokp, eval_every=3,
                       log=lambda *a: logs.append(" ".join(map(str, a))))
    assert step == 6 and np.isfinite(loss)
    assert any("[eval]" in ln for ln in logs)
    # vocab sanity: losses are over a 320-token space, ln(320) ~ 5.77 --
    # a byte-space model would start near ln(256) ~ 5.55; just assert
    # the run didn't silently fall back to bytes via the cfg default
    with pytest.raises(ValueError, match="data-dir"):
        train(steps=1, tokenizer=tokp)


def test_cfg_vocab_mismatch_refused(tmp_path):
    from tpulab.models.labformer import LabformerConfig
    from tpulab.train import train

    data = tmp_path / "data"
    data.mkdir()
    (data / "c.txt").write_bytes(b"hello world " * 200)
    tok = train_bpe((data / "c.txt").read_bytes(), vocab=300)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)
    small = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                            max_seq=64, vocab=256)
    with pytest.raises(ValueError, match="silently clamp"):
        train(steps=1, cfg=small, tokenizer=tokp, data_dir=str(data))


def test_stop_byte_found_inside_merged_tokens(tmp_path, capsys, monkeypatch):
    """Under BPE the stop byte is detected in DECODED bytes: a newline
    merged inside a larger token still stops/trims the output."""
    import tpulab.models.generate as gen_cli

    corpus = b"abc\ndef\n" * 200
    tok = train_bpe(corpus, vocab=280)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)
    # at least one learned token must hide a newline mid-expansion for
    # this test to mean anything
    assert any(b"\n" in tok.decode([i]) and tok.decode([i]) != b"\n"
               for i in range(256, tok.vocab))

    # force the model to emit a token whose expansion contains '\n'
    nl_tok = next(i for i in range(256, tok.vocab)
                  if b"\n" in tok.decode([i]) and len(tok.decode([i])) > 1)

    def fake_generate(params, prompt, cfg, **kw):
        return np.asarray([[ord("x"), nl_tok, ord("y"), ord("z")]], np.int32)

    monkeypatch.setattr(gen_cli, "generate", fake_generate)
    rc = gen_cli.main(["--tokenizer", tokp, "--steps", "4",
                       "--temperature", "0", "--prompt", "Q",
                       "--stop-byte", "10"])
    # the stop byte is KEPT (engine contract: it is the final token), so
    # the output line ends exactly at the newline hidden inside nl_tok —
    # take the line that carries the prompt, not the empty tail line
    out = [l for l in capsys.readouterr().out.splitlines()
           if l.startswith("Q")][-1]
    assert rc in (0, None)
    # output = "Q" + "x" + (thru-newline part of nl_tok); 'y'/'z' trimmed
    assert out.startswith("Qx") and "y" not in out and "z" not in out


def test_generate_with_tokenizer(tmp_path, capsys):
    from tpulab.models import generate as gen_cli
    from tpulab.train import train

    data = tmp_path / "data"
    data.mkdir()
    # big enough that the encoded corpus covers train windows + the
    # held-out eval tail even at ~32-byte merged tokens
    (data / "c.txt").write_bytes(b"hello world " * 3000)
    tok = train_bpe((data / "c.txt").read_bytes(), vocab=280)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)

    ck = str(tmp_path / "ck")
    train(steps=4, batch=2, seq=32, data_dir=str(data), tokenizer=tokp,
          ckpt_dir=ck, save_every=2, log=lambda *a: None)
    rc = gen_cli.main(["--ckpt-dir", ck, "--tokenizer", tokp,
                       "--steps", "8", "--temperature", "0",
                       "--prompt", "hello"])
    out = capsys.readouterr().out
    assert rc in (0, None)
    assert "hello" in out
