"""Disaggregated prefill/decode serving: the round-20 suite.

The fleet layer now splits a serving fleet into POOLS by phase
(``--pool-spec prefill=1..2,decode=1``): placement is phase-aware
(a request enters through the prefill pool, decodes in the decode
pool), and at the PREFILLING→DECODING boundary the prefill engine
exports the request's KV blocks in the digest-keyed host-block format
(the PR-13 spill tier's wire format) for the decode engine to import —
admission's spill prefetch restores the prefix to HBM and recomputes
only the sub-block tail, so the handoff moves bytes, not compute.

Certified here:

  * ``_parse_pool_spec`` accepts fixed (``role=N``) and ranged
    (``role=MIN..MAX``) pools and rejects unknown/duplicate roles and
    inverted bounds;
  * ``choose_replica`` routes each phase to its pool and lets unified
    replicas serve anything;
  * a decode pool's AutoscalePolicy scales on ITL p99
    (``latency_high_s``) with the same half-mark hysteresis as
    queue-wait — the pools' burn signals are independent;
  * a pooled fleet serves greedy AND sampled streams BIT-IDENTICAL to
    unified serving, with the handoff counters advancing, the decode
    engine's admission prefetch actually consuming the imported
    blocks, and exact block accounting on both pools afterwards;
  * the pool-scoped park frame (``rebuilding pool=<role>
    retry_after_ms=N``) parses through ``loadgen.SHED_RE`` with the
    same group numbering as the whole-fleet frame;
  * pools scale INDEPENDENTLY through the round-17 reconcile
    machinery: a prefill reconcile adds/retires prefill replicas only,
    and ``scale_in`` refuses to dip a pool below its floor.
"""

import numpy as np
import pytest

import tpulab.daemon as daemon_mod
from tpulab import autoscale, faults, loadgen, router
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _mk_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("n_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_seq", 64)
    # the disaggregated serving arrangement: radix index + armed spill
    # tier on every replica (the handoff wire format IS the host tier)
    kw.setdefault("prefix_index", "radix")
    kw.setdefault("spill_blocks", 16)
    return PagedEngine(params, CFG, **kw)


def _mk_fleet(params, n, pools=None, **eng_kw):
    def builder():
        return _mk_engine(params, **eng_kw), None

    return daemon_mod._make_fleet(builder, n, pools=pools)


def _no_leaks(eng):
    """Radix-aware exact block accounting: every non-free block is
    held by the prefix cache (one ref per radix node)."""
    cached = set(eng._radix.blocks())
    assert len(eng.free) + len(cached) == eng.n_usable_blocks, (
        len(eng.free), sorted(cached), eng.n_usable_blocks)
    assert len(set(eng.free)) == len(eng.free), "double-freed block"
    assert all(eng.block_refs[b] == 0 for b in eng.free)


def _engines(fleet):
    out = []
    for r in fleet.replicas:
        with r.cond:
            if not r.dead:
                out.append((r.role, r.engine))
    return out


# ------------------------------------------------------- pool-spec units
def test_parse_pool_spec_fixed_and_ranged():
    assert daemon_mod._parse_pool_spec("prefill=1,decode=1") == [
        ("prefill", 1, 1), ("decode", 1, 1)]
    assert daemon_mod._parse_pool_spec("prefill=1..3, decode=2") == [
        ("prefill", 1, 3), ("decode", 2, 2)]
    assert daemon_mod._parse_pool_spec("unified=2") == [("unified", 2, 2)]


@pytest.mark.parametrize("bad", [
    "", "  ", "draft=1", "prefill", "prefill=0", "prefill=3..2",
    "prefill=1,prefill=2", "prefill=x", "prefill=1..y",
])
def test_parse_pool_spec_rejects(bad):
    with pytest.raises(ValueError):
        daemon_mod._parse_pool_spec(bad)


# ---------------------------------------------------------- router units
def test_choose_replica_is_phase_aware():
    views = [
        router.ReplicaView(0, True, False, 0, 0, role=router.ROLE_PREFILL),
        router.ReplicaView(1, True, False, 0, 0, role=router.ROLE_DECODE),
    ]
    assert router.choose_replica(views, phase=router.ROLE_PREFILL) == 0
    assert router.choose_replica(views, phase=router.ROLE_DECODE) == 1
    # a unified replica serves BOTH phases; a pool replica never
    # serves the other pool's phase
    uni = [router.ReplicaView(2, True, False, 0, 0)]
    assert router.choose_replica(uni, phase=router.ROLE_PREFILL) == 2
    assert router.choose_replica(uni, phase=router.ROLE_DECODE) == 2
    only_prefill = views[:1]
    assert router.choose_replica(
        only_prefill, phase=router.ROLE_DECODE) is None


def test_entry_phase_only_on_pooled_fleets(trained):
    unified = _mk_fleet(trained, 1)
    assert daemon_mod._FleetService._entry_phase(unified) is None
    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 1),
                                          ("decode", 1, 1)])
    assert (daemon_mod._FleetService._entry_phase(pooled)
            == router.ROLE_PREFILL)


# ------------------------------------------------------- autoscale units
def test_decode_pool_scales_on_itl_signal():
    pol = autoscale.AutoscalePolicy(1, 2, latency_high_s=0.5,
                                    out_after=2, out_cooldown_s=0.0)
    hot = autoscale.Signals(active_replicas=1, load_per_replica=0.0,
                            latency_p99_s=0.9)
    assert pol.observe(0.0, hot) == 1     # one tick: streak, no move
    assert pol.observe(1.0, hot) == 2     # sustained ITL burn scales
    # half-mark hysteresis: ITL between half and full threshold is
    # ambiguous, never shrinkable
    warm = autoscale.Signals(active_replicas=2, load_per_replica=0.0,
                             latency_p99_s=0.3)
    assert not pol.underloaded(warm)
    calm = autoscale.Signals(active_replicas=2, load_per_replica=0.0,
                             latency_p99_s=0.1)
    assert pol.underloaded(calm)


def test_latency_signal_ignored_without_threshold():
    pol = autoscale.AutoscalePolicy(1, 2)
    hot = autoscale.Signals(active_replicas=1, load_per_replica=0.0,
                            latency_p99_s=10.0)
    assert not pol.overloaded(hot)  # pre-round-20 policies are blind


# ----------------------------------------------------- handoff end-to-end
def test_pooled_fleet_greedy_bit_identical_with_handoff(trained):
    svc = daemon_mod._FleetService()
    prompt = _cycle_prompt(20)

    unified = _mk_fleet(trained, 1)
    want = svc.generate(unified, prompt, 12)

    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 1),
                                          ("decode", 1, 1)])
    h0 = daemon_mod._C_HANDOFFS.value
    b0 = daemon_mod._C_HANDOFF_BYTES.value
    got = svc.generate(pooled, prompt, 12)
    assert np.array_equal(want, got)
    assert daemon_mod._C_HANDOFFS.value == h0 + 1
    assert daemon_mod._C_HANDOFF_BYTES.value > b0

    roles = dict(_engines(pooled))
    prefill_eng = roles[router.ROLE_PREFILL]
    decode_eng = roles[router.ROLE_DECODE]
    # the work actually split by phase: the prefill engine finished
    # nothing, the decode engine emitted every token — and it did so
    # from the IMPORTED blocks, not a recompute
    assert prefill_eng.counters["requests_done"] == 0
    assert decode_eng.counters["requests_done"] == 1
    assert decode_eng.counters["tokens_out"] == 12
    assert decode_eng.counters["spill_prefetched"] >= 1
    for _, eng in _engines(pooled):
        _no_leaks(eng)


def test_pooled_fleet_sampled_bit_identical(trained):
    svc = daemon_mod._FleetService()
    prompt = _cycle_prompt(20)
    unified = _mk_fleet(trained, 1)
    want = svc.generate(unified, prompt, 12, temperature=0.8, seed=3)
    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 1),
                                          ("decode", 1, 1)])
    got = svc.generate(pooled, prompt, 12, temperature=0.8, seed=3)
    # resubmit's resume-key contract, applied across the handoff: the
    # decode engine re-seeds the slot's key chain where the prefill
    # engine would have started drawing
    assert np.array_equal(want, got)
    for _, eng in _engines(pooled):
        _no_leaks(eng)


def test_fleet_status_surfaces_roles_and_pools(trained):
    svc = daemon_mod._FleetService()
    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 2),
                                          ("decode", 1, 1)])
    st = svc.fleet_status(pooled)
    assert [r["role"] for r in st["replica"]] == [
        router.ROLE_PREFILL, router.ROLE_DECODE]
    assert st["pools"]["prefill"]["min"] == 1
    assert st["pools"]["prefill"]["max"] == 2
    assert st["pools"]["prefill"]["autoscale"]["target"] == 1
    # a fixed pool has no policy to snapshot
    assert st["pools"]["decode"]["autoscale"] is None
    # unified fleets don't grow the key (wire-compat with round 13)
    unified = _mk_fleet(trained, 1)
    assert "pools" not in svc.fleet_status(unified)


# ------------------------------------------------------- park-frame wire
def test_pool_park_frame_matches_shed_re():
    err = daemon_mod.PoolRebuildingError(250, router.ROLE_PREFILL,
                                         "no placeable replica in pool")
    m = loadgen.SHED_RE.search(str(err))
    assert m is not None, str(err)
    assert m.group(1) == "rebuilding"
    assert m.group(2) == "250"
    # a pool park IS a RebuildingError: every round-13 client handler
    # (park-and-retry, never a hard failure) applies unchanged
    assert isinstance(err, daemon_mod.RebuildingError)
    # and the whole-fleet frame still parses with the same groups
    m2 = loadgen.SHED_RE.search(
        str(daemon_mod.RebuildingError(100, "rolling restart")))
    assert m2 is not None
    assert (m2.group(1), m2.group(2)) == ("rebuilding", "100")


# ------------------------------------------------- independent pool scale
def test_pools_scale_independently(trained):
    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 2),
                                          ("decode", 1, 1)])
    svc = daemon_mod._FLEET_SERVICE

    def count(role):
        with pooled.cv:
            return sum(1 for r in pooled.replicas
                       if not r.retired and r.role == role)

    # a prefill-scoped reconcile grows ONLY the prefill pool, through
    # the round-17 machinery (fresh engine, stepper, router views)
    daemon_mod._reconcile_fleet(pooled, 2, router.ROLE_PREFILL)
    assert count(router.ROLE_PREFILL) == 2
    assert count(router.ROLE_DECODE) == 1
    new = pooled.replicas[-1]
    assert new.role == router.ROLE_PREFILL
    with new.cond:
        assert new.engine.handoff_at_boundary  # pool role arms the edge

    # role-scoped scale-in honours the pool floor: prefill shrinks
    # back to 1, then refuses; the decode pool never had headroom
    assert svc.scale_in(pooled, role=router.ROLE_PREFILL) is not None
    assert count(router.ROLE_PREFILL) == 1
    assert svc.scale_in(pooled, role=router.ROLE_PREFILL) is None
    assert svc.scale_in(pooled, role=router.ROLE_DECODE) is None
    assert count(router.ROLE_DECODE) == 1


def test_pooled_fleet_serves_after_prefill_scale_out(trained):
    svc = daemon_mod._FleetService()
    prompt = _cycle_prompt(20)
    unified = _mk_fleet(trained, 1)
    want = svc.generate(unified, prompt, 12)
    pooled = _mk_fleet(trained, 0, pools=[("prefill", 1, 2),
                                          ("decode", 1, 1)])
    daemon_mod._reconcile_fleet(pooled, 2, router.ROLE_PREFILL)
    got = svc.generate(pooled, prompt, 12)
    assert np.array_equal(want, got)
    for _, eng in _engines(pooled):
        _no_leaks(eng)
