"""Distillation (tpulab.models.distill): a small student learns the
teacher's distribution, and the distilled student is a BETTER
speculative draft than a random model of the same size — the property
the module exists for.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.distill import distill, make_distill_step
from tpulab.models.labformer import LabformerConfig, forward, init_params
from tpulab.models.speculative import speculative_generate

TEACHER_CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                              max_seq=128)
STUDENT_CFG = LabformerConfig(d_model=16, n_heads=2, n_layers=1, d_ff=32,
                              max_seq=128)


@pytest.fixture(scope="module")
def teacher(trained_small, trained_small_cfg):
    assert TEACHER_CFG == trained_small_cfg  # drift fails loudly
    return trained_small


def _cycle_batch(step):
    # the teacher's training distribution: the 0..6 byte cycle
    return np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))


def _agreement(a_params, a_cfg, b_params, b_cfg, tokens):
    la = np.asarray(forward(a_params, jnp.asarray(tokens), a_cfg))
    lb = np.asarray(forward(b_params, jnp.asarray(tokens), b_cfg))
    return float(np.mean(la.argmax(-1) == lb.argmax(-1)))


def test_distilled_student_tracks_teacher(teacher):
    student, loss = distill(
        teacher, TEACHER_CFG, STUDENT_CFG, steps=120, batch_at=_cycle_batch,
        log=lambda *a: None,
    )
    assert np.isfinite(loss)
    probe = np.tile(np.arange(16, dtype=np.int32) % 7, (4, 1))
    distilled = _agreement(student, STUDENT_CFG, teacher, TEACHER_CFG, probe)
    random = _agreement(
        init_params(STUDENT_CFG, seed=0), STUDENT_CFG, teacher, TEACHER_CFG,
        probe,
    )
    assert distilled > max(random, 0.5), (distilled, random)


def test_distilled_draft_beats_random_draft(teacher):
    student, _ = distill(
        teacher, TEACHER_CFG, STUDENT_CFG, steps=120, batch_at=_cycle_batch,
        log=lambda *a: None,
    )
    prompt = np.tile(np.arange(5, dtype=np.int32) % 7, (1, 1))
    toks_d, acc_d = speculative_generate(
        student, STUDENT_CFG, teacher, TEACHER_CFG, prompt, steps=14, k=4
    )
    toks_r, acc_r = speculative_generate(
        init_params(STUDENT_CFG, seed=3), STUDENT_CFG, teacher, TEACHER_CFG,
        prompt, steps=14, k=4,
    )
    # losslessness regardless of draft...
    assert np.array_equal(toks_d, toks_r)
    # ...but the distilled draft gets more proposals accepted
    assert acc_d > acc_r, (acc_d, acc_r)


def test_vocab_mismatch_rejected(teacher):
    bad = LabformerConfig(vocab=128, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32)
    with pytest.raises(ValueError, match="vocab"):
        make_distill_step(teacher, TEACHER_CFG, bad)


def test_pure_kl_and_pure_ce_both_train(teacher):
    for alpha in (0.0, 1.0):
        _, loss = distill(
            teacher, TEACHER_CFG, STUDENT_CFG, steps=10,
            batch_at=_cycle_batch, alpha=alpha, log=lambda *a: None,
        )
        assert np.isfinite(loss)


def test_distill_cli_produces_servable_student(tmp_path, capsys):
    """`tpulab distill` end to end: a BPE+sidecar teacher distills into
    a SMALLER student whose checkpoint serves through the standard
    surfaces (sidecar reconstruction, tokenizer copied, eval loads)."""
    import json

    from tpulab.evaluate import evaluate
    from tpulab.io.bpe import train_bpe
    from tpulab.models.distill import main as distill_main
    from tpulab.models.generate import load_sidecar
    from tpulab.train import train

    data = tmp_path / "data"
    data.mkdir()
    (data / "c.txt").write_bytes(b"pack my box with five dozen jugs. " * 2000)
    tok = train_bpe((data / "c.txt").read_bytes(), vocab=300)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)
    teacher_dir = str(tmp_path / "teacher")
    train(steps=6, batch=2, seq=32, data_dir=str(data), tokenizer=tokp,
          ckpt_dir=teacher_dir, save_every=3, log=lambda *a: None)

    out = str(tmp_path / "student")
    rc = distill_main(["--teacher", teacher_dir, "--out", out,
                       "--steps", "6", "--batch", "2", "--seq", "32",
                       "--data-dir", str(data)])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and np.isfinite(report["final_loss"])
    assert report["student_layers"] == 2  # half the trainer default L4

    s_cfg, s_tok = load_sidecar(out)
    assert s_cfg.n_layers == 2 and s_cfg.vocab == tok.vocab
    assert s_tok is not None and s_tok.vocab == tok.vocab
    rep = evaluate(out, str(data), batches=1, batch=2, seq=32)
    assert np.isfinite(rep["loss_nats_per_token"])
