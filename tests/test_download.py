"""Behavioral coverage for tpulab.utils.download (reference
``utils/download_files.py:5-35`` parity) — a real localhost HTTP
round-trip, closing the last import-level-only component (round-4
verdict, weak #6 / next #7): success streams bytes to disk atomically,
an existing file short-circuits without re-fetching, and HTTP errors
degrade to None with no partial file left behind."""

import http.server
import threading

import pytest

from tpulab.utils.download import download_file

requests = pytest.importorskip("requests")


@pytest.fixture(scope="module")
def httpd():
    hits = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            hits.append(self.path)
            if self.path == "/files/blob.bin":
                body = bytes(range(256)) * 300  # ~77KB: spans chunks
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, *a):  # keep pytest output clean
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", hits
    finally:
        srv.shutdown()


def test_success_streams_and_names_from_url(tmp_path, httpd):
    base, _ = httpd
    got = download_file(f"{base}/files/blob.bin", str(tmp_path / "dl"))
    assert got == str(tmp_path / "dl" / "blob.bin")
    data = open(got, "rb").read()
    assert data == bytes(range(256)) * 300
    assert not (tmp_path / "dl" / "blob.bin.part").exists()  # atomic


def test_existing_file_short_circuits(tmp_path, httpd):
    base, hits = httpd
    d = tmp_path / "dl"
    d.mkdir()
    (d / "blob.bin").write_bytes(b"local copy")
    n0 = len(hits)
    got = download_file(f"{base}/files/blob.bin", str(d))
    assert got == str(d / "blob.bin")
    assert (d / "blob.bin").read_bytes() == b"local copy"  # untouched
    assert len(hits) == n0  # no request went out


def test_explicit_filename_overrides_url_name(tmp_path, httpd):
    base, _ = httpd
    got = download_file(f"{base}/files/blob.bin", str(tmp_path),
                        filename="renamed.dat")
    assert got == str(tmp_path / "renamed.dat")
    assert open(got, "rb").read()[:4] == bytes(range(4))


def test_http_error_returns_none_no_partial(tmp_path, httpd, capsys):
    base, _ = httpd
    got = download_file(f"{base}/missing.bin", str(tmp_path / "dl"))
    assert got is None
    assert list((tmp_path / "dl").iterdir()) == []  # no *.part litter
    assert "skipped" in capsys.readouterr().out


def test_unreachable_host_returns_none(tmp_path):
    # port 9 (discard) on localhost: connection refused fast
    got = download_file("http://127.0.0.1:9/nope.bin", str(tmp_path / "dl"))
    assert got is None
