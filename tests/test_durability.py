"""Crash-durable serving: the round-16 write-ahead journal suite.

The journal (``tpulab/durability.py``) makes daemon DEATH a recoverable
event: accepts are fsynced before admission, committed prefixes are
checkpointed incrementally at a bounded cadence, and a fresh process
replays every incomplete request through the certified
``PagedEngine.resubmit`` fold-tokens-into-prompt path while clients
resume their streams by rid.  Headline properties certified here:

  * scan tolerates exactly one crash artifact — a torn FINAL record —
    and raises ``JournalCorrupt`` on interior corruption (silently
    skipping interior records would silently drop accepted requests);
  * the incremental checkpoint chain stitches by end-index: overlaps
    re-slice, gaps drop the record and keep the valid shorter prefix
    (recovery regenerates the rest bit-identically);
  * compaction atomically keeps incomplete accepts + ONE merged
    checkpoint, drops completed rids, and re-seeds the delta cadence so
    post-compact checkpoints never duplicate the merged prefix;
  * the journal is OFF by default and the armed serving path is
    bit-identical to the unarmed one;
  * resume-by-rid skips EXACTLY the acknowledged byte prefix — no
    duplicates, no gaps — and unknown rids answer a parseable error;
  * restart recovery replays an incomplete journaled request to a
    bit-identical completion, records it ``done ok`` (a second replay
    of the same journal is a no-op — idempotence), and NEVER replays a
    rid cancelled before the crash;
  * live subprocess: a ``daemon.kill`` fault (``os._exit`` after the
    accept fsync, before admission — the worst-ordered crash) loses
    nothing: the restarted daemon recovers the request and the client's
    resume-by-rid answer is byte-equal to an uninterrupted submission;
    graceful SIGTERM drains, compacts the journal, persists a shutdown
    flight-recorder bundle, and exits 0;
  * the new counters (``daemon_journal_records``, ``daemon_recoveries``,
    ``daemon_resumed_streams``) are registered and documented (the
    tests/test_obs.py lint pattern).
"""

import importlib.util
import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import tpulab.daemon as daemon_mod
from tpulab import durability, obs
from tpulab.durability import (Journal, JournalCorrupt, decode_payload,
                               encode_payload, scan)
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _fresh_resume_table(monkeypatch):
    """Each test gets its own resume-by-rid table (the daemon global
    would otherwise leak finished entries across tests)."""
    monkeypatch.setattr(daemon_mod, "_RESUME", {})
    yield


@pytest.fixture
def fleet_patched(trained, monkeypatch):
    """Route every in-process ``_fleet_for`` build to ONE tiny trained
    fleet (cold demo builds would dominate the suite)."""
    def builder():
        return PagedEngine(trained, CFG, slots=2, n_blocks=32,
                           block_size=8, max_seq=64), None

    fleet = daemon_mod._make_fleet(builder, 1)
    monkeypatch.setattr(daemon_mod, "_fleet_for", lambda *a, **k: fleet)
    return fleet


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _ref(trained, payload: bytes, steps: int):
    """(bytes, tokens) a fault-free greedy run produces for a byte-LM
    payload — the bit-identity oracle every durability path is held
    to."""
    prompt = np.frombuffer(payload, np.uint8).astype(np.int32)
    out = generate(trained, prompt[None, :], CFG, steps=steps,
                   temperature=0.0)[0]
    toks = [int(t) for t in out]
    return bytes(t & 0xFF for t in toks), toks


def _write_records(path, recs, torn_tail: bytes = b""):
    with open(path, "wb") as f:
        for r in recs:
            f.write(json.dumps(r, separators=(",", ":")).encode() + b"\n")
        if torn_tail:
            f.write(torn_tail)


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", ROOT / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    return rep


# ------------------------------------------------------- journal units
def test_accept_ckpt_done_roundtrip(tmp_path):
    """The record lifecycle: fsynced accept, cadence-gated incremental
    checkpoints, terminal done — scan folds them back exactly."""
    path = tmp_path / "j.jsonl"
    jnl = Journal(path, ckpt_every=4)
    payload = b"\x01\x02\x03"
    jnl.append_accept("r1", "tag-1", payload, {"steps": 8, "rid": "r1"})
    jnl.note_tokens("r1", [1, 2, 3])          # below cadence: no record
    st = jnl.scan()
    assert st.records == 1 and st.entries["r1"].ckpt is None
    toks = [1, 2, 3, 4, 5]
    jnl.note_tokens("r1", toks)               # 5 >= 4: first delta
    jnl.note_tokens("r1", toks)               # no NEW tokens: no record
    toks += [6, 7, 8, 9]
    jnl.note_tokens("r1", toks)               # second delta [6..9]
    st = jnl.scan()
    assert st.entries["r1"].ckpt == toks      # chain stitched
    assert not st.entries["r1"].complete
    assert list(st.incomplete()) == ["r1"]
    jnl.append_done("r1", "ok", tokens=toks)
    st = jnl.scan()
    e = st.entries["r1"]
    assert e.complete and e.done["status"] == "ok"
    assert e.done["tokens"] == toks
    assert st.incomplete() == {} and list(st.completed_ok()) == ["r1"]
    assert decode_payload(e.accept["payload"]) == payload
    assert e.accept["config"]["steps"] == 8
    jnl.close()


def test_scan_tolerates_torn_final_record_only(tmp_path):
    """A crash mid-append leaves at most one partial FINAL line — scan
    drops it and recovers everything durable; the same garbage anywhere
    earlier is real corruption and must raise."""
    path = tmp_path / "torn.jsonl"
    acc = {"t": "accept", "rid": "r1", "tag": "",
           "payload": encode_payload(b"hi"), "config": {}}
    _write_records(path, [acc], torn_tail=b'{"t":"ckpt","rid":"r1","n')
    st = scan(path)
    assert st.torn and st.records == 1
    assert list(st.incomplete()) == ["r1"]
    # interior corruption: the torn line is FOLLOWED by a valid record
    _write_records(path, [], torn_tail=b'{"t":"ckpt","rid":"r1","n\n')
    with open(path, "ab") as f:
        f.write(json.dumps(acc).encode() + b"\n")
    with pytest.raises(JournalCorrupt, match="interior record"):
        scan(path)
    # a missing file scans as empty, not as an error
    st = scan(tmp_path / "absent.jsonl")
    assert st.records == 0 and st.entries == {}


def test_ckpt_chain_overlap_and_gap(tmp_path):
    """Delta stitching by authoritative end-index ``n``: an overlap
    re-slices the base (no duplication), a gap drops the record and
    keeps the shorter valid prefix (no fabricated tokens — recovery
    regenerates the rest bit-identically)."""
    path = tmp_path / "chain.jsonl"
    _write_records(path, [
        {"t": "accept", "rid": "r1", "tag": "",
         "payload": encode_payload(b"x"), "config": {}},
        {"t": "ckpt", "rid": "r1", "n": 4, "tokens": [1, 2, 3, 4]},
        # overlap: a retransmitted window — n says it ENDS at 6
        {"t": "ckpt", "rid": "r1", "n": 6, "tokens": [3, 4, 5, 6]},
        # gap: an interior delta was lost (buffered ckpts may tear);
        # this record's start (10) is past the known prefix (6)
        {"t": "ckpt", "rid": "r1", "n": 12, "tokens": [11, 12]},
        # ckpt for a rid never accepted: ignored, not an error
        {"t": "ckpt", "rid": "ghost", "n": 2, "tokens": [1, 2]},
    ])
    st = scan(path)
    assert st.entries["r1"].ckpt == [1, 2, 3, 4, 5, 6]
    assert "ghost" not in st.entries


def test_compaction_drops_completed_merges_ckpts(tmp_path):
    """Compaction keeps ONLY incomplete rids (accept + one merged
    checkpoint), atomically, and re-seeds the delta cadence so the next
    checkpoint continues the chain instead of duplicating it."""
    path = tmp_path / "c.jsonl"
    jnl = Journal(path, ckpt_every=4)
    jnl.append_accept("done-ok", "", b"a", {})
    jnl.note_tokens("done-ok", [1, 2, 3, 4])
    jnl.append_done("done-ok", "ok", tokens=[1, 2, 3, 4])
    jnl.append_accept("cancelled", "", b"b", {})
    jnl.append_done("cancelled", "cancelled")
    live = [9, 8, 7, 6, 5, 4, 3, 2]
    jnl.append_accept("live", "", b"c", {"steps": 16})
    jnl.note_tokens("live", live[:4])
    jnl.note_tokens("live", live)
    kept = jnl.compact()
    assert kept == 2  # live's accept + its merged ckpt
    st = scan(path)
    assert list(st.entries) == ["live"]
    assert st.entries["live"].ckpt == live
    # raw file: exactly one ckpt record, carrying the full merged
    # prefix with its end-index
    recs = [json.loads(line) for line in
            open(path, "rb").read().splitlines() if line.strip()]
    cks = [r for r in recs if r["t"] == "ckpt"]
    assert len(cks) == 1 and cks[0]["n"] == len(live)
    # post-compact checkpoints append the DELTA only — scan must see a
    # clean continuation, not a duplicated prefix
    live += [1, 0, 1, 0]
    jnl.note_tokens("live", live)
    st = jnl.scan()
    assert st.entries["live"].ckpt == live
    jnl.close()


def test_group_commit_concurrent_accepts(tmp_path):
    """N threads accepting concurrently: every accept is durable (the
    group-commit fsync shares work, never skips it)."""
    jnl = Journal(tmp_path / "g.jsonl")
    errs = []

    def accept(i):
        try:
            jnl.append_accept(f"r{i}", "", bytes([i]), {"i": i})
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=accept, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs
    st = jnl.scan()
    assert len(st.entries) == 8 and st.records == 8
    assert decode_payload(st.entries["r5"].accept["payload"]) == b"\x05"
    jnl.close()


# --------------------------------------------- in-process daemon paths
def test_journal_off_by_default_and_armed_bit_identical(
        tmp_path, fleet_patched, monkeypatch):
    """Default = no journal object at all (the pre-round-16 serving
    path); arming it must not change a single output byte."""
    assert daemon_mod._JOURNAL is None  # module default: off
    payload = b"hello"
    want, want_toks = _ref(fleet_patched.replicas[0].engine.params,
                           payload, 12)
    hdr = {"lab": "generate", "config": {"steps": 12, "rid": "bit-1"}}
    off = daemon_mod.handle_request(dict(hdr), payload)
    assert off == want
    assert daemon_mod._resume_lookup("bit-1") is None  # no table entry
    jnl = Journal(tmp_path / "j.jsonl",
                  on_record=daemon_mod._C_JOURNAL_RECORDS.inc)
    monkeypatch.setattr(daemon_mod, "_JOURNAL", jnl)
    c0 = daemon_mod._C_JOURNAL_RECORDS.value
    on = daemon_mod.handle_request(dict(hdr), payload)
    assert on == off == want
    st = jnl.scan()
    e = st.entries["bit-1"]
    assert decode_payload(e.accept["payload"]) == payload
    assert e.done["status"] == "ok" and e.done["tokens"] == want_toks
    assert daemon_mod._C_JOURNAL_RECORDS.value - c0 >= 2  # accept+done
    jnl.close()


def test_resume_by_rid_skips_exact_prefix(tmp_path, fleet_patched,
                                          monkeypatch):
    """The no-duplicates-no-gaps contract: a client holding ``k`` bytes
    gets chunks for exactly ``bytes[k:]`` and a terminal frame carrying
    the FULL output."""
    jnl = Journal(tmp_path / "j.jsonl")
    monkeypatch.setattr(daemon_mod, "_JOURNAL", jnl)
    payload = b"resume me"
    want, _ = _ref(fleet_patched.replicas[0].engine.params, payload, 12)
    full = daemon_mod.handle_request(
        {"lab": "generate", "config": {"steps": 12, "rid": "t-res"}},
        payload)
    assert full == want
    r0 = daemon_mod._C_RESUMED_STREAMS.value
    for k in (0, 5, len(full)):
        chunks = []
        out = daemon_mod.handle_request(
            {"lab": "resume",
             "config": {"rid": "t-res", "received": k, "stream": True}},
            b"", send_chunk=chunks.append)
        assert out == full
        assert b"".join(chunks) == full[k:]
    assert daemon_mod._C_RESUMED_STREAMS.value - r0 == 3
    # unknown rid: the parseable fall-back-to-fresh-submission signal
    with pytest.raises(ValueError, match="resume unknown rid"):
        daemon_mod.handle_request(
            {"lab": "resume", "config": {"rid": "nope"}}, b"")
    with pytest.raises(ValueError, match="received must be >= 0"):
        daemon_mod.handle_request(
            {"lab": "resume",
             "config": {"rid": "t-res", "received": -1}}, b"")
    jnl.close()


def test_recovery_replays_incomplete_bit_identical(tmp_path,
                                                   fleet_patched):
    """The tentpole, in-process: a journal whose process died mid-decode
    (accept + one checkpoint + a torn final line) replays to a
    completion BYTE-EQUAL to an uninterrupted run, records done-ok, and
    a second replay of the same journal is a no-op (idempotence)."""
    payload = b"crashed"
    want, want_toks = _ref(fleet_patched.replicas[0].engine.params,
                           payload, 12)
    path = tmp_path / "dead.jsonl"
    _write_records(path, [
        {"t": "accept", "rid": "t-rec", "tag": "tr",
         "payload": encode_payload(payload),
         "config": {"steps": 12, "rid": "t-rec"}},
        {"t": "ckpt", "rid": "t-rec", "n": 5, "tokens": want_toks[:5]},
    ], torn_tail=b'{"t":"ckpt","rid":"t-rec","n":9,"to')
    jnl = Journal(path)
    rec0 = daemon_mod._C_RECOVERIES.value
    assert daemon_mod._recover_from_journal(jnl) == 1
    # the rid is in the table BEFORE the replay finishes (synchronous
    # registration): resume waits on the recovery thread's stream
    out = daemon_mod.handle_request(
        {"lab": "resume", "config": {"rid": "t-rec", "received": 0}}, b"")
    assert out == want
    assert daemon_mod._C_RECOVERIES.value == rec0 + 1
    st = jnl.scan()
    e = st.entries["t-rec"]
    assert e.done["status"] == "ok" and e.done["tokens"] == want_toks
    jnl.close()
    # second restart over the same journal: nothing incomplete, but the
    # completed stream re-registers so a late client still resumes
    daemon_mod._RESUME.clear()
    jnl2 = Journal(path)
    assert daemon_mod._recover_from_journal(jnl2) == 0
    out2 = daemon_mod.handle_request(
        {"lab": "resume", "config": {"rid": "t-rec", "received": 3}}, b"")
    assert out2 == want
    assert daemon_mod._C_RECOVERIES.value == rec0 + 1  # no re-replay
    jnl2.close()


def test_cancelled_before_crash_not_replayed(tmp_path, fleet_patched):
    """A rid whose client hung up (done ``cancelled``) before the crash
    is excluded from recovery AND from the resume table — replaying
    work nobody waits for would burn restart capacity."""
    path = tmp_path / "c.jsonl"
    _write_records(path, [
        {"t": "accept", "rid": "t-can", "tag": "",
         "payload": encode_payload(b"bye"), "config": {"steps": 8}},
        {"t": "done", "rid": "t-can", "status": "cancelled"},
    ])
    jnl = Journal(path)
    assert daemon_mod._recover_from_journal(jnl) == 0
    with pytest.raises(ValueError, match="resume unknown rid"):
        daemon_mod.handle_request(
            {"lab": "resume", "config": {"rid": "t-can"}}, b"")
    # and compaction dropped it from the file entirely
    assert scan(path).entries == {}
    jnl.close()


def test_shed_and_error_outcomes_journal_done(tmp_path, fleet_patched,
                                              monkeypatch):
    """Failure outcomes write terminal records too — a shed or errored
    request must never come back from the dead on restart."""
    jnl = Journal(tmp_path / "j.jsonl")
    monkeypatch.setattr(daemon_mod, "_JOURNAL", jnl)
    with pytest.raises(ValueError, match="rid must be"):
        daemon_mod.handle_request(
            {"lab": "generate", "config": {"steps": 2, "rid": "x" * 300}},
            b"hi")
    monkeypatch.setattr(
        daemon_mod._FLEET_SERVICE, "generate",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    with pytest.raises(RuntimeError, match="boom"):
        daemon_mod.handle_request(
            {"lab": "generate", "config": {"steps": 2, "rid": "t-err"}},
            b"hi")
    st = jnl.scan()
    assert st.entries["t-err"].done["status"] == "error"
    assert st.incomplete() == {}
    # the entry failed, not vanished: a resuming client gets the error
    with pytest.raises(RuntimeError, match="boom"):
        daemon_mod.handle_request(
            {"lab": "resume", "config": {"rid": "t-err"}}, b"")
    jnl.close()


# ------------------------------------------------------ live subprocess
def _spawn_daemon(sock, log_path, *extra, env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PYTHONPATH"] = str(ROOT)
    env.update(env_extra or {})
    # file, not pipe: nothing drains a pipe mid-test (test_native's
    # observed 64 KB-buffer deadlock)
    log_f = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", str(sock),
         *extra], env=env, stdout=log_f, stderr=subprocess.STDOUT)


def _wait_socket(sock, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pathlib.Path(sock).exists():
            return
        time.sleep(0.1)
    raise AssertionError("daemon socket never appeared")


def test_sigterm_graceful_drain_compact_exit0(tmp_path):
    """Satellite 1 live: SIGTERM -> drain, journal flush+compact,
    shutdown flight-recorder bundle, exit 0."""
    sock = tmp_path / "g.sock"
    journal = tmp_path / "g.jsonl"
    pm_dir = tmp_path / "postmortems"
    proc = _spawn_daemon(
        sock, tmp_path / "daemon.log", "--journal", str(journal),
        env_extra={"TPULAB_POSTMORTEM_DIR": str(pm_dir)})
    try:
        _wait_socket(sock)
        rep = _load_obs_report()
        assert b"daemon_journal_records" in rep.request_with_retry(
            str(sock), "metrics", deadline_s=60.0)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    log = (tmp_path / "daemon.log").read_bytes()
    assert b"graceful shutdown" in log
    st = scan(journal)           # compacted: clean, nothing in flight
    assert not st.torn and st.incomplete() == {}
    assert list(pm_dir.glob("*")), "no shutdown flight-recorder bundle"


def test_kill_mid_request_recover_and_resume_live(tmp_path):
    """The acceptance scenario end to end, live: the ``daemon.kill``
    fault SIGKILL-equivalently dies AFTER the accept fsync and BEFORE
    admission — the worst-ordered crash — yet a restarted daemon on the
    same journal replays the request and answers the client's
    resume-by-rid with bytes EQUAL to an uninterrupted submission."""
    sock = tmp_path / "k.sock"
    journal = tmp_path / "k.jsonl"
    log = tmp_path / "daemon.log"
    payload = b"hello"
    cfg = {"steps": 6, "rid": "kill-1"}
    rep = _load_obs_report()
    schedule = json.dumps(
        [{"site": "daemon.kill", "kind": "kill", "at": 1}])
    proc = _spawn_daemon(sock, log, "--journal", str(journal),
                         env_extra={"TPULAB_FAULTS": schedule})
    proc2 = None
    try:
        _wait_socket(sock)
        with pytest.raises((ConnectionError, OSError)):
            rep.request(str(sock), "generate", dict(cfg), payload)
        assert proc.wait(timeout=60) == 1  # os._exit(1), no cleanup
        st = scan(journal)  # the accept survived the crash, unfinished
        assert list(st.incomplete()) == ["kill-1"]
        # restart: same socket, same journal, injector DISARMED
        proc2 = _spawn_daemon(sock, log, "--journal", str(journal))
        _wait_socket(sock, timeout_s=120.0)
        out = rep.request_with_retry(
            str(sock), "resume", {"rid": "kill-1", "received": 0},
            deadline_s=300.0)
        # the oracle: the SAME submission, uninterrupted, on the same
        # demo checkpoint (greedy decode is deterministic)
        want = rep.request_with_retry(
            str(sock), "generate",
            {"steps": 6, "rid": "kill-ref"}, payload, deadline_s=300.0)
        assert out == want and len(out) == 6
        text = rep.request_with_retry(
            str(sock), "metrics", deadline_s=60.0).decode()
        for pat in (r"^daemon_recoveries [1-9]", r"^daemon_resumed_streams [1-9]",
                    r"^daemon_journal_records [1-9]"):
            assert re.search(pat, text, re.M), pat
        proc2.send_signal(signal.SIGTERM)
        assert proc2.wait(timeout=60) == 0
        proc2 = None
        assert scan(journal).incomplete() == {}  # compacted clean
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)


# ------------------------------------------------------------------ lint
def test_durability_counters_registered_and_documented():
    """The standing counters lint (tests/test_obs.py pattern): every
    round-16 counter is a registered metric AND documented."""
    import tpulab.daemon  # noqa: F401 — registers the counters

    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("daemon_journal_records", "daemon_recoveries",
                 "daemon_resumed_streams"):
        assert obs.REGISTRY.get(name) is not None, name
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"
    # the kill fault site + the resume wire protocol are documented too
    assert "daemon.kill" in (ROOT / "tpulab" / "faults.py").read_text()
    assert "resume" in docs and "journal" in docs


def test_bench_registry_has_journal_overhead():
    """The <1% decode-budget claim stays enforced: the bench registry
    carries journal_overhead and the baselines file pins its metric."""
    from tpulab.bench import bench_journal_overhead  # noqa: F401

    baselines = json.loads(
        (ROOT / "results" / "baselines.json").read_text())
    row = baselines["baselines"]["journal_overhead_4slots_ticks_per_s"]
    assert row["direction"] == "higher" and row["value"] > 0


@pytest.mark.slow
def test_journal_overhead_bench_under_budget():
    """The journal_overhead microbench: runs the real A/B windows and
    asserts the <1% budget internally (wall-clock sensitive — slow
    tier; the committed baselines.json row gates the CPU-proxy number
    round over round)."""
    from tpulab.bench import bench_journal_overhead

    row = bench_journal_overhead(reps=2)
    assert row["metric"] == "journal_overhead_4slots_ticks_per_s"
    assert row["value"] > 0 and row["ckpt_every"] == 16
    assert "overhead_pct_best" in row
