"""`tpulab eval` (standalone held-out evaluation) and the optimizer zoo.

Claims under test:
  * eval honors checkpoint sidecars (BPE vocab, LoRA fold) and reports
    loss / perplexity / bits-per-byte with consistent accounting
    (byte LM: bpb == loss/ln2);
  * a trained checkpoint evaluates better than a random one on its own
    corpus;
  * BPE checkpoints refuse the synthetic stream (byte-space noise in a
    subword vocab would be a meaningless number);
  * every optimizer in the zoo trains (finite, decreasing-ish loss) and
    unknown names refuse.
"""

import json

import numpy as np
import pytest

from tpulab.evaluate import evaluate
from tpulab.train import build_optimizer, train


def _corpus(tmp_path, text=b"the quick brown fox jumps over the lazy dog. ",
            reps=2000):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    (data / "c.txt").write_bytes(text * reps)
    return str(data)


def test_eval_byte_lm_accounting(tmp_path):
    data = _corpus(tmp_path)
    ck = str(tmp_path / "ck")
    train(steps=6, batch=2, seq=32, data_dir=data, ckpt_dir=ck,
          save_every=3, log=lambda *a: None)
    rep = evaluate(ck, data, batches=2, batch=2, seq=32)
    assert rep["step"] == 6
    assert np.isfinite(rep["loss_nats_per_token"])
    # byte LM: one token == one byte, so bpb is exactly loss/ln2
    assert rep["bits_per_byte"] == pytest.approx(
        rep["loss_nats_per_token"] / np.log(2), abs=1e-3)
    assert rep["perplexity"] == pytest.approx(
        np.exp(rep["loss_nats_per_token"]), rel=1e-3)


def test_eval_trained_beats_random(tmp_path):
    data = _corpus(tmp_path)
    ck = str(tmp_path / "ck")
    train(steps=30, batch=4, seq=32, data_dir=data, ckpt_dir=ck,
          save_every=30, log=lambda *a: None)
    trained = evaluate(ck, data, batches=2, batch=2, seq=32)
    # random-weights baseline: same arch, no checkpoint found -> error,
    # so compare against the model's ceiling ln(256) instead
    assert trained["loss_nats_per_token"] < np.log(256) - 0.5


def test_eval_bpe_sidecar_and_refusal(tmp_path):
    data = _corpus(tmp_path)
    tokp = str(tmp_path / "tok.json")
    from tpulab.io.bpe import train_bpe

    tok = train_bpe(open(tmp_path / "data" / "c.txt", "rb").read(), 300)
    tok.save(tokp)
    ck = str(tmp_path / "ck")
    train(steps=6, batch=2, seq=32, data_dir=data, tokenizer=tokp,
          lora_rank=2, ckpt_dir=ck, save_every=3, log=lambda *a: None)
    rep = evaluate(ck, data, batches=2, batch=2, seq=32)
    assert rep["tokenizer_vocab"] == tok.vocab
    # BPE packs >1 byte per token, so bpb must be BELOW loss/ln2
    assert rep["bits_per_byte"] < rep["loss_nats_per_token"] / np.log(2)
    with pytest.raises(ValueError, match="data-dir"):
        evaluate(ck, None, batches=1)


def test_eval_synthetic_matches_trainers_stream(tmp_path):
    """No --data-dir: eval must score the trainer's own structured
    synthetic stream (at the disjoint eval seed), not uniform noise —
    a synthetically-trained checkpoint must beat the ln(vocab) ceiling."""
    ck = str(tmp_path / "ck")
    train(steps=60, batch=4, seq=32, ckpt_dir=ck, save_every=60,
          log=lambda *a: None)
    rep = evaluate(ck, None, batches=2, batch=4, seq=32)
    assert rep["data"] == "synthetic"
    # 60 steps reach ~5.40 on the structured stream (uniform-noise eval
    # pinned ~5.63, ABOVE the ln(256)=5.545 ceiling — the old bug)
    assert rep["loss_nats_per_token"] < np.log(256) - 0.1, rep


def test_eval_reports_corpus_truncation(tmp_path):
    data = _corpus(tmp_path)
    ck = str(tmp_path / "ck")
    train(steps=4, batch=2, seq=32, data_dir=data, ckpt_dir=ck,
          save_every=2, log=lambda *a: None)
    rep = evaluate(ck, data, batches=1, batch=2, seq=32, limit_bytes=4096)
    assert rep["corpus_bytes"] == 4096
    assert rep["corpus_truncated_at_limit"] is True
    rep2 = evaluate(ck, data, batches=1, batch=2, seq=32)
    assert rep2["corpus_truncated_at_limit"] is False


def test_eval_cli(tmp_path, capsys):
    from tpulab.evaluate import main as eval_main

    data = _corpus(tmp_path)
    ck = str(tmp_path / "ck")
    train(steps=4, batch=2, seq=32, data_dir=data, ckpt_dir=ck,
          save_every=2, log=lambda *a: None)
    rc = eval_main(["--ckpt-dir", ck, "--data-dir", data,
                    "--batches", "1", "--batch", "2", "--seq", "32"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["step"] == 4 and "bits_per_byte" in out


@pytest.mark.parametrize("name", ["adamw", "lion", "adafactor", "sgd"])
def test_optimizer_zoo_trains(name):
    import jax.numpy as jnp
    import optax  # noqa: F401  (import check)

    from tpulab.models.labformer import LabformerConfig, init_train_state

    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=64)
    opt = build_optimizer(lr=1e-3 if name != "lion" else 3e-4, steps=20,
                          optimizer=name)
    params, opt_state, step = init_train_state(cfg, mesh=None, seed=0,
                                               optimizer=opt)
    cyc = np.tile(np.arange(33, dtype=np.int32) % 7, (4, 1))
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, jnp.asarray(cyc))
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (name, losses[:3],
                                                        losses[-3:])


def test_optimizer_unknown_refused():
    with pytest.raises(ValueError, match="unknown optimizer"):
        build_optimizer(lr=1e-3, steps=10, optimizer="adam2")
