"""Fault-tolerant serving: the round-11 chaos suite.

Everything here is driven by the deterministic fault injector
(``tpulab/faults.py``) — seeded, schedule-driven fault firings at named
sites in the engine/daemon hot paths — so each failure sequence replays
identically on every run.  Headline properties:

  * the injector is INERT by default: a disabled injector's ``fire`` is
    never even called from the engine hot path (monkeypatch proof), and
    the ``fault_overhead`` bench bounds the enabled-idle upper bound
    under 1% of steady-state ticks/s;
  * a mid-wave engine fault (dispatch exception / NaN-token integrity
    trip / slot-table corruption) is SUPERVISED: the daemon quarantines
    the engine, rebuilds it from its recipe, and replays the in-flight
    requests from their snapshots — greedy streams BIT-IDENTICAL to an
    uninterrupted run, sampled streams resuming their per-slot key
    chain — with a per-request retry budget before the failure
    surfaces;
  * KV-pressure preemption: a strictly-higher-priority head evicts the
    lowest-priority slot (blocks released — no leaks, no double-frees —
    request requeued) and the victim RESUMES from its committed prefix,
    again bit-identically;
  * deadline-aware admission: bounded queues and queue-wait-p99
    shedding reject with a parseable ``shed retry_after_ms=N`` response
    the client helpers honor with backoff;
  * a wedged client (half a frame, then silence) is evicted on the
    frame deadline without stalling other clients;
  * the new counters (``engine_preemptions``, ``daemon_engine_restarts``,
    ``daemon_replays``, ``daemon_shed_requests``) are registered,
    documented, and visible in the Prometheus scrape (lint, the
    tests/test_obs.py pattern).
"""

import importlib.util
import json
import pathlib
import re
import threading
import time

import numpy as np
import pytest

import tpulab.models.paged as paged_mod
from tpulab import faults, obs
from tpulab.faults import InjectedFault
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import (EngineIntegrityError, PagedEngine,
                                 QueueFullError)

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _mk_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("n_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_seq", 64)
    return PagedEngine(params, CFG, **kw)


def _no_leaks(eng):
    """Block-accounting invariant: every usable block is either free or
    held (only) by the prefix cache; nothing is leaked to a dead slot
    and nothing was double-freed (the free list would then exceed the
    pool, or a refcount would have gone negative in _deref's assert)."""
    cache_blocks = {b for blocks in eng.prefix_cache.values()
                    for b in blocks}
    assert len(eng.free) + len(cache_blocks) == eng.n_usable_blocks, (
        len(eng.free), sorted(cache_blocks), eng.n_usable_blocks)
    assert len(set(eng.free)) == len(eng.free), "double-freed block"
    assert all(eng.block_refs[b] == 0 for b in eng.free)


# ------------------------------------------------------------- injector
def test_injector_deterministic_schedule():
    """A rule fires on exact site hit counts — same schedule, same
    firing sequence, every run."""
    with faults.active([{"site": "a", "kind": "raise", "at": 3},
                        {"site": "b", "kind": "slow_ms", "at": 1,
                         "count": 2, "arg": 1.0}], seed=7) as inj:
        assert faults.fire("a") is None
        assert faults.fire("a") is None
        with pytest.raises(InjectedFault, match="site a|at a"):
            faults.fire("a")
        assert faults.fire("a") is None  # count=1: fires exactly once
        r = faults.fire("b")
        assert r is not None and r.kind == "slow_ms"
        assert faults.fire("b") is not None
        assert faults.fire("b") is None
        assert inj.hits("a") == 4 and inj.hits("b") == 3
        assert inj.fired() == {"a": 1, "b": 2}
    # disabled again: inert
    assert faults.fire("a") is None and not faults.ACTIVE


def test_injector_rejects_bad_schedules():
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.configure([{"site": "x", "kind": "explode"}])
    with pytest.raises(ValueError, match="must be >= 1"):
        faults.configure([{"site": "x", "kind": "raise", "at": 0}])


def test_disabled_injector_never_called_from_engine(trained, monkeypatch):
    """The zero-cost-when-disabled claim, made falsifiable: with the
    injector off, the engine hot path must never call ``faults.fire``
    at all (the ACTIVE guard short-circuits before the module call)."""
    def _boom(site):
        raise AssertionError(f"fire({site!r}) called with injector off")

    monkeypatch.setattr(faults, "fire", _boom)
    eng = _mk_engine(trained)
    rid = eng.submit(_cycle_prompt(4), max_new=6)
    out = eng.run()
    assert len(out[rid]) == 6


# ----------------------------------------------------- engine tripwires
def test_tick_dispatch_fault_raises(trained):
    eng = _mk_engine(trained)
    eng.submit(_cycle_prompt(4), max_new=10)
    with faults.active([{"site": "paged.tick", "kind": "raise", "at": 3}]):
        with pytest.raises(InjectedFault):
            eng.run()
        assert faults.INJECTOR.fired() == {"paged.tick": 1}


def test_nan_tokens_trip_integrity_check(trained):
    """The NaN-logits signature: a drained tick carrying out-of-vocab
    tokens raises EngineIntegrityError instead of emitting garbage."""
    eng = _mk_engine(trained)
    eng.submit(_cycle_prompt(4), max_new=10)
    with faults.active([{"site": "paged.drain", "kind": "nan_tokens",
                         "at": 2}]):
        with pytest.raises(EngineIntegrityError, match="out-of-vocab"):
            eng.run()


def test_slot_table_corruption_tripwire(trained):
    """An injected out-of-range table entry is caught by the
    release-time integrity check — a clean EngineIntegrityError, never
    an IndexError or a silent double-free into the pool."""
    eng = _mk_engine(trained)
    eng.submit(_cycle_prompt(4), max_new=4)
    with faults.active([{"site": "paged.step", "kind": "corrupt_table",
                         "at": 2}]):
        with pytest.raises(EngineIntegrityError, match="table corrupt"):
            eng.run()


def test_slow_sync_fault_delays_but_preserves_stream(trained):
    """A slow host sync (kind slow_ms) perturbs timing only: the token
    stream is untouched."""
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=8,
                    temperature=0.0)[0]
    eng = _mk_engine(trained)
    rid = eng.submit(_cycle_prompt(4), max_new=8)
    with faults.active([{"site": "paged.drain", "kind": "slow_ms",
                         "at": 2, "count": 3, "arg": 5.0}]) as inj:
        out = eng.run()
        assert inj.fired() == {"paged.drain": 3}
    assert np.array_equal(out[rid], want)


# ------------------------------------------------ KV-pressure preemption
def test_preempt_resume_greedy_bit_identical_no_leaks(trained):
    """A strictly-higher-priority arrival evicts the lowest-priority
    slot under pool pressure; the victim resumes from its committed
    prefix and BOTH streams match the dense goldens; block accounting
    balances exactly (no leaked or double-freed blocks)."""
    eng = _mk_engine(trained, n_blocks=9)  # 8 usable: can't hold both
    rlo = eng.submit(_cycle_prompt(4), max_new=40, priority=0)  # 6 blocks
    for _ in range(6):
        eng.step()
    rhi = eng.submit(_cycle_prompt(4), max_new=30, priority=5)  # 5 blocks
    out = eng.run()
    st = eng.stats()
    assert st["preemptions"] >= 1
    for rid, steps in ((rlo, 40), (rhi, 30)):
        want = generate(trained, _cycle_prompt(4)[None, :], CFG,
                        steps=steps, temperature=0.0)[0]
        assert np.array_equal(out[rid], want), rid
    _no_leaks(eng)


def test_preempt_resume_sampled_stream_bit_identical(trained):
    """The per-slot key chain survives preemption: the resumed sampled
    stream equals the uninterrupted run of the same seed (the engine
    advances one key split per emitted token; resubmit re-seeds at
    split^len(out) of the original key)."""
    base_eng = _mk_engine(trained)
    rs = base_eng.submit(_cycle_prompt(4), max_new=40, temperature=1.3,
                         seed=7)
    base = base_eng.run()[rs]
    eng = _mk_engine(trained, n_blocks=9)
    rs2 = eng.submit(_cycle_prompt(4), max_new=40, temperature=1.3,
                     seed=7, priority=0)
    for _ in range(8):
        eng.step()
    eng.submit(_cycle_prompt(4), max_new=30, priority=5)
    out = eng.run()
    assert eng.stats()["preemptions"] >= 1
    assert np.array_equal(out[rs2], base)
    _no_leaks(eng)


def test_equal_priority_never_preempts(trained):
    """FIFO arrivals must not evict each other: with equal priorities
    the head simply waits for blocks, exactly the pre-round-11
    behavior."""
    eng = _mk_engine(trained, n_blocks=9)
    r1 = eng.submit(_cycle_prompt(4), max_new=40)
    for _ in range(6):
        eng.step()
    r2 = eng.submit(_cycle_prompt(4), max_new=30)
    out = eng.run()
    assert eng.stats()["preemptions"] == 0
    assert len(out[r1]) == 40 and len(out[r2]) == 30
    _no_leaks(eng)


def test_bounded_queue_raises_queue_full(trained):
    eng = _mk_engine(trained, slots=1, max_pending=1)
    eng.submit(_cycle_prompt(4), max_new=4)
    with pytest.raises(QueueFullError, match="max_pending=1"):
        eng.submit(_cycle_prompt(4), max_new=4)


# ------------------------------------------------------------ supervisor
def _service_with_rebuildable_engine(trained, **eng_kw):
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()

    def mk():
        # every rebuild carries the recipe forward, like _build_engine
        # does for the daemon's real engines — a SECOND crash can
        # rebuild again (until the per-request replay budget runs out)
        e = _mk_engine(trained, **eng_kw)
        e._rebuild = lambda: (mk(), None)
        return e

    return svc, mk()


def test_supervisor_replay_greedy_and_sampled_bit_identical(trained):
    """The tentpole acceptance: an engine crash mid-wave is supervised
    — quarantine, rebuild, replay — and the surviving requests complete
    with greedy streams bit-identical to a fault-free run and sampled
    streams resuming their key chain.  Counters advance."""
    from tpulab.daemon import _C_REPLAYS, _C_RESTARTS

    svc, eng = _service_with_rebuildable_engine(trained)
    r0_restart, r0_replay = _C_RESTARTS.value, _C_REPLAYS.value
    outs = {}

    def run(name, **kw):
        outs[name] = svc.generate(eng, _cycle_prompt(4), 16, **kw)

    with faults.active([{"site": "paged.tick", "kind": "raise", "at": 6}]):
        ts = [threading.Thread(target=run, args=("g",)),
              threading.Thread(target=run, args=("s",),
                               kwargs=dict(temperature=1.3, seed=7))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert faults.INJECTOR.fired() == {"paged.tick": 1}
    want_g = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=16,
                      temperature=0.0)[0]
    clean = _mk_engine(trained)
    rs = clean.submit(_cycle_prompt(4), max_new=16, temperature=1.3, seed=7)
    want_s = clean.run()[rs]
    assert np.array_equal(outs["g"], want_g)
    assert np.array_equal(outs["s"], want_s)
    assert _C_RESTARTS.value == r0_restart + 1
    assert _C_REPLAYS.value >= r0_replay + 1
    st = svc._state_for(eng)
    assert st.engine is not eng, "supervisor must swap in the rebuilt engine"
    _no_leaks(st.engine)


def test_supervisor_integrity_fault_also_replays(trained):
    """EngineIntegrityError (the NaN-token tripwire) rides the same
    supervisor path as a dispatch exception."""
    svc, eng = _service_with_rebuildable_engine(trained)
    with faults.active([{"site": "paged.drain", "kind": "nan_tokens",
                         "at": 3}]):
        out = svc.generate(eng, _cycle_prompt(4), 12)
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)


def test_replay_budget_exhaustion_surfaces_failure(trained):
    """A persistent fault burns the per-request replay budget and then
    SURFACES: the waiter gets a clear error instead of an infinite
    rebuild loop (or a hang)."""
    svc, eng = _service_with_rebuildable_engine(trained)
    with faults.active([{"site": "paged.tick", "kind": "raise",
                         "at": 2, "count": 100000}]):
        with pytest.raises(RuntimeError, match="engine step failed"):
            svc.generate(eng, _cycle_prompt(4), 8)


def test_engine_without_rebuild_recipe_fails_all(trained):
    """Graceful degradation: a directly-constructed engine (no
    ``_rebuild`` recipe) keeps the old fail-every-request behavior —
    waiters still never hang."""
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()
    eng = _mk_engine(trained)
    with faults.active([{"site": "paged.tick", "kind": "raise", "at": 2}]):
        with pytest.raises(RuntimeError, match="engine step failed"):
            svc.generate(eng, _cycle_prompt(4), 8)


def test_cancel_after_quarantine_does_not_leak_into_replay(trained):
    """The satellite regression: a rid cancelled AFTER its engine was
    quarantined (waiter abandoned during the rebuild window) must be
    dropped from the replay set — not replayed for a dead waiter, not
    parked in results forever — and the cancel must route through
    ``st.engine`` so it can never act on the dead object."""
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()
    eng = _mk_engine(trained)
    eng._rebuild = lambda: (_mk_engine(trained), None)
    st = svc._state_for(eng)
    rid = eng.submit(_cycle_prompt(4), max_new=8)
    live_rid = eng.submit(_cycle_prompt(5), max_new=6)
    # the waiter abandoned while the engine was already quarantined:
    # its rid sits in st.cancelled when the supervisor collects the
    # replay set
    st.cancelled.add(rid)
    svc._supervise(eng, st, RuntimeError("boom"))
    new_eng = st.engine
    assert new_eng is not eng
    replayed = [r.req_id for r in new_eng.pending] + [
        r.req_id for r in new_eng.active if r is not None]
    assert rid not in replayed, "cancelled rid leaked into the replay set"
    assert live_rid in replayed
    assert rid not in st.cancelled and rid not in st.results
    # the surviving request still completes through the new stepper
    deadline = time.monotonic() + 60
    with st.cond:
        while live_rid not in st.results and time.monotonic() < deadline:
            st.cond.wait(timeout=1)
        out = st.results.pop(live_rid)
    want = generate(trained, _cycle_prompt(5)[None, :], CFG, steps=6,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    _no_leaks(new_eng)


# ---------------------------------------------------------- load shedding
def test_bounded_queue_sheds_with_retry_after(trained):
    from tpulab.daemon import _GenerateService, ShedError

    svc = _GenerateService()
    eng = _mk_engine(trained, slots=1, max_pending=1)
    svc._state_for(eng)
    eng.submit(_cycle_prompt(4), max_new=4)  # park one pending
    before = obs.REGISTRY.get("daemon_shed_requests").value
    with pytest.raises(ShedError, match=r"shed retry_after_ms=\d+"):
        svc.generate(eng, _cycle_prompt(4), 4)
    assert obs.REGISTRY.get("daemon_shed_requests").value == before + 1


def test_deadline_blown_queue_wait_sheds(trained):
    """Once the observed queue_wait p99 exceeds a request's
    ``deadline_ms`` budget (and there IS a queue), admission rejects
    with retry-after instead of queueing a request that cannot meet its
    deadline."""
    from tpulab.daemon import _GenerateService, ShedError

    svc = _GenerateService()
    eng = _mk_engine(trained, slots=1)
    svc._state_for(eng)
    eng.submit(_cycle_prompt(4), max_new=4)  # queue pressure exists
    h = obs.REGISTRY.get("queue_wait_seconds")
    for _ in range(300):  # force p99 far above any sane deadline
        h.observe(30.0)
    with pytest.raises(ShedError) as ei:
        svc.generate(eng, _cycle_prompt(4), 4, deadline_ms=5.0)
    assert 50 <= ei.value.retry_after_ms <= 5000
    # without a deadline the same request queues normally (no shed):
    # drain the engine so the module-scoped model is left clean
    out = svc.generate(eng, _cycle_prompt(4), 4)
    assert len(out) == 4


def test_handle_generate_validates_deadline_and_priority():
    from tpulab.daemon import _handle_generate

    with pytest.raises(ValueError, match="deadline_ms must be > 0"):
        _handle_generate({"config": {"deadline_ms": -5}}, b"hi")
    with pytest.raises(ValueError):
        _handle_generate({"config": {"priority": "not-an-int"}}, b"hi")


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", ROOT / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    return rep


def test_client_retry_honors_shed_and_backoff(tmp_path):
    """The client-resilience satellite, against a fake daemon socket:
    attempt 1 is refused at connect (daemon restarting), attempt 2 gets
    a shed frame with retry-after, attempt 3 succeeds — all inside one
    request_with_retry call.  No jax, no engine: protocol only."""
    import socket
    import struct

    rep = _load_obs_report()
    path = str(tmp_path / "fake.sock")
    state = {"n": 0}

    def server():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(4)
        while state["n"] < 2:
            conn, _ = srv.accept()
            state["n"] += 1
            # read one full request frame
            hlen = struct.unpack("<I", conn.recv(4))[0]
            conn.recv(hlen)
            plen = struct.unpack("<Q", conn.recv(8))[0]
            if plen:
                conn.recv(plen)
            if state["n"] == 1:
                body = b"shed retry_after_ms=20 (test backpressure)"
                conn.sendall(struct.pack("<BQ", 1, len(body)) + body)
            else:
                conn.sendall(struct.pack("<BQ", 0, 4) + b"done")
            conn.close()
        srv.close()

    t = threading.Thread(target=server, daemon=True)
    # connect-retry leg: the socket does not even exist yet
    result = {}

    def client():
        result["out"] = rep.request_with_retry(
            path, "metrics", deadline_s=30.0)

    c = threading.Thread(target=client, daemon=True)
    c.start()
    time.sleep(0.15)  # let at least one connect attempt fail
    t.start()
    c.join(timeout=30)
    assert result.get("out") == b"done"
    assert state["n"] == 2  # shed once, then served


def test_client_retry_surfaces_shed_past_deadline(tmp_path):
    """A daemon that sheds forever: request_with_retry gives up at its
    deadline with ShedResponse (carrying the hint), not an endless
    loop."""
    import socket
    import struct

    rep = _load_obs_report()
    path = str(tmp_path / "shed.sock")
    stop = threading.Event()

    def server():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(4)
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            hlen = struct.unpack("<I", conn.recv(4))[0]
            conn.recv(hlen)
            plen = struct.unpack("<Q", conn.recv(8))[0]
            if plen:
                conn.recv(plen)
            body = b"shed retry_after_ms=40 (always)"
            conn.sendall(struct.pack("<BQ", 1, len(body)) + body)
            conn.close()
        srv.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    try:
        with pytest.raises(rep.ShedResponse) as ei:
            rep.request_with_retry(path, "metrics", deadline_s=0.3)
        assert ei.value.retry_after_ms == 40
    finally:
        stop.set()
        t.join(timeout=5)


# ------------------------------------------------------- wedged clients
def test_wedged_client_does_not_stall_serving(tmp_path):
    """A client that sends half a frame and goes silent must be evicted
    on the frame deadline while OTHER clients keep being served — the
    live daemon subprocess case (real sockets, real handler threads)."""
    import os
    import subprocess
    import sys

    rep = _load_obs_report()
    sock = str(tmp_path / "wedge.sock")
    env = dict(os.environ, TPULAB_DAEMON_RECV_TIMEOUT_S="2")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock,
         "--max-requests", "2"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        for _ in range(600):
            if pathlib.Path(sock).exists():
                break
            time.sleep(0.1)
        else:
            raise AssertionError("daemon socket never appeared")
        w = faults.open_wedged_client(sock)
        # the wedged connection holds a handler slot; a normal request
        # must still complete promptly (metrics touches no engine)
        out = rep.request_with_retry(sock, "metrics", deadline_s=60.0)
        assert b"daemon_shed_requests" in out
        w.close()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


# ------------------------------------------------------ chaos acceptance
def test_chaos_schedule_end_to_end(trained):
    """The ISSUE acceptance scenario in one seeded schedule: an engine
    crash mid-wave PLUS KV-pool exhaustion (priority preemption) on a
    small pool, concurrent requests riding through both.  Every
    surviving request completes with its greedy stream bit-identical to
    a fault-free run, the pool balances to zero leaked blocks, and the
    restart/preemption/shed counters are visible in the Prometheus
    scrape."""
    from tpulab import daemon as daemon_mod
    from tpulab.daemon import ShedError, handle_request

    svc, eng = _service_with_rebuildable_engine(
        trained, n_blocks=9, max_pending=2)
    outs, errs = {}, {}

    def run(name, prompt_len, steps, **kw):
        try:
            outs[name] = svc.generate(eng, _cycle_prompt(prompt_len),
                                      steps, **kw)
        except Exception as e:  # noqa: BLE001 — recorded for assertion
            errs[name] = e

    # phase 1 — engine crash mid-wave with two concurrent riders: the
    # supervisor quarantines, rebuilds, and replays both
    with faults.active([{"site": "paged.tick", "kind": "raise", "at": 6}],
                       seed=11):
        ts = [threading.Thread(target=run, args=("a", 4, 16)),
              threading.Thread(target=run, args=("b", 5, 12))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        fired = faults.INJECTOR.fired()
    assert not errs, errs
    assert fired.get("paged.tick") == 1, fired
    st = svc._state_for(eng)
    final = st.engine
    assert final is not eng
    # phase 2 — KV-pool exhaustion on the REBUILT engine: a
    # higher-priority arrival preempts the low-priority long request
    adm0 = final.stats()["admissions"]  # the phase-1 replays admitted here
    t_lo = threading.Thread(target=run, args=("lo", 4, 40))   # 6 blocks
    t_lo.start()
    deadline = time.monotonic() + 60
    while (final.stats()["admissions"] < adm0 + 1
           and time.monotonic() < deadline):
        time.sleep(0.01)  # wait until the victim actually holds blocks
    t_hi = threading.Thread(target=run, args=("hi", 4, 30),
                            kwargs=dict(priority=5))          # 5 blocks
    t_hi.start()
    t_lo.join(timeout=120)
    t_hi.join(timeout=120)
    assert not errs, errs
    assert final.stats()["preemptions"] >= 1
    for name, plen, steps in (("a", 4, 16), ("b", 5, 12),
                              ("lo", 4, 40), ("hi", 4, 30)):
        want = generate(trained, _cycle_prompt(plen)[None, :], CFG,
                        steps=steps, temperature=0.0)[0]
        assert np.array_equal(outs[name], want), name
    _no_leaks(final)
    # shed on the bounded queue still enforced on the REBUILT engine
    with st.cond:
        final.submit(_cycle_prompt(4), max_new=2)
        final.submit(_cycle_prompt(4), max_new=2)
    with pytest.raises(ShedError):
        svc.generate(final, _cycle_prompt(4), 2)
    with st.cond:  # unpark the probe submissions
        final.pending.clear()
    # counters visible in the Prometheus scrape (the daemon's metrics
    # request over the warm engine)
    key = (None, "gather", "native", 1, -11, "")
    daemon_mod._ENGINES[key] = (None, final, None)
    try:
        text = handle_request({"lab": "metrics"}, b"").decode("utf-8")
    finally:
        daemon_mod._ENGINES.pop(key, None)
    for pat in (r"^engine_preemptions [1-9]\d*", r"^daemon_engine_restarts [1-9]\d*",
                r"^daemon_replays [1-9]\d*", r"^daemon_shed_requests [1-9]\d*"):
        assert re.search(pat, text, re.M), pat


# ------------------------------------------------- handoff chaos (r20)
def test_handoff_crash_replays_from_journaled_prompt(trained):
    """The ``daemon.handoff`` site (round 20): a crash between the
    prefill engine's KV export and the decode-side admit loses the
    payload at its most exposed moment — exported (prefill blocks
    already released) but not yet imported.  The supervisor replays
    the request from the prompt the ticket still journals, re-entering
    through the PREFILL pool like any migration: the retry prefills,
    parks at the boundary, and hands off cleanly (the ``at=1`` rule is
    spent).  Greedy stream bit-identical to unified serving, the
    replay charged like a replica failure, zero leaked blocks on
    either pool."""
    import tpulab.daemon as daemon_mod
    from tpulab import router

    svc = daemon_mod._FleetService()
    prompt = _cycle_prompt(20)

    def builder():
        return _mk_engine(trained, prefix_index="radix",
                          spill_blocks=16), None

    unified = daemon_mod._make_fleet(builder, 1)
    want = svc.generate(unified, prompt, 12)

    pooled = daemon_mod._make_fleet(
        builder, 0, pools=[("prefill", 1, 1), ("decode", 1, 1)])
    h0 = daemon_mod._C_HANDOFFS.value
    m0 = obs.REGISTRY.get("daemon_migrations").value
    with faults.active([{"site": "daemon.handoff", "kind": "raise",
                         "at": 1}]) as inj:
        got = svc.generate(pooled, prompt, 12)
        assert inj.fired().get("daemon.handoff") == 1
    assert np.array_equal(want, got)
    # the crashed attempt is charged as a migration (the journaled-
    # prompt replay path); the RETRY's boundary handoff then lands
    assert obs.REGISTRY.get("daemon_migrations").value == m0 + 1
    assert daemon_mod._C_HANDOFFS.value == h0 + 1
    for r in pooled.replicas:
        with r.cond:
            assert not r.dead
            eng = r.engine
            cached = set(eng._radix.blocks())
            assert (len(eng.free) + len(cached)
                    == eng.n_usable_blocks), (
                r.role, len(eng.free), sorted(cached))
            assert all(eng.block_refs[b] == 0 for b in eng.free)
            if r.role == router.ROLE_DECODE:
                assert eng.counters["requests_done"] == 1


# ------------------------------------------------------------------ lint
def test_fault_counters_registered_and_documented():
    """The round-11 lint (tests/test_obs.py pattern): every new
    fault-tolerance counter is a registered metric AND has a docs
    entry.  (``engine_preemptions`` additionally rides the existing
    stats()-key lint in test_obs.)"""
    import tpulab.daemon  # noqa: F401 — registers the counters

    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("daemon_engine_restarts", "daemon_replays",
                 "daemon_shed_requests",
                 # round 20: the disaggregated-serving surface
                 "daemon_handoffs", "handoff_bytes",
                 "pool_prefill_replicas", "pool_prefill_target",
                 "pool_decode_replicas", "pool_decode_target"):
        assert obs.REGISTRY.get(name) is not None, name
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"
    assert "engine_preemptions" in docs


def test_relay_lib_is_the_one_wait_relay():
    """The dedup satellite: every on-chip queue script sources
    tools/relay_lib.sh and none carries its own wait_relay copy."""
    lib = ROOT / "tools" / "relay_lib.sh"
    assert lib.exists() and "wait_relay()" in lib.read_text()
    for sh in sorted(ROOT.glob("tools/onchip_queue*.sh")):
        text = sh.read_text()
        assert "relay_lib.sh" in text, f"{sh.name} does not source relay_lib"
        assert "wait_relay()" not in text, f"{sh.name} still defines wait_relay"


def test_bench_registry_has_fault_overhead():
    from tpulab.bench import bench_fault_overhead  # noqa: F401

    baselines = json.loads(
        (ROOT / "results" / "baselines.json").read_text())
    row = baselines["baselines"]["fault_overhead_4slots_ticks_per_s"]
    assert row["direction"] == "higher" and row["value"] > 0


@pytest.mark.slow
def test_fault_overhead_bench_under_budget():
    """The fault_overhead microbench: runs the real A/B windows and
    asserts the <1% budget internally (wall-clock sensitive — slow
    tier; the committed baselines.json row gates the CPU-proxy number
    round over round)."""
    from tpulab.bench import bench_fault_overhead

    row = bench_fault_overhead(reps=2)
    assert row["metric"] == "fault_overhead_4slots_ticks_per_s"
    assert row["value"] > 0 and row["enabled_idle_ticks_per_s"] > 0
    assert "overhead_pct_best" in row
