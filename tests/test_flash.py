"""Pallas flash attention vs the dense reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpulab.ops.pallas.attention import flash_attention
from tpulab.parallel.ring import attention_reference


def _qkv(rng, b=2, s=128, h=4, d=32):
    shape = (b, s, h, d)
    mk = lambda: jnp.asarray(rng.standard_normal(shape), jnp.float32)
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("s,bq,bk", [(128, 64, 64), (128, 128, 128), (256, 64, 128)])
    def test_causal_matches_reference(self, rng, s, bq, bk):
        q, k, v = _qkv(rng, s=s)
        got = np.asarray(flash_attention(q, k, v, block_q=bq, block_k=bk))
        want = np.asarray(attention_reference(q, k, v, causal=True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_noncausal_matches_reference(self, rng):
        q, k, v = _qkv(rng, s=128)
        got = np.asarray(flash_attention(q, k, v, causal=False, block_q=64, block_k=64))
        want = np.asarray(attention_reference(q, k, v, causal=False))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_ragged_seq_causal(self, rng):
        """seq not divisible by the block: padded path, causal."""
        q, k, v = _qkv(rng, s=100)
        got = np.asarray(flash_attention(q, k, v, block_q=64, block_k=64))
        want = np.asarray(attention_reference(q, k, v, causal=True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_ragged_seq_unequal_blocks(self, rng):
        """Padding must reach a multiple of BOTH blocks (lcm, not max):
        s=20 with bq=16, bk=12 pads to 48 — a max-based pad (32) would
        leave trailing K rows unprocessed with no error."""
        q, k, v = _qkv(rng, s=20)
        got = np.asarray(flash_attention(q, k, v, block_q=16, block_k=12))
        want = np.asarray(attention_reference(q, k, v, causal=True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_noncausal_ragged_raises(self, rng):
        q, k, v = _qkv(rng, s=100)
        with pytest.raises(NotImplementedError):
            flash_attention(q, k, v, causal=False, block_q=64, block_k=64)

    def test_bf16_io(self, rng):
        q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(rng, s=128))
        got = flash_attention(q, k, v, block_q=64, block_k=64)
        assert got.dtype == jnp.bfloat16
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=0.1, atol=0.1
        )


class TestFlashBackward:
    """custom_vjp gradients vs jax.grad through the dense reference."""

    def _grads(self, fn, q, k, v, tgt):
        import jax

        loss = lambda q, k, v: jnp.sum((fn(q, k, v) - tgt) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize(
        "s,bq,bk,causal",
        [
            (128, 64, 64, True),
            (128, 64, 64, False),
            (128, 32, 64, True),   # unequal blocks
            (100, 64, 64, True),   # padded seq: pad rows must not leak grad
        ],
    )
    def test_grads_match_reference(self, rng, s, bq, bk, causal):
        q, k, v = _qkv(rng, s=s)
        tgt = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
        flash = lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk
        )
        dense = lambda q, k, v: attention_reference(q, k, v, causal=causal)
        got = self._grads(flash, q, k, v, tgt)
        want = self._grads(dense, q, k, v, tgt)
        for g, w, name in zip(got, want, "q k v".split()):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_bwd_block_cap_preserves_divisibility(self):
        from tpulab.ops.pallas.attention import _bwd_block

        assert _bwd_block(1024) == 512
        assert _bwd_block(768) == 384   # halving, not clamping to 512
        assert _bwd_block(96) == 96
        for b in (1024, 768, 512, 96, 24):
            assert b % _bwd_block(b) == 0


class TestIndependentBackwardBlocks:
    """bwd_block_q/bwd_block_k tile the backward kernels independently
    of the forward (0 = inherit + VMEM halving); gradients must be
    invariant to the tiling choice."""

    def test_grads_match_inherited_blocks(self, rng):
        import jax

        q, k, v = _qkv(rng, s=128)
        tgt = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

        def grads(**kw):
            fn = lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64, **kw)
            loss = lambda q, k, v: jnp.sum((fn(q, k, v) - tgt) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        base = grads()
        for bq, bk in [(32, 32), (32, 64), (128, 32)]:
            got = grads(bwd_block_q=bq, bwd_block_k=bk)
            for g, w, name in zip(got, base, "q k v".split()):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-5,
                    err_msg=f"d{name} mismatch at bwd blocks ({bq},{bk})")

    def test_indivisible_bwd_blocks_raise(self, rng):
        import jax

        q, k, v = _qkv(rng, s=128)
        fn = lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64, bwd_block_q=48)
        loss = lambda q: jnp.sum(fn(q, k, v) ** 2)
        with pytest.raises(ValueError, match="divisible"):
            jax.grad(loss)(q)


class TestSlidingWindow:
    """window > 0: each query sees its `window` most recent keys only."""

    def _dense_window(self, q, k, v, window):
        import jax

        s = q.shape[1]
        d = q.shape[-1]
        scale = 1.0 / np.sqrt(d)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qpos = np.arange(s)[:, None]
        kpos = np.arange(s)[None, :]
        keep = (kpos <= qpos) & (kpos > qpos - window)
        logits = jnp.where(keep[None, None], logits, -jnp.inf)
        w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)

    @pytest.mark.parametrize("window,bq,bk", [
        (64, 64, 64),     # window == block: interior blocks fully visible
        (100, 64, 32),    # window not a block multiple: both edges masked
        (17, 32, 32),     # window << block: single diagonal-straddling band
        (256, 128, 64),   # window == seq: must equal full causal
    ])
    def test_forward_matches_dense_window(self, rng, window, bq, bk):
        q, k, v = _qkv(rng, s=256)
        got = np.asarray(flash_attention(
            q, k, v, causal=True, window=window, block_q=bq, block_k=bk))
        want = np.asarray(self._dense_window(q, k, v, window))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_window_seq_equals_full_causal(self, rng):
        q, k, v = _qkv(rng, s=128)
        got = np.asarray(flash_attention(
            q, k, v, causal=True, window=128, block_q=64, block_k=64))
        want = np.asarray(flash_attention(q, k, v, causal=True,
                                          block_q=64, block_k=64))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_grads_match_dense_window(self, rng):
        import jax

        q, k, v = _qkv(rng, s=128)
        f = lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=48, block_q=32, block_k=32).sum()
        fr = lambda q, k, v: self._dense_window(q, k, v, 48).sum()
        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=3e-5, atol=3e-5)

    def test_reference_oracle_agrees(self, rng):
        """attention_reference(window=...) is the model tier's dense
        window path — it must match the kernel too."""
        q, k, v = _qkv(rng, s=128)
        got = np.asarray(attention_reference(q, k, v, causal=True, window=32))
        want = np.asarray(self._dense_window(q, k, v, 32))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_noncausal_window_raises(self, rng):
        q, k, v = _qkv(rng, s=128)
        with pytest.raises(NotImplementedError):
            flash_attention(q, k, v, causal=False, window=32,
                            block_q=64, block_k=64)


class TestBlockEdgePredicates:
    """_block_edges gates which (qb, kb) blocks the fwd AND both bwd
    kernels compute/mask: a wrong ``active`` silently ZEROES real
    contributions (no crash), a wrong ``edge`` skips the positional
    mask.  Brute-force the predicates against the kernels' own mask
    condition across window/offset/block geometries."""

    def test_predicates_match_mask_brute_force(self):
        from tpulab.ops.pallas.attention import _block_edges

        for bq, bk in ((8, 8), (8, 16), (16, 8)):
            for s_q, s_k in ((32, 32), (16, 32)):
                for window in (0, 1, 5, 8, 17, 64):
                    for q_offset in (0, 8, 32, 48):
                        for qb in range(s_q // bq):
                            for kb in range(s_k // bk):
                                keep = [
                                    (k_pos <= q_pos)
                                    and (not window
                                         or k_pos > q_pos - window)
                                    for i in range(bq)
                                    for j in range(bk)
                                    for q_pos in [q_offset + qb * bq + i]
                                    for k_pos in [kb * bk + j]
                                ]
                                active, edge = _block_edges(
                                    qb, kb, bq, bk, window, q_offset)
                                want_active = any(keep)
                                want_fully_visible = all(keep)
                                # active must never UNDER-approximate
                                # (dropping a live block loses weight);
                                # over-approximation is mere waste
                                if want_active:
                                    assert bool(active), (
                                        bq, bk, window, q_offset, qb, kb)
                                # a block the kernel treats as fully
                                # visible (active and not edge) must
                                # truly have every position visible
                                if bool(active) and not bool(edge):
                                    assert want_fully_visible, (
                                        bq, bk, window, q_offset, qb, kb)
