"""Replicated serving fleet: the round-13 router/migration suite.

The daemon now serves each warm config from ``--replicas N``
PagedEngine replicas behind a router (policy in ``tpulab/router.py``,
mechanics in ``tpulab/daemon.py._FleetService``).  Headline
properties certified here:

  * placement is least-loaded + prefix-affinity over health-checked
    replicas (HEALTHY -> SUSPECT on slow/stalled ticks -> QUARANTINED
    on crash -> REBUILDING -> HEALTHY), policy unit-tested without an
    engine;
  * a replica failure MIGRATES its in-flight requests to a healthy
    peer (``PagedEngine.resubmit(fresh_id=True)``) — greedy streams
    BIT-IDENTICAL to a fault-free run, sampled streams resuming their
    per-slot key chain, exact block accounting on both sides — while
    the failed replica rebuilds in the background and rejoins;
  * the replay budget (``TPULAB_DAEMON_REPLAY_BUDGET``) is charged
    per migration: a request bounced around a failing fleet surfaces
    its failure at the same budget, never loops;
  * a rid cancelled during a migration window is dropped from the
    replay set (the round-11 cancel-after-quarantine regression,
    generalized to the fleet);
  * hot drain: placement stops, the replica quiesces, rebuilds, and
    returns on undrain — composing into a zero-shed rolling restart
    under steady load;
  * hedged retries: a straggler with no first token inside its hedge
    budget is duplicated on a second replica, first token wins, the
    loser is cancelled with its blocks released;
  * fleet chaos schedules target individual replicas by scoped site
    (``paged.tick@replica1``) deterministically;
  * observability: ``engine_*_replica<i>`` per-replica gauge
    breakdown next to the process-wide sums, the router counters
    (``daemon_migrations`` / ``daemon_hedges`` / ``daemon_hedge_wins``
    / ``daemon_drains``) registered + documented, and slow-log
    entries carrying their replica hops / first-token replica /
    migration count.
"""

import json
import pathlib
import re
import threading
import time

import numpy as np
import pytest

import tpulab.daemon as daemon_mod
from tpulab import faults, obs, router
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _mk_engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("n_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_seq", 64)
    return PagedEngine(params, CFG, **kw)


def _mk_fleet(params, n, **eng_kw):
    def builder():
        return _mk_engine(params, **eng_kw), None

    return daemon_mod._make_fleet(builder, n)


def _no_leaks(eng):
    cache_blocks = {b for blocks in eng.prefix_cache.values()
                    for b in blocks}
    assert len(eng.free) + len(cache_blocks) == eng.n_usable_blocks, (
        len(eng.free), sorted(cache_blocks), eng.n_usable_blocks)
    assert len(set(eng.free)) == len(eng.free), "double-freed block"
    assert all(eng.block_refs[b] == 0 for b in eng.free)


def _fleet_quiesce(fleet, timeout=60):
    """Wait until every replica is idle, alive, and healthy-or-suspect
    (background rebuilds finished) — keeps module-scoped params clean
    between tests."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        busy = False
        for r in fleet.replicas:
            with r.cond:
                eng = r.engine
                if (r.dead or r.stepper_alive or eng.pending
                        or eng.inflight_depth
                        or any(a is not None for a in eng.active)):
                    busy = True
            with fleet.cv:
                if r.health.state in (router.QUARANTINED,
                                      router.REBUILDING):
                    busy = True
        if not busy:
            return
        time.sleep(0.02)
    raise AssertionError("fleet never quiesced")


# ------------------------------------------------------------ router units
def test_health_state_machine_transitions():
    h = router.ReplicaHealth(slow_tick_s=0.1, suspect_after=2,
                             recover_after=3)
    assert h.state == router.HEALTHY and h.placeable
    h.note_tick(0.01)
    h.note_tick(0.5)           # one slow tick: not yet suspect
    assert h.state == router.HEALTHY
    h.note_tick(0.5)           # second consecutive: SUSPECT
    assert h.state == router.SUSPECT and h.placeable
    assert h.suspects == 1
    h.note_tick(0.01)
    h.note_tick(0.01)
    assert h.state == router.SUSPECT  # hysteresis: 2 of 3 fast ticks
    h.note_tick(0.01)
    assert h.state == router.HEALTHY
    # stalled ticks count as slow evidence regardless of duration
    h.note_tick(0.01, stalled=True)
    h.note_tick(0.01, stalled=True)
    assert h.state == router.SUSPECT
    # crash wins from any state; only the rebuild lifecycle leaves it
    h.note_crash()
    assert h.state == router.QUARANTINED and not h.placeable
    assert h.crashes == 1
    h.note_tick(0.01)          # trailing ticks prove nothing
    assert h.state == router.QUARANTINED
    h.note_rebuild_start()
    assert h.state == router.REBUILDING and not h.placeable
    h.note_rebuild_failed()
    assert h.state == router.QUARANTINED
    h.note_rebuild_start()
    h.note_rebuilt()
    assert h.state == router.HEALTHY and h.placeable


def test_choose_replica_scoring():
    V = router.ReplicaView
    # least-loaded wins among healthy equals
    assert router.choose_replica(
        [V(0, True, False, 3), V(1, True, False, 1)]) == 1
    # prefix affinity outweighs load at the documented 2-blocks-per-
    # request exchange rate
    assert router.choose_replica(
        [V(0, True, False, 2, affinity=2), V(1, True, False, 0)]) == 0
    # SUSPECT is strictly deprioritized even when less loaded...
    assert router.choose_replica(
        [V(0, True, True, 0), V(1, True, False, 5)]) == 1
    # ...but still serves when it is the only placeable replica
    assert router.choose_replica(
        [V(0, True, True, 0), V(1, False, False, 0)]) == 0
    # unplaceable excluded entirely; empty -> None
    assert router.choose_replica([V(0, False, False, 0)]) is None
    assert router.choose_replica([]) is None
    # deterministic tie-break: lowest index
    assert router.choose_replica(
        [V(1, True, False, 0), V(0, True, False, 0)]) == 0


def test_scoped_fault_sites_are_per_replica_deterministic():
    """A rule written ``site@scope`` counts hits on the scope's OWN
    counter — replica interleaving cannot perturb it — while bare
    rules keep the global count."""
    with faults.active([{"site": "s@replica1", "kind": "raise", "at": 2},
                        {"site": "s", "kind": "slow_ms", "at": 5,
                         "arg": 0.0}]) as inj:
        assert faults.fire("s", "replica0") is None
        assert faults.fire("s", "replica1") is None   # replica1 hit 1
        assert faults.fire("s", "replica0") is None
        with pytest.raises(faults.InjectedFault):
            faults.fire("s", "replica1")              # replica1 hit 2
        # the bare rule fires on the GLOBAL 5th hit of the site
        r = faults.fire("s", "replica0")
        assert r is not None and r.kind == "slow_ms"
        assert inj.hits("s") == 5
        assert inj.hits("s@replica1") == 2
        assert inj.fired() == {"s@replica1": 1, "s": 1}


# ------------------------------------------------------------- placement
def test_placement_least_loaded_and_prefix_affinity(trained):
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    prompt = _cycle_prompt(20)
    # warm the prefix on replica 0 (idle fleet ties break to index 0)
    out = svc.generate(fleet, prompt, 4)
    assert len(out) == 4
    _fleet_quiesce(fleet)
    # occupy replica 0 so pure least-loaded would pick replica 1...
    hold = {}
    t = threading.Thread(
        target=lambda: hold.setdefault(
            "out", svc.generate(fleet, _cycle_prompt(5), 40)))
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with fleet.replicas[0].cond:
            eng = fleet.replicas[0].engine
            if any(a is not None for a in eng.active):
                break
        time.sleep(0.01)
    # ...a fresh unrelated prompt (no shared prefix anywhere) goes to
    # the idle replica 1
    other = (np.arange(30) % 5 + 1).astype(np.int32)
    assert svc._place(fleet, other).index == 1
    # but the CACHED-prefix prompt still routes to replica 0: two
    # resident shared blocks outweigh one active request of load
    assert svc._place(fleet, prompt).index == 0
    t.join(timeout=60)
    assert len(hold["out"]) == 40
    _fleet_quiesce(fleet)


# ------------------------------------------------------------- migration
def test_migration_greedy_bit_identical_no_leaks(trained):
    """The tentpole: replica0 crashes mid-wave; its request resumes on
    replica1 with the greedy stream bit-identical to a fault-free run,
    blocks balance on BOTH engines, and the crashed replica rebuilds
    and rejoins."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    m0 = daemon_mod._C_MIGRATIONS.value
    with faults.active([{"site": "paged.tick@replica0", "kind": "raise",
                         "at": 6}]):
        out = svc.generate(fleet, _cycle_prompt(4), 16)
        assert faults.INJECTOR.fired() == {"paged.tick@replica0": 1}
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=16,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    assert daemon_mod._C_MIGRATIONS.value == m0 + 1
    _fleet_quiesce(fleet)
    st = svc.fleet_status(fleet)
    assert st["replica"][0]["health"] == "healthy"
    assert st["replica"][0]["generation"] == 1   # rebuilt and rejoined
    assert st["replica"][0]["restarts"] == 1
    for r in fleet.replicas:
        with r.cond:
            _no_leaks(r.engine)


def test_migration_sampled_stream_resumes_key_chain(trained):
    base = _mk_engine(trained)
    rs = base.submit(_cycle_prompt(4), max_new=16, temperature=1.3, seed=7)
    want = base.run()[rs]
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    with faults.active([{"site": "paged.tick@replica0", "kind": "raise",
                         "at": 6}]):
        out = svc.generate(fleet, _cycle_prompt(4), 16, temperature=1.3,
                           seed=7)
    assert np.array_equal(out, want)
    _fleet_quiesce(fleet)


def test_replay_budget_charged_across_migrations(trained, monkeypatch):
    """A request migrated twice then crashed again surfaces failure at
    the SAME TPULAB_DAEMON_REPLAY_BUDGET — bounced around a failing
    fleet, it never loops."""
    monkeypatch.setattr(daemon_mod, "REPLAY_BUDGET", 2)
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    t0 = time.monotonic()
    with faults.active([{"site": "paged.tick", "kind": "raise",
                         "at": 2, "count": 10 ** 6}]):
        with pytest.raises(RuntimeError, match="engine step failed"):
            svc.generate(fleet, _cycle_prompt(4), 8)
    assert time.monotonic() - t0 < 120  # surfaced, not looping
    _fleet_quiesce(fleet)


def test_cancel_during_migration_not_replayed(trained):
    """The round-11 cancel-after-quarantine regression, fleet form: a
    ticket cancelled while its replica is being harvested must NOT be
    resubmitted on the peer — and the live rider must migrate and
    complete normally."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    r0 = fleet.replicas[0]
    with r0.cond:
        eng = r0.engine
        eng.submit(_cycle_prompt(4), max_new=8)
        dead_tkt = daemon_mod._Ticket(eng.pending[-1], r0)
        r0.tickets[dead_tkt.req.req_id] = dead_tkt
        eng.submit(_cycle_prompt(5), max_new=6)
        live_tkt = daemon_mod._Ticket(eng.pending[-1], r0)
        r0.tickets[live_tkt.req.req_id] = live_tkt
    with fleet.cv:
        dead_tkt.cancelled = True   # waiter abandoned pre-harvest
    svc._fail_replica(r0, eng, RuntimeError("boom"))
    r1 = fleet.replicas[1]
    with r1.cond:
        replayed = [r.rid for r in r1.engine.pending] + [
            r.rid for r in r1.engine.active if r is not None]
        assert dead_tkt.req.rid not in replayed, (
            "cancelled rid leaked into the migration set")
        assert live_tkt.req.rid in replayed
    deadline = time.monotonic() + 60
    with fleet.cv:
        while not live_tkt.done and time.monotonic() < deadline:
            fleet.cv.wait(timeout=1)
        assert live_tkt.done
        out = live_tkt.result
    want = generate(trained, _cycle_prompt(5)[None, :], CFG, steps=6,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    assert not dead_tkt.done
    _fleet_quiesce(fleet)
    with r1.cond:
        _no_leaks(r1.engine)


# ------------------------------------------------------------ drain / roll
def test_drain_rebuilds_and_placement_avoids(trained):
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    d0 = daemon_mod._C_DRAINS.value
    out = svc.generate(fleet, _cycle_prompt(4), 4)
    assert len(out) == 4
    _fleet_quiesce(fleet)
    row = svc.drain(fleet, 0)
    assert row["draining"]
    assert daemon_mod._C_DRAINS.value == d0 + 1
    svc.drain(fleet, 0)  # idempotent: counted once per drain edge
    assert daemon_mod._C_DRAINS.value == d0 + 1
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        row = svc.replica_status(fleet.replicas[0])
        if row["generation"] >= 1 and row["health"] == "healthy":
            break
        time.sleep(0.02)
    assert row["generation"] == 1, row   # quiesced -> rebuilt
    # placement excludes the drained replica even though it is healthy
    for _ in range(3):
        assert svc._place(fleet, _cycle_prompt(6)).index == 1
    out = svc.generate(fleet, _cycle_prompt(6), 4)
    assert len(out) == 4
    svc.undrain(fleet, 0)
    assert svc._place(fleet, _cycle_prompt(9)).index == 0  # least-loaded
    _fleet_quiesce(fleet)


def test_rolling_restart_under_load_zero_shed(trained):
    """The acceptance scenario in-process: steady load across a
    2-replica fleet while each replica in turn is drained, rebuilt,
    and undrained — every request completes, none sheds or parks."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    stop = threading.Event()
    errors = []
    done = [0]
    lock = threading.Lock()

    def loader():
        while not stop.is_set():
            try:
                out = svc.generate(fleet, _cycle_prompt(4), 4)
                assert len(out) == 4
                with lock:
                    done[0] += 1
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(e)
                return

    threads = [threading.Thread(target=loader) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(2):
            base = svc.replica_status(fleet.replicas[i])["generation"]
            svc.drain(fleet, i)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                row = svc.replica_status(fleet.replicas[i])
                if row["generation"] > base and row["health"] == "healthy":
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(f"replica{i} never rebuilt")
            svc.undrain(fleet, i)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    assert done[0] > 0
    for i in range(2):
        assert svc.replica_status(fleet.replicas[i])["generation"] >= 1
    _fleet_quiesce(fleet)
    for r in fleet.replicas:
        with r.cond:
            _no_leaks(r.engine)


# --------------------------------------------------------------- hedging
def test_hedge_first_token_wins_loser_cancelled(trained):
    """Replica0's drains are wedged; the hedge fires onto replica1,
    wins the first-token race (greedy stream identical), the loser is
    cancelled, and block accounting balances on both replicas."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    h0 = daemon_mod._C_HEDGES.value
    w0 = daemon_mod._C_HEDGE_WINS.value
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=8,
                    temperature=0.0)[0]
    with faults.active([{"site": "paged.drain@replica0",
                         "kind": "slow_ms", "at": 1, "count": 80,
                         "arg": 200.0}]):
        out = svc.generate(fleet, _cycle_prompt(4), 8, hedge_ms=100.0)
    assert np.array_equal(out, want)
    assert daemon_mod._C_HEDGES.value == h0 + 1
    assert daemon_mod._C_HEDGE_WINS.value == w0 + 1
    _fleet_quiesce(fleet)
    for r in fleet.replicas:
        with r.cond:
            _no_leaks(r.engine)


def test_hedge_not_fired_when_primary_is_prompt(trained):
    """A healthy primary that answers inside the budget never hedges
    (the duplicate would only waste a slot)."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    h0 = daemon_mod._C_HEDGES.value
    out = svc.generate(fleet, _cycle_prompt(4), 8, hedge_ms=5000.0)
    assert len(out) == 8
    assert daemon_mod._C_HEDGES.value == h0
    _fleet_quiesce(fleet)


# ----------------------------------------------------------- park / retry
def test_whole_fleet_drained_parks_then_rebuilding_frame(trained,
                                                         monkeypatch):
    """Every replica draining: submits park briefly, then surface the
    parseable ``rebuilding retry_after_ms=N`` frame (NOT a shed — and
    not counted as one)."""
    monkeypatch.setattr(daemon_mod, "REBUILD_PARK_S", 0.4)
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 1)
    svc.generate(fleet, _cycle_prompt(4), 2)
    _fleet_quiesce(fleet)
    svc.drain(fleet, 0)
    shed0 = obs.REGISTRY.get("daemon_shed_requests").value
    with pytest.raises(daemon_mod.RebuildingError,
                       match=r"rebuilding retry_after_ms=\d+"):
        svc.generate(fleet, _cycle_prompt(4), 2)
    assert obs.REGISTRY.get("daemon_shed_requests").value == shed0
    svc.undrain(fleet, 0)
    out = svc.generate(fleet, _cycle_prompt(4), 2)  # serves again
    assert len(out) == 2
    _fleet_quiesce(fleet)


def test_client_retry_honors_rebuilding_park(tmp_path):
    """The obs_report satellite, protocol-only: a ``rebuilding
    retry_after_ms=N`` error frame is retried with the same backoff
    contract as shed — the capture survives a rolling restart."""
    import importlib.util
    import socket
    import struct

    spec = importlib.util.spec_from_file_location(
        "obs_report", ROOT / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    path = str(tmp_path / "park.sock")
    state = {"n": 0}

    def server():
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(4)
        while state["n"] < 2:
            conn, _ = srv.accept()
            state["n"] += 1
            hlen = struct.unpack("<I", conn.recv(4))[0]
            conn.recv(hlen)
            plen = struct.unpack("<Q", conn.recv(8))[0]
            if plen:
                conn.recv(plen)
            if state["n"] == 1:
                body = b"rebuilding retry_after_ms=20 (rolling restart)"
                conn.sendall(struct.pack("<BQ", 1, len(body)) + body)
            else:
                conn.sendall(struct.pack("<BQ", 0, 4) + b"done")
            conn.close()
        srv.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    out = rep.request_with_retry(path, "metrics", deadline_s=30.0)
    assert out == b"done"
    assert state["n"] == 2  # parked once, then served


# --------------------------------------------------------- observability
def test_metrics_per_replica_breakdown(trained):
    """The scrape carries engine_*_replica<i> gauges NEXT TO the
    process-wide sums — one sick replica stays visible — and zeroes
    them once the fleet is gone."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    svc.generate(fleet, _cycle_prompt(4), 3)
    _fleet_quiesce(fleet)
    # route one request to each replica so both gauges are non-trivial
    hold = {}
    t = threading.Thread(target=lambda: hold.setdefault(
        "out", svc.generate(fleet, _cycle_prompt(5), 30)))
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with fleet.replicas[0].cond:
            if any(a is not None
                   for a in fleet.replicas[0].engine.active):
                break
        time.sleep(0.01)
    svc.generate(fleet, _cycle_prompt(9), 3)
    t.join(timeout=60)
    _fleet_quiesce(fleet)
    key = (None, "gather", "native", 1, -13, "")
    daemon_mod._FLEETS[key] = (None, fleet)
    try:
        text = daemon_mod.handle_request(
            {"lab": "metrics"}, b"").decode("utf-8")
    finally:
        daemon_mod._FLEETS.pop(key, None)
    m_sum = re.search(r"^engine_tokens_out (\d+)$", text, re.M)
    m_r0 = re.search(r"^engine_tokens_out_replica0 (\d+)$", text, re.M)
    m_r1 = re.search(r"^engine_tokens_out_replica1 (\d+)$", text, re.M)
    assert m_sum and m_r0 and m_r1
    assert int(m_r0.group(1)) > 0 and int(m_r1.group(1)) > 0
    assert int(m_sum.group(1)) == int(m_r0.group(1)) + int(m_r1.group(1))
    # fleet gone -> the replica breakdown zeroes like the sums do
    text = daemon_mod.handle_request(
        {"lab": "metrics"}, b"").decode("utf-8")
    m_r0 = re.search(r"^engine_tokens_out_replica0 (\d+)$", text, re.M)
    assert m_r0 and int(m_r0.group(1)) == 0


def test_slowlog_carries_replica_hops_and_migrations(trained):
    """A migrated request's slow-log entry names its hop chain, the
    replica that served its first token, and its migration count — a
    slow request blames a replica, not the fleet."""
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    tag = "fleet-slowlog-test"
    with faults.active([{"site": "paged.tick@replica0", "kind": "raise",
                         "at": 6}]):
        out = svc.generate(fleet, _cycle_prompt(4), 16, tag=tag)
    assert len(out) == 16
    _fleet_quiesce(fleet)
    entries = [e for e in obs.SLOWLOG.worst()
               if e.get("tag") == tag and e.get("migrations")]
    assert entries, "migrated request missing from the slow log"
    e = entries[0]
    assert e["replica_hops"] == [0, 1]
    assert e["migrations"] == 1
    assert e["replica_first_token"] in (0, 1)


def test_fleet_status_and_generate_stats_shape(trained):
    svc = daemon_mod._FleetService()
    fleet = _mk_fleet(trained, 2)
    svc.generate(fleet, _cycle_prompt(4), 4)
    _fleet_quiesce(fleet)
    st = svc.fleet_status(fleet)
    assert st["replicas"] == 2
    assert [r["replica"] for r in st["replica"]] == [0, 1]
    for row in st["replica"]:
        assert row["health"] == "healthy"
        assert not row["draining"] and not row["dead"]
    # generate_stats over a warm FLEET key: replica-summed stats + count
    key = (None, "gather", "native", 1, -17, "")
    daemon_mod._FLEETS[key] = (None, fleet)
    try:
        got = json.loads(daemon_mod.handle_request(
            {"lab": "generate_stats",
             "config": {"prefill_chunk": -17}}, b""))
    finally:
        daemon_mod._FLEETS.pop(key, None)
    assert got["replicas"] == 2
    assert got["requests_done"] >= 1 and got["tokens_out"] >= 4


def test_fleet_counters_registered_and_documented():
    """The round-13 lint (tests/test_obs.py pattern): every router
    counter is a registered metric AND has a docs entry."""
    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("daemon_migrations", "daemon_hedges",
                 "daemon_hedge_wins", "daemon_drains"):
        assert obs.REGISTRY.get(name) is not None, name
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"
    # the chaos surfaces are documented too
    for needle in ("engine_tokens_out_replica", "rebuilding "
                   "retry_after_ms", "paged.tick@replica"):
        assert needle in docs, needle


def test_loadgen_separates_rebuilding_park_from_shed():
    """RebuildingError's client half: a rolling restart's drain park
    must not masquerade as load shedding in goodput accounting — both
    arms count against attainment (the request was not served), but
    they are tallied separately."""
    from tpulab import loadgen

    m = loadgen.SHED_RE.search("rebuilding retry_after_ms=120 (x)")
    assert m and m.group(1) == "rebuilding" and m.group(2) == "120"
    trace = loadgen.build_trace(loadgen.built_in_spec("chaos"))
    cls = trace.classes[0]["name"]
    rows = []
    for i, kind in enumerate(("ok", "shed", "rebuilding")):
        r = {"i": i, "cls": cls, "tag": f"t{i}",
             "ok": kind == "ok", "shed": kind == "shed",
             "rebuilding": kind == "rebuilding", "cancelled": False,
             "error": None, "retry_after_ms": None,
             "ttft_ms": 1.0 if kind == "ok" else None,
             "e2e_ms": 2.0 if kind == "ok" else None,
             "itl_max_ms": 0.5, "n_chunks": 1, "bytes_out": 4,
             "sha": None, "stream_ok": None}
        rows.append(r)
    got = loadgen.summarize(rows, trace, wall_s=1.0)["overall"]
    assert got["shed"] == 1 and got["rebuilding"] == 1
    assert got["completed"] == 1 and got["errors"] == 0
    assert got["attainment"] == round(1 / 3, 4)  # both arms count


def test_handle_generate_validates_hedge_ms():
    with pytest.raises(ValueError, match="hedge_ms must be >= 0"):
        daemon_mod._handle_generate(
            {"config": {"hedge_ms": -3}}, b"hi")


# ----------------------------------------------------------- live daemon
def test_live_daemon_fleet_drain_undrain_cycle(tmp_path):
    """Acceptance over the real wire: a --replicas 2 daemon serves,
    reports its fleet table, rolls one replica (drain -> generation
    advance -> undrain) while a request lands on the other replica,
    and exposes the per-replica gauge breakdown in its scrape."""
    import importlib.util
    import os
    import signal as _signal
    import subprocess
    import sys

    spec = importlib.util.spec_from_file_location(
        "obs_report", ROOT / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    sock = str(tmp_path / "fleet.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock,
         "--replicas", "2"],
        env=dict(os.environ),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        for _ in range(600):
            if pathlib.Path(sock).exists():
                break
            time.sleep(0.1)
        else:
            raise AssertionError("daemon socket never appeared")
        out = rep.request_with_retry(sock, "generate", {"steps": 5},
                                     b"fleet live", deadline_s=300.0)
        assert len(out) == 5
        st = json.loads(rep.request(sock, "fleet"))
        assert st["replicas"] == 2
        assert all(r["health"] == "healthy" for r in st["replica"])
        row = json.loads(rep.request(sock, "drain", {"replica": 0}))
        assert row["draining"]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = json.loads(rep.request(sock, "fleet"))
            if (st["replica"][0]["generation"] >= 1
                    and st["replica"][0]["health"] == "healthy"):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("drained replica never rebuilt")
        # traffic during the drain is served by replica 1
        out = rep.request_with_retry(sock, "generate", {"steps": 4},
                                     b"drained window", deadline_s=300.0)
        assert len(out) == 4
        st = json.loads(rep.request(sock, "fleet"))
        assert st["replica"][1]["requests_done"] >= 1
        json.loads(rep.request(sock, "undrain", {"replica": 0}))
        text = rep.request(sock, "metrics").decode("utf-8")
        assert re.search(r"^engine_tokens_out_replica1 [1-9]", text, re.M)
        assert re.search(r"^daemon_drains [1-9]", text, re.M)
    finally:
        proc.send_signal(_signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
