"""Decode-path tests: KV-cache generation vs the full forward pass."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpulab.models.generate import generate, generate_jit, init_kv_cache
from tpulab.models.labformer import LabformerConfig, forward, init_params

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)


class TestGenerate:
    def test_greedy_matches_full_forward(self, rng):
        """Greedy cached decode must pick the same tokens as re-running
        the full forward at every step."""
        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (2, 8)).astype(np.int32)
        got = generate(params, prompt, CFG, steps=6, temperature=0.0)

        ctx = prompt.copy()
        for _ in range(6):
            logits = np.asarray(forward(params, jnp.asarray(ctx), CFG))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
        want = ctx[:, 8:]
        np.testing.assert_array_equal(got, want)

    def test_single_token_prompt(self, rng):
        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (1, 1)).astype(np.int32)
        out = generate(params, prompt, CFG, steps=4, temperature=0.0)
        assert out.shape == (1, 4)
        assert (out >= 0).all() and (out < 256).all()

    def test_sampling_is_seeded(self, rng):
        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (2, 4)).astype(np.int32)
        a = generate(params, prompt, CFG, steps=8, temperature=1.0, seed=3)
        b = generate(params, prompt, CFG, steps=8, temperature=1.0, seed=3)
        c = generate(params, prompt, CFG, steps=8, temperature=1.0, seed=4)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_one_jitted_program(self, rng):
        """The whole decode is a single jit entry (no per-token dispatch)."""
        params = init_params(CFG, seed=0)
        prompt = jnp.asarray(rng.integers(0, 256, (1, 4)), jnp.int32)
        key = jax.random.PRNGKey(0)
        with jax.checking_leaks():
            out = generate_jit(params, prompt, key, CFG, 4, 0.0)
        assert out.shape == (1, 4)

    def test_cache_shapes(self):
        kc, vc = init_kv_cache(CFG, batch=3, max_seq=16)
        assert kc.shape == (2, 3, 16, 4, 8) and vc.shape == kc.shape

    def test_flash_prefill_matches_dense(self, rng):
        """Batched prefill through the Pallas flash kernel (attn_impl=
        flash) must sample the same greedy tokens as the dense prefill."""
        import dataclasses

        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (2, 32)).astype(np.int32)
        dense = generate(params, prompt, CFG, steps=6, temperature=0.0)
        fl = generate(
            params, prompt, dataclasses.replace(CFG, attn_impl="flash"),
            steps=6, temperature=0.0,
        )
        np.testing.assert_array_equal(dense, fl)

    def test_tp_sharded_decode_matches_single_device(self, rng):
        """Distributed inference: shard_params' tp layout partitions the
        whole jitted generate loop (projections column-sharded, caches
        head-sharded, wo row-sharded + psum — all inserted by GSPMD)
        and must reproduce the single-device tokens exactly."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpulab.models.labformer import shard_params
        from tpulab.parallel.mesh import cpu_test_mesh

        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (2, 8)).astype(np.int32)
        key = jax.random.PRNGKey(0)
        want = np.asarray(generate_jit(params, jnp.asarray(prompt), key, CFG, 6, 0.0))
        mesh = cpu_test_mesh({"tp": 4})
        sp = shard_params(params, CFG, mesh)
        tok = jax.device_put(jnp.asarray(prompt), NamedSharding(mesh, P()))
        got = np.asarray(generate_jit(sp, tok, key, CFG, 6, 0.0))
        np.testing.assert_array_equal(got, want)

    def test_moe_decode_matches_full_forward(self, rng):
        """KV-cache decode with the dense-gate MoE block (the decode
        path's expert execution) must agree with the full forward."""
        import dataclasses

        cfg = dataclasses.replace(CFG, n_experts=4)
        params = init_params(cfg, seed=0)
        prompt = rng.integers(0, 256, (2, 8)).astype(np.int32)
        got = generate(params, prompt, cfg, steps=5, temperature=0.0)
        ctx = prompt.copy()
        for _ in range(5):
            logits = np.asarray(forward(params, jnp.asarray(ctx), cfg))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, ctx[:, 8:])


class TestSamplingFilters:
    """top-k / top-p logit filtering (generate._filter_logits)."""

    def _logits(self):
        # a known distribution: token i has logit i (vocab 8)
        return jnp.asarray(np.arange(8.0)[None, :], jnp.float32)

    def test_top_k_masks_all_but_k(self):
        from tpulab.models.generate import _filter_logits

        out = np.asarray(_filter_logits(self._logits(), top_k=3, top_p=1.0))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [5, 6, 7]

    def test_top_p_keeps_nucleus_with_boundary_token(self):
        from tpulab.models.generate import _filter_logits

        # probs ~ softmax(0..7): mass-before per descending rank is
        # 0, .632, .865, .950, .982, ... — exact expected sets, so a
        # degenerate filter (e.g. one that always keeps only the argmax)
        # cannot pass
        out = np.asarray(_filter_logits(self._logits(), top_k=0, top_p=0.5))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [7]  # .632 > .5: top token alone crosses
        out = np.asarray(_filter_logits(self._logits(), top_k=0, top_p=0.9))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [5, 6, 7]  # mass-before .865 <= .9 < .950
        out = np.asarray(_filter_logits(self._logits(), top_k=0, top_p=0.99))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [3, 4, 5, 6, 7]
        # composes with top_k: the nucleus renormalizes over the k kept
        out = np.asarray(_filter_logits(self._logits(), top_k=4, top_p=0.99))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [4, 5, 6, 7]

    def test_top_k_overlarge_and_negative(self):
        from tpulab.models.generate import _filter_logits

        out = np.asarray(_filter_logits(self._logits(), top_k=300, top_p=1.0))
        assert np.array_equal(out, np.asarray(self._logits()))  # clamped: all kept
        with pytest.raises(ValueError, match="top_k"):
            _filter_logits(self._logits(), top_k=-1, top_p=1.0)

    def test_filters_off_are_identity(self):
        from tpulab.models.generate import _filter_logits

        logits = self._logits()
        out = np.asarray(_filter_logits(logits, top_k=0, top_p=1.0))
        assert np.array_equal(out, np.asarray(logits))

    def test_generate_with_filters_runs_and_respects_top_k1(self, rng):
        from tpulab.models.generate import generate
        from tpulab.models.labformer import init_params

        cfg = CFG
        params = init_params(cfg, seed=0)
        prompt = rng.integers(0, cfg.vocab, (2, 4)).astype(np.int32)
        # top_k=1 at any temperature must equal greedy
        hot = generate(params, prompt, cfg, steps=5, temperature=5.0,
                       top_k=1, seed=3)
        greedy = generate(params, prompt, cfg, steps=5, temperature=0.0)
        assert np.array_equal(hot, greedy)

    def test_top_p_zero_is_top1(self):
        from tpulab.models.generate import _filter_logits

        out = np.asarray(_filter_logits(self._logits(), top_k=0, top_p=0.0))
        kept = np.nonzero(out[0] > -1e29)[0]
        assert kept.tolist() == [7]

    def test_top_p_zero_sampling_equals_greedy(self, rng):
        from tpulab.models.generate import generate
        from tpulab.models.labformer import init_params

        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, CFG.vocab, (2, 4)).astype(np.int32)
        out = generate(params, prompt, CFG, steps=5, temperature=3.0,
                       top_p=0.0, seed=1)
        greedy = generate(params, prompt, CFG, steps=5, temperature=0.0)
        assert np.array_equal(out, greedy)


def test_load_params_ignores_optimizer_stack(tmp_path):
    """Checkpoints trained with ANY optax stack (clipping + schedules
    change the chain's pytree length) must load for inference — the
    restore is params-only/partial."""
    from tpulab.models.generate import load_params
    from tpulab.train import train

    cfg = LabformerConfig(d_model=16, n_heads=2, n_layers=1, d_ff=32,
                          max_seq=64)
    train(steps=2, batch=2, seq=16, cfg=cfg, ckpt_dir=str(tmp_path),
          save_every=1, lr=1e-3, clip_norm=1.0, schedule="cosine",
          warmup_steps=1, log=lambda *a: None)
    params, step = load_params(cfg, str(tmp_path))
    assert step == 2
    out = forward(params, jnp.zeros((1, 4), jnp.int32), cfg)
    assert np.isfinite(np.asarray(out)).all()


def test_load_params_from_mesh_trained_checkpoint(tmp_path):
    """A snapshot saved by MESH training (NamedSharding leaves in the
    checkpoint) must load for single-process inference — restore targets
    come from the live template, not the checkpoint's sharding file."""
    from tpulab.models.generate import load_params
    from tpulab.train import train

    cfg = LabformerConfig(d_model=16, n_heads=2, n_layers=2, d_ff=32,
                          max_seq=64)
    train(steps=2, batch=4, seq=16, cfg=cfg, ckpt_dir=str(tmp_path),
          save_every=1, mesh_devices=2, log=lambda *a: None)
    params, step = load_params(cfg, str(tmp_path))
    assert step == 2
    out = forward(params, jnp.zeros((1, 4), jnp.int32), cfg)
    assert np.isfinite(np.asarray(out)).all()


class TestPenaltyAndStop:
    """Repetition penalty (HF convention) and stop-byte freezing in the
    jitted decode loop.  The math is pinned by an exact unit test on
    ``apply_repetition_penalty``; behavior tests use the session-scoped
    TRAINED model (sharp logits — untrained argmax ties flip under
    benign numeric reorderings, see conftest.trained_small)."""

    def test_penalty_math_exact(self):
        from tpulab.models.generate import apply_repetition_penalty

        logits = jnp.asarray([[2.0, -3.0, 0.5, -0.25]])
        seen = jnp.asarray([[True, True, False, False]])
        got = np.asarray(apply_repetition_penalty(logits, seen, 2.0))
        # seen positive: /2; seen negative: *2; unseen: untouched
        np.testing.assert_allclose(got, [[1.0, -6.0, 0.5, -0.25]])
        # penalty 1.0 is exactly identity regardless of the mask
        noop = np.asarray(apply_repetition_penalty(logits, seen, 1.0))
        np.testing.assert_allclose(noop, np.asarray(logits))

    def test_penalty_one_is_bit_identical_noop(self, trained_small,
                                               trained_small_cfg):
        prompt = np.array([[1, 2, 3]], np.int32)
        base = generate(trained_small, prompt, trained_small_cfg,
                        steps=16, temperature=0.0)
        noop = generate(trained_small, prompt, trained_small_cfg,
                        steps=16, temperature=0.0, repetition_penalty=1.0)
        assert np.array_equal(base, noop)

    def test_penalized_greedy_matches_full_forward_oracle(self, rng):
        """Penalized cached decode == re-running the full forward with
        apply_repetition_penalty applied by hand at every step — pins
        the integration (prompt tokens pre-seen, each emitted token
        marked before the NEXT sample, penalty before argmax)."""
        from tpulab.models.generate import apply_repetition_penalty

        params = init_params(CFG, seed=0)
        prompt = rng.integers(0, 256, (2, 8)).astype(np.int32)
        penalty = 4.0
        got = generate(params, prompt, CFG, steps=6, temperature=0.0,
                       repetition_penalty=penalty)

        ctx = prompt.copy()
        seen = np.zeros((2, 256), bool)
        for b in range(2):
            seen[b, prompt[b]] = True
        for _ in range(6):
            logits = np.asarray(forward(params, jnp.asarray(ctx), CFG))[:, -1]
            logits = np.asarray(apply_repetition_penalty(
                jnp.asarray(logits), jnp.asarray(seen), penalty))
            nxt = logits.argmax(-1).astype(np.int32)
            seen[np.arange(2), nxt] = True
            ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, ctx[:, 8:])

    def test_stop_byte_freezes_row_and_preserves_prefix(
            self, trained_small, trained_small_cfg):
        """Stopping must not perturb sampling before the stop byte: the
        output equals the unstopped stream up to the first occurrence,
        then repeats the stop byte (callers trim)."""
        prompt = np.array([[1, 2, 3]], np.int32)
        base = generate(trained_small, prompt, trained_small_cfg,
                        steps=16, temperature=0.0)
        toks = base[0].tolist()
        # any token that recurs works; pick the middle one of the stream
        stop = toks[len(toks) // 2]
        first = toks.index(stop)
        got = generate(trained_small, prompt, trained_small_cfg,
                       steps=16, temperature=0.0,
                       stop_token=stop)[0].tolist()
        assert got[:first + 1] == toks[:first + 1]
        assert all(t == stop for t in got[first:]), got


class TestSlidingWindowDecode:
    """attn_window must mean the SAME function across forward, cached
    decode, and the paged engine — train/serve consistency."""

    WCFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                           max_seq=64, attn_window=6)

    def test_windowed_greedy_matches_windowed_forward(self, rng):
        params = init_params(self.WCFG, seed=0)
        prompt = rng.integers(0, 256, (2, 12)).astype(np.int32)
        got = generate(params, prompt, self.WCFG, steps=8, temperature=0.0)

        ctx = prompt.copy()
        for _ in range(8):
            logits = np.asarray(forward(params, jnp.asarray(ctx), self.WCFG))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, ctx[:, 12:])

    def test_window_changes_the_function(self, rng):
        """A prompt longer than the window must decode differently from
        the full-causal model (otherwise the mask is dead code)."""
        params = init_params(self.WCFG, seed=0)
        full = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                               max_seq=64)
        prompt = rng.integers(0, 256, (1, 24)).astype(np.int32)
        got_w = generate(params, prompt, self.WCFG, steps=8, temperature=0.0)
        got_f = generate(params, prompt, full, steps=8, temperature=0.0)
        assert not np.array_equal(got_w, got_f)

    def test_paged_engine_matches_solo_windowed_decode(self, rng):
        from tpulab.models.paged import PagedEngine

        params = init_params(self.WCFG, seed=0)
        eng = PagedEngine(params, self.WCFG, slots=2, n_blocks=16,
                          block_size=8, max_seq=64)
        prompts = [rng.integers(0, 256, n).astype(np.int32)
                   for n in (3, 14, 9)]
        rids = [eng.submit(p, max_new=6) for p in prompts]
        out = eng.run()
        for rid, p in zip(rids, prompts):
            want = generate(params, p[None, :], self.WCFG, steps=6,
                            temperature=0.0)[0]
            np.testing.assert_array_equal(out[rid], want)
