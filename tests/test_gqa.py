"""Grouped-query attention (LabformerConfig.n_kv_heads).

K/V projections and the decode KV cache live at kv_heads width; the
training-side repeat restores head parity for the flash/ring/ulysses
paths.  These tests pin the parameter/cache shapes, the MHA-reduction
(n_kv_heads == n_heads is bit-identical to the default), numerical
behavior of grouped cached decode vs the full forward, and a sharded
GQA train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.generate import generate, init_kv_cache
from tpulab.models.labformer import (
    LabformerConfig,
    forward,
    init_params,
    init_train_state,
)
from tpulab.parallel.mesh import make_mesh

CFG = LabformerConfig(
    d_model=32, n_heads=4, n_kv_heads=2, n_layers=2, d_ff=64, max_seq=64
)


def test_config_validates_group_divisibility():
    with pytest.raises(ValueError, match="n_kv_heads"):
        LabformerConfig(n_heads=8, n_kv_heads=3)


def test_param_and_cache_shapes_shrink():
    params = init_params(CFG)
    L, d, dh = CFG.n_layers, CFG.d_model, CFG.head_dim
    assert params["blocks"]["wq"].shape == (L, d, d)
    assert params["blocks"]["wk"].shape == (L, d, 2 * dh)
    assert params["blocks"]["wv"].shape == (L, d, 2 * dh)
    kc, vc = init_kv_cache(CFG, batch=3, max_seq=16)
    assert kc.shape == (L, 3, 16, 2, dh) and vc.shape == kc.shape


def test_kv_heads_equal_heads_is_mha():
    """n_kv_heads == n_heads must reproduce the default model exactly
    (same param draw, same forward bits)."""
    base = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    gqa = LabformerConfig(
        d_model=32, n_heads=4, n_kv_heads=4, n_layers=2, d_ff=64, max_seq=64
    )
    p0, p1 = init_params(base, seed=3), init_params(gqa, seed=3)
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        assert np.array_equal(a, b)
    tok = np.random.default_rng(0).integers(0, base.vocab, (2, 16)).astype(np.int32)
    out0 = np.asarray(forward(p0, jnp.asarray(tok), base))
    out1 = np.asarray(forward(p1, jnp.asarray(tok), gqa))
    assert np.array_equal(out0, out1)


def test_gqa_greedy_decode_matches_full_forward():
    """Cached grouped decode must emit the token the full (repeat-based)
    forward would pick at every step."""
    params = init_params(CFG, seed=1)
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab, (2, 5)).astype(np.int32)
    steps = 6
    toks = generate(params, prompt, CFG, steps=steps, temperature=0.0)
    assert toks.shape == (2, steps)  # generated continuation only
    ctx = prompt
    for i in range(steps):
        logits = np.asarray(forward(params, jnp.asarray(ctx), CFG))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        assert np.array_equal(toks[:, i], nxt), i
        ctx = np.concatenate([ctx, nxt[:, None]], axis=1)


def test_gqa_trains():
    # a learnable stream (fixed repeating bytes), not random tokens —
    # random bytes sit at the ln(256) entropy floor where loss cannot
    # move and the assertion would be a coin flip
    mesh = make_mesh({"dp": 2, "tp": 2})
    params, opt_state, step = init_train_state(CFG, mesh, seed=0, zero1=True)
    tok = np.tile(np.arange(32, dtype=np.int32) % 7, (4, 1))
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, tok)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0] - 0.3


def test_gqa_sp_ring_matches_single_device():
    """Sequence-parallel ring attention over a GQA model must match the
    single-device forward (the repeat happens before the shard_map)."""
    mesh = make_mesh({"sp": 4})
    cfg = LabformerConfig(
        d_model=32, n_heads=4, n_kv_heads=2, n_layers=2, d_ff=64, max_seq=64
    )
    params = init_params(cfg, seed=2)
    tok = np.random.default_rng(1).integers(0, cfg.vocab, (2, 32)).astype(np.int32)
    want = np.asarray(forward(params, jnp.asarray(tok), cfg))
    got = np.asarray(forward(params, jnp.asarray(tok), cfg, mesh=mesh))
    assert np.allclose(got, want, atol=1e-5)
