"""Harness tests: sweep orchestration, verification gating, artifacts."""

import asyncio
import os
import sys

import numpy as np
import pandas as pd
import pytest

from tpulab.harness import InProcessTarget, SubprocessTarget, Tester, run_once
from tpulab.harness.base import PreparedRun, WorkloadProcessor
from tpulab.harness.processors import (
    Hw1Processor,
    Hw2Processor,
    Lab1Processor,
    Lab2Processor,
    Lab3Processor,
    Lab5Processor,
)
from tpulab.harness.run import infer_lab_from_path, main as harness_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_tester(tester, processor):
    return asyncio.run(tester.run_experiments(processor))


def make_tester(target, tmp_path, **kw):
    kw.setdefault("log", lambda *a, **k: None)
    return Tester(target, artifact_dir=str(tmp_path), **kw)


class TestLab1Sweep:
    def test_sweep_all_verified(self, tmp_path):
        target = InProcessTarget(
            name="lab1_tpu", device_label="TPU", workload="lab1", sweep=True,
            config={"warmup": 0, "reps": 1},
        )
        cpu = InProcessTarget(
            name="lab1_cpu", device_label="CPU", workload="lab1",
            config={"warmup": 0, "reps": 1},
        )
        tester = make_tester(
            target, tmp_path, cpu_target=cpu, k_times=2,
            kernel_sizes=[[1, 32], [256, 256]],
        )
        proc = Lab1Processor(seed=1, size_min=64, size_max=128)
        df = run_tester(tester, proc)
        # 2 reps x 2 configs + 2 CPU reference runs
        assert len(df) == 6
        assert bool((df["verified"] == True).all())  # noqa: E712
        assert (tmp_path / "stats_lab1_tpu.csv").exists()
        assert (tmp_path / "runs_lab1_tpu.csv").exists()
        stats = pd.read_csv(tmp_path / "stats_lab1_tpu.csv")
        assert set(stats["device"]) == {"TPU", "CPU"}

    def test_return_inp_and_task_res_columns(self, tmp_path):
        """--return_inp/--return_task_res debug columns (reference
        run_test.py:44-49): raw stdin payload + parsed task result land
        in the runs CSV only when requested."""
        target = InProcessTarget(
            name="lab1_dbg", workload="lab1", config={"warmup": 0, "reps": 1}
        )
        proc = Lab1Processor(seed=5, size_min=8, size_max=16)
        df = run_tester(
            make_tester(target, tmp_path, k_times=1,
                        return_inp=True, return_task_res=True),
            proc,
        )
        assert "input_str" in df.columns and "task_result" in df.columns
        # the recorded stdin payload starts with the vector length line
        n = int(str(df["input_str"].iloc[0]).split()[0])
        assert 8 <= n <= 16
        df2 = run_tester(
            make_tester(target, tmp_path / "plain",
                        k_times=1), Lab1Processor(seed=5, size_min=8, size_max=16)
        )
        assert "input_str" not in df2.columns and "task_result" not in df2.columns

    def test_verification_gate_withholds_stats(self, tmp_path):
        # add-op processor against a subtract-computing target -> all fail
        target = InProcessTarget(
            name="lab1_bad", workload="lab1", config={"warmup": 0, "reps": 1}
        )
        tester = make_tester(target, tmp_path, k_times=1)
        proc = Lab1Processor(seed=2, size_min=32, size_max=64, op="add")
        df = run_tester(tester, proc)
        assert not bool((df["verified"] == True).all())  # noqa: E712
        assert (tmp_path / "failed_lab1_bad.csv").exists()
        assert not (tmp_path / "stats_lab1_bad.csv").exists()


class TestImageProcessors:
    def test_lab2_golden_sweep(self, tmp_path):
        proc = Lab2Processor(
            dir_to_data=os.path.join(REPO, "data/lab2/data"),
            dir_to_data_out=str(tmp_path / "out"),
            dir_to_data_out_gt=os.path.join(REPO, "data/lab2/data_out_gt"),
            log=lambda *a: None,
        )
        target = InProcessTarget(
            name="lab2_tpu", workload="lab2", sweep=True,
            config={"warmup": 0, "reps": 1},
        )
        tester = make_tester(
            target, tmp_path, k_times=2, kernel_sizes=[[[32, 32], [16, 16]]]
        )
        df = run_tester(tester, proc)
        assert bool((df["verified"] == True).all())  # noqa: E712
        assert (tmp_path / "stats_lab2_tpu.csv").exists()

    def test_lab2_detects_corruption(self, tmp_path):
        # a target that writes a corrupted image must fail verification
        class CorruptTarget(InProcessTarget):
            async def execute(self, stdin_text, sweep=None):
                out = await super().execute(stdin_text, sweep=sweep)
                out_path = stdin_text.splitlines()[1]
                blob = bytearray(open(out_path, "rb").read())
                blob[8] ^= 0xFF
                open(out_path, "wb").write(bytes(blob))
                return out

        proc = Lab2Processor(
            dir_to_data=os.path.join(REPO, "data/lab2/data"),
            dir_to_data_out=str(tmp_path / "out"),
            dir_to_data_out_gt=os.path.join(REPO, "data/lab2/data_out_gt"),
            verbose_diff=False,
            log=lambda *a: None,
        )
        target = CorruptTarget(
            name="lab2_corrupt", workload="lab2", config={"warmup": 0, "reps": 1}
        )
        tester = make_tester(target, tmp_path, k_times=1)
        df = run_tester(tester, proc)
        assert not bool(df["verified"].any())
        assert (tmp_path / "failed_lab2_corrupt.csv").exists()

    def test_lab2_downloaded_png_extends_dataset(self, tmp_path):
        """Reference lab2_processor.py:68-73 behavior: extra PNG links
        are downloaded into the data dir and join the round-robin; the
        downloaded image is benchmark-only (no golden) so it verifies
        automatically.  Served from a local HTTP server (zero egress)."""
        import functools
        import http.server
        import shutil
        import threading

        from PIL import Image

        serve_dir = tmp_path / "www"
        serve_dir.mkdir()
        rng = np.random.default_rng(5)
        Image.fromarray(
            rng.integers(0, 255, (6, 7, 4), dtype=np.uint8), "RGBA"
        ).save(serve_dir / "extra.png")
        handler = functools.partial(
            http.server.SimpleHTTPRequestHandler, directory=str(serve_dir)
        )
        httpd = http.server.ThreadingHTTPServer(("localhost", 0), handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://localhost:{httpd.server_address[1]}/extra.png"
            data_dir = tmp_path / "data"
            shutil.copytree(os.path.join(REPO, "data/lab2/data"), data_dir)
            n_base = len(
                Lab2Processor(
                    dir_to_data=str(data_dir),
                    dir_to_data_out=str(tmp_path / "out0"),
                    log=lambda *a: None,
                ).dataset.paths
            )
            proc = Lab2Processor(
                dir_to_data=str(data_dir),
                dir_to_data_out=str(tmp_path / "out"),
                dir_to_data_out_gt=os.path.join(REPO, "data/lab2/data_out_gt"),
                extra_links_to_png=[url],
                log=lambda *a: None,
            )
            assert len(proc.dataset.paths) == n_base + 1
            target = InProcessTarget(
                name="lab2_tpu", workload="lab2", sweep=True,
                config={"warmup": 0, "reps": 1},
            )
            tester = make_tester(
                target, tmp_path, k_times=n_base + 1,
                kernel_sizes=[[[32, 32], [16, 16]]],
            )
            df = run_tester(tester, proc)
            assert bool((df["verified"] == True).all())  # noqa: E712
            assert len(df) == n_base + 1  # the extra PNG really ran
        finally:
            httpd.shutdown()

    def test_downloads_redirect_away_from_protected_dir(self, tmp_path, monkeypatch):
        """A read-only (protected) data dir must not receive downloads;
        they land under data_out/_downloads instead."""
        import functools
        import http.server
        import threading

        from PIL import Image

        from tpulab.harness.processors.imageset import ImageDataset

        serve_dir = tmp_path / "www"
        serve_dir.mkdir()
        Image.new("RGBA", (3, 3), (1, 2, 3, 255)).save(serve_dir / "x.png")
        handler = functools.partial(
            http.server.SimpleHTTPRequestHandler, directory=str(serve_dir)
        )
        httpd = http.server.ThreadingHTTPServer(("localhost", 0), handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            import shutil as _sh

            data_dir = str(tmp_path / "data")  # hermetic copy, marked protected
            _sh.copytree(os.path.join(REPO, "data/lab2/data"), data_dir)
            monkeypatch.setenv("TPULAB_PROTECTED_DIRS", data_dir)
            before = set(os.listdir(data_dir))
            out_dir = tmp_path / "out"
            ds = ImageDataset(
                data_dir,
                str(out_dir),
                extra_links_to_png=[
                    f"http://localhost:{httpd.server_address[1]}/x.png"
                ],
            )
            extras = [p for p in ds.paths if p.startswith(str(out_dir))]
            assert len(extras) == 1 and os.path.exists(extras[0])
            assert os.sep + "_downloads" + os.sep in extras[0]
            assert set(os.listdir(data_dir)) == before  # protected dir untouched
        finally:
            httpd.shutdown()

    def test_lab3_golden_sweep(self, tmp_path):
        proc = Lab3Processor(
            dir_to_data=os.path.join(REPO, "data/lab3/data"),
            dir_to_data_out=str(tmp_path / "out"),
            dir_to_data_out_gt=os.path.join(REPO, "data/lab3/data_out_gt"),
            log=lambda *a: None,
        )
        target = InProcessTarget(
            name="lab3_tpu", workload="lab3", config={"warmup": 0, "reps": 1}
        )
        tester = make_tester(target, tmp_path, k_times=2)
        df = run_tester(tester, proc)
        assert bool((df["verified"] == True).all())  # noqa: E712


class TestSmallProcessors:
    @pytest.mark.parametrize(
        "proc_cls,workload,cfg",
        [
            (Hw1Processor, "hw1", {"timing": True}),
            (Hw2Processor, "hw2", {"timing": True, "warmup": 0, "reps": 1}),
            (Lab5Processor, "lab5", {"warmup": 0, "reps": 1}),
        ],
    )
    def test_roundtrip_verified(self, tmp_path, proc_cls, workload, cfg):
        proc = (
            proc_cls(workdir=str(tmp_path / "work"))
            if proc_cls is Lab5Processor
            else proc_cls()
        )
        target = InProcessTarget(name=workload, workload=workload, config=cfg)
        tester = make_tester(target, tmp_path, k_times=3)
        df = run_tester(tester, proc)
        assert bool((df["verified"] == True).all())  # noqa: E712

    def test_lab5_sort_task(self, tmp_path):
        proc = Lab5Processor(task="sort", workdir=str(tmp_path / "work"))
        target = InProcessTarget(
            name="lab5_sort", workload="lab5",
            config={"task": "sort", "warmup": 0, "reps": 1},
        )
        tester = make_tester(target, tmp_path, k_times=2)
        df = run_tester(tester, proc)
        assert bool((df["verified"] == True).all())  # noqa: E712


class TestSubprocessTarget:
    def test_error_capture(self, tmp_path):
        target = SubprocessTarget(name="false", argv=["/bin/false"])
        proc = Lab1Processor(seed=3, size_min=8, size_max=16)
        record = asyncio.run(run_once(target, proc, None))
        assert record.verified is False
        assert "exited 1" in record.error

    def test_real_subprocess_contract(self, tmp_path):
        env_argv = [
            sys.executable, "-m", "tpulab", "run", "lab1",
            "--warmup", "0", "--reps", "1",
        ]
        target = SubprocessTarget(name="tpulab_sub", argv=env_argv)
        proc = Lab1Processor(seed=4, size_min=8, size_max=16)
        record = asyncio.run(run_once(target, proc, None))
        assert record.error is None, record.error
        assert record.verified is True
        assert record.time_kernel_ms is not None


class TestRunCli:
    def test_infer_lab_from_path(self):
        assert infer_lab_from_path("/x/lab2/src/to_plot_exe") == "lab2"

    def test_cli_end_to_end(self, tmp_path, capsys):
        rc = harness_main(
            [
                "--lab", "lab1", "--k-times", "1",
                "--kernel-sizes", "[[1, 32]]",
                "--artifact-dir", str(tmp_path),
                "--size_min", "16", "--size_max", "32",
                "--warmup", "0", "--reps", "1",
            ]
        )
        assert rc == 0
        assert (tmp_path / "stats_tpulab_lab1.csv").exists()

    def test_cli_lab1_narrow_dtypes(self, tmp_path):
        # regression: --dtype must reach both the workload and the oracle
        for dtype in ("float32", "bfloat16"):
            rc = harness_main(
                [
                    "--lab", "lab1", "--k-times", "1",
                    "--artifact-dir", str(tmp_path / dtype),
                    "--size_min", "16", "--size_max", "32",
                    "--dtype", dtype, "--warmup", "0", "--reps", "1",
                ]
            )
            assert rc == 0, dtype

    def test_cli_lab5_mesh(self, tmp_path):
        # regression: --mesh N routes through the distributed collectives
        for task in ("sum", "sort"):
            rc = harness_main(
                [
                    "--lab", "lab5", "--k-times", "1", "--task", task,
                    "--mesh", "8", "--artifact-dir", str(tmp_path / task),
                    "--warmup", "0", "--reps", "1",
                ]
            )
            assert rc == 0, task
