"""hw1/hw2/lab5/tpu_info workload tests."""

import subprocess

import numpy as np
import pytest

from tpulab.io import load_typed_array, save_typed_array
from tpulab.labs import hw1, hw2, lab5, tpu_info
from tpulab.ops.quadratic import ANY, INCORRECT, NO_REAL, ONE_ROOT, TWO_ROOTS, solve_batch, solve_scalar
from tpulab.runtime.timing import parse_timing_line

import jax.numpy as jnp


class TestHw1:
    # cases mirroring every branch of reference hw1/src/main.c:8-32
    CASES = [
        ((0, 0, 0), "any"),
        ((0, 0, 5), "incorrect"),
        ((0, 2, -4), "2.000000"),
        ((1, -3, 2), "2.000000 1.000000"),
        ((1, 2, 1), "-1.000000"),
        ((1, 0, 1), "imaginary"),
    ]

    @pytest.mark.parametrize("coeffs,expect", CASES)
    def test_scalar_branches(self, coeffs, expect):
        assert solve_scalar(*coeffs) == expect

    def test_stdin_contract(self):
        assert hw1.run("1 -3 2\n") == "2.000000 1.000000\n"

    def test_timing_flag(self):
        out = hw1.run("1 -3 2\n", timing=True)
        lines = out.splitlines()
        assert parse_timing_line(lines[0]) is not None
        assert lines[1] == "2.000000 1.000000"

    def test_batched_solver_agrees(self):
        coeffs = np.array([c for c, _ in self.CASES], np.float32)
        status, roots = solve_batch(jnp.asarray(coeffs))
        status = np.asarray(status)
        roots = np.asarray(roots)
        assert list(status) == [ANY, INCORRECT, ONE_ROOT, TWO_ROOTS, ONE_ROOT, NO_REAL]
        np.testing.assert_allclose(roots[2, 0], 2.0)
        np.testing.assert_allclose(roots[3], [2.0, 1.0])
        np.testing.assert_allclose(roots[4, 0], -1.0)


class TestHw2:
    def test_sort_contract(self):
        out = hw2.run("4\n3.5 -1.0 2.25 0.0\n", warmup=0, reps=1)
        assert out == "-1.000000e+00 0.000000e+00 2.250000e+00 3.500000e+00 \n"

    def test_timing_flag(self, rng):
        vals = rng.normal(size=100).astype(np.float32)
        text = f"{len(vals)}\n" + " ".join(str(v) for v in vals) + "\n"
        out = hw2.run(text, timing=True, warmup=0, reps=1)
        lines = out.splitlines()
        assert parse_timing_line(lines[0]) is not None
        parsed = np.array([float(t) for t in lines[1].split()], np.float32)
        np.testing.assert_allclose(parsed, np.sort(vals), rtol=1e-6)


class TestLab5:
    def test_sum_reference_fixture(self, reference_root):
        out = lab5.run(str(reference_root / "lab5/data/int10") + "\n", warmup=0, reps=1)
        lines = out.splitlines()
        assert parse_timing_line(lines[0]) is not None
        assert lines[1] == "45"  # 0+9+8+...+1

    def test_float_reduction(self, reference_root):
        out = lab5.run(
            str(reference_root / "lab5/data/float10") + "\n",
            task="max",
            warmup=0,
            reps=1,
        )
        assert out.splitlines()[1] == f"{9.0:.6e}"

    def test_uchar_sum(self, reference_root):
        out = lab5.run(
            str(reference_root / "lab5/data/uchar10") + "\n", warmup=0, reps=1
        )
        assert out.splitlines()[1] == "22"  # 1+2+3+1+2+3+1+2+3+4

    def test_sort_roundtrip(self, tmp_path, rng):
        vals = rng.integers(-1000, 1000, size=37).astype(np.int32)
        inp = str(tmp_path / "int37")
        outp = str(tmp_path / "int37_sorted")
        save_typed_array(inp, vals)
        out = lab5.run(f"{inp}\n{outp}\n", task="sort", warmup=0, reps=1)
        assert parse_timing_line(out) is not None
        np.testing.assert_array_equal(load_typed_array(outp), np.sort(vals))

    def test_unknown_task(self, reference_root):
        with pytest.raises(ValueError):
            lab5.run(str(reference_root / "lab5/data/int10") + "\n", task="median")


class TestTpuInfo:
    def test_reports_devices(self):
        out = tpu_info.run("")
        assert "Device 0:" in out and "platform: cpu" in out
        assert "num_devices: 8" in out  # virtual CPU mesh from conftest
        assert "ici_num_chips: 8" in out  # fleet topology section

    def test_generation_limits_table(self):
        """The gpu_info launch-limit analog: VMEM / MXU / VPU limits are
        reported for known TPU generations and omitted for unknowns."""
        from tpulab.runtime.device import generation_limits

        v5e = generation_limits("TFRT TPU v5 lite")
        assert v5e["mxu_shape"] == (128, 128)
        assert v5e["bf16_peak_tflops_per_chip"] == 197
        assert generation_limits("cpu") == {}

    def test_ici_topology_shape(self):
        from tpulab.runtime.device import ici_topology

        topo = ici_topology()
        assert topo["num_chips"] == 8  # virtual CPU fleet
