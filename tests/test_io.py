"""IO layer tests: tri-format image codec, lab5 binary format, protocols."""

import numpy as np
import pytest

from tpulab.io import (
    bytes_to_hex,
    hex_to_bytes,
    load_image,
    load_typed_array,
    pack_image,
    save_image,
    save_typed_array,
    unpack_image,
)
from tpulab.io import protocol
from tpulab.utils import ImgData, coerce_cli_kwargs


def random_rgba(rng, h, w):
    return rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)


class TestImageCodec:
    def test_pack_unpack_roundtrip(self, rng):
        img = random_rgba(rng, 5, 3)
        assert np.array_equal(unpack_image(pack_image(img)), img)

    def test_hex_roundtrip(self, rng):
        img = random_rgba(rng, 2, 7)
        blob = pack_image(img)
        assert hex_to_bytes(bytes_to_hex(blob)) == blob

    def test_hex_grouping(self):
        img = np.zeros((1, 1, 4), np.uint8)
        img[0, 0] = [1, 2, 3, 4]
        # header: w=1, h=1 little-endian; one pixel group r,g,b,a
        assert bytes_to_hex(pack_image(img)) == "01000000 01000000 01020304"

    def test_file_roundtrip_all_formats(self, rng, tmp_path):
        img = random_rgba(rng, 4, 6)
        img[..., 3] = 255  # png path forces opaque alpha; keep formats comparable
        for ext in (".data", ".txt", ".png"):
            p = str(tmp_path / f"img{ext}")
            save_image(p, img)
            assert np.array_equal(load_image(p), img)

    def test_png_import_forces_alpha(self, rng, tmp_path):
        img = random_rgba(rng, 3, 3)
        img[..., 3] = 7
        p = str(tmp_path / "a.png")
        save_image(p, img)
        out = load_image(p)
        assert (out[..., 3] == 255).all()
        assert np.array_equal(out[..., :3], img[..., :3])

    def test_reference_fixture_parses(self, reference_root):
        img = load_image(str(reference_root / "lab2/data/test_01.txt"))
        assert img.shape == (3, 3, 4)
        assert img[0, 0, 0] == 0x01 and img[0, 0, 1] == 0x02 and img[0, 0, 2] == 0x03

    def test_reference_data_files_parse(self, reference_root):
        img = load_image(str(reference_root / "lab2/data/02.data"))
        assert img.shape[2] == 4 and img.size > 0

    def test_imgdata_materializes_siblings(self, rng, tmp_path):
        img = random_rgba(rng, 3, 3)
        p = str(tmp_path / "x.data")
        save_image(p, img)
        obj = ImgData(p)
        assert (tmp_path / "x.txt").exists() and (tmp_path / "x.png").exists()
        assert obj.width == 3 and obj.height == 3
        assert hex_to_bytes(obj.hex) == obj.c_data_bytes


class TestTypedArray:
    def test_roundtrip(self, tmp_path, rng):
        vals = rng.normal(size=11).astype(np.float32)
        p = str(tmp_path / "float11")
        save_typed_array(p, vals)
        assert np.array_equal(load_typed_array(p), vals)

    def test_reference_lab5_files(self, reference_root):
        ints = load_typed_array(str(reference_root / "lab5/data/int10"))
        floats = load_typed_array(str(reference_root / "lab5/data/float10"))
        chars = load_typed_array(str(reference_root / "lab5/data/uchar10"))
        assert list(ints) == [0, 9, 8, 7, 6, 5, 4, 3, 2, 1]
        assert floats.dtype == np.float32 and floats.size == 10
        assert list(chars) == [1, 2, 3, 1, 2, 3, 1, 2, 3, 4]


class TestProtocol:
    def test_lab1_roundtrip(self, rng):
        a = rng.uniform(-1e100, 1e100, 16)
        b = rng.uniform(-1e100, 1e100, 16)
        text = protocol.format_lab1_input(a, b, launch=(256, 256))
        parsed = protocol.parse_lab1(text, sweep=True)
        assert parsed.launch == (256, 256)
        np.testing.assert_allclose(parsed.a, a, rtol=1e-10)

    def test_lab1_no_sweep(self):
        parsed = protocol.parse_lab1("2\n1.0 2.0\n3.0 4.0")
        assert parsed.launch is None
        assert list(parsed.a) == [1.0, 2.0] and list(parsed.b) == [3.0, 4.0]

    def test_lab2(self):
        p = protocol.parse_lab2("32 32 16 16\nin.data\nout.data", sweep=True)
        assert p.launch == (32, 32, 16, 16)
        assert p.input_path == "in.data" and p.output_path == "out.data"

    def test_lab3_grammar(self):
        text = protocol.format_lab3_input(
            "in.data", "out.data", [np.array([[1, 2], [1, 0]]), np.array([[0, 0]])]
        )
        p = protocol.parse_lab3(text)
        assert len(p.classes) == 2
        assert p.classes[0].points.tolist() == [[1, 2], [1, 0]]
        assert p.classes[1].points.tolist() == [[0, 0]]

    def test_hw2_roundtrip(self):
        vals = np.array([3.5, -1.25, 0.5], dtype=np.float32)
        parsed = protocol.parse_hw2(protocol.format_hw2_input(vals))
        np.testing.assert_allclose(parsed, vals, rtol=1e-6)

    def test_payload_formats(self):
        assert protocol.format_vector_10e(np.array([1.0])) == "1.0000000000e+00 "
        assert protocol.format_vector_6e(np.array([1.0])) == "1.000000e+00 \n"


class TestArgCfg:
    def test_coercion(self):
        kw = coerce_cli_kwargs(
            ["--seed", "7", "--atol", "1e-10", "--name", "abc", "--flag", "--ks", "[[1,2]]"]
        )
        assert kw == {"seed": 7, "atol": 1e-10, "name": "abc", "flag": True, "ks": [[1, 2]]}
