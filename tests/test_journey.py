"""Cross-engine request journeys (round 21): the stitching tier.

Certified here:

  * the mark store stitches the full disaggregated phase waterfall
    (queue_wait → prefill_chunks → handoff export/transfer/import →
    decode_queue → decode) with CONTIGUOUS shared-boundary timestamps,
    the handoff phases summing to the recorded ``handoff_ms``, and the
    payload bytes attributed to every handoff phase;
  * unified journeys collapse to queue_wait → prefill_chunks → decode;
  * the store is bounded: FIFO eviction by first mark, in-place
    resize, capacity 0 disables recording entirely;
  * histogram exemplars: at most one ``(rid, value)`` pair per bucket
    (newest wins), written under the existing per-metric lock — the
    torn-snapshot hammer proves a scrape racing rid-carrying observes
    still sees consistent counts/sum AND intact exemplar tuples;
  * a real ``obs=True`` engine produces a journey whose e2e/queue-wait
    agree EXACTLY with the slow-log entry for the same rid (shared
    timestamps, same rounding), and whose rid lands in a histogram
    exemplar;
  * the daemon's ``journey`` request (rid / tag / recent-N forms) and
    the flight recorder's ``journeys`` bundle section;
  * the shared renderers (``format_journey`` waterfall,
    ``format_journeys`` listing, the fleet table's pool census via
    ``router.pool_counts``, the slow-log line's pool/handoff fields);
  * the trace-event catalog lint: every literal name passed to
    ``tracer.event``/``span``/``begin`` under ``tpulab/`` appears in
    docs/ARCHITECTURE.md (the mirror of the metric↔docs lint).
"""

import json
import pathlib
import re
import threading

import numpy as np
import pytest

from tpulab import obs, router
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs import render
from tpulab.obs.journey import (HANDOFF_PHASES, PHASES, JourneyStore,
                                configure_journey)
from tpulab.obs.registry import Registry
from tpulab.obs.slowlog import SLOWLOG
from tpulab.obs.tracer import next_rid

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _mark_disagg_chain(store, rid, t0=100.0, nbytes=4096, tag="t"):
    """One full disaggregated mark sequence with easy round numbers."""
    store.mark(rid, "submit", t=t0, replica=0, pool="prefill", tag=tag)
    store.mark(rid, "admit", t=t0 + 0.010, replica=0, pool="prefill")
    store.mark(rid, "prefill_done", t=t0 + 0.050, replica=0,
               pool="prefill")
    store.mark(rid, "handoff_ready", t=t0 + 0.050, replica=0,
               pool="prefill")
    store.mark(rid, "handoff_export", t=t0 + 0.060, replica=0,
               pool="prefill")
    store.mark(rid, "handoff_import_begin", t=t0 + 0.070, replica=1,
               pool="decode")
    store.mark(rid, "handoff_import", t=t0 + 0.080, replica=1,
               pool="decode", nbytes=nbytes)
    store.mark(rid, "admit", t=t0 + 0.090, replica=1, pool="decode")
    store.mark(rid, "retire", t=t0 + 0.200, replica=1, pool="decode")


# ------------------------------------------------------- stitching
def test_journey_store_stitches_disagg_waterfall():
    s = JourneyStore(capacity=8)
    _mark_disagg_chain(s, 7, nbytes=4096, tag="row:7")
    j = s.snapshot(7)
    assert j["rid"] == 7 and j["tag"] == "row:7" and j["completed"]
    assert [p["phase"] for p in j["phases"]] == list(PHASES)
    # contiguity by construction: each phase ends at the exact stamp
    # the next starts from, and the waterfall is monotonic
    for a, b in zip(j["phases"], j["phases"][1:]):
        assert a["t1_ms"] == b["t0_ms"]
    for p in j["phases"]:
        assert p["ms"] >= 0 and p["t0_ms"] <= p["t1_ms"]
    by = {p["phase"]: p for p in j["phases"]}
    assert by["queue_wait"]["ms"] == pytest.approx(10.0)
    assert by["prefill_chunks"]["ms"] == pytest.approx(40.0)
    # the handoff phases sum EXACTLY to the recorded handoff_ms (the
    # same number the slow log and the handoff_bytes counter path see)
    hsum = round(sum(p["ms"] for p in j["phases"]
                     if p["phase"] in HANDOFF_PHASES), 3)
    assert hsum == j["handoff_ms"] == pytest.approx(30.0)
    assert j["handoff_bytes"] == 4096
    for name in HANDOFF_PHASES:
        assert by[name]["bytes"] == 4096
    assert by["decode"]["ms"] == pytest.approx(110.0)
    assert j["e2e_ms"] == pytest.approx(200.0)
    assert j["pools"] == ["prefill", "decode"]
    assert j["replicas"] == [0, 1]
    # phase attribution: the handoff_transfer phase belongs to the
    # RECEIVING side (its closing mark), the export to the sender
    assert by["handoff_export"]["pool"] == "prefill"
    assert by["handoff_import"]["pool"] == "decode"


def test_journey_store_unified_fallback():
    s = JourneyStore(capacity=8)
    s.mark(3, "submit", t=10.0, replica=0, tag="u")
    s.mark(3, "admit", t=10.020, replica=0)
    s.mark(3, "prefill_done", t=10.060, replica=0)
    s.mark(3, "retire", t=10.100, replica=0)
    j = s.snapshot(3)
    assert [p["phase"] for p in j["phases"]] == [
        "queue_wait", "prefill_chunks", "decode"]
    assert j["handoff_ms"] is None and j["handoff_bytes"] == 0
    assert j["e2e_ms"] == pytest.approx(100.0)  # retire - submit
    # in-flight journeys stitch what their marks support
    s.mark(4, "submit", t=20.0)
    assert s.snapshot(4)["phases"] == []
    assert not s.snapshot(4)["completed"]
    assert s.snapshot(99) is None


def test_journey_store_bounds_resize_and_disable():
    s = JourneyStore(capacity=2)
    s.mark(1, "submit", t=1.0)
    s.mark(2, "submit", t=2.0)
    s.mark(2, "retire", t=2.5)
    s.mark(3, "submit", t=3.0)  # evicts rid 1, which never retired
    assert s.snapshot(1) is None
    assert s.stats() == {"capacity": 2, "resident": 2, "completed": 1,
                         "evicted_inflight": 1}
    s.resize(1)  # in-place shrink evicts FIFO (rid 2, completed)
    assert s.snapshot(2) is None and s.snapshot(3) is not None
    with pytest.raises(ValueError, match=">= 0"):
        s.resize(-1)
    off = JourneyStore(0)
    off.mark(9, "submit", t=1.0)
    off.mark(9, "retire", t=2.0)
    assert off.snapshot(9) is None
    assert off.stats()["resident"] == 0 and off.stats()["completed"] == 0
    s.clear()
    assert s.stats()["resident"] == s.stats()["completed"] == 0


def test_journey_find_tag_and_recent():
    s = JourneyStore(capacity=8)
    _mark_disagg_chain(s, 10, t0=50.0, tag="shared")
    s.mark(11, "submit", t=60.0, tag="shared")  # retry reuses the tag
    assert s.find_tag("shared")["rid"] == 11  # newest wins
    assert s.find_tag("absent") is None
    recent = s.recent(5)
    assert [j["rid"] for j in recent] == [11, 10]  # newest first
    assert [j["rid"] for j in s.recent(5, completed_only=True)] == [10]
    assert [j["rid"] for j in s.recent(1)] == [11]


# ------------------------------------------------------- exemplars
def test_histogram_exemplars_one_per_bucket_newest_wins():
    r = Registry()
    h = r.histogram("ex_seconds", buckets=(0.01, 1.0))
    h.observe(0.005)  # rid-less observe writes no exemplar
    assert h.snapshot()["exemplars"] == [None, None, None]
    h.observe(0.005, rid=1)
    h.observe(0.007, rid=2)  # same bucket: newest wins
    h.observe(0.5, rid=3)
    snap = h.snapshot()
    assert snap["exemplars"] == [(2, 0.007), (3, 0.5), None]
    # copy-on-read: mutating the snapshot cannot corrupt the store
    snap["exemplars"][0] = "garbage"
    assert h.snapshot()["exemplars"][0] == (2, 0.007)
    # render emits the OpenMetrics suffix; parse_prometheus recovers it
    parsed = render.parse_prometheus(r.render())
    assert parsed["ex_seconds"]["exemplars"] == {
        0.01: (2, 0.007), 1.0: (3, 0.5)}


def test_exemplar_torn_snapshot_hammer():
    """A scrape racing rid-carrying observes must see a CONSISTENT
    histogram — counts/sum invariants intact (the round-10 contract,
    now exercised on the exemplar-writing path) and every exemplar
    slot either None or an intact ``(rid, value)`` pair whose value is
    the one this test ever observes (a torn exemplar write would
    surface a mismatched tuple)."""
    r = Registry()
    h = r.histogram("torn_ex_seconds", buckets=(1.0,))
    stop = threading.Event()
    n = {"i": 0}

    def hammer():
        while not stop.is_set():
            n["i"] += 1
            h.observe(0.5, rid=n["i"])

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for _ in range(300):
            snap = h.snapshot()
            assert sum(snap["counts"]) == snap["count"]
            assert snap["sum"] == snap["count"] * 0.5
            ex = snap["exemplars"]
            assert len(ex) == 2 and ex[1] is None
            if ex[0] is not None:
                rid, v = ex[0]
                assert v == 0.5 and 1 <= rid <= n["i"] + 1
    finally:
        stop.set()
        t.join()
    assert h.snapshot()["exemplars"][0] is not None


# ----------------------------------------------------- live engine
def test_engine_journey_exemplar_and_slowlog_agree(trained):
    """One request through a real ``obs=True`` engine: the stitched
    journey, the slow-log entry, and the histogram exemplars must all
    name the same rid — and the numbers that share timestamps
    (e2e, queue wait) must agree EXACTLY, not approximately."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, obs=True)
    eng.submit(_cycle_prompt(4), max_new=6, tag="journey-live")
    eng.run()
    j = obs.JOURNEY.find_tag("journey-live")
    assert j is not None and j["completed"]
    assert [p["phase"] for p in j["phases"]] == [
        "queue_wait", "prefill_chunks", "decode"]
    for a, b in zip(j["phases"], j["phases"][1:]):
        assert a["t1_ms"] == b["t0_ms"]
    assert j["handoff_ms"] is None and j["pools"] == []
    entry = SLOWLOG.find(j["rid"])
    assert entry is not None and entry["tag"] == "journey-live"
    assert entry["e2e_ms"] == j["e2e_ms"]
    assert entry["queue_wait_ms"] == j["phases"][0]["ms"]
    assert entry["pool"] is None  # bare engine: no pool role
    assert entry["handoff_ms"] is None and entry["handoff_bytes"] == 0
    # the per-request observes carried the rid: this request was the
    # newest in whatever buckets it landed in, so its rid is resident
    rids = set()
    for name in ("queue_wait_seconds", "ttft_seconds", "e2e_seconds"):
        for ex in obs.REGISTRY.get(name).snapshot()["exemplars"]:
            if ex is not None:
                rids.add(ex[0])
    assert j["rid"] in rids
    # and the tracer ring can replay the rid's event spine
    names = [n for _, n, _ in obs.TRACER.rid_events(j["rid"])]
    assert "journey.complete" in names


# ------------------------------------------------- daemon + bundles
def test_daemon_journey_handler_rid_tag_and_listing():
    from tpulab.daemon import handle_request

    rid = next_rid()
    _mark_disagg_chain(obs.JOURNEY, rid, t0=500.0, tag=f"jreq:{rid}")
    got = json.loads(handle_request(
        {"lab": "journey", "config": {"rid": rid}}, b""))
    assert got["journey"]["rid"] == rid
    assert [p["phase"] for p in got["journey"]["phases"]] == list(PHASES)
    got = json.loads(handle_request(
        {"lab": "journey", "config": {"tag": f"jreq:{rid}"}}, b""))
    assert got["journey"]["rid"] == rid
    got = json.loads(handle_request(
        {"lab": "journey", "config": {"n": 4, "completed": True}}, b""))
    assert any(j["rid"] == rid for j in got["journeys"])
    assert all(j["completed"] for j in got["journeys"])
    assert got["stats"]["capacity"] == obs.JOURNEY.capacity
    got = json.loads(handle_request(
        {"lab": "journey", "config": {"rid": 1 << 60}}, b""))
    assert got["journey"] is None


def test_configure_journey_resizes_global_in_place():
    store = obs.JOURNEY
    prior = store.capacity
    try:
        configure_journey(3)
        assert obs.JOURNEY is store and store.capacity == 3
        assert store.stats()["resident"] <= 3
    finally:
        configure_journey(prior)


def test_flightrec_bundle_carries_journeys(tmp_path):
    from tpulab.obs import flightrec

    rid = next_rid()
    _mark_disagg_chain(obs.JOURNEY, rid, t0=700.0, tag="crashing")
    flightrec.configure_flightrec(tmp_path)
    try:
        path = flightrec.record_postmortem("journey-test", engine=None)
        assert path is not None
        bundle = json.loads(path.read_text())
        assert any(j["rid"] == rid for j in bundle["journeys"])
    finally:
        flightrec.configure_flightrec(None)


# ------------------------------------------------------- rendering
def test_format_journey_waterfall_and_listing():
    s = JourneyStore(capacity=4)
    _mark_disagg_chain(s, 21, nbytes=2048, tag="render-me")
    j = s.snapshot(21)
    out = render.format_journey(j)
    assert "journey rid=21 tag=render-me complete" in out
    assert "pools=prefill>decode" in out
    assert "handoff=30.0ms/2048B" in out
    for name in PHASES:
        assert name in out
    assert "2048B" in out and "█" in out
    assert render.format_journey(None).startswith("journey: not found")
    listing = render.format_journeys(
        {"journeys": s.recent(4), "stats": s.stats()})
    assert "journeys: 1 shown, 1 completed" in listing
    assert "rid=21" in listing and "dom=decode:110.0ms" in listing
    assert render.format_journeys(None) == "journeys: none recorded"


def test_pool_counts_and_fleet_table_roles():
    assert router.pool_counts(
        ["prefill", "prefill", "decode", None, ""]) == {
            "prefill": 2, "decode": 1, "unified": 2}
    fleet = {
        "replicas": 3,
        "pools": {"prefill": {"min": 1, "max": 2},
                  "decode": {"min": 1, "max": 1}},
        "replica": [
            {"replica": 0, "health": "healthy", "role": "prefill",
             "pending": 0, "active": 1, "requests_done": 4},
            {"replica": 1, "health": "healthy", "role": "prefill",
             "pending": 2, "active": 2, "requests_done": 1},
            {"replica": 2, "health": "healthy", "role": "decode",
             "pending": 0, "active": 3, "requests_done": 5},
        ]}
    out = render.format_fleet(fleet, {})
    assert "pools: decode=1[1..1] prefill=2[1..2]" in out
    assert "replica0 healthy     prefill" in out
    assert "replica2 healthy     decode" in out
    # a unified fleet renders WITHOUT the role column or pools line
    for r in fleet["replica"]:
        r["role"] = "unified"
    fleet.pop("pools")
    out = render.format_fleet(fleet, {})
    assert "pools:" not in out and "unified" not in out


def test_format_slowlog_pool_and_handoff_fields():
    entry = {"rid": 5, "tag": "t", "e2e_ms": 12.0, "ttft_ms": 3.0,
             "itl_max_ms": 1.0, "itl_max_at_token": 2,
             "queue_wait_ms": 0.5, "prefill_chunks": 1, "tokens": 8,
             "pool": "decode", "handoff_ms": 4.25, "handoff_bytes": 512}
    out = render.format_slowlog({"worst": [entry], "recorded": 1})
    assert "pool=decode" in out and "handoff=4.25ms/512B" in out
    # pre-round-21 entries (no pool/handoff keys) render unchanged
    for k in ("pool", "handoff_ms", "handoff_bytes"):
        entry.pop(k)
    out = render.format_slowlog({"worst": [entry], "recorded": 1})
    assert "pool=" not in out and "handoff=" not in out


# ------------------------------------------------------ catalog lint
_EVT_RE = re.compile(r'\.(?:event|span|begin)\(\s*(f?)"([^"]+)"')


def test_trace_event_catalog_lint():
    """Every literal name passed to ``tracer.event``/``span``/``begin``
    anywhere under tpulab/ must appear in the docs/ARCHITECTURE.md
    trace-event catalog (mirror of the metric↔docs lint in
    test_obs.py).  F-string names (``daemon.brownout.{direction}``)
    lint their literal prefix."""
    names = set()
    for path in (ROOT / "tpulab").rglob("*.py"):
        for m in _EVT_RE.finditer(path.read_text()):
            name = m.group(2)
            if m.group(1):  # f-string: lint the stable prefix
                name = name.split("{", 1)[0]
            names.add(name)
    # the scan found the live emitters (guards against a refactor
    # silently renaming the call pattern out from under the lint)
    assert {"engine.submit", "engine.retire", "daemon.handoff",
            "handoff.transfer", "journey.complete",
            "engine.handoff_ready", "daemon.brownout."} <= names
    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    missing = sorted(n for n in names if n not in docs)
    assert not missing, (
        f"trace events emitted but undocumented in "
        f"docs/ARCHITECTURE.md: {missing}")
