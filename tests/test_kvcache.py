"""Hierarchical prefix/KV cache (round 18): radix-tree partial hits +
host-RAM spill tier (tpulab.kvcache, tpulab/models/paged.py wiring).

Covers the round-18 ISSUE checklist:

  * the radix prefix index property-tested against a brute-force
    oracle that mirrors its touch clock exactly — lookup results,
    adopted-block lists, LRU leaf-eviction victims, and node/entry
    counts all match over thousands of random operations;
  * dict-vs-radix engine bit-equality BOTH WAYS on exact-hit traces
    (identical repeated prompts): same tokens out, and both engines
    record the exact hits — the radix rewire changes WHAT can hit
    (partial prefixes), never what a hit returns;
  * the host spill tier: lossless ``native`` round-trips for dense
    AND (q, s) int8-pool payloads, LRU capacity drops, the lossy
    int8/int4 host formats' error bounds, and the int4 nibble
    pack/unpack round-trip (tpulab.models.quant);
  * the full spill cycle on a live engine: evict under pressure ->
    host tier -> prefetch back at admission -> outputs bit-identical
    to a spill-disabled engine and to plain ``generate``;
  * SATELLITE: ``_evict_prefixes`` can never free a block a live slot
    still references — asserted directly against the slot tables in
    dict, radix, and radix+spill modes;
  * standing contracts RE-CERTIFIED with the tier armed: the steady
    decode window stays flat-h2d under ``jax.transfer_guard`` + the
    ``jnp.asarray`` tripwire, and records ZERO recompiles under
    ``strict()`` even after real spill/prefetch traffic warmed the
    transfer programs;
  * constructor validation: spill requires the radix index, bounds,
    and dtype names.  (Round 19 certified the tier on mesh-sharded
    pools and round 20 extended that to the int4 host format — the
    spill-on-mesh arms live in tests/test_mesh_serving.py.)
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpulab.models.paged as paged_mod
from tpulab.kvcache import (DEFAULT_WATERMARK, SPILL_DTYPES,
                            HostSpillTier, RadixPrefixIndex, SpillPolicy)
from tpulab.kvcache.spill import _decode, _encode
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import TRASH, PagedEngine
from tpulab.models.quant import pack_int4, unpack_int4
from tpulab.obs import compilestats as cstats

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


# ---------------------------------------------- radix vs brute force
class _Oracle:
    """Transparent O(n^2) model of RadixPrefixIndex: a flat dict of
    chunk-path -> (block, last_use) plus the same strictly-increasing
    touch clock (lookup and insert both freshen every node on the
    walked path, shallowest first), so even LRU ties are impossible
    and eviction victims must match exactly."""

    def __init__(self, bs):
        self.bs = bs
        self.nodes = {}      # path tuple-of-chunks -> [block, last_use]
        self.entries = set()
        self.clock = 0

    def _chunks(self, tokens):
        n = len(tokens) // self.bs
        return tuple(tuple(int(t) for t in tokens[i * self.bs:(i + 1) * self.bs])
                     for i in range(n))

    def _touch(self, path):
        self.clock += 1
        self.nodes[path][1] = self.clock

    def lookup(self, tokens):
        blocks = []
        chunks = self._chunks(tokens)
        for j in range(1, len(chunks) + 1):
            path = chunks[:j]
            if path not in self.nodes:
                break
            blocks.append(self.nodes[path][0])
            self._touch(path)
        return blocks, len(blocks)

    def insert(self, tokens, blocks):
        chunks = self._chunks(tokens)
        adopted = []
        for j in range(1, len(chunks) + 1):
            path = chunks[:j]
            if path not in self.nodes:
                self.nodes[path] = [int(blocks[j - 1]), 0]
                adopted.append(int(blocks[j - 1]))
            self._touch(path)
        if chunks:
            self.entries.add(chunks)
        return adopted

    def evict_leaf(self):
        leaves = [p for p in self.nodes
                  if not any(q[:len(p)] == p and len(q) > len(p)
                             for q in self.nodes)]
        if not leaves:
            return None
        victim = min(leaves, key=lambda p: self.nodes[p][1])
        block = self.nodes.pop(victim)[0]
        self.entries.discard(victim)
        return block, tuple(t for chunk in victim for t in chunk)


def test_radix_matches_oracle_over_random_ops():
    """Thousands of mixed insert/lookup/evict ops from a seeded stream:
    every return value and both counters match the brute-force model."""
    bs = 4
    rng = random.Random(1234)
    tree, oracle = RadixPrefixIndex(bs), _Oracle(bs)
    next_block = 1
    for step in range(3000):
        op = rng.random()
        # small alphabet + short paths force dense prefix sharing
        tokens = [rng.randrange(3) for _ in range(bs * rng.randrange(1, 5))]
        if op < 0.45:
            need = len(tokens) // bs
            blocks = list(range(next_block, next_block + need))
            next_block += need
            a = tree.insert(tokens, blocks)
            b = oracle.insert(tokens, blocks)
            assert a == b, step
        elif op < 0.8:
            assert tree.lookup(tokens) == oracle.lookup(tokens), step
        else:
            assert tree.evict_leaf() == oracle.evict_leaf(), step
        assert tree.n_blocks == len(oracle.nodes), step
        assert tree.n_entries == len(oracle.entries) == len(tree), step
    assert sorted(tree.blocks()) == sorted(b for b, _ in oracle.nodes.values())
    # drain: eviction order over the whole surviving tree still agrees
    while True:
        a, b = tree.evict_leaf(), oracle.evict_leaf()
        assert a == b
        if a is None:
            break
    assert tree.n_blocks == 0 and tree.n_entries == 0


def test_radix_first_writer_wins_and_partial_hits():
    t = RadixPrefixIndex(2)
    assert t.insert([1, 2, 3, 4], [10, 11]) == [10, 11]
    # shared first chunk: only the divergent tail is adopted
    assert t.insert([1, 2, 9, 9], [77, 12]) == [12]
    assert t.n_blocks == 3 and t.n_entries == 2
    # longest PARTIAL hit: unseen suffix still reuses the shared chunk
    assert t.lookup([1, 2, 8, 8, 5, 5]) == ([10], 1)
    assert t.lookup([1, 2, 3, 4, 5, 5]) == ([10, 11], 2)
    assert t.lookup([9, 9]) == ([], 0)
    # sub-chunk tokens never match (block-aligned only)
    assert t.lookup([1]) == ([], 0)


def test_radix_leaf_only_lru_eviction():
    t = RadixPrefixIndex(1)
    t.insert([1, 2, 3], [10, 11, 12])     # chain: 1 -> 2 -> 3
    t.insert([1, 9], [0, 13])             # sibling leaf under 1
    t.lookup([1, 9])                       # freshen the sibling branch
    # LRU leaf is the chain tip (12): interior 10/11 are untouchable
    assert t.evict_leaf() == (12, (1, 2, 3))
    assert t.evict_leaf() == (11, (1, 2))  # becomes a leaf only now
    assert t.evict_leaf() == (13, (1, 9))
    assert t.evict_leaf() == (10, (1,))
    assert t.evict_leaf() is None


def test_radix_validation():
    with pytest.raises(ValueError, match="block_size"):
        RadixPrefixIndex(0)
    t = RadixPrefixIndex(2)
    with pytest.raises(ValueError, match="one block per chunk"):
        t.insert([1, 2, 3, 4], [10])
    t.insert([1, 2], [10])
    t.clear()
    assert t.n_blocks == 0 and t.lookup([1, 2]) == ([], 0)


# ------------------------------------------------- int4 pack/unpack
def test_int4_roundtrip_property():
    rng = np.random.default_rng(7)
    for n in (0, 1, 2, 7, 8, 33, 256, 1001):
        q = rng.integers(-8, 8, size=(n,)).astype(np.int8)
        packed, odd = pack_int4(q)
        assert packed.dtype == np.uint8
        assert packed.size == (n + 1) // 2 and odd == bool(n % 2)
        out = unpack_int4(packed, odd)
        assert out.dtype == np.int8
        assert np.array_equal(out, q), n
    with pytest.raises(ValueError, match="int4"):
        pack_int4(np.array([8], dtype=np.int8))


# ------------------------------------------------------ spill tier
def test_spill_tier_native_roundtrip_dense_and_quantized():
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, 1, 4, 8)).astype(np.float32)
    v = rng.standard_normal((2, 1, 4, 8)).astype(np.float32)
    tier = HostSpillTier(4, "native")
    tier.put(b"a", k, v)
    kk, vv = tier.get(b"a", pool_is_quantized=False, pool_dtype=np.float32)
    assert np.array_equal(kk, k) and np.array_equal(vv, v)
    # int8 pools spill their (q, s) representation verbatim — lossless
    q = rng.integers(-127, 128, size=k.shape).astype(np.int8)
    s = rng.random((2, 1, 4), dtype=np.float32) + 0.1
    tier.put(b"b", (q, s), (q, s))
    (q2, s2), _ = tier.get(b"b", pool_is_quantized=True, pool_dtype=np.int8)
    assert np.array_equal(q2, q) and np.array_equal(s2, s)
    assert b"a" in tier and len(tier) == 2 and tier.nbytes > 0


def test_spill_tier_lru_capacity_and_lossy_dtypes():
    rng = np.random.default_rng(1)
    mk = lambda: rng.standard_normal((2, 1, 2, 4)).astype(np.float32)
    tier = HostSpillTier(2, "native")
    tier.put(b"a", mk(), mk())
    tier.put(b"b", mk(), mk())
    tier.get(b"a", pool_is_quantized=False, pool_dtype=np.float32)  # freshen
    tier.put(b"c", mk(), mk())          # capacity 2: LRU b drops
    assert b"b" not in tier and b"a" in tier and b"c" in tier
    assert tier.dropped == 1
    for dtype, tol in (("int8", 0.02), ("int4", 0.15)):
        k = mk()
        entry = _encode(k, dtype)
        out = _decode(entry, False, np.float32)
        rel = np.abs(out - k).max() / np.abs(k).max()
        assert rel < tol, (dtype, rel)
    with pytest.raises(ValueError, match="spill dtype"):
        HostSpillTier(2, "fp7")


def test_spill_policy_overage():
    pol = SpillPolicy(watermark=0.90, batch=8)
    assert pol.overage(100, 128) == 0       # below the watermark
    assert pol.overage(116, 128) == 1       # 1 over int(0.9 * 128)
    assert pol.overage(128, 128) == 8       # 13 over, batch-bounded
    assert SpillPolicy(watermark=0.5, batch=2).overage(10, 10) == 2
    assert DEFAULT_WATERMARK == 0.90 and "native" in SPILL_DTYPES


# ------------------------------------- engine wiring: dict vs radix
def test_dict_radix_bit_equality_exact_hit_traces(trained):
    """Acceptance: the SAME exact-hit workload (repeated prompts across
    waves) through a dict engine and a radix engine yields bit-equal
    tokens per request — and matches plain generate — while both
    engines record the exact hits."""
    outs, engines = {}, {}
    for mode in ("dict", "radix"):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=24,
                          block_size=8, max_seq=64, prefix_index=mode)
        got = {}
        for wave in range(3):                 # waves 2/3 hit exactly
            rids = {eng.submit(_cycle_prompt(p), max_new=5): p
                    for p in (9, 17)}
            res = eng.run()
            for rid, p in rids.items():
                got[(wave, p)] = res[rid]
        outs[mode], engines[mode] = got, eng
        assert eng.counters["prefix_hits"] >= 4, mode  # 2 waves x 2
    for key, toks in outs["dict"].items():
        assert np.array_equal(toks, outs["radix"][key]), key
        p = key[1]
        want = generate(trained, _cycle_prompt(p)[None, :], CFG, steps=5,
                        temperature=0.0)[0]
        assert np.array_equal(toks, want), key
    # the radix engine additionally serves PARTIAL hits: with ONLY a
    # 2-block prefix registered, a prompt diverging inside block 2
    # still reuses block 1 — the dict index has no depth-1 entry to
    # probe and must miss
    div = np.concatenate([_cycle_prompt(8),
                          np.full(9, 5, np.int32)]).astype(np.int32)
    for mode in ("dict", "radix"):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=24,
                          block_size=8, max_seq=64, prefix_index=mode)
        _spin_waves(eng, [_cycle_prompt(17)])
        h0 = eng.counters["prefix_hits"]
        _spin_waves(eng, [div])
        hit = eng.counters["prefix_hits"] - h0
        assert hit == (1 if mode == "radix" else 0), mode


def _spin_waves(eng, prompts, max_new=5):
    rids = {eng.submit(p, max_new=max_new): i
            for i, p in enumerate(prompts)}
    res = eng.run()
    return {i: res[r] for r, i in rids.items()}


def test_spill_roundtrip_bit_equality(trained):
    """The full tier cycle: a tiny pool evicts A's prefix to host under
    filler pressure, resubmitting A prefetches it back, and every token
    stream is bit-identical to a spill-disabled engine's."""
    def mk(spill):
        kw = ({"prefix_index": "radix", "spill_blocks": 16}
              if spill else {})
        return PagedEngine(trained, CFG, slots=1, n_blocks=8,
                           block_size=8, max_seq=64, **kw)

    a = _cycle_prompt(17)                     # 2 full blocks of prefix
    fillers = [(np.arange(i, i + 17) % 11).astype(np.int32)
               for i in (1, 2, 3)]            # distinct working sets
    outs = {}
    for spill in (False, True):
        eng = mk(spill)
        outs[spill] = [_spin_waves(eng, [a])]
        for f in fillers:                     # 7-usable-block pool churns
            outs[spill].append(_spin_waves(eng, [f]))
        outs[spill].append(_spin_waves(eng, [a]))   # back for A
        if spill:
            assert eng.counters["spill_spilled"] >= 1
            assert eng.counters["spill_prefetched"] >= 1
            assert eng.counters["spill_hits"] >= 1
            assert eng.stats()["spill_capacity_blocks"] == 16
    for w, (ref, run) in enumerate(zip(outs[False], outs[True])):
        for i in ref:
            assert np.array_equal(ref[i], run[i]), (w, i)
    want = generate(trained, a[None, :], CFG, steps=5, temperature=0.0)[0]
    assert np.array_equal(outs[True][-1][0], want)


@pytest.mark.parametrize("mode", ["dict", "radix", "radix+spill"])
def test_evict_prefixes_never_frees_live_slot_blocks(trained, mode):
    """SATELLITE: prefix eviction under pressure must never free a
    block a PREFILLING/DECODING slot still references.  A second wave
    re-admits over the cached prefix (cache ref + slot ref on the same
    blocks); a forced over-demand eviction then drains the whole index
    — the shared blocks must survive in the slot tables, off the free
    list, and the stream must stay bit-exact."""
    kw = {"prefix_index": "radix"} if "radix" in mode else {}
    if mode == "radix+spill":
        kw["spill_blocks"] = 8
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64, **kw)
    p = _cycle_prompt(17)
    _spin_waves(eng, [p])                     # registers the prefix
    eng.submit(p, max_new=8)
    for _ in range(2):                        # admit + a tick or two
        eng.step()
    live = {int(b) for b in np.asarray(eng.tables).ravel() if b != TRASH}
    assert live, "no live slot blocks — the scenario is vacuous"
    eng._evict_prefixes(eng.n_usable_blocks + 1)   # over-demand: drain
    if "radix" in mode:
        assert eng._radix.n_blocks == 0
    else:
        assert not eng.prefix_cache
    for b in live:
        assert b not in eng.free, (mode, b)
        assert eng.block_refs[b] >= 1, (mode, b)
    out = eng.run()
    want = generate(trained, p[None, :], CFG, steps=8, temperature=0.0)[0]
    assert np.array_equal(out[max(out)], want)


def test_engine_validation(trained):
    with pytest.raises(ValueError, match="prefix_index"):
        PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, prefix_index="btree")
    with pytest.raises(ValueError, match="spill_blocks"):
        PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, spill_blocks=-1)
    with pytest.raises(ValueError, match="radix"):
        PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, spill_blocks=4)       # dict + spill
    with pytest.raises(ValueError, match="spill_dtype"):
        PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, prefix_index="radix", spill_blocks=4,
                    spill_dtype="fp8")
    # disarmed engines still expose the spill stats surface (zeros)
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                      max_seq=32)
    st = eng.stats()
    assert st["spill_capacity_blocks"] == 0
    assert st["spill_host_blocks"] == 0 and st["spill_dropped"] == 0


# ----------------------- standing contracts re-certified, tier armed
class _NoUpload:
    """jnp stand-in whose ``asarray`` (the engine's one host-upload
    idiom) raises — same tripwire as tests/test_paged_overlap.py."""

    def __getattr__(self, name):
        return getattr(jnp, name)

    def asarray(self, *a, **kw):  # noqa: D102 - tripwire
        raise AssertionError("host->device upload in steady-state decode")


def test_spill_armed_steady_window_flat_h2d(trained, monkeypatch):
    """Transfer-guard re-certification: with radix + spill ARMED, a
    steady window moves nothing host<->device — spill/prefetch traffic
    is admission-boundary work and its programs are warm-compiled at
    init, so the armed-but-idle tier must be invisible to the guard,
    the asarray tripwire, and the h2d_ticks counter alike."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, prefix_index="radix", spill_blocks=16)
    eng.submit(_cycle_prompt(4), max_new=30)
    eng.submit(_cycle_prompt(6), max_new=30, temperature=1.5, seed=3)
    for _ in range(4):    # admission + compile happen OUTSIDE the guard
        eng.step()
    before = eng.stats()
    monkeypatch.setattr(paged_mod, "jnp", _NoUpload())
    with jax.transfer_guard("disallow"):
        for _ in range(8):
            eng.step()
    monkeypatch.undo()
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 8
    assert st["h2d_ticks"] == before["h2d_ticks"], "steady tick uploaded"
    assert st["host_syncs"] == before["host_syncs"], "steady tick synced"
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=30,
                    temperature=0.0)[0]
    assert np.array_equal(eng.run()[0], want)


def test_spill_armed_steady_window_zero_recompiles(trained):
    """Recompile-tripwire re-certification: after REAL spill and
    prefetch traffic (so the transfer programs have run, not merely
    warm-compiled), a steady decode window under strict() still
    records zero recompiles — ``decode_steady_recompiles == 0`` holds
    with the tier armed."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                      max_seq=64, prefix_index="radix", spill_blocks=16)
    a = _cycle_prompt(17)
    _spin_waves(eng, [a])
    for f in [(np.arange(i, i + 17) % 11).astype(np.int32)
              for i in (1, 2, 3)]:
        _spin_waves(eng, [f])                 # churn: spill A out
    assert eng.counters["spill_spilled"] >= 1
    eng.submit(a, max_new=24)                 # prefetch A back in
    for _ in range(4):
        eng.step()
    assert eng.counters["spill_prefetched"] >= 1
    assert eng._steady, "engine never reached the steady state"
    r0 = eng.counters["recompiles"]
    with cstats.strict():
        for _ in range(12):
            eng.step()
    assert eng.counters["recompiles"] == r0 == 0
    eng.run()
