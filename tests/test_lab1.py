"""lab1 elementwise op tests: f64 oracle, Pallas tile sweep, CLI contract."""

import numpy as np
import pytest

from tpulab.io import protocol
from tpulab.labs import lab1
from tpulab.ops.elementwise import subtract, subtract_oracle
from tpulab.ops.pallas.elementwise import launch_to_tile_rows, pallas_binary
from tpulab.runtime.timing import parse_timing_line

import jax.numpy as jnp


class TestSubtract:
    def test_f64_oracle_extreme_range(self, rng):
        # reference input synthesis: uniform doubles in [-1e100, 1e100]
        a = rng.uniform(-1e100, 1e100, 2048)
        b = rng.uniform(-1e100, 1e100, 2048)
        out = np.asarray(subtract(a, b))
        assert out.dtype == np.float64
        np.testing.assert_allclose(out, subtract_oracle(a, b), atol=1e-10)

    def test_f32_path(self, rng):
        a = rng.normal(size=1000).astype(np.float32)
        b = rng.normal(size=1000).astype(np.float32)
        out = np.asarray(subtract(a, b))
        np.testing.assert_allclose(out, a - b, rtol=1e-6)

    def test_pallas_kernel_matches_xla(self, rng):
        for n in (1, 127, 128, 1000, 4096, 100_000):
            a = jnp.asarray(rng.normal(size=n).astype(np.float32))
            b = jnp.asarray(rng.normal(size=n).astype(np.float32))
            out = pallas_binary(a, b, jnp.subtract, tile_rows=64, interpret=True)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(a - b))

    def test_launch_mapping(self):
        assert launch_to_tile_rows(None) == 512
        assert launch_to_tile_rows((1, 32)) == 8      # degenerate -> min tile
        assert launch_to_tile_rows((256, 256)) == 512
        assert launch_to_tile_rows((1024, 1024)) == 2048  # clamped
        assert launch_to_tile_rows((512, 512)) == 2048

    def test_2d_arrays_fall_back_to_xla(self, rng):
        a = rng.normal(size=(8, 16)).astype(np.float32)
        b = rng.normal(size=(8, 16)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(subtract(a, b)), a - b, rtol=1e-6)

    def test_other_ops(self, rng):
        a = rng.normal(size=64)
        b = rng.normal(size=64)
        np.testing.assert_allclose(np.asarray(lab1.compute(a, b, op="add")), a + b)
        np.testing.assert_allclose(
            np.asarray(lab1.compute(a, b, op="multiply")), a * b, rtol=1e-12
        )


class TestLab1Protocol:
    def _roundtrip(self, a, b, **kw):
        text = protocol.format_lab1_input(a, b, launch=kw.pop("launch", None))
        out = lab1.run(text, warmup=0, reps=1, **kw)
        lines = out.split("\n")
        ms = parse_timing_line(lines[0])
        assert ms is not None and ms >= 0
        return np.array([float(tok) for tok in lines[1].split()])

    def test_end_to_end_f64(self, rng):
        a = rng.uniform(-1e100, 1e100, 300)
        b = rng.uniform(-1e100, 1e100, 300)
        result = self._roundtrip(a, b)
        # the compute must match the oracle on what was actually sent over
        # the wire (%.10e quantizes the inputs; cancellation can amplify
        # that quantization, so the pre-serialization arrays are not the
        # right ground truth — the parsed ones are)
        sent = protocol.parse_lab1(protocol.format_lab1_input(a, b))
        np.testing.assert_allclose(result, sent.a - sent.b, rtol=1e-9)

    def test_sweep_prefix(self, rng):
        a = rng.normal(size=64)
        b = rng.normal(size=64)
        text = protocol.format_lab1_input(a, b, launch=(256, 256))
        out = lab1.run(text, sweep=True, warmup=0, reps=1)
        assert parse_timing_line(out) is not None

    def test_payload_format_is_10e(self):
        out = lab1.run("1\n2.0\n0.5\n", warmup=0, reps=1)
        payload = out.split("\n")[1]
        assert payload == "1.5000000000e+00 "

    def test_timing_line_first_and_parsable(self):
        out = lab1.run("2\n1 2\n3 4\n", warmup=0, reps=1)
        assert parse_timing_line(out.split("\n")[0]) is not None
