"""lab2 Roberts tests: golden bit-exactness, C-semantics oracle, Pallas parity."""

import numpy as np
import pytest

from tpulab.io import load_image, protocol, save_image
from tpulab.labs import lab2
from tpulab.ops.roberts import roberts_edges
from tpulab.ops.pallas.stencil import launch_to_tile, roberts_pallas
from tpulab.runtime.timing import parse_timing_line


def roberts_oracle_c(pixels: np.ndarray) -> np.ndarray:
    """C-semantics Roberts oracle — ONE copy, shared with the selftest
    command (tpulab/selftest.py).  Independence of this suite's golden
    checks is anchored by the reference's committed golden files, not
    by a duplicate oracle implementation."""
    from tpulab.selftest import roberts_oracle_np

    return roberts_oracle_np(pixels)


class TestGolden:
    @pytest.mark.parametrize("name", ["test_01", "test_02"])
    def test_reference_goldens_bit_exact(self, reference_root, name):
        img = load_image(str(reference_root / f"lab2/data/{name}.txt"))
        expect = load_image(str(reference_root / f"lab2/data_out_gt/{name}.txt"))
        out = np.asarray(roberts_edges(img))
        np.testing.assert_array_equal(out, expect)

    def test_lenna_note(self, reference_root):
        # lab2/test_data/lenna_out.data predates the committed kernel (its
        # pixels are not gray, the committed kernel always emits r==g==b),
        # so it is NOT a golden. We instead pin lenna against the
        # independent C-semantics numpy oracle, bit-exact.
        img = load_image(str(reference_root / "lab2/test_data/lenna.data"))
        out = np.asarray(roberts_edges(img))
        np.testing.assert_array_equal(out, roberts_oracle_c(img))

    def test_committed_showcase_pair_bit_exact(self):
        """The committed 512x512 before/after pair (data/lab2/showcase,
        the reference lab2/test_data analog) stays bit-exact to the op:
        edges(committed input) == committed output, and the .png mirrors
        hold the same pixels as the .data files."""
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        show = os.path.join(repo, "data/lab2/showcase")
        inp = load_image(os.path.join(show, "cityline_512.data"))
        expect = load_image(os.path.join(show, "cityline_512_roberts.data"))
        assert inp.shape == (512, 512, 4)
        np.testing.assert_array_equal(np.asarray(roberts_edges(inp)), expect)
        np.testing.assert_array_equal(
            load_image(os.path.join(show, "cityline_512.png")), inp
        )
        np.testing.assert_array_equal(
            load_image(os.path.join(show, "cityline_512_roberts.png")), expect
        )

    def test_random_images_vs_oracle(self, rng):
        for h, w in [(1, 1), (1, 5), (3, 3), (17, 31), (64, 129)]:
            img = rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)
            np.testing.assert_array_equal(
                np.asarray(roberts_edges(img)), roberts_oracle_c(img)
            )

    def test_alpha_preserved(self, rng):
        img = rng.integers(0, 256, size=(4, 4, 4), dtype=np.uint8)
        out = np.asarray(roberts_edges(img))
        np.testing.assert_array_equal(out[..., 3], img[..., 3])


class TestPallasStencil:
    def test_matches_jnp_bit_exact(self, rng):
        for h, w in [(3, 3), (16, 130), (64, 257), (200, 100)]:
            img = rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)
            out_p = np.asarray(roberts_pallas(img, interpret=True))
            out_j = np.asarray(roberts_edges(img))
            np.testing.assert_array_equal(out_p, out_j)

    def test_sweep_tile_config(self, rng):
        img = rng.integers(0, 256, size=(40, 300, 4), dtype=np.uint8)
        out = np.asarray(roberts_pallas(img, launch=(32, 32, 16, 16), interpret=True))
        np.testing.assert_array_equal(out, roberts_oracle_c(img))

    def test_launch_to_tile_mapping(self):
        assert launch_to_tile(None, 2048, 2048) == (256, 512)
        assert launch_to_tile((32, 32, 16, 16), 2048, 2048) == (256, 512)
        assert launch_to_tile((2, 2, 16, 16), 2048, 2048) == (16, 128)
        assert launch_to_tile((16, 16, 1024, 1024), 2048, 2048) == (128, 256)
        # small image clamps the tile
        assert launch_to_tile((32, 32, 16, 16), 3, 3) == (8, 128)


class TestLab2Protocol:
    def test_end_to_end(self, tmp_path, rng, reference_root):
        src = str(reference_root / "lab2/data/test_01.txt")
        img = load_image(src)
        inp = str(tmp_path / "in.data")
        out = str(tmp_path / "out.data")
        save_image(inp, img)
        text = protocol.format_lab2_input(inp, out)
        stdout = lab2.run(text, warmup=0, reps=1)
        assert parse_timing_line(stdout) is not None
        expect = load_image(str(reference_root / "lab2/data_out_gt/test_01.txt"))
        np.testing.assert_array_equal(load_image(out), expect)

    def test_sweep_mode_prints_finished(self, tmp_path, rng):
        img = rng.integers(0, 256, size=(3, 3, 4), dtype=np.uint8)
        inp = str(tmp_path / "in.data")
        out = str(tmp_path / "out.data")
        save_image(inp, img)
        text = protocol.format_lab2_input(inp, out, launch=(32, 32, 16, 16))
        stdout = lab2.run(text, sweep=True, warmup=0, reps=1)
        assert stdout.splitlines()[0].startswith("CPU execution time")
        assert stdout.splitlines()[1] == "FINISHED!"
