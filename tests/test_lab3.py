"""lab3 Mahalanobis classifier tests: golden, statistics, Pallas parity."""

import numpy as np
import pytest

from tpulab.io import load_image, protocol, save_image
from tpulab.labs import lab3
from tpulab.ops.mahalanobis import ClassStats, class_statistics, classify, classify_labels
from tpulab.runtime.timing import parse_timing_line

import jax.numpy as jnp

# the reference harness's hard-coded class definition for the golden
# fixture (lab3/lab3_processor.py MAP_TO_INIT_POINTS)
GOLDEN_CLASSES = [
    np.array([[1, 2], [1, 0], [2, 2], [2, 1]]),
    np.array([[0, 0], [0, 1], [1, 1], [2, 0]]),
]


def classify_oracle(pixels, stats):
    """Pure-NumPy f64 restatement of the classify kernel (main.cu:40-76)."""
    h, w = pixels.shape[:2]
    p = pixels[..., :3].astype(np.float64)
    labels = np.zeros((h, w), np.uint8)
    for y in range(h):
        for x in range(w):
            best, best_d = -1, np.inf
            for c in range(len(stats.mean)):
                d = p[y, x] - stats.mean[c]
                t = d @ stats.inv_cov[c]
                dist = float(t @ d)
                if dist < best_d:
                    best_d, best = dist, c
            labels[y, x] = best
    return labels


class TestGolden:
    def test_reference_golden_bit_exact(self, reference_root):
        img = load_image(str(reference_root / "lab3/data/test_01_lab3.txt"))
        expect = load_image(str(reference_root / "lab3/data_out_gt/test_01_lab3.txt"))
        stats = class_statistics(img, GOLDEN_CLASSES)
        out = np.asarray(classify(img, stats))
        np.testing.assert_array_equal(out, expect)

    def test_golden_with_f32_kernel(self, reference_root):
        # the TPU fast path computes in f32; labels must agree on the golden
        img = load_image(str(reference_root / "lab3/data/test_01_lab3.txt"))
        expect = load_image(str(reference_root / "lab3/data_out_gt/test_01_lab3.txt"))
        stats = class_statistics(img, GOLDEN_CLASSES)
        labels = np.asarray(
            classify_labels(img, jnp.asarray(stats.mean), jnp.asarray(stats.inv_cov))
        )
        np.testing.assert_array_equal(labels, expect[..., 3])


class TestStatistics:
    def test_mean_and_cov(self, rng):
        img = rng.integers(0, 256, size=(8, 8, 4), dtype=np.uint8)
        pts = np.array([[0, 0], [1, 1], [2, 2], [3, 3], [4, 4]])
        stats = class_statistics(img, [pts])
        samples = img[pts[:, 1], pts[:, 0], :3].astype(np.float64)
        np.testing.assert_allclose(stats.mean[0], samples.mean(0))
        cov = np.cov(samples.T, ddof=1)
        np.testing.assert_allclose(stats.inv_cov[0], np.linalg.inv(cov), rtol=1e-8)

    def test_single_point_class_degenerate(self, rng):
        # /(np-1) with np==1 -> division by zero, preserved from main.cu:137
        img = rng.integers(0, 256, size=(4, 4, 4), dtype=np.uint8)
        stats = class_statistics(img, [np.array([[0, 0]])])
        assert not np.isfinite(stats.inv_cov[0]).all()

    def test_max_classes_enforced(self, rng):
        img = rng.integers(0, 256, size=(4, 4, 4), dtype=np.uint8)
        with pytest.raises(ValueError):
            class_statistics(img, [np.array([[0, 0]])] * 33)


class TestClassify:
    def _random_case(self, rng, h=12, w=17, nc=3):
        img = rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)
        classes = []
        for _ in range(nc):
            pts = np.stack(
                [rng.integers(0, w, size=5), rng.integers(0, h, size=5)], axis=1
            )
            classes.append(pts)
        return img, class_statistics(img, classes)

    def test_degenerate_class_never_wins(self, rng):
        # a single-point class has NaN inv_cov; its NaN distances must lose
        # to any finite distance (C strict-< rejects NaN, main.cu:68-71)
        img = rng.integers(0, 256, size=(6, 6, 4), dtype=np.uint8)
        degenerate = np.array([[0, 0]])
        normal = np.stack([rng.integers(0, 6, 5), rng.integers(0, 6, 5)], axis=1)
        stats = class_statistics(img, [degenerate, normal])
        assert not np.isfinite(stats.inv_cov[0]).all()
        out = np.asarray(classify(img, stats))
        assert (out[..., 3] == 1).all()  # the normal class wins everywhere

    def test_matches_oracle_f64(self, rng):
        img, stats = self._random_case(rng)
        out = np.asarray(classify(img, stats, compute_dtype=jnp.float64))
        np.testing.assert_array_equal(out[..., 3], classify_oracle(img, stats))
        np.testing.assert_array_equal(out[..., :3], img[..., :3])  # RGB preserved

    def test_pallas_matches_jnp(self, rng):
        from tpulab.ops.pallas.classify import classify_labels_pallas

        img, stats = self._random_case(rng, h=33, w=70, nc=4)
        mu = jnp.asarray(stats.mean, jnp.float32)
        ic = jnp.asarray(stats.inv_cov, jnp.float32)
        ref = np.asarray(classify_labels(img, mu, ic, compute_dtype=jnp.float32))
        out = np.asarray(classify_labels_pallas(img, mu, ic, interpret=True))
        np.testing.assert_array_equal(out, ref)

    def test_pallas_sweep_configs(self, rng):
        from tpulab.ops.pallas.classify import classify_labels_pallas, launch_to_rows

        assert launch_to_rows(None) == 512
        assert launch_to_rows((1, 32)) == 8
        assert launch_to_rows((256, 256)) == 512
        img, stats = self._random_case(rng, h=9, w=200, nc=2)
        mu = jnp.asarray(stats.mean, jnp.float32)
        ic = jnp.asarray(stats.inv_cov, jnp.float32)
        ref = np.asarray(classify_labels(img, mu, ic, compute_dtype=jnp.float32))
        for launch in [(1, 32), (16, 16), (256, 256)]:
            out = np.asarray(
                classify_labels_pallas(img, mu, ic, launch=launch, interpret=True)
            )
            np.testing.assert_array_equal(out, ref)


class TestLab3Protocol:
    def test_end_to_end_golden(self, tmp_path, reference_root):
        img = load_image(str(reference_root / "lab3/data/test_01_lab3.txt"))
        inp = str(tmp_path / "in.data")
        outp = str(tmp_path / "out.data")
        save_image(inp, img)
        text = protocol.format_lab3_input(inp, outp, GOLDEN_CLASSES)
        stdout = lab3.run(text, warmup=0, reps=1)
        assert parse_timing_line(stdout) is not None
        expect = load_image(str(reference_root / "lab3/data_out_gt/test_01_lab3.txt"))
        np.testing.assert_array_equal(load_image(outp), expect)

    def test_sweep_prefix(self, tmp_path, rng):
        img = rng.integers(0, 256, size=(3, 3, 4), dtype=np.uint8)
        inp = str(tmp_path / "in.data")
        outp = str(tmp_path / "out.data")
        save_image(inp, img)
        text = protocol.format_lab3_input(
            inp, outp, [np.array([[0, 0], [1, 1]])], launch=(256, 256)
        )
        stdout = lab3.run(text, sweep=True, warmup=0, reps=1)
        assert parse_timing_line(stdout) is not None
