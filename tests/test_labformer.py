"""Labformer tests: shapes, training, and sharded-vs-single-device parity.

Runs on the 8-virtual-device CPU mesh (conftest).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpulab.models.labformer import (
    ACT_SPEC,
    LabformerConfig,
    _restrict,
    dryrun_train_step,
    expert_load,
    forward,
    forward_with_aux,
    init_params,
    init_train_state,
    loss_fn,
    shard_params,
)
from tpulab.parallel.mesh import cpu_test_mesh


CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)


def _tokens(rng, b=2, s=32):
    return jnp.asarray(rng.integers(0, 256, (b, s)), jnp.int32)


class TestForward:
    def test_logit_shape(self, rng):
        params = init_params(CFG, seed=0)
        logits = forward(params, _tokens(rng), CFG)
        assert logits.shape == (2, 32, 256)
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality(self, rng):
        """Changing a future token must not affect earlier logits."""
        params = init_params(CFG, seed=0)
        t1 = np.asarray(_tokens(rng))
        t2 = t1.copy()
        t2[:, -1] = (t2[:, -1] + 1) % 256
        l1 = np.asarray(forward(params, jnp.asarray(t1), CFG))
        l2 = np.asarray(forward(params, jnp.asarray(t2), CFG))
        np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], rtol=1e-5, atol=1e-5)
        assert not np.allclose(l1[:, -1], l2[:, -1])

    def test_flash_backend_matches_dense(self, rng):
        import dataclasses

        dense_cfg = dataclasses.replace(CFG, attn_impl="dense")
        flash_cfg = dataclasses.replace(CFG, attn_impl="flash")
        params = init_params(dense_cfg, seed=0)
        tokens = _tokens(rng, b=2, s=64)
        a = np.asarray(forward(params, tokens, dense_cfg))
        b = np.asarray(forward(params, tokens, flash_cfg))
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    def test_moe_forward(self, rng):
        cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, n_experts=4)
        params = init_params(cfg, seed=0)
        logits = forward(params, _tokens(rng), cfg)
        assert logits.shape == (2, 32, 256)
        assert np.isfinite(np.asarray(logits)).all()


class TestSlidingWindowModel:
    def test_windowed_forward_matches_masked_oracle(self, rng):
        """cfg.attn_window must equal dense attention with the window
        mask — checked through the full model forward."""
        import dataclasses

        wcfg = dataclasses.replace(CFG, attn_window=5)
        params = init_params(wcfg, seed=0)
        tok = jnp.asarray(rng.integers(0, 256, (2, 16)).astype(np.int32))
        got = np.asarray(forward(params, tok, wcfg))
        assert np.all(np.isfinite(got))
        # window >= seq is exactly full causal
        wide = dataclasses.replace(CFG, attn_window=16)
        np.testing.assert_allclose(
            np.asarray(forward(params, tok, wide)),
            np.asarray(forward(params, tok, CFG)),
            rtol=1e-6, atol=1e-6,
        )
        # window < seq is a different function
        assert not np.allclose(got, np.asarray(forward(params, tok, CFG)))

    def test_ring_sp_windows_match_single_device(self, rng):
        """Windowed ring sp: the windowed ring body (dense and flash
        local) must equal the single-device windowed forward — the
        refusal this replaced existed exactly because silently dropping
        the window would change the model function between topologies."""
        import dataclasses

        mesh = cpu_test_mesh({"sp": 2})
        tok = jnp.asarray(rng.integers(0, 256, (2, 16)).astype(np.int32))
        for impl in ("dense", "flash"):
            wcfg = dataclasses.replace(CFG, attn_window=5, sp_impl="ring",
                                       attn_impl=impl)
            params = init_params(wcfg, seed=0)
            got = np.asarray(forward(params, tok, wcfg, mesh=mesh))
            want = np.asarray(forward(params, tok, dataclasses.replace(
                wcfg, attn_impl="dense")))
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5,
                                       err_msg=impl)

    def test_window_rejected_on_zigzag_sp_mesh(self, rng):
        """zigzag keeps refusing a window: its balance rationale is void
        there and ring is the windowed path."""
        import dataclasses

        mesh = cpu_test_mesh({"sp": 2})
        wcfg = dataclasses.replace(CFG, attn_window=5, sp_impl="zigzag")
        params = init_params(wcfg, seed=0)
        tok = jnp.asarray(rng.integers(0, 256, (2, 16)).astype(np.int32))
        with pytest.raises(NotImplementedError, match="attn_window"):
            forward(params, tok, wcfg, mesh=mesh)

    def test_ulysses_sp_windows_match_single_device(self, rng):
        """Ulysses gathers the full sequence per head group, so the
        window mask applies globally — sp output must equal the
        single-device windowed forward."""
        import dataclasses

        mesh = cpu_test_mesh({"sp": 2})
        wcfg = dataclasses.replace(CFG, attn_window=5, sp_impl="ulysses")
        params = init_params(wcfg, seed=0)
        tok = jnp.asarray(rng.integers(0, 256, (2, 16)).astype(np.int32))
        got = np.asarray(forward(params, tok, wcfg, mesh=mesh))
        want = np.asarray(forward(params, tok, wcfg))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_negative_window_rejected(self):
        import dataclasses

        with pytest.raises(ValueError, match="attn_window"):
            dataclasses.replace(CFG, attn_window=-1)


class TestTraining:
    def test_loss_decreases(self, rng):
        params, opt_state, step = init_train_state(CFG, mesh=None, seed=0)
        tokens = _tokens(rng, b=4, s=33)
        losses = []
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))

    def test_flash_attention_is_trainable(self, rng):
        """The Pallas flash path (attn_impl auto kicks in from s=1024)
        must differentiate via its custom_vjp — long-context training
        depends on it (round-1 gap: no VJP, grad through the kernel
        failed)."""
        import dataclasses

        cfg = dataclasses.replace(CFG, attn_impl="flash")
        params, opt_state, step = init_train_state(cfg, mesh=None, seed=0)
        tokens = _tokens(rng, b=2, s=33)
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))


class TestMoeAuxLoss:
    def test_aux_near_one_at_init(self, rng):
        """A fresh (small-scale random) router routes near-uniformly, so
        aux sits near its uniform optimum of 1 (not a general lower
        bound — concentrated routing with skewed gates can dip below)."""
        cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, n_experts=4)
        params = init_params(cfg, seed=0)
        _, aux = forward_with_aux(params, _tokens(rng), cfg)
        assert 0.9 < float(aux) < 1.5, float(aux)

    def test_dense_model_has_zero_aux(self, rng):
        _, aux = forward_with_aux(init_params(CFG, seed=0), _tokens(rng), CFG)
        assert float(aux) == 0.0

    def test_aux_discriminates_collapsed_routing(self):
        """The aux loss itself must rank collapsed routing strictly worse
        than balanced routing — this unit check (not the training smoke
        below) is the regression guard on aux efficacy.  Switch loss
        (Fedus et al. 2021 eq. 4): uniform == 1, full collapse == E."""
        from tpulab.models.labformer import _moe_aux_loss

        b, s, n_experts = 2, 32, 4  # gate (b, s, E), top (b, s)
        # balanced: router spreads probability evenly, tokens round-robin
        gate_u = jnp.full((b, s, n_experts), 1.0 / n_experts)
        top_u = (jnp.arange(b * s, dtype=jnp.int32) % n_experts).reshape(b, s)
        aux_u, _ = _moe_aux_loss(gate_u, top_u, n_experts)
        # collapsed: all probability mass and all tokens on expert 0
        gate_c = jnp.zeros((b, s, n_experts)).at[..., 0].set(1.0)
        top_c = jnp.zeros((b, s), jnp.int32)
        aux_c, _ = _moe_aux_loss(gate_c, top_c, n_experts)
        np.testing.assert_allclose(float(aux_u), 1.0, atol=1e-6)
        np.testing.assert_allclose(float(aux_c), float(n_experts), atol=1e-6)

    def test_no_collapse_under_dispatch_training(self, rng):
        """Training through the all_to_all dispatch path stays finite and
        keeps expert assignment spread.  This is a stability smoke test
        of the dispatch path, NOT an aux-efficacy guard: measured
        2026-07-30, this config with moe_aux_weight=0.0 does not collapse
        within 100 steps either (frac drifts ~[0.20,0.29,0.31,0.20] —
        the horizon cut 100->60 lost no discrimination; the aux guard
        lives in test_aux_discriminates_collapsed_routing)."""
        mesh = cpu_test_mesh({"dp": 2, "sp": 2, "tp": 2})
        cfg = LabformerConfig(
            d_model=32,
            n_heads=4,
            n_layers=2,
            d_ff=32,
            n_experts=4,
            moe_impl="dispatch",
            moe_aux_weight=0.05,
        )
        params, opt_state, step = init_train_state(cfg, mesh, seed=0)
        tok_sharding = NamedSharding(mesh, _restrict(P("dp", None), mesh))
        data = rng.integers(0, 256, (16, 4, 33)).astype(np.int32)
        for i in range(60):
            tokens = jax.device_put(jnp.asarray(data[i % 16]), tok_sharding)
            params, opt_state, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))
        host = jax.device_get(params)
        eval_tokens = jnp.asarray(data.reshape(-1, 33)[:, :-1])
        frac = np.asarray(expert_load(host, eval_tokens, cfg)).mean(axis=0)
        assert frac.max() < 0.8, f"router collapsed: {frac}"
        assert (frac > 0.02).sum() >= 2, f"experts starved: {frac}"


class TestSharded:
    @pytest.fixture(scope="class")
    def mesh(self):
        return cpu_test_mesh({"dp": 2, "sp": 2, "tp": 2})

    def test_forward_parity(self, mesh, rng):
        """Sharded forward (ring attention over sp, tp matmuls, dp batch)
        must match the single-device forward to float tolerance."""
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, CFG, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_ulysses_sp_parity(self, mesh, rng):
        """Ulysses sequence parallelism == ring == single-device."""
        import dataclasses

        uly = dataclasses.replace(CFG, sp_impl="ulysses")
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, uly, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_zigzag_sp_parity(self, mesh, rng):
        """Zigzag (load-balanced causal ring) sp == single-device; the
        layout shuffle is internal, so tokens/labels/rope stay in normal
        order at the model boundary."""
        import dataclasses

        zz = dataclasses.replace(CFG, sp_impl="zigzag")
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, zz, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_zigzag_sp_trains(self, mesh, rng):
        """The zigzag path differentiates through its cond/fori_loop and
        layout gathers: a few sharded train steps decrease a finite loss."""
        import dataclasses

        cfg = dataclasses.replace(CFG, sp_impl="zigzag")
        params, opt_state, step = init_train_state(cfg, mesh, seed=0)
        tok_sharding = NamedSharding(mesh, _restrict(P("dp", None), mesh))
        losses = []
        for i in range(4):
            tokens = jax.device_put(_tokens(rng, b=4, s=33), tok_sharding)
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    def test_zigzag_flash_local_parity(self, mesh, rng):
        """Zigzag sp with flash local attends (attn_impl=flash) ==
        single-device dense."""
        import dataclasses

        zz = dataclasses.replace(CFG, sp_impl="zigzag", attn_impl="flash")
        base = dataclasses.replace(CFG, attn_impl="dense")
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, base, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, zz, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_ulysses_flash_local_parity(self, mesh, rng):
        """Ulysses sp with the Pallas flash kernel as the gathered-sequence
        local attention (attn_impl=flash) == single-device dense."""
        import dataclasses

        uly = dataclasses.replace(CFG, sp_impl="ulysses", attn_impl="flash")
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, uly, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_ring_flash_local_parity(self, mesh, rng):
        """Ring sp with flash per-step block attention (attn_impl=flash)
        == single-device dense."""
        import dataclasses

        rf = dataclasses.replace(CFG, sp_impl="ring", attn_impl="flash")
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(jax.jit(lambda p, t: forward(p, t, rf, mesh=mesh))(sharded, tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_dispatch_moe_parity(self, mesh, rng):
        """all_to_all expert dispatch == dense-gate MoE at full capacity."""
        import dataclasses

        base = dataclasses.replace(CFG, n_experts=4)
        dispatch = dataclasses.replace(
            base, moe_impl="dispatch", moe_capacity_factor=float(base.n_experts)
        )
        params = init_params(base, seed=0)
        tokens = _tokens(rng, b=4, s=32)
        want = np.asarray(forward(params, tokens, base, mesh=None))
        sharded = shard_params(params, base, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = np.asarray(
            jax.jit(lambda p, t: forward(p, t, dispatch, mesh=mesh))(sharded, tok_sh)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_loss_parity(self, mesh, rng):
        params = init_params(CFG, seed=0)
        tokens = _tokens(rng, b=4, s=33)
        want = float(loss_fn(params, tokens, CFG, mesh=None))
        sharded = shard_params(params, CFG, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, _restrict(P("dp", None), mesh)))
        got = float(jax.jit(lambda p, t: loss_fn(p, t, CFG, mesh=mesh))(sharded, tok_sh))
        assert abs(got - want) < 1e-3, (got, want)


class TestDryrun:
    @pytest.mark.parametrize("n", [1, 2, 4, 8])
    def test_dryrun_train_step(self, n):
        dryrun_train_step(n, backend="cpu")
