"""labvision CNN: learns the lab3 color-class task; dp-sharded training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.models.labvision import (
    LabvisionConfig,
    accuracy,
    class_color_means,
    forward,
    init_params,
    init_train_state,
    shard_batch,
    synth_batch,
)

CFG = LabvisionConfig(n_classes=4, img_size=16, channels=(8, 16))


@pytest.fixture
def rng():
    return np.random.default_rng(0)


class TestForward:
    def test_shapes_and_dtype(self, rng):
        params = init_params(CFG, seed=0)
        imgs, _ = synth_batch(CFG, 4, rng)
        logits = forward(params, jnp.asarray(imgs), CFG)
        assert logits.shape == (4, CFG.n_classes)
        assert logits.dtype == jnp.float32

    def test_uint8_and_float_agree(self, rng):
        params = init_params(CFG, seed=0)
        imgs, _ = synth_batch(CFG, 4, rng)
        a = np.asarray(forward(params, jnp.asarray(imgs), CFG))
        b = np.asarray(
            forward(params, jnp.asarray(imgs.astype(np.float32) / 255.0), CFG)
        )
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


class TestTraining:
    def test_learns_the_lab3_task(self, rng):
        """The CNN must learn what lab3 computes analytically: which
        Gaussian color class produced the image."""
        params, opt_state, step = init_train_state(CFG, seed=0)
        for _ in range(150):
            imgs, labels = synth_batch(CFG, 64, rng)
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(imgs), jnp.asarray(labels)
            )
        assert np.isfinite(float(loss))
        imgs, labels = synth_batch(CFG, 256, rng)
        acc = accuracy(params, imgs, labels, CFG)
        assert acc > 0.9, f"accuracy {acc}"

    def test_class_means_separated(self):
        mus = class_color_means(LabvisionConfig(n_classes=8))
        d = np.linalg.norm(mus[:, None] - mus[None], axis=-1)
        np.fill_diagonal(d, np.inf)
        assert d.min() > 10.0  # distinct classes


class TestSharded:
    def test_dp_training_matches_single_device(self, rng):
        mesh = cpu_test_mesh({"dp": 8})
        cfg = CFG
        imgs, labels = synth_batch(cfg, 64, rng)

        params_s, opt_s, step_s = init_train_state(cfg, mesh, seed=0)
        im_s, lb_s = shard_batch(jnp.asarray(imgs), jnp.asarray(labels), mesh)
        params_s, opt_s, loss_s = step_s(params_s, opt_s, im_s, lb_s)

        params_1, opt_1, step_1 = init_train_state(cfg, seed=0)
        params_1, opt_1, loss_1 = step_1(
            params_1, opt_1, jnp.asarray(imgs), jnp.asarray(labels)
        )
        np.testing.assert_allclose(float(loss_s), float(loss_1), rtol=1e-5)
        w_s = np.asarray(jax.device_get(params_s["head"]["w"]))
        w_1 = np.asarray(jax.device_get(params_1["head"]["w"]))
        np.testing.assert_allclose(w_s, w_1, rtol=1e-4, atol=1e-6)
