"""Native prefetching token loader (native/loader/tpulab_loader.cpp).

Properties pinned: byte-token fidelity (every emitted token is a byte
of some input file), step-ordered delivery, bit-determinism across
thread counts (the concurrency must be unobservable), start_step resume
alignment, small-file rejection, and the train-driver integration.
"""

import pathlib
import shutil
import subprocess
import sys

import numpy as np
import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def loader_lib():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    subprocess.run([sys.executable, str(ROOT / "tools" / "build_native.py")],
                   check=True)
    from tpulab.io.loader import TokenLoader

    return TokenLoader


@pytest.fixture
def corpus(tmp_path):
    (tmp_path / "a.bin").write_bytes(bytes(range(256)) * 8)
    (tmp_path / "b.bin").write_bytes(b"\x07" * 1024)
    return tmp_path


def test_shapes_and_byte_range(loader_lib, corpus):
    with loader_lib.from_dir(corpus, batch=4, row_tokens=33, seed=1) as ld:
        for _ in range(3):
            b = ld.next()
            assert b.shape == (4, 33) and b.dtype == np.int32
            assert b.min() >= 0 and b.max() < 256


def test_rows_come_from_files(loader_lib, tmp_path):
    # single constant-byte file: every token must be that byte
    (tmp_path / "x.bin").write_bytes(b"\x2a" * 500)
    with loader_lib.from_dir(tmp_path, batch=3, row_tokens=17, seed=0) as ld:
        assert np.all(ld.next() == 0x2A)


def test_deterministic_across_thread_counts(loader_lib, corpus):
    def stream(threads, n=5):
        with loader_lib.from_dir(
            corpus, batch=4, row_tokens=21, seed=9, threads=threads
        ) as ld:
            return [ld.next() for _ in range(n)]

    a, b = stream(1), stream(4)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


def test_step_order_and_resume(loader_lib, corpus):
    with loader_lib.from_dir(corpus, batch=2, row_tokens=9, seed=3) as ld:
        seq = [ld.next() for _ in range(6)]
        assert ld.last_step == 5
    with loader_lib.from_dir(
        corpus, batch=2, row_tokens=9, seed=3, start_step=4
    ) as ld:
        assert np.array_equal(ld.next(), seq[4])
        assert np.array_equal(ld.next(), seq[5])


def test_small_files_skipped_and_empty_rejected(loader_lib, tmp_path):
    (tmp_path / "tiny.bin").write_bytes(b"ab")  # < row_tokens: skipped
    (tmp_path / "ok.bin").write_bytes(b"z" * 100)
    with loader_lib.from_dir(tmp_path, batch=2, row_tokens=10) as ld:
        assert np.all(ld.next() == ord("z"))
    only_tiny = tmp_path / "sub"
    only_tiny.mkdir()
    (only_tiny / "tiny.bin").write_bytes(b"ab")
    with pytest.raises(RuntimeError, match="full row"):
        loader_lib.from_dir(only_tiny, batch=2, row_tokens=10)


def test_train_driver_streams_from_data_dir(loader_lib, corpus):
    from tpulab.train import train

    step, loss = train(
        steps=3, batch=4, seq=16, data_dir=str(corpus), log=lambda *a: None
    )
    assert step == 3 and np.isfinite(loss)


def test_train_eval_stream_uses_corpus(loader_lib, corpus):
    # eval under data_dir must draw from the corpus loader (seed-offset
    # stream), not the synthetic generator
    from tpulab.train import train

    lines = []
    step, loss = train(
        steps=4, batch=4, seq=16, data_dir=str(corpus), eval_every=2,
        log=lambda *a: lines.append(" ".join(map(str, a))),
    )
    evals = [l for l in lines if "[eval]" in l]
    assert len(evals) == 2 and np.isfinite(loss)


def test_train_refuses_data_dir_for_labvision(loader_lib, corpus):
    from tpulab.train import train

    with pytest.raises(ValueError, match="labformer"):
        train(steps=1, model="labvision", data_dir=str(corpus))
