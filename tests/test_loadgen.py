"""Goodput layer (round 12): trace-driven load generator, per-request
rid-linked tracing + slow log, and the goodput gate.

Covers the round-12 ISSUE acceptance:
  * a seeded trace build is BYTE-deterministic (same spec -> identical
    JSON twice), round-trips exactly, and carries the workload features
    (bursty on-off arrivals, heavy-tail sizes, multi-turn sessions that
    extend their parent's prompt verbatim, per-class deadline/priority
    mixes, scripted mid-stream cancellations);
  * every request threads ONE process-unique ``rid`` through daemon ->
    engine -> tracer, so its events form a linked span tree and its
    slow-log entry (worst-N by e2e, with queue-wait / prefill-chunk /
    TTFT / worst-ITL-gap-and-token summaries) keys straight into the
    trace;
  * the daemon answers a ``slowlog`` request with those entries;
  * ``tools/goodput_gate.py`` replays a trace against a LIVE daemon
    and reports per-class goodput-under-SLO plus the slowlog, emitting
    the bench rows ``check_regression.py`` gates against the signed
    baselines;
  * the new surfaces are documented (catalog lint, the test_obs
    pattern).
"""

import json
import pathlib

import numpy as np
import pytest

from tpulab import loadgen, obs
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs.slowlog import SlowLog

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


# ------------------------------------------------------------ trace build
def test_trace_build_byte_deterministic():
    """The acceptance criterion: same spec -> byte-identical JSON, so a
    committed trace file IS the workload and a replay is exact."""
    spec = loadgen.built_in_spec("fast")
    a = loadgen.build_trace(spec).to_json()
    b = loadgen.build_trace(spec).to_json()
    assert a == b
    # a different seed is a different workload
    from dataclasses import replace

    c = loadgen.build_trace(replace(spec, seed=spec.seed + 1)).to_json()
    assert c != a


def test_trace_roundtrip_and_schema():
    trace = loadgen.build_trace(loadgen.built_in_spec("fast"))
    again = loadgen.Trace.from_json(trace.to_json())
    assert again.requests == trace.requests
    assert again.classes == trace.classes
    ts = [r["t_ms"] for r in trace.requests]
    assert ts == sorted(ts)
    names = {c["name"] for c in trace.classes}
    for r in trace.requests:
        assert r["cls"] in names
        # every request fits the daemon serving window
        assert len(r["prompt"]) + r["steps"] <= trace.spec["max_total"]
        assert r["steps"] >= trace.spec["steps_min"]
    with pytest.raises(ValueError, match="version"):
        loadgen.Trace.from_json('{"version": 99}')


def test_trace_workload_features():
    """The fast spec exercises every workload dimension: both SLO
    classes (distinct priority/deadline), multi-turn sessions whose
    follow-up prompts EXTEND the parent verbatim (the prefix-cache
    reuse shape), scripted cancellations, and heavy-tailed sizes."""
    trace = loadgen.build_trace(loadgen.built_in_spec("fast"))
    by_cls = {}
    for r in trace.requests:
        by_cls.setdefault(r["cls"], []).append(r)
    assert set(by_cls) == {"interactive", "bulk"}
    prios = {r["priority"] for r in trace.requests}
    assert len(prios) > 1  # preemption-rank mix on the wire
    assert any(r["deadline_ms"] is not None for r in trace.requests)
    assert any(r["deadline_ms"] is None for r in trace.requests)
    assert any(r["cancel_after_ms"] is not None for r in trace.requests)
    # session prefix reuse: turn t+1 starts with turn t's full prompt
    by_sess = {}
    for r in trace.requests:
        by_sess.setdefault(r["session"], []).append(r)
    pairs = 0
    for rs in by_sess.values():
        rs.sort(key=lambda r: r["turn"])
        for a, b in zip(rs, rs[1:]):
            assert b["prompt"].startswith(a["prompt"])
            pairs += 1
    assert pairs > 0, "no multi-turn sessions in the fast trace"
    # heavy tail: the longest prompt well past the median
    lens = sorted(len(r["prompt"]) for r in trace.requests)
    assert lens[-1] >= 2 * lens[len(lens) // 2]


def test_shed_re_wire_contract():
    """SHED_RE is THE one copy of the client-side park/shed pattern:
    it must accept all three wire frames — load shed, whole-fleet
    rebuilding park, and the round-20 pool-scoped rebuilding park —
    with stable group numbering (1 = arm, 2 = retry-after ms), and the
    optional pool tag must never let the arms blur together."""
    cases = [
        ("req shed retry_after_ms=40 (queue past deadline)",
         ("shed", "40")),
        ("rebuilding retry_after_ms=120 (rolling restart)",
         ("rebuilding", "120")),
        # round 20: disaggregated pool park tags the frame with the
        # pool role; the non-capturing tag keeps group numbers stable
        ("rebuilding pool=prefill retry_after_ms=250 (no placeable "
         "replica in pool)", ("rebuilding", "250")),
        ("rebuilding pool=decode retry_after_ms=75 (scale-in drain)",
         ("rebuilding", "75")),
    ]
    for text, want in cases:
        m = loadgen.SHED_RE.search(text)
        assert m is not None, text
        assert m.groups() == want, text
    # a pool tag on the SHED arm would be a protocol violation today,
    # but the regex still parses arm+ms correctly if one ever appears
    m = loadgen.SHED_RE.search("shed pool=decode retry_after_ms=10")
    assert m.groups() == ("shed", "10")
    # non-frames must not match: no ms, wrong keyword, malformed tag
    for text in ("shed", "rebuilding pool=prefill", "parked for 100ms",
                 "rebuilding retry_after_ms=abc"):
        assert loadgen.SHED_RE.search(text) is None, text


def test_arrival_processes():
    from dataclasses import replace

    fast = loadgen.built_in_spec("fast")
    onoff = loadgen.build_trace(fast)
    poisson = loadgen.build_trace(replace(fast, arrival="poisson"))
    assert onoff.to_json() != poisson.to_json()
    with pytest.raises(ValueError, match="arrival"):
        loadgen.build_trace(replace(fast, arrival="bogus"))
    with pytest.raises(ValueError, match="unknown spec"):
        loadgen.built_in_spec("nope")
    # on-off arrivals actually burst: some inter-arrival gap is far
    # above the in-burst spacing (the off period)
    first_turn = [r["t_ms"] for r in onoff.requests if r["turn"] == 0]
    gaps = [b - a for a, b in zip(first_turn, first_turn[1:])]
    in_burst = 1e3 / (fast.rate_rps * fast.burst_factor)
    assert max(gaps) > 5 * in_burst


# ------------------------------------------------------------- slow log
def test_slowlog_worst_n_and_capacity():
    log = SlowLog(capacity=3)
    for i, e2e in enumerate((50.0, 10.0, 99.0, 70.0, 5.0)):
        log.record({"rid": i, "e2e_ms": e2e})
    worst = log.worst()
    assert [e["e2e_ms"] for e in worst] == [99.0, 70.0, 50.0]
    assert log.recorded == 5
    assert [e["rid"] for e in log.worst(2)] == [2, 3]
    log.clear()
    assert log.worst() == [] and log.recorded == 0
    disabled = SlowLog(capacity=0)
    disabled.record({"e2e_ms": 1.0})
    assert disabled.worst() == [] and disabled.recorded == 0
    with pytest.raises(ValueError, match="capacity"):
        SlowLog(capacity=-1)


def test_engine_records_rid_linked_slowlog(trained):
    """One engine wave, observability on: the slow log gains one span
    summary per retired request, and the entry's rid keys the SAME
    request's tracer events (submit -> admit -> first_token -> token*
    -> retire — the linked span tree)."""
    prior = obs.TRACER.capacity
    obs.SLOWLOG.clear()
    try:
        obs.configure_tracer(1 << 12)  # fresh, private window
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64, prefill_chunk=8)
        eng.submit(_cycle_prompt(20), max_new=6, tag="slow-a")
        eng.submit(_cycle_prompt(4), max_new=4, tag="slow-b")
        eng.run()
        worst = obs.SLOWLOG.worst()
        assert {e["tag"] for e in worst} == {"slow-a", "slow-b"}
        by_tag = {e["tag"]: e for e in worst}
        a = by_tag["slow-a"]
        assert a["tokens"] == 6 and a["prompt_len"] == 20
        assert a["prefill_chunks"] >= 2  # 19 prefill positions / chunk 8
        assert a["e2e_ms"] >= a["ttft_ms"] >= a["queue_wait_ms"] >= 0
        assert a["itl_max_ms"] >= 0 and 1 <= a["itl_max_at_token"] < 6
        assert a["preemptions"] == 0 and a["resubmits"] == 0
        # rid-linkage: the tracer's per-request events carry this rid
        events = obs.TRACER.chrome_trace()["traceEvents"]
        rid = a["rid"]
        mine = {e["name"] for e in events
                if e.get("args", {}).get("arg") == rid}
        assert {"engine.submit", "engine.admit", "engine.first_token",
                "engine.token", "engine.retire"} <= mine
        # the prefill chunk spans carry the rid on their B records
        assert any(e["name"] == "engine.prefill_chunk" and e["ph"] == "B"
                   and e.get("args", {}).get("arg") == rid for e in events)
        # rids are process-unique, distinct across requests
        assert by_tag["slow-b"]["rid"] != rid
    finally:
        obs.configure_tracer(prior)
        obs.SLOWLOG.clear()


def test_engine_obs_off_records_no_slowlog(trained):
    obs.SLOWLOG.clear()
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64, obs=False)
    eng.submit(_cycle_prompt(4), max_new=4)
    eng.run()
    assert obs.SLOWLOG.recorded == 0


def test_daemon_slowlog_request(trained):
    """Acceptance: the daemon ``slowlog`` request returns the worst-N
    with their span summaries, rid-linked and tag-labelled."""
    from tpulab.daemon import _GenerateService, handle_request

    obs.SLOWLOG.clear()
    try:
        svc = _GenerateService()
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64)
        rid = obs.next_rid()
        out = svc.generate(eng, _cycle_prompt(4), 8, req_rid=rid,
                           tag="wire-tag")
        assert len(out) == 8
        got = json.loads(handle_request({"lab": "slowlog",
                                         "config": {"n": 5}}, b""))
        assert got["recorded"] >= 1 and got["capacity"] > 0
        entry = next(e for e in got["worst"] if e["tag"] == "wire-tag")
        assert entry["rid"] == rid and entry["tokens"] == 8
        assert entry["e2e_ms"] > 0 and entry["ttft_ms"] is not None
        # config {"clear": true} resets after the read
        json.loads(handle_request(
            {"lab": "slowlog", "config": {"clear": True}}, b""))
        got = json.loads(handle_request({"lab": "slowlog"}, b""))
        assert got["recorded"] == 0 and got["worst"] == []
    finally:
        obs.SLOWLOG.clear()


# ------------------------------------------------------- live-daemon gate
def test_goodput_gate_against_live_daemon(tmp_path, capsys):
    """The round-12 acceptance scenario end to end: a seeded trace
    replayed by tools/goodput_gate.py against a LIVE daemon (spawned by
    the gate, CPU tier) — per-class goodput-under-SLO, the server
    window percentiles diffed from the PR-5 histograms, the slowlog
    worst-N with rid/tag linkage, and the bench rows the regression
    gate consumes."""
    import importlib.util
    from dataclasses import replace

    spec = importlib.util.spec_from_file_location(
        "goodput_gate", ROOT / "tools" / "goodput_gate.py")
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    tiny = replace(loadgen.built_in_spec("fast"), name="tiny",
                   n_requests=6, p_cancel=0.0, steps_median=8,
                   steps_max=12, prompt_median=24, prompt_max=64)
    trace_path = tmp_path / "tiny_trace.json"
    loadgen.build_trace(tiny).save(trace_path)
    out_path = tmp_path / "goodput.json"
    sock = str(tmp_path / "gate.sock")
    rc = gate.main(["--socket", sock, "--spawn-daemon",
                    "--trace", str(trace_path), "--out", str(out_path),
                    "--warmup", "1", "--slowlog", "4",
                    "--time-scale", "0.25", "--min-attainment", "0.0"])
    assert rc == 0
    report = json.loads(out_path.read_text())
    overall = report["goodput"]["overall"]
    assert overall["n"] == 6 and overall["errors"] == 0
    assert overall["completed"] == 6 and overall["shed"] == 0
    assert overall["goodput_tokens_per_s"] > 0
    assert set(report["goodput"]["classes"]) == {"interactive", "bulk"}
    # server-side window percentiles came from the scraped histograms
    assert report["server_window"]["ttft_seconds"]["count"] >= 6
    assert "daemon_shed_requests" in report["counters"]
    # slowlog entries are rid-linked and tag-labelled with trace rows
    assert report["slowlog"], "slowlog empty after a live replay"
    tags = {e["tag"] for e in report["slowlog"] if e["tag"]}
    assert any(t.startswith("tiny:") for t in tags), tags
    assert all(e["rid"] > 0 for e in report["slowlog"])
    # the emitted bench rows are what check_regression gates
    rows = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")]
    metrics = {r["metric"] for r in rows}
    assert {"goodput_tiny_goodput_tokens_per_s",
            "goodput_tiny_slo_attainment"} <= metrics


# ------------------------------------------------------------------ lint
def test_goodput_surfaces_documented():
    """Catalog lint (the test_obs pattern): the new trace events, the
    slowlog surface, and the goodput baseline rows are documented, and
    the committed fast-trace artifacts exist and parse."""
    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("engine.submit", "engine.token", "engine.resubmit",
                 "daemon.shed", "daemon.replay", "slowlog",
                 "goodput_fast_goodput_tokens_per_s"):
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"
    baselines = json.loads(
        (ROOT / "results" / "baselines.json").read_text())["baselines"]
    assert "goodput_fast_goodput_tokens_per_s" in baselines
    assert "goodput_fast_slo_attainment" in baselines
    # the committed r12 artifacts replay-match the in-repo spec
    trace = loadgen.Trace.load(ROOT / "results" / "goodput_trace_fast.json")
    assert trace.to_json() == loadgen.build_trace(
        loadgen.built_in_spec("fast")).to_json()
    report = json.loads((ROOT / "results" / "goodput_r12.json").read_text())
    assert report["goodput"]["overall"]["n"] == len(trace.requests)
    # the r12 queue script runs the goodput fast tier host-only and
    # sources the shared relay lib (the dedup contract of r11)
    r12 = (ROOT / "tools" / "onchip_queue_r12.sh").read_text()
    assert "goodput_gate.py" in r12 and "relay_lib.sh" in r12
    assert "JAX_PLATFORMS=cpu" in r12


def test_tune_flash_best_pool_excludes_batched_rows():
    """Round-5 advisor satellite, made directly testable: phase-3
    --train-shape rows (batch > 1) may NEVER win the per-seq b=1
    winner pools even when faster, while the train shape keeps its own
    dedicated key (and a batch=1 train shape legitimately shares)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tune_flash", ROOT / "tools" / "tune_flash.py")
    tf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tf)
    rows = [
        {"seq": 2048, "batch": 1, "block_q": 128, "block_k": 128,
         "fwd_ms": 5.0, "bwd_ms": 8.0, "fwdbwd_ms": 13.0},
        # batched row, FASTER on every axis: must not contaminate b=1
        {"seq": 2048, "batch": 8, "block_q": 64, "block_k": 64,
         "fwd_ms": 0.5, "bwd_ms": 0.8, "fwdbwd_ms": 1.3},
    ]
    best = tf.select_best(rows, [2048], train_shape=(2048, 8))
    assert best["fwd_s2048"]["fwd_ms"] == 5.0
    assert best["bwd_s2048"]["bwd_ms"] == 8.0
    assert best["fwdbwd_s2048"]["fwdbwd_ms"] == 13.0
    assert best["fwdbwd_train_s2048_b8"]["fwdbwd_ms"] == 1.3
    # legacy rows without a batch key count as b=1
    legacy = [{"seq": 1024, "block_q": 64, "block_k": 64, "fwd_ms": 2.0}]
    assert tf.select_best(legacy, [1024])["fwd_s1024"]["fwd_ms"] == 2.0
