"""LoRA parameter-efficient finetuning (labformer.lora_rank > 0).

Claims under test:
  * zero-initialized B makes the adapted model start bit-identical;
  * the finetune step updates ONLY adapter leaves (base frozen bitwise)
    and its optimizer state covers the adapter subtree alone;
  * finetuning actually learns (loss decreases on a cyclic stream);
  * merge_lora folds the adapters so the merged base-structure model
    reproduces the adapter-active forward, and serving surfaces refuse
    unmerged adapter models instead of silently dropping the finetune;
  * the sharded path (tp mesh) matches the single-device finetune.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.labformer import (
    LabformerConfig,
    _split_lora,
    forward,
    init_params,
    init_train_state,
    merge_lora,
)


def _cfg(**kw):
    base = dict(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64,
                lora_rank=4)
    base.update(kw)
    return LabformerConfig(**base)


def _tokens(cfg, b=4, s=17, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab, (b, s)).astype(np.int32)


def test_lora_init_is_identity():
    """B == 0 at init: adapter-active forward == base forward bitwise."""
    cfg = _cfg()
    params = init_params(cfg, seed=0)
    base_cfg = dataclasses.replace(cfg, lora_rank=0)
    lora_tree, base_params = _split_lora(params)
    toks = jnp.asarray(_tokens(cfg))
    got = forward(params, toks, cfg)
    want = forward(base_params, toks, base_cfg)
    assert np.array_equal(np.asarray(got), np.asarray(want))
    # and the adapter tree is exactly the four expected leaves
    assert sorted(lora_tree["blocks"]) == [
        "wq_lora_a", "wq_lora_b", "wv_lora_a", "wv_lora_b"]


def test_finetune_updates_adapters_only():
    cfg = _cfg()
    params, opt_state, step = init_train_state(cfg, mesh=None, seed=0)
    toks = _tokens(cfg, s=33)
    before_lora, before_base = _split_lora(jax.device_get(params))
    params2, opt_state, loss = step(params, opt_state, jnp.asarray(toks))
    assert np.isfinite(float(loss))
    after_lora, after_base = _split_lora(jax.device_get(params2))
    for k, v in before_base["blocks"].items():
        assert np.array_equal(np.asarray(v), np.asarray(after_base["blocks"][k])), (
            f"base leaf {k} moved under the lora step")
    assert np.array_equal(np.asarray(before_base["embed"]),
                          np.asarray(after_base["embed"]))
    # A starts gaussian and B zero; after one step with nonzero grads
    # both must move (B gets grads through A@B's product rule)
    moved = {k: not np.array_equal(np.asarray(before_lora["blocks"][k]),
                                   np.asarray(after_lora["blocks"][k]))
             for k in before_lora["blocks"]}
    assert all(moved.values()), moved


def test_opt_state_covers_adapters_only():
    cfg = _cfg()
    params, opt_state, _ = init_train_state(cfg, mesh=None, seed=0)
    lora_tree, _ = _split_lora(params)
    n_lora = sum(np.size(x) for x in jax.tree_util.tree_leaves(lora_tree))
    n_all = sum(np.size(x) for x in jax.tree_util.tree_leaves(params))
    n_opt = sum(np.size(x) for x in jax.tree_util.tree_leaves(opt_state))
    # adamw keeps two moments (+ scalar counts); full-model state would
    # be ~2x n_all — adapter-only is ~2x n_lora, orders smaller
    assert n_opt < 3 * n_lora + 16
    assert n_opt < n_all  # sanity: far below even ONE model copy


def test_finetune_learns():
    import optax

    cfg = _cfg()
    # adapters take a finetune-scale LR (the base head/embedding are
    # frozen, so the default pretrain LR barely moves the loss in a
    # 40-step horizon: measured 5.52 -> 5.43 at 3e-4 vs -> 5.05 at 1e-2)
    params, opt_state, step = init_train_state(
        cfg, mesh=None, seed=0, optimizer=optax.adamw(1e-2))
    cyc = np.tile(np.arange(33, dtype=np.int32) % 7, (4, 1))
    losses = []
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, jnp.asarray(cyc))
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, (
        losses[:5], losses[-5:])


def test_merge_matches_adapter_forward():
    cfg = _cfg()
    params, opt_state, step = init_train_state(cfg, mesh=None, seed=0)
    toks = _tokens(cfg, s=33)
    # a few steps so the adapters are nonzero and the fold is non-trivial
    for _ in range(5):
        params, opt_state, _ = step(params, opt_state, jnp.asarray(toks))
    merged, merged_cfg = merge_lora(params, cfg)
    assert merged_cfg.lora_rank == 0
    assert not any("_lora_" in k for k in merged["blocks"])
    toks_eval = jnp.asarray(_tokens(cfg, seed=3))
    got = np.asarray(forward(merged, toks_eval, merged_cfg), np.float32)
    want = np.asarray(forward(params, toks_eval, cfg), np.float32)
    # fold is f32 then cast back to the param dtype: rounding-level skew
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_merge_noop_without_lora():
    cfg = _cfg(lora_rank=0)
    params = init_params(cfg, seed=0)
    merged, merged_cfg = merge_lora(params, cfg)
    assert merged is params and merged_cfg is cfg


def test_serving_refuses_unmerged_adapters():
    cfg = _cfg()
    params = init_params(cfg, seed=0)
    from tpulab.models.generate import generate_jit
    from tpulab.models.paged import PagedEngine

    with pytest.raises(ValueError, match="merge_lora"):
        generate_jit(params, jnp.zeros((1, 4), jnp.int32),
                     jax.random.PRNGKey(0), cfg, steps=2)
    with pytest.raises(ValueError, match="merge_lora"):
        PagedEngine(params, cfg, slots=1, n_blocks=8, block_size=8,
                    max_seq=32)
    # the blessed path works end to end
    merged, mcfg = merge_lora(params, cfg)
    out = generate_jit(merged, jnp.zeros((1, 4), jnp.int32),
                       jax.random.PRNGKey(0), mcfg, steps=2)
    assert out.shape == (1, 2)


def test_lora_rejects_zero1():
    cfg = _cfg()
    from tpulab.models.labformer import make_train_step

    with pytest.raises(ValueError, match="zero1"):
        make_train_step(cfg, mesh=None, zero1=True)


def test_lora_sharded_matches_single_device():
    """tp-sharded finetune step == single-device finetune step."""
    from tpulab.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    cfg = _cfg()
    toks = _tokens(cfg, b=4, s=33)

    params_s, opt_s, step_s = init_train_state(cfg, mesh=None, seed=0)
    mesh = make_mesh({"tp": 2})
    params_m, opt_m, step_m = init_train_state(cfg, mesh, seed=0)
    for _ in range(3):
        params_s, opt_s, loss_s = step_s(params_s, opt_s, jnp.asarray(toks))
        params_m, opt_m, loss_m = step_m(params_m, opt_m, jnp.asarray(toks))
    assert np.isclose(float(loss_s), float(loss_m), atol=1e-5)
    ls, _ = _split_lora(jax.device_get(params_s))
    lm, _ = _split_lora(jax.device_get(params_m))
    for k in ls["blocks"]:
        np.testing.assert_allclose(
            np.asarray(ls["blocks"][k], np.float32),
            np.asarray(lm["blocks"][k], np.float32),
            atol=1e-5, rtol=1e-4)


def test_train_cli_lora(tmp_path):
    """The driver surface: tpulab.train --lora-rank runs and learns."""
    from tpulab.train import train

    logs = []
    step, loss = train(steps=5, batch=2, seq=32, lora_rank=2,
                       log=lambda *a: logs.append(a))
    assert step == 5 and np.isfinite(loss)


def test_warm_start_grafts_pretrained_base(tmp_path):
    """--init-from: pretrained base weights land bitwise in the finetune
    state; adapter leaves keep their fresh (delta == 0) init."""
    from tpulab.models.generate import load_params
    from tpulab.train import _warm_start, train

    pre = str(tmp_path / "pre")
    train(steps=4, batch=2, seq=32, ckpt_dir=pre, save_every=2,
          log=lambda *a: None)

    cfg = LabformerConfig(d_model=128, n_heads=8, n_layers=4, d_ff=512,
                          max_seq=32, lora_rank=2)
    params, _, _ = init_train_state(cfg, mesh=None, seed=1)
    grafted = _warm_start(params, cfg, pre)

    want, step = load_params(dataclasses.replace(cfg, lora_rank=0), pre)
    assert step == 4
    g_lora, g_base = _split_lora(grafted)
    for k, v in want["blocks"].items():
        assert np.array_equal(np.asarray(g_base["blocks"][k]), np.asarray(v)), k
    assert np.array_equal(np.asarray(g_base["embed"]), np.asarray(want["embed"]))
    p_lora, _ = _split_lora(params)
    for k in p_lora["blocks"]:
        assert np.array_equal(np.asarray(g_lora["blocks"][k]),
                              np.asarray(p_lora["blocks"][k])), k


def test_train_init_from_end_to_end(tmp_path):
    from tpulab.train import train

    pre = str(tmp_path / "pre")
    train(steps=2, batch=2, seq=32, ckpt_dir=pre, save_every=2,
          log=lambda *a: None)
    step, loss = train(steps=3, batch=2, seq=32, lora_rank=2,
                       init_from=pre, log=lambda *a: None)
    assert step == 3 and np.isfinite(loss)
    with pytest.raises(ValueError, match="mutually exclusive"):
        train(steps=1, init_from=pre, resume=True, ckpt_dir=pre)


def test_generate_sidecar_autodiscovers_lora_and_tokenizer(tmp_path, capsys):
    """`tpulab generate --ckpt-dir` ALONE serves a lora+BPE checkpoint:
    the config sidecar reconstructs dims/vocab/adapters and the copied
    tokenizer encodes/decodes — no flags to forget."""
    from tpulab.io.bpe import train_bpe
    from tpulab.models import generate as gen_cli
    from tpulab.train import train

    data = tmp_path / "data"
    data.mkdir()
    (data / "c.txt").write_bytes(b"the quick brown fox. " * 2000)
    tok = train_bpe((data / "c.txt").read_bytes(), vocab=300)
    tokp = str(tmp_path / "tok.json")
    tok.save(tokp)

    ck = str(tmp_path / "ck")
    train(steps=4, batch=2, seq=32, data_dir=str(data), tokenizer=tokp,
          lora_rank=2, ckpt_dir=ck, save_every=2, log=lambda *a: None)
    rc = gen_cli.main(["--ckpt-dir", ck, "--steps", "4",
                       "--temperature", "0", "--prompt", "the"])
    out = capsys.readouterr().out
    assert rc in (0, None)
    assert "config sidecar" in out and "lora r2" in out
    assert "merged LoRA adapters (rank 2)" in out


def test_generate_cli_merges_lora_checkpoint(tmp_path, capsys):
    """train --lora-rank checkpoint -> generate --lora-rank: the CLI
    restores the adapter leaves and folds them before serving (without
    the flag a partial restore would silently drop the finetune)."""
    from tpulab.models import generate as gen_cli
    from tpulab.train import train

    ck = str(tmp_path / "ck")
    train(steps=4, batch=2, seq=32, lora_rank=2, ckpt_dir=ck,
          save_every=2, log=lambda *a: None)
    rc = gen_cli.main(["--ckpt-dir", ck, "--lora-rank", "2",
                       "--steps", "4", "--temperature", "0",
                       "--prompt", "ab"])
    out = capsys.readouterr().out
    assert rc in (0, None)
    assert "merged LoRA adapters (rank 2)" in out
