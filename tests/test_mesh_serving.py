"""Mesh-sharded PagedEngine (round 19): tensor-parallel decode over the
2D ``("batch", "model")`` serving mesh, on 8 forced virtual CPU devices.

The tentpole's acceptance harness IS the standing contracts,
re-certified on-mesh:

  * greedy token streams bit-identical between ``serving_mesh(1, 1)``
    and ``serving_mesh(2, 4)`` — and to the mesh=None engine and the
    dense ``generate`` oracle — for plain, sampled, penalized,
    speculative (prompt-lookup), and prefix-hit slots;
  * the degenerate 1x1 mesh == current (meshless) behavior exactly;
  * transfer-guard flat-h2d steady window and ``decode_steady_
    recompiles == 0`` (strict mode) on the full 2x4 mesh;
  * obs on/off stats bit-equality unchanged by sharding;
  * the PR-13 spill tier CERTIFIED on sharded pools: d2h -> evict ->
    prefetch -> restore round-trips bit-identical for native and int8
    host payloads with the spill counters advancing, the lossy
    int8/int4 host formats serving end-to-end (int4 certified in
    round 20 — it unblocks int4 handoff payloads), plus the armed-
    tier flat-h2d/zero-recompile recert on-mesh;
  * ``EngineConfigError`` arms for every still-uncertified combination
    (pallas kernel, dense-draft proposer) and the
    indivisible head/slot sharding rejections;
  * the round-19 byte-accounting fix: ``kv_pool_device_bytes`` /
    ``device_bytes_estimate()`` sum PHYSICAL per-shard bytes
    (replicated leaves cost n_devices x logical; sharded leaves ~1x),
    and the per-shard gauge mirror ``engine_*_shard<i>`` publishes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpulab.models.paged as paged_mod
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import EngineConfigError, PagedEngine
from tpulab.obs import compilestats as cstats
from tpulab.parallel.mesh import serving_mesh

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module", autouse=True)
def _isolated_compile_cache():
    """Two kinds of process-global isolation for the mesh tier.

    DISK: run this module with the PERSISTENT compile cache OFF.  This
    module deliberately compiles the same engine programs both
    single-device (``mesh=None`` comparison arms) and GSPMD-partitioned
    (1x1 / 2x4), and the CPU AOT loader on this jaxlib cross-loads
    those entries between PROCESSES: a warm cache dir from an earlier
    run serves a single-device executable where a sharded compile
    should happen (and vice versa), which surfaces as garbage token
    streams on the degenerate 1x1 mesh, then heap corruption
    (``free(): invalid pointer`` / segfaults in later cache
    operations).  Namespacing the cache dir is NOT enough — the mix is
    between this module's own entries across runs — so the module pays
    fresh compiles every process and stays hermetic.  In-process
    executable caches key correctly; only the disk round-trip is
    poisoned.

    MEMORY: drop this module's executables at teardown.  Every
    8-virtual-device GSPMD executable holds JIT code mappings for the
    process lifetime (the engine's programs are module-level jits, so
    their executable caches are never collected), and the full tier-1
    run already peaks near the kernel's vm.max_map_count=65530 — the
    mesh tier's extra mappings pushed it OVER, segfaulting inside an
    unrelated LLVM compile at ~96% of the suite.  ``jax.clear_caches``
    releases the mappings.  This also guarantees the mesh tier leaves
    no pre-warmed same-shape executables behind that would flip a
    later engine STEADY before its full program set compiled (the
    round-14 recompile-tripwire tests bracket exactly that).
    """
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()
    try:
        yield
    finally:
        jax.clear_caches()
        jax.config.update("jax_enable_compilation_cache", old)
        _cc.reset_cache()


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(scope="module")
def mesh24():
    return serving_mesh(2, 4)


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def _spin_waves(eng, prompts, max_new=5, **per_req):
    rids = {eng.submit(p, max_new=max_new,
                       **{k: v[i] for k, v in per_req.items()}): i
            for i, p in enumerate(prompts)}
    res = eng.run()
    return {i: res[r] for r, i in rids.items()}


# ------------------------------------------------- stream bit-equality
def _mixed_workload(eng):
    """Two waves over one engine: plain greedy, sampled, penalized, and
    prompt-lookup speculative slots in one batch, then a repeat of the
    first wave's prompts so wave 2 rides prefix-cache hits.  Returns
    ({wave: {slot: tokens}}, prefix_hits)."""
    prompts = [_cycle_prompt(9), _cycle_prompt(17),
               (np.arange(24) % 5).astype(np.int32), _cycle_prompt(12)]
    waves = {}
    for w in range(2):
        rids = [
            eng.submit(prompts[0], max_new=8),                    # plain
            eng.submit(prompts[1], max_new=8, temperature=0.9,    # sampled
                       seed=3),
            eng.submit(prompts[2], max_new=10, spec="lookup",     # spec
                       spec_k=4, spec_ngram=3),
            eng.submit(prompts[3], max_new=8,                     # penalized
                       repetition_penalty=1.3),
        ]
        res = eng.run()
        waves[w] = [res[r].tolist() for r in rids]
    return waves, eng.counters["prefix_hits"]


def test_mesh24_streams_bit_identical(trained, mesh24):
    """THE acceptance criterion: plain/sampled/penalized/spec/prefix-hit
    streams bit-identical across mesh=None, the degenerate 1x1 mesh,
    and the full 2x4 mesh — and the plain greedy stream matches the
    dense ``generate`` oracle."""
    results = {}
    for name, mesh in (("none", None), ("1x1", serving_mesh(1, 1)),
                       ("2x4", mesh24)):
        eng = PagedEngine(trained, CFG, slots=4, n_blocks=32,
                          block_size=8, max_seq=72, spec_k=4, mesh=mesh)
        results[name] = _mixed_workload(eng)
    assert results["none"] == results["1x1"], "1x1 drifted from meshless"
    assert results["none"] == results["2x4"], "2x4 drifted from meshless"
    waves, hits = results["2x4"]
    assert hits >= 1, "wave 2 never hit the prefix cache"
    want = generate(trained, _cycle_prompt(9)[None, :], CFG, steps=8,
                    temperature=0.0)[0]
    assert np.array_equal(np.asarray(waves[0][0]), want)


def test_mesh24_spec_lookup_accepts(trained, mesh24):
    """paged_verify is one of the sharded fixed-shape programs: the
    lookup proposer must actually ACCEPT drafts on-mesh (a silent
    fall-back to one-token ticks would pass bit-equality)."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=16, block_size=8,
                      max_seq=72, spec_k=4, mesh=mesh24)
    eng.submit((np.arange(24) % 5).astype(np.int32), max_new=10,
               spec="lookup", spec_k=4, spec_ngram=3)
    eng.run()
    assert eng.counters["spec_accepted"] >= 1


# ----------------------------------- standing contracts, re-certified
class _NoUpload:
    """jnp stand-in whose ``asarray`` (the engine's one host-upload
    idiom) raises — same tripwire as tests/test_paged_overlap.py."""

    def __getattr__(self, name):
        return getattr(jnp, name)

    def asarray(self, *a, **kw):  # noqa: D102 - tripwire
        raise AssertionError("host->device upload in steady-state decode")


def test_mesh_steady_window_flat_h2d(trained, mesh24, monkeypatch):
    """Transfer-guard re-certification ON-MESH: a steady decode window
    over sharded pools/params/state moves nothing host<->device — the
    mesh placement all happens at init and admission, and GSPMD's
    cross-shard collectives are device-side, invisible to the guard."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=72, mesh=mesh24)
    eng.submit(_cycle_prompt(4), max_new=30)
    eng.submit(_cycle_prompt(6), max_new=30, temperature=1.5, seed=3)
    for _ in range(4):    # admission + compile happen OUTSIDE the guard
        eng.step()
    before = eng.stats()
    monkeypatch.setattr(paged_mod, "jnp", _NoUpload())
    with jax.transfer_guard("disallow"):
        for _ in range(8):
            eng.step()
    monkeypatch.undo()
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 8
    assert st["h2d_ticks"] == before["h2d_ticks"], "steady tick uploaded"
    assert st["host_syncs"] == before["host_syncs"], "steady tick synced"
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=30,
                    temperature=0.0)[0]
    assert np.array_equal(eng.run()[0], want)


def test_mesh_steady_window_zero_recompiles(trained, mesh24):
    """``decode_steady_recompiles == 0`` ON-MESH under strict(): the
    donated sharded state must round-trip through paged_tick with a
    stable sharding — any output-sharding drift would re-specialize
    the jit and trip here."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=72, mesh=mesh24)
    eng.submit(_cycle_prompt(4), max_new=24)
    eng.submit(_cycle_prompt(6), max_new=24)
    for _ in range(4):
        eng.step()
    assert eng._steady, "engine never reached the steady state"
    r0 = eng.counters["recompiles"]
    with cstats.strict():
        for _ in range(12):
            eng.step()
    assert eng.counters["recompiles"] == r0 == 0
    eng.run()


def test_mesh_obs_on_off_bit_equality(trained, mesh24):
    """The obs on/off contract is orthogonal to sharding: identical
    streams and identical DETERMINISTIC stats either way on-mesh."""
    outs = {}
    for obs_on in (False, True):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=16,
                          block_size=8, max_seq=72, mesh=mesh24,
                          obs=obs_on)
        outs[obs_on] = (_spin_waves(eng, [_cycle_prompt(9),
                                          _cycle_prompt(12)]),
                        eng.stats())
    got_off, got_on = outs[False], outs[True]
    for i in got_off[0]:
        assert np.array_equal(got_off[0][i], got_on[0][i]), i
    assert got_off[1] == got_on[1]


# ------------------------------------------- spill tier, mesh-certified
@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
@pytest.mark.parametrize("spill_dtype", ["native", "int8"])
def test_spill_on_mesh_roundtrip_bit_equality(trained, mesh24, kv_dtype,
                                              spill_dtype):
    """The full tier cycle ON SHARDED POOLS: filler pressure evicts A's
    prefix d2h (``_spill_read`` gathers the sharded block to host),
    resubmitting A prefetches + restores it (``_spill_restore``
    re-places into the pool sharding), and every stream is
    bit-identical to the spill-disabled MESH reference.  int8 host
    payloads stay lossless here because the pool representation is
    spilled verbatim (native) or requantized from already-int8 pools."""
    if kv_dtype == "native" and spill_dtype == "int8":
        pytest.skip("lossy: int8 host format over f32 pools certifies "
                    "end-to-end serving, not bit-equality (covered by "
                    "the counters arm below)")

    def mk(spill):
        kw = {"kv_dtype": kv_dtype} if kv_dtype != "native" else {}
        if spill:
            kw.update(prefix_index="radix", spill_blocks=16,
                      spill_dtype=spill_dtype)
        return PagedEngine(trained, CFG, slots=2, n_blocks=8,
                           block_size=8, max_seq=72, mesh=mesh24, **kw)

    a = _cycle_prompt(17)                     # 2 full blocks of prefix
    fillers = [(np.arange(i, i + 17) % 11).astype(np.int32)
               for i in (1, 2, 3)]            # distinct working sets
    outs = {}
    for spill in (False, True):
        eng = mk(spill)
        outs[spill] = [_spin_waves(eng, [a])]
        for f in fillers:                     # tiny pool churns
            outs[spill].append(_spin_waves(eng, [f]))
        outs[spill].append(_spin_waves(eng, [a]))   # back for A
        if spill:
            assert eng.counters["spill_spilled"] >= 1
            assert eng.counters["spill_prefetched"] >= 1
            assert eng.counters["spill_hits"] >= 1
    for w, (ref, run) in enumerate(zip(outs[False], outs[True])):
        for i in ref:
            assert np.array_equal(ref[i], run[i]), (w, i)


@pytest.mark.parametrize("spill_dtype", ["int8", "int4"])
def test_spill_on_mesh_lossy_host_formats_serve(trained, mesh24,
                                                spill_dtype):
    """The lossy arms: int8 AND int4 (round 20 — previously rejected on
    mesh) HOST payloads over f32 sharded pools must serve end-to-end
    with the counters advancing (bit-equality is not the contract there
    — requantization error is documented).  The int4 round-trip
    exercises the full nibble-pack/unpack path against ``_spill_read``
    gathers and ``_spill_restore`` re-placements on sharded pools —
    the certification that unblocks int4 handoff payloads."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=72, mesh=mesh24, prefix_index="radix",
                      spill_blocks=16, spill_dtype=spill_dtype)
    a = _cycle_prompt(17)
    _spin_waves(eng, [a])
    for f in [(np.arange(i, i + 17) % 11).astype(np.int32)
              for i in (1, 2, 3)]:
        _spin_waves(eng, [f])
    got = _spin_waves(eng, [a])
    assert eng.counters["spill_spilled"] >= 1
    assert eng.counters["spill_prefetched"] >= 1
    assert len(got[0]) == 5


def test_spill_armed_on_mesh_steady_contracts(trained, mesh24,
                                              monkeypatch):
    """Flat-h2d AND zero-recompile recert with the tier ARMED on-mesh,
    after REAL spill + prefetch traffic (the transfer programs have
    run against sharded pools, not merely warm-compiled at init)."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=72, mesh=mesh24, prefix_index="radix",
                      spill_blocks=16)
    a = _cycle_prompt(17)
    _spin_waves(eng, [a])
    for f in [(np.arange(i, i + 17) % 11).astype(np.int32)
              for i in (1, 2, 3)]:
        _spin_waves(eng, [f])                 # churn: spill A out
    assert eng.counters["spill_spilled"] >= 1
    eng.submit(a, max_new=24)                 # prefetch A back in
    for _ in range(4):
        eng.step()
    assert eng.counters["spill_prefetched"] >= 1
    assert eng._steady, "engine never reached the steady state"
    before = eng.stats()
    monkeypatch.setattr(paged_mod, "jnp", _NoUpload())
    with jax.transfer_guard("disallow"), cstats.strict():
        for _ in range(8):
            eng.step()
    monkeypatch.undo()
    st = eng.stats()
    assert st["h2d_ticks"] == before["h2d_ticks"], "steady tick uploaded"
    assert st["recompiles"] == before["recompiles"] == 0
    eng.run()


def test_handoff_between_mesh_engines_bit_identical(trained, mesh24):
    """The round-20 cross-engine handoff with mesh(2x4) engines on
    BOTH ends: the prefill engine's export d2h-gathers SHARDED pool
    blocks into the digest-keyed host format, the decode engine's
    import + admission prefetch restores them into its OWN sharded
    pools, and the resumed stream equals unified mesh serving
    bit-for-bit — the disaggregated daemon's tensor-parallel
    arrangement, driven at engine level."""
    kw = dict(slots=2, n_blocks=16, block_size=8, max_seq=72,
              prefix_index="radix", spill_blocks=16, mesh=mesh24)
    prompt = _cycle_prompt(17)
    uni = PagedEngine(trained, CFG, **kw)
    rid = uni.submit(prompt, max_new=8)
    want = uni.run()[rid]

    engp = PagedEngine(trained, CFG, **kw)
    engd = PagedEngine(trained, CFG, **kw)
    engp.handoff_at_boundary = True
    engp.submit(prompt, max_new=8)
    while not engp.handoff_ready:
        engp.step()
    (req, payload), = engp.export_handoff()
    assert len(payload) == 2, "17-token prompt exports 2 full blocks"
    assert engd.import_handoff(payload) > 0
    engd.resubmit(req, fresh_id=True)
    (got,) = engd.run().values()
    assert np.array_equal(want, got)
    # the decode side actually CONSUMED the imported blocks (a silent
    # recompute would pass bit-equality)
    assert engd.counters["spill_prefetched"] >= 1
    assert engp.counters["requests_done"] == 0
    # exact accounting on both ends: the exporter released its slot's
    # blocks (its radix keeps the registered prefix refs), the
    # importer holds only cache-referenced blocks
    for eng in (engp, engd):
        cached = set(eng._radix.blocks())
        assert len(eng.free) + len(cached) == eng.n_usable_blocks, (
            len(eng.free), sorted(cached), eng.n_usable_blocks)


def test_handoff_journey_stitched_across_mesh_engines(trained, mesh24):
    """Round 21: the same mesh(2x4)-both-ends handoff, with the
    journey tier armed — one rid's marks, dropped by TWO sharded
    engines plus the (here hand-driven) daemon import site, stitch
    into the full seven-phase disaggregated waterfall with shared
    boundary timestamps, the handoff phases summing to ``handoff_ms``
    and carrying the real payload byte count."""
    from tpulab import obs
    from tpulab.obs.journey import HANDOFF_PHASES, PHASES

    kw = dict(slots=2, n_blocks=16, block_size=8, max_seq=72,
              prefix_index="radix", spill_blocks=16, mesh=mesh24,
              obs=True)
    engp = PagedEngine(trained, CFG, **kw)
    engd = PagedEngine(trained, CFG, **kw)
    engp.pool_role = "prefill"  # daemon-stamped in production
    engd.pool_role = "decode"
    engp.handoff_at_boundary = True
    engp.submit(_cycle_prompt(17), max_new=8, tag="mesh-journey")
    while not engp.handoff_ready:
        engp.step()
    (req, payload), = engp.export_handoff()
    # the daemon's import site (tpulab/daemon.py _resubmit_on),
    # hand-driven: begin mark, import, end mark with measured bytes
    obs.JOURNEY.mark(req.rid, "handoff_import_begin", pool="decode")
    nbytes = engd.import_handoff(payload)
    assert nbytes > 0
    obs.JOURNEY.mark(req.rid, "handoff_import", pool="decode",
                     nbytes=nbytes)
    engd.resubmit(req, fresh_id=True)
    engd.run()
    j = obs.JOURNEY.snapshot(req.rid)
    assert j is not None and j["completed"]
    assert j["tag"] == "mesh-journey"
    assert [p["phase"] for p in j["phases"]] == list(PHASES)
    for a, b in zip(j["phases"], j["phases"][1:]):
        assert a["t1_ms"] == b["t0_ms"]  # contiguous across engines
    for p in j["phases"]:
        assert p["ms"] >= 0
    assert j["pools"] == ["prefill", "decode"]
    assert j["handoff_bytes"] == nbytes
    hsum = round(sum(p["ms"] for p in j["phases"]
                     if p["phase"] in HANDOFF_PHASES), 3)
    assert abs(hsum - j["handoff_ms"]) <= 0.01
    # phase-side attribution: prefill phases ran in the prefill pool,
    # decode phases in the decode pool
    by = {p["phase"]: p for p in j["phases"]}
    assert by["prefill_chunks"]["pool"] == "prefill"
    assert by["decode"]["pool"] == "decode"


# ------------------------------------------------ config-error arms
def test_engine_config_error_arms(trained, mesh24):
    """Every still-uncertified combination refuses LOUDLY with
    ``EngineConfigError`` (a ValueError subclass — pre-round-19
    ``except ValueError`` callers keep working), never a silent
    fallback."""
    assert issubclass(EngineConfigError, ValueError)
    with pytest.raises(EngineConfigError, match="pallas"):
        PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                    max_seq=72, mesh=mesh24, attn="pallas")
    # (int4 host spill on mesh was certified in round 20 — see
    # test_spill_on_mesh_lossy_host_formats_serve — so it no longer
    # appears here)
    # slots must split evenly over the batch axis (batch=2 here)
    with pytest.raises(EngineConfigError, match="slots"):
        PagedEngine(trained, CFG, slots=3, n_blocks=8, block_size=8,
                    max_seq=72, mesh=mesh24)
    # the model axis must divide the kv heads
    cfg1 = LabformerConfig(d_model=32, n_heads=4, n_kv_heads=1,
                           n_layers=2, d_ff=64, max_seq=128)
    with pytest.raises(EngineConfigError, match="must divide kv_heads=1"):
        PagedEngine(trained, cfg1, mesh=mesh24)
    # the dense-draft proposer has no certified sharding yet
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=72, spec_k=4, mesh=mesh24)
    with pytest.raises(EngineConfigError, match="draft"):
        eng.set_draft(trained, CFG)


def test_daemon_mesh_knob_validation():
    """--mesh parses/canonicalizes at the argparse boundary: bad specs
    exit 2 before any build (the int4-spill combo certified in round
    20 and is accepted now — only malformed specs remain)."""
    from tpulab.daemon import main

    for argv in (["--mesh", "nope"], ["--mesh", "2x"],
                 ["--mesh", "0x4"]):
        with pytest.raises(SystemExit) as e:
            main(argv)
        assert e.value.code == 2, argv


# --------------------------------- shard byte accounting + gauges
def test_shard_byte_accounting(trained, mesh24):
    """The round-19 bytes bugfix, asserted structurally: pools shard
    on model (4-way) and replicate across batch (2-way), so physical
    pool bytes are exactly 2x logical; per-shard is the even 1/8th;
    params replicate everywhere, so the physical estimate strictly
    exceeds pools + one logical param copy."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=72, mesh=mesh24)
    st = eng.stats()
    assert st["mesh_devices"] == 8
    assert st["kv_pool_device_bytes"] == 2 * st["kv_pool_bytes"]
    assert (st["kv_pool_bytes_per_shard"]
            == st["kv_pool_device_bytes"] // 8)
    param_logical = sum(
        int(x.nbytes) for x in jax.tree_util.tree_leaves(trained))
    est = eng.device_bytes_estimate()
    # matmul params shard 4-way on model but REPLICATE 2-way across
    # batch (norms replicate 8-way): physical param bytes are at least
    # 2x logical, which the logical-bytes accounting this test guards
    # against would have missed entirely
    assert est >= st["kv_pool_device_bytes"] + 2 * param_logical
    ss = eng.shard_stats()
    assert set(ss) == set(range(8))
    assert sum(s["kv_pool_bytes"] for s in ss.values()) \
        == st["kv_pool_device_bytes"]
    assert sum(s["hbm_bytes_in_use"] for s in ss.values()) == est
    # off-mesh: the same surface collapses to one shard == the totals
    eng0 = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                       max_seq=72)
    st0 = eng0.stats()
    assert st0["mesh_devices"] == 1
    assert st0["kv_pool_device_bytes"] == st0["kv_pool_bytes"]
    ss0 = eng0.shard_stats()
    assert set(ss0) == {0}
    assert ss0[0]["hbm_bytes_in_use"] == eng0.device_bytes_estimate()


def test_per_shard_gauges_publish(trained, mesh24):
    """publish_metrics mirrors the per-shard breakdown into the
    registry: one ``engine_hbm_bytes_in_use_shard<i>`` and
    ``engine_kv_pool_bytes_shard<i>`` gauge per mesh device, values
    matching shard_stats()."""
    from tpulab import obs

    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=72, mesh=mesh24)
    eng.submit(_cycle_prompt(9), max_new=3)
    eng.run()
    eng.publish_metrics()
    ss = eng.shard_stats()
    for i in range(8):
        g = obs.REGISTRY.get(f"engine_hbm_bytes_in_use_shard{i}")
        assert g is not None and g.value == ss[i]["hbm_bytes_in_use"], i
        g = obs.REGISTRY.get(f"engine_kv_pool_bytes_shard{i}")
        assert g is not None and g.value == ss[i]["kv_pool_bytes"], i


def test_stale_suffix_sweep_spares_base_gauges():
    """The daemon's stale-breakdown zeroing matches only NUMBERED
    ``_replica<i>``/``_shard<i>`` suffixes — a bare substring test
    zeroed ``engine_kv_pool_bytes_per_shard`` (the process-wide sum
    whose own name ends in ``_shard``) right after publishing it, so
    every daemon scrape reported 0 for it next to correct _shard<i>
    mirrors."""
    from tpulab.daemon import _STALE_SUFFIX_RE as sweep

    assert sweep.search("engine_kv_pool_bytes_shard3")
    assert sweep.search("engine_hbm_bytes_in_use_shard0")
    assert sweep.search("engine_ticks_replica12")
    assert sweep.search("engine_kv_pool_bytes_per_shard_replica0")
    assert not sweep.search("engine_kv_pool_bytes_per_shard")
    assert not sweep.search("engine_mesh_devices")
    assert not sweep.search("engine_shard_xxx")
