"""Expert-parallel switch MoE: dispatch path vs dense-gate oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.parallel.moe import switch_moe, switch_moe_reference


def _setup(rng, n=64, d=16, e=8, ff=32):
    mk = lambda *s, sc=0.5: jnp.asarray(rng.standard_normal(s) * sc, jnp.float32)
    return mk(n, d), mk(d, e, sc=0.1), mk(e, d, ff), mk(e, ff, d)


class TestSwitchMoe:
    @pytest.mark.parametrize("axes,sizes", [
        (("ep",), {"ep": 8}),
        (("ep",), {"ep": 4}),
        (("dp", "sp"), {"dp": 2, "sp": 4}),  # fused ep over the data axes
    ])
    def test_exact_at_full_capacity(self, rng, axes, sizes):
        mesh = cpu_test_mesh(sizes)
        x, rw, w1, w2 = _setup(rng)
        # capacity_factor = E guarantees C >= n_local: nothing drops
        got = np.asarray(
            switch_moe(x, rw, w1, w2, mesh=mesh,
                       axis=axes[0] if len(axes) == 1 else axes,
                       capacity_factor=float(w1.shape[0]))
        )
        want = np.asarray(switch_moe_reference(x, rw, w1, w2))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_overflow_drops_to_zero(self, rng):
        """A tiny capacity must zero some token outputs, never corrupt."""
        mesh = cpu_test_mesh({"ep": 4})
        x, rw, w1, w2 = _setup(rng, n=64)
        got = np.asarray(switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep",
                                    capacity_factor=0.25))
        want = np.asarray(switch_moe_reference(x, rw, w1, w2))
        zeroed = np.all(got == 0, axis=-1)
        kept = ~zeroed
        assert zeroed.any()  # capacity really binds
        np.testing.assert_allclose(got[kept], want[kept], rtol=1e-5, atol=1e-6)

    def test_experts_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"ep": 8})
        x, rw, w1, w2 = _setup(rng, e=6)
        with pytest.raises(ValueError, match="experts"):
            switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep")
