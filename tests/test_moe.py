"""Expert-parallel switch MoE: dispatch path vs dense-gate oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.parallel.moe import switch_moe, switch_moe_reference


def _setup(rng, n=64, d=16, e=8, ff=32):
    mk = lambda *s, sc=0.5: jnp.asarray(rng.standard_normal(s) * sc, jnp.float32)
    return mk(n, d), mk(d, e, sc=0.1), mk(e, d, ff), mk(e, ff, d)


class TestSwitchMoe:
    @pytest.mark.parametrize("axes,sizes", [
        (("ep",), {"ep": 8}),
        (("ep",), {"ep": 4}),
        (("dp", "sp"), {"dp": 2, "sp": 4}),  # fused ep over the data axes
    ])
    def test_exact_at_full_capacity(self, rng, axes, sizes):
        mesh = cpu_test_mesh(sizes)
        x, rw, w1, w2 = _setup(rng)
        # capacity_factor = E guarantees C >= n_local: nothing drops
        got = np.asarray(
            switch_moe(x, rw, w1, w2, mesh=mesh,
                       axis=axes[0] if len(axes) == 1 else axes,
                       capacity_factor=float(w1.shape[0]))
        )
        want = np.asarray(switch_moe_reference(x, rw, w1, w2))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_overflow_drops_to_zero(self, rng):
        """A tiny capacity must zero some token outputs, never corrupt."""
        mesh = cpu_test_mesh({"ep": 4})
        x, rw, w1, w2 = _setup(rng, n=64)
        got = np.asarray(switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep",
                                    capacity_factor=0.25))
        want = np.asarray(switch_moe_reference(x, rw, w1, w2))
        zeroed = np.all(got == 0, axis=-1)
        kept = ~zeroed
        assert zeroed.any()  # capacity really binds
        np.testing.assert_allclose(got[kept], want[kept], rtol=1e-5, atol=1e-6)

    def test_experts_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"ep": 8})
        x, rw, w1, w2 = _setup(rng, e=6)
        with pytest.raises(ValueError, match="experts"):
            switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep")


class TestTopK:
    """GShard-style top-2 routing (k > 1): the dispatch path must match
    the dense top-k oracle, the oracle must be a true convex
    combination, and k=1 must keep switch semantics."""

    def test_top2_dispatch_matches_oracle(self, rng):
        mesh = cpu_test_mesh({"ep": 4})
        x, rw, w1, w2 = _setup(rng)
        got = np.asarray(switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep",
                                    capacity_factor=float(w1.shape[0]), k=2))
        want = np.asarray(switch_moe_reference(x, rw, w1, w2, k=2))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # and it genuinely differs from top-1 (two experts contribute)
        top1 = np.asarray(switch_moe_reference(x, rw, w1, w2, k=1))
        assert not np.allclose(want, top1, atol=1e-4)

    def test_oracle_is_convex_combination(self, rng):
        import jax
        import jax.numpy as jnp_

        x, rw, w1, w2 = _setup(rng, n=8, e=4)
        gate = jax.nn.softmax((x @ rw).astype(jnp_.float32), axis=-1)
        tv, ti = jax.lax.top_k(gate, 2)
        tv = np.asarray(tv / tv.sum(axis=-1, keepdims=True))
        hid = jax.nn.gelu(jnp_.einsum("nd,edf->nef", x, w1))
        per_expert = np.asarray(jnp_.einsum("nef,efd->ned", hid, w2))
        want = np.stack([
            tv[i, 0] * per_expert[i, ti[i, 0]] + tv[i, 1] * per_expert[i, ti[i, 1]]
            for i in range(x.shape[0])
        ])
        got = np.asarray(switch_moe_reference(x, rw, w1, w2, k=2))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_top2_overflow_partial_contribution(self, rng):
        """Tight capacity: a token may keep one of its two experts —
        kept contributions stay exact, dropped ones contribute zero."""
        mesh = cpu_test_mesh({"ep": 4})
        x, rw, w1, w2 = _setup(rng, n=64)
        got = np.asarray(switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep",
                                    capacity_factor=0.25, k=2))
        # no NaN/corruption, and at least some outputs differ from the
        # full-capacity result (capacity really binds at 0.25)
        full = np.asarray(switch_moe_reference(x, rw, w1, w2, k=2))
        assert np.all(np.isfinite(got))
        assert not np.allclose(got, full, atol=1e-5)

    def test_k_bounds(self, rng):
        mesh = cpu_test_mesh({"ep": 4})
        x, rw, w1, w2 = _setup(rng)
        with pytest.raises(ValueError, match="k="):
            switch_moe(x, rw, w1, w2, mesh=mesh, axis="ep", k=9)


class TestLabformerTopK:
    def test_top2_model_trains_and_dispatch_matches_dense(self):
        import jax
        from tpulab.models.labformer import (LabformerConfig, forward,
                                             init_params, init_train_state)

        dense_cfg = LabformerConfig(
            d_model=32, n_heads=4, n_layers=2, d_ff=16, n_experts=4,
            max_seq=64, moe_top_k=2,
        )
        params = init_params(dense_cfg, seed=0)
        toks = np.random.default_rng(0).integers(0, 256, (2, 16)).astype(
            np.int32)
        want = np.asarray(forward(params, toks, dense_cfg))

        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpulab.models.labformer import _restrict, shard_params

        mesh = cpu_test_mesh({"dp": 2, "sp": 2})
        disp_cfg = LabformerConfig(
            d_model=32, n_heads=4, n_layers=2, d_ff=16, n_experts=4,
            max_seq=64, moe_top_k=2, moe_impl="dispatch",
            moe_capacity_factor=4.0,
        )
        sp = shard_params(init_params(disp_cfg, seed=0), disp_cfg, mesh)
        tok_sh = jax.device_put(
            jnp.asarray(toks), NamedSharding(mesh, _restrict(P("dp", None),
                                                             mesh)))
        got = np.asarray(
            jax.jit(lambda p, t: forward(p, t, disp_cfg, mesh=mesh))(sp,
                                                                     tok_sh))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

        # and the top-2 model trains
        p, o, step = init_train_state(dense_cfg, mesh=None, seed=0)
        p, o, loss = step(p, o, np.tile(np.arange(33, dtype=np.int32) % 7,
                                        (2, 1)))
        assert np.isfinite(float(loss))

    def test_top_k_validation(self):
        from tpulab.models.labformer import LabformerConfig

        with pytest.raises(ValueError, match="moe_top_k"):
            LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=16,
                            n_experts=4, max_seq=64, moe_top_k=5)
