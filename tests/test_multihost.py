"""Multi-host bring-up tests (single-process semantics on the CPU mesh)."""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from tpulab.parallel.multihost import (
    global_mesh,
    host_shard_to_global,
    initialize,
    runtime_info,
    sync_global_devices,
)


class TestInitialize:
    def test_noop_outside_distributed_env(self, monkeypatch):
        for k in ("JAX_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                  "MEGASCALE_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(k, raising=False)
        assert initialize() is False  # single-process: no-op, no crash

    def test_runtime_info(self):
        info = runtime_info()
        assert info["process_count"] == 1
        assert info["global_device_count"] == 8  # conftest virtual fleet


class TestGlobalMesh:
    def test_all_devices_covered(self):
        mesh = global_mesh(("dp", "sp", "tp", "pp"))
        assert mesh.devices.size == 8
        assert set(mesh.axis_names) == {"dp", "sp", "tp", "pp"}

    def test_explicit_sizes(self):
        mesh = global_mesh(("dp", "tp"), {"dp": 2, "tp": 4})
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


class TestHostShard:
    def test_assembles_global_batch(self, rng):
        mesh = global_mesh(("dp",), {"dp": 8})
        local = rng.standard_normal((16, 4)).astype(np.float32)
        arr = host_shard_to_global(local, mesh, P("dp", None))
        assert arr.shape == (16, 4)  # 1 process: local IS global
        np.testing.assert_allclose(np.asarray(arr), local)
        # sharded over dp: each device owns 2 rows
        assert len(arr.sharding.device_set) == 8

    def test_sync_is_noop_single_process(self):
        sync_global_devices("test")  # must not raise
