"""Multi-host bring-up tests: single-process semantics on the CPU mesh,
mocked process topology for host-locality, and a REAL 2-process
jax.distributed smoke (gloo collectives over localhost subprocesses)."""

import os
import pathlib
import subprocess
import sys
import typing

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from tpulab.parallel.multihost import (
    global_mesh,
    host_shard_to_global,
    initialize,
    runtime_info,
    sync_global_devices,
)


class TestInitialize:
    def test_noop_outside_distributed_env(self, monkeypatch):
        for k in ("JAX_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                  "MEGASCALE_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(k, raising=False)
        assert initialize() is False  # single-process: no-op, no crash

    def test_runtime_info(self):
        info = runtime_info()
        assert info["process_count"] == 1
        assert info["global_device_count"] == 8  # conftest virtual fleet


class TestGlobalMesh:
    def test_all_devices_covered(self):
        mesh = global_mesh(("dp", "sp", "tp", "pp"))
        assert mesh.devices.size == 8
        assert set(mesh.axis_names) == {"dp", "sp", "tp", "pp"}

    def test_explicit_sizes(self):
        mesh = global_mesh(("dp", "tp"), {"dp": 2, "tp": 4})
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4

    def test_host_locality_ordering(self, monkeypatch):
        """With a mocked 2-process x 4-local topology the leading axis
        absorbs the process count and the inner axes are factored from
        the LOCAL device count, so each process's (host-major) device
        block fills a whole leading-axis slice — inner-axis collectives
        never cross hosts."""
        import tpulab.parallel.multihost as mh

        monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
        monkeypatch.setattr(mh.jax, "local_device_count", lambda b=None: 4)
        mesh = global_mesh(("dp", "sp", "tp"))
        assert mesh.shape["dp"] == 2
        assert mesh.shape["sp"] * mesh.shape["tp"] == 4
        devs = mesh.devices.reshape(2, -1)
        all_devs = jax.devices()
        assert list(devs[0]) == all_devs[:4]  # "process 0"'s block
        assert list(devs[1]) == all_devs[4:]

    def test_annotations_resolvable(self):
        """multihost annotations must survive get_type_hints (a missing
        numpy import once hid behind `from __future__ import annotations`)."""
        import tpulab.parallel.multihost as mh

        typing.get_type_hints(mh.host_shard_to_global)


WORKER = """
import sys
pid, port = int(sys.argv[1]), sys.argv[2]
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
import numpy as np
from jax.sharding import PartitionSpec as P
from tpulab.parallel.multihost import (
    global_mesh, host_shard_to_global, initialize, runtime_info,
    sync_global_devices,
)
ok = initialize(coordinator_address=f"localhost:{port}", num_processes=2,
                process_id=pid)
assert ok, "initialize returned False"
assert runtime_info()["process_count"] == 2
mesh = global_mesh(("dp", "tp"))
assert dict(mesh.shape) == {"dp": 2, "tp": 4}, dict(mesh.shape)
local = np.full((2, 4), pid, np.float32)   # my half of the global batch
garr = host_shard_to_global(local, mesh, P("dp", None))
assert garr.shape == (4, 4)
total = float(jax.jit(lambda x: x.sum())(garr))
assert total == 8.0, total                  # proc0 zeros + proc1 ones
sync_global_devices("smoke")
print(f"proc {pid} OK")
"""


class TestTwoProcessSmoke:
    def test_distributed_initialize_and_reduce(self, tmp_path):
        """Two real processes join via jax.distributed over localhost,
        build the host-locality global mesh, assemble a global batch
        from per-process shards, and reduce it across processes."""
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER)
        import socket

        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        root = pathlib.Path(__file__).resolve().parent.parent
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=str(root),
        )
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(i), str(port)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(2)
        ]
        outs = [p.communicate(timeout=180)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i} OK" in out


class TestHostShard:
    def test_assembles_global_batch(self, rng):
        mesh = global_mesh(("dp",), {"dp": 8})
        local = rng.standard_normal((16, 4)).astype(np.float32)
        arr = host_shard_to_global(local, mesh, P("dp", None))
        assert arr.shape == (16, 4)  # 1 process: local IS global
        np.testing.assert_allclose(np.asarray(arr), local)
        # sharded over dp: each device owns 2 rows
        assert len(arr.sharding.device_set) == 8

    def test_sync_is_noop_single_process(self):
        sync_global_devices("test")  # must not raise
