"""Native tier tests: C codec parity, daemon protocol, C++ thin client.

Builds artifacts on demand with tools/build_native.py (g++ is part of
the toolchain contract); the daemon runs on the CPU backend.
"""

import json
import os
import pathlib
import shutil
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
CLIENT = ROOT / "native" / "bin" / "tpulab_client"


@pytest.fixture(scope="module", autouse=True)
def built_native():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    subprocess.run([sys.executable, str(ROOT / "tools" / "build_native.py")], check=True)


class TestFastcodec:
    @pytest.fixture(scope="class")
    def codec(self):
        sys.path.append(str(ROOT / "native" / "lib"))
        return pytest.importorskip("_tpulab_fastcodec")

    def test_encode_matches_python(self, codec, rng):
        import binascii

        blob = rng.integers(0, 256, 4 * 37 + 8, dtype=np.uint8).tobytes()
        hx = binascii.hexlify(blob).decode()
        want = " ".join(hx[i : i + 8] for i in range(0, len(hx), 8))
        assert codec.hex_encode(blob, 8) == want

    def test_roundtrip(self, codec, rng):
        blob = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
        assert codec.hex_decode(codec.hex_encode(blob, 8)) == blob

    def test_decode_whitespace_and_case(self, codec):
        assert codec.hex_decode(" De\nAD\tbe  ef \r") == bytes.fromhex("deadbeef")

    def test_decode_rejects_garbage(self, codec):
        with pytest.raises(ValueError):
            codec.hex_decode("xyz")
        with pytest.raises(ValueError):
            codec.hex_decode("abc")  # odd digit count

    def test_empty(self, codec):
        assert codec.hex_encode(b"", 8) == ""
        assert codec.hex_decode("") == b""

    def test_io_layer_uses_it(self, codec):
        from tpulab.io import bytes_to_hex, hex_to_bytes

        blob = b"\x01\x02\x03\x04\xff\xfe\xfd\xfc"
        assert hex_to_bytes(bytes_to_hex(blob)) == blob


@pytest.fixture(scope="module")
def daemon(tmp_path_factory, built_native):
    sock = str(tmp_path_factory.mktemp("d") / "tpulab.sock")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PYTHONPATH"] = str(ROOT)
    # short socket-op timeout: bounds only socket reads/writes (compute
    # inside handle_request is unaffected), keeps the stalled-client
    # regression test below fast
    env["TPULAB_DAEMON_RECV_TIMEOUT_S"] = "2"
    # daemon output goes to a FILE, not a PIPE: nothing drains the pipe
    # during the tests, so 64 KB of daemon/XLA chatter would block the
    # next print() inside a handler forever — the handler then never
    # sends its response and the requesting test hangs in recv
    # (observed 2026-07-30: thread stuck in anon_pipe_write, suite
    # deadlocked at ~50 min)
    log_path = pathlib.Path(sock).parent / "daemon.log"
    log_f = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock],
        env=env,
        stdout=log_f,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=str(ROOT),
    )
    for _ in range(300):  # JAX import can take a while
        if os.path.exists(sock):
            break
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon died: {log_path.read_text()[-4000:]}")
        time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError("daemon socket never appeared")
    yield sock
    proc.terminate()
    proc.wait(timeout=10)
    log_f.close()


def _raw_request_bytes(sock_path, header: bytes, payload: bytes):
    """_raw_request without the utf-8 decode: generate responses are raw
    byte-LM tokens, not text."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(sock_path)
    s.sendall(struct.pack("<I", len(header)) + header)
    s.sendall(struct.pack("<Q", len(payload)) + payload)
    status = s.recv(1)[0]
    (n,) = struct.unpack("<Q", s.recv(8))
    out = b""
    while len(out) < n:
        out += s.recv(n - len(out))
    s.close()
    return status, out


def _raw_request(sock_path, header: bytes, payload: bytes):
    status, out = _raw_request_bytes(sock_path, header, payload)
    return status, out.decode()


class TestDaemon:
    def test_lab1_over_socket(self, daemon):
        status, out = _raw_request(
            daemon, b'{"lab": "lab1", "config": {"warmup": 0, "reps": 1}}', b"3 1 2 3 4 5 6"
        )
        assert status == 0
        lines = out.splitlines()
        assert "execution time:" in lines[0]
        got = np.array(lines[1].split(), dtype=np.float64)
        np.testing.assert_allclose(got, [-3.0, -3.0, -3.0])

    def test_error_reported(self, daemon):
        status, out = _raw_request(daemon, b'{"lab": "nope"}', b"")
        assert status == 1
        assert "nope" in out

    def test_stalled_client_is_evicted(self, daemon):
        """A client that connects but never completes a frame must be
        disconnected once RECV_TIMEOUT_S elapses, releasing its handler
        slot — otherwise 32 such stalls would wedge accept() for every
        later client (round-3 advisor finding: the conn_sem bound plus
        unbounded header reads turned one idle socket into a daemon-wide
        stall)."""
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(daemon)
        s.sendall(b"\x01\x02")  # half a header-length prefix, then stall
        s.settimeout(8)  # daemon side times out at 2s
        t0 = time.perf_counter()
        try:
            got = s.recv(1)
        except OSError:
            got = b""  # reset instead of EOF is an equally valid eviction
        dt = time.perf_counter() - t0
        s.close()
        assert got == b"", "daemon sent data to a half-dead client?"
        assert dt < 7, f"stalled client not evicted after {dt:.1f}s"
        # and the daemon still serves followers normally
        status, out = _raw_request(daemon, b'{"lab": "hw1"}', b"1 -3 2")
        assert status == 0 and "1.000000" in out

    def test_trickling_client_is_evicted(self, daemon):
        """The eviction deadline is absolute per frame, not per socket
        op: a client feeding one byte per interval keeps every recv
        alive yet must still be cut off at RECV_TIMEOUT_S (review
        finding: per-op settimeout resets on each recv, so a trickle
        held the slot forever)."""
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(daemon)
        s.settimeout(10)
        t0 = time.perf_counter()
        evicted = False
        try:
            # header-length prefix says 16-byte header; trickle it slowly
            s.sendall(struct.pack("<I", 16))
            for _ in range(12):  # 6s of trickle >> the 2s deadline
                time.sleep(0.5)
                s.sendall(b"x")  # raises once the daemon closes on us
        except OSError:
            evicted = True
        dt = time.perf_counter() - t0
        s.close()
        assert evicted, "trickling client was never disconnected"
        assert dt < 7, f"trickling client held its slot for {dt:.1f}s"
        status, out = _raw_request(daemon, b'{"lab": "hw1"}', b"1 -3 2")
        assert status == 0 and "1.000000" in out

    def test_warm_requests_are_fast(self, daemon):
        _raw_request(daemon, b'{"lab": "hw1"}', b"1 -3 2")  # warm
        t0 = time.perf_counter()
        status, out = _raw_request(daemon, b'{"lab": "hw1"}', b"1 -3 2")
        dt = time.perf_counter() - t0
        assert status == 0 and "1.000000" in out and "2.000000" in out
        # an interpreter cold start alone is >1s; warm round-trip must be far under
        assert dt < 1.0, f"warm request took {dt:.2f}s"


class TestClient:
    def test_client_via_daemon(self, daemon):
        env = dict(os.environ)
        env["TPULAB_DAEMON_SOCKET"] = daemon
        r = subprocess.run(
            [str(CLIENT), "lab1", "--warmup", "0", "--reps", "1"],
            input="2 10 20 1 2",
            env=env,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stderr
        lines = r.stdout.splitlines()
        assert "execution time:" in lines[0]
        got = np.array(lines[1].split(), dtype=np.float64)
        np.testing.assert_allclose(got, [9.0, 18.0])

    def test_client_sweep_flag(self, daemon):
        env = dict(os.environ)
        env["TPULAB_DAEMON_SOCKET"] = daemon
        with_tmp = pathlib.Path(daemon).parent
        inp = with_tmp / "in.txt"
        out_path = with_tmp / "out.data"
        # 3x3 test image from the reference fixtures
        src = pathlib.Path("/root/reference/lab2/data/test_01.txt")
        if not src.exists():
            pytest.skip("reference fixtures not mounted")
        inp.write_text(src.read_text())
        r = subprocess.run(
            [str(CLIENT), "lab2", "--to-plot", "--warmup", "0", "--reps", "1"],
            input=f"32 32 16 16\n{inp}\n{out_path}\n",
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "execution time:" in r.stdout.splitlines()[0]
        assert "FINISHED!" in r.stdout
        from tpulab.io import load_image

        golden = load_image("/root/reference/lab2/data_out_gt/test_01.txt")
        np.testing.assert_array_equal(load_image(str(out_path)), golden)

    def test_client_rejects_bad_usage(self, built_native):
        r = subprocess.run([str(CLIENT)], capture_output=True, text=True)
        assert r.returncode == 2

    def test_client_quotes_nonjson_numbers(self, daemon):
        """Number-looking kwargs that are not valid JSON numbers ("007",
        "1.", "-", ".") must be forwarded as quoted strings — unquoted
        they would make the daemon's json.loads reject the request."""
        env = dict(os.environ)
        env["TPULAB_DAEMON_SOCKET"] = daemon
        r = subprocess.run(
            [str(CLIENT), "hw1", "--a1", "007", "--a2", "1.", "--a3", "-", "--a4", "."],
            input="1 -3 2",
            env=env,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stderr
        assert "1.000000" in r.stdout and "2.000000" in r.stdout


class TestHarnessDrivesClient:
    def test_full_stack(self, daemon, tmp_path):
        """harness -> native client subprocess -> daemon -> warm JAX:
        the reference's run_test.py flow with the compiled binary."""
        env = dict(os.environ)
        env.update(
            TPULAB_DAEMON_SOCKET=daemon,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            PYTHONPATH=str(ROOT),
        )
        art = tmp_path / "art"
        r = subprocess.run(
            [sys.executable, "-m", "tpulab.harness.run",
             "--lab", "lab1",
             "--binary-path", str(CLIENT),
             "--binary-args", "lab1 --warmup 0 --reps 1",
             "--k-times", "2",
             "--artifact-dir", str(art)],
            env=env, capture_output=True, text=True, timeout=300, cwd=str(ROOT),
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert (art / "stats_tpulab_client.csv").exists(), list(art.iterdir())


class TestDaemonGenerate:
    """The `generate` pseudo-lab: warm byte-LM serving over the socket."""

    def test_generate_over_socket_matches_local_engine(self, daemon):
        status, out = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6}}', b"hello"
        )
        assert status == 0 and len(out) == 6
        # same code path locally (demo config, seed-0 params, PagedEngine)
        # must produce the identical byte stream
        import numpy as np

        from tpulab.models.generate import demo_config, load_params
        from tpulab.models.paged import PagedEngine

        cfg = demo_config()
        params, _ = load_params(cfg, None)
        eng = PagedEngine(params, cfg, slots=4, n_blocks=128, block_size=16,
                          max_seq=512)
        rid = eng.submit(
            np.frombuffer(b"hello", np.uint8).astype(np.int32), max_new=6
        )
        want = bytes(int(t) & 0xFF for t in eng.run()[rid])
        assert out == want

    def test_generate_is_deterministic_and_warm(self, daemon):
        h = b'{"lab": "generate", "config": {"steps": 5}}'
        s1, out1 = _raw_request_bytes(daemon, h, b"abcabc")
        t0 = time.perf_counter()
        s2, out2 = _raw_request_bytes(daemon, h, b"abcabc")
        warm = time.perf_counter() - t0
        assert s1 == 0 and s2 == 0 and out1 == out2 and len(out1) == 5
        # a repeated request rides the cached engine + jit programs: it
        # must come back in interactive time (cold compile is tens of s;
        # a generous bound keeps this robust to CI noise)
        assert warm < 5.0

    def test_generate_empty_prompt_rejected(self, daemon):
        status, out = _raw_request(daemon, b'{"lab": "generate"}', b"")
        assert status == 1 and "empty prompt" in out

    def test_generate_streaming_chunks(self, daemon):
        """{"stream": true}: status-2 chunk frames arrive before the
        terminal frame; their concatenation equals the terminal frame's
        full output, which equals the non-streamed response."""
        h = b'{"lab": "generate", "config": {"steps": 6, "stream": true}}'
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(daemon)
        s.sendall(struct.pack("<I", len(h)) + h)
        s.sendall(struct.pack("<Q", 5) + b"hello")

        def _read_exact(n):
            body = b""
            while len(body) < n:
                part = s.recv(n - len(body))
                assert part, f"peer closed mid-frame ({len(body)}/{n})"
                body += part
            return body

        chunks, final, status = [], None, None
        while True:
            st_b = _read_exact(1)[0]
            (n,) = struct.unpack("<Q", _read_exact(8))
            body = _read_exact(n)
            if st_b == 2:
                chunks.append(body)
                continue
            status, final = st_b, body
            break
        s.close()
        assert status == 0
        # >= 1, not a per-tick count: the waiter only sees increments
        # when it wins the condition lock between ticks, so chunks may
        # legally coalesce under scheduler pressure
        assert len(chunks) >= 1, chunks
        assert b"".join(chunks) == final
        st2, plain = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6}}', b"hello")
        assert st2 == 0 and plain == final

    def test_speculative_over_wire_is_lossless(self, daemon):
        """{"speculative": true}: byte-identical to plain greedy (the
        losslessness contract), and sampling combos refuse."""
        plain = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 8}}', b"spec")
        spec = _raw_request_bytes(
            daemon,
            b'{"lab": "generate", "config": {"steps": 8, '
            b'"speculative": true, "draft_k": 3}}',
            b"spec")
        assert plain[0] == 0 and spec[0] == 0
        assert spec[1] == plain[1]
        status, err = _raw_request(
            daemon,
            b'{"lab": "generate", "config": {"steps": 2, '
            b'"speculative": true, "temperature": 0.7}}',
            b"x")
        assert status == 1 and "greedy" in err

    def test_beam_search_over_wire(self, daemon):
        """{"beams": 1} equals plain greedy (the beam contract); wider
        beams serve deterministically; invalid combos refuse."""
        plain = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6}}', b"beam")
        b1 = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6, "beams": 1}}',
            b"beam")
        b4a = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6, "beams": 4}}',
            b"beam")
        b4b = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6, "beams": 4}}',
            b"beam")
        assert plain[0] == b1[0] == b4a[0] == 0
        assert b1[1] == plain[1]
        assert b4a[1] == b4b[1] and len(b4a[1]) == 6
        status, err = _raw_request(
            daemon,
            b'{"lab": "generate", "config": {"steps": 2, "beams": 2, '
            b'"temperature": 0.5}}', b"x")
        assert status == 1 and "deterministic" in err

    def test_engine_knobs_over_wire(self, daemon):
        """{"attn": "pallas"} and {"kv_dtype": "int8"} build distinct
        cached engines; pallas serves the gather path's exact bytes
        (interpret mode on the CPU daemon) and typos refuse loudly."""
        base = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 5}}', b"knob")
        pallas = _raw_request_bytes(
            daemon,
            b'{"lab": "generate", "config": {"steps": 5, "attn": "pallas"}}',
            b"knob")
        int8 = _raw_request_bytes(
            daemon,
            b'{"lab": "generate", "config": {"steps": 5, '
            b'"kv_dtype": "int8"}}',
            b"knob")
        assert base[0] == 0 and pallas[0] == 0 and int8[0] == 0
        assert pallas[1] == base[1]  # same math, kernel vs gather
        assert len(int8[1]) == 5
        status, err = _raw_request(
            daemon,
            b'{"lab": "generate", "config": {"steps": 2, "attn": "wat"}}',
            b"x")
        assert status == 1 and "attn=" in err

    def test_aborted_stream_leaves_daemon_healthy(self, daemon):
        """A streaming client that disconnects mid-generation must not
        wedge or leak the daemon: the abandoned request is cancelled
        (stepper discards its output) and the next request serves."""
        h = (b'{"lab": "generate", "config": {"steps": 40, '
             b'"stream": true}}')
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(daemon)
        s.sendall(struct.pack("<I", len(h)) + h)
        s.sendall(struct.pack("<Q", 3) + b"abc")
        s.recv(1)  # at least the first chunk frame has started
        s.close()  # die mid-stream
        # the daemon must still serve (and the stepper must drain the
        # abandoned request without parking its output forever)
        st2, out = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 4}}', b"zz")
        assert st2 == 0 and len(out) == 4

    def test_native_client_streams(self, daemon, built_native, tmp_path):
        """The C++ client prints chunk frames as they arrive and
        suppresses the terminal body (no duplicated output)."""
        client = ROOT / "native" / "bin" / "tpulab_client"
        if not client.exists():
            pytest.skip("native client not built")
        env = dict(os.environ, TPULAB_DAEMON_SOCKET=daemon,
                   JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   PYTHONPATH=str(ROOT))
        r = subprocess.run(
            [str(client), "generate", "--steps", "6", "--stream", "true"],
            input=b"hello", stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        st2, plain = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 6}}', b"hello")
        assert st2 == 0 and r.stdout == plain

    def test_generate_sidecar_checkpoint_bpe_lora(self, daemon,
                                                  tmp_path_factory):
        """A lora+BPE trainer checkpoint served over the wire: the
        daemon honors the config sidecar (dims/vocab), folds the
        adapters, and transparently BPE-en/decodes the byte payload —
        matching the local merge+tokenize path exactly."""
        import json as _json

        import numpy as np

        from tpulab.io.bpe import BPETokenizer, train_bpe
        from tpulab.models.generate import load_params
        from tpulab.models.labformer import cfg_from_dict, merge_lora
        from tpulab.models.paged import PagedEngine
        from tpulab.train import train

        work = tmp_path_factory.mktemp("sidecar")
        data = work / "data"
        data.mkdir()
        (data / "c.txt").write_bytes(b"the quick brown fox. " * 2000)
        tok = train_bpe((data / "c.txt").read_bytes(), vocab=300)
        tokp = str(work / "tok.json")
        tok.save(tokp)
        ck = str(work / "ck")
        train(steps=4, batch=2, seq=32, data_dir=str(data), tokenizer=tokp,
              lora_rank=2, ckpt_dir=ck, save_every=2, log=lambda *a: None)

        header = _json.dumps(
            {"lab": "generate", "config": {"steps": 5, "ckpt_dir": ck}}
        ).encode()
        status, out = _raw_request_bytes(daemon, header, b"the quick")
        assert status == 0, out

        sc = _json.loads((pathlib.Path(ck) / "tpulab_config.json").read_text())
        cfg = cfg_from_dict(sc["config"])
        params, _ = load_params(cfg, ck)
        params, cfg = merge_lora(params, cfg)
        tok2 = BPETokenizer.load(str(pathlib.Path(ck) / "tokenizer.json"))
        eng = PagedEngine(params, cfg, slots=4, n_blocks=128, block_size=16,
                          max_seq=512)
        rid = eng.submit(tok2.encode(b"the quick"), max_new=5)
        want = tok2.decode([int(t) for t in eng.run()[rid]])
        assert out == want


    def test_generate_tp_mesh_over_wire(self, daemon, tmp_path_factory):
        """Daemon-on-mesh: ``{"tp": 2}`` builds the checkpoint's engine
        GSPMD-partitioned over a 2-device mesh; two CONCURRENT clients
        read bytes identical to the single-device engine (round-4
        verdict, stretch #9 — the serving WIRE on a mesh, not just the
        engine)."""
        import concurrent.futures as cf
        import json as _json

        from tpulab.models.generate import load_params
        from tpulab.models.labformer import LabformerConfig, cfg_from_dict
        from tpulab.models.paged import PagedEngine
        from tpulab.train import train

        work = tmp_path_factory.mktemp("tpwire")
        ck = str(work / "ck")
        # trained weights: untrained argmax ties would flip under GSPMD
        # partial-sum reordering and void the tp-vs-single comparison
        cfg = LabformerConfig(d_model=32, n_heads=4, n_kv_heads=2,
                              n_layers=2, d_ff=64, max_seq=32)
        train(steps=30, batch=4, seq=16, cfg=cfg, ckpt_dir=ck,
              save_every=30, log=lambda *a: None)

        header = _json.dumps({
            "lab": "generate",
            "config": {"steps": 6, "ckpt_dir": ck, "tp": 2},
        }).encode()

        with cf.ThreadPoolExecutor(2) as ex:
            futs = [ex.submit(_raw_request_bytes, daemon, header, b"ab")
                    for _ in range(2)]
            results = [f.result(timeout=300) for f in futs]

        sc = _json.loads((pathlib.Path(ck) / "tpulab_config.json").read_text())
        oc = cfg_from_dict(sc["config"])
        params, _ = load_params(oc, ck)
        eng = PagedEngine(params, oc, slots=4, n_blocks=128, block_size=16,
                          max_seq=512)
        rid = eng.submit(np.frombuffer(b"ab", np.uint8).astype(np.int32),
                         max_new=6)
        want = bytes(int(t) & 0xFF for t in eng.run()[rid])
        for status, out in results:
            assert status == 0 and out == want, (status, out, want)

    def test_generate_tp_rejected_cleanly(self, daemon):
        """tp config errors come back as error frames BEFORE any engine
        build: tp < 1, tp > device count, and mesh-incompatible knobs.
        (int8 KV and prompt_lookup are mesh-certified as of round 19
        and no longer reject; the dense-draft ``speculative`` path and
        host-orchestrated beams still do, as does naming both mesh
        grammars at once.)"""
        for cfg_d, msg in (
            ({"tp": 0}, b"tp must be >= 1"),
            ({"tp": 4096}, b"devices"),
            ({"mesh": "64x64"}, b"devices"),
            ({"tp": 2, "attn": "pallas"}, b"mesh serving"),
            ({"tp": 2, "mesh": "1x2"}, b"both"),
            ({"mesh": "nope"}, b"mesh"),
            ({"tp": 2, "beams": 2}, b"engine decode path"),
            ({"tp": 2, "speculative": True}, b"uncertified on mesh serving"),
        ):
            import json as _json

            h = _json.dumps({"lab": "generate",
                             "config": {"steps": 2, **cfg_d}}).encode()
            status, out = _raw_request_bytes(daemon, h, b"x")
            assert status == 1 and msg in out, (cfg_d, status, out)


class TestDaemonConcurrency:
    """Per-connection threads + the shared-engine stepper: concurrent
    generate clients batch through ONE decode loop."""

    def test_concurrent_clients_batch_and_match(self, daemon):
        import concurrent.futures as cf
        import json as _json

        steps = 20
        prompts = [b"alpha", b"beta", b"gamma", b"delta"]
        h = (b'{"lab": "generate", "config": {"steps": %d}}'
             % steps)

        def solo(prompt):
            return _raw_request_bytes(daemon, h, prompt)

        # record tick count before, fire 4 clients at once, re-read
        s0, st0 = _raw_request_bytes(daemon, b'{"lab": "generate_stats"}', b"")
        ticks0 = _json.loads(st0).get("ticks", 0)
        with cf.ThreadPoolExecutor(4) as ex:
            results = list(ex.map(solo, prompts))
        s1, st1 = _raw_request_bytes(daemon, b'{"lab": "generate_stats"}', b"")
        stats = _json.loads(st1)
        for status, out in results:
            assert status == 0 and len(out) == steps
        # every prompt still decodes to its solo greedy stream
        for prompt, (_, out) in zip(prompts, results):
            s_again, again = _raw_request_bytes(daemon, h, prompt)
            assert s_again == 0 and again == out, prompt
        # batching evidence: 4 overlapping requests of 20 tokens must
        # take strictly fewer engine ticks than 4 sequential runs (80) —
        # the loosest bound that still proves co-residency, robust to
        # admission staggering on a loaded machine
        delta = stats["ticks"] - ticks0
        assert delta < 4 * steps, delta
        assert stats["requests_done"] >= 4


class TestDaemonSampling:
    def test_sampled_generation_seeded_over_socket(self, daemon):
        h = (b'{"lab": "generate", '
             b'"config": {"steps": 8, "temperature": 1.5, "seed": 11}}')
        s1, a = _raw_request_bytes(daemon, h, b"xyz")
        s2, b = _raw_request_bytes(daemon, h, b"xyz")
        assert s1 == 0 and s2 == 0 and a == b  # one stream per seed
        g = b'{"lab": "generate", "config": {"steps": 8}}'
        s3, greedy = _raw_request_bytes(daemon, g, b"xyz")
        assert s3 == 0 and len(greedy) == 8
        # hot sampling almost surely diverges from greedy within 8 bytes
        assert a != greedy


class TestDaemonSamplingControls:
    def test_stop_byte_over_socket(self, daemon):
        """The engine's stop-byte control rides the wire: the response
        ends at (and includes) the stop byte while the unstopped stream
        continues past it."""
        base_status, base = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 8}}', b"hi")
        assert base_status == 0 and len(base) == 8
        stop = base[3]
        first = base.index(bytes([stop]))
        hdr = json.dumps({"lab": "generate",
                          "config": {"steps": 8, "stop_byte": stop}}).encode()
        status, out = _raw_request_bytes(daemon, hdr, b"hi")
        assert status == 0
        assert out == base[:first + 1], (out, base, stop)

    def test_bad_penalty_rejected_over_socket(self, daemon):
        hdr = json.dumps({"lab": "generate",
                          "config": {"steps": 4,
                                     "repetition_penalty": -1.0}}).encode()
        status, out = _raw_request(daemon, hdr, b"hi")
        assert status == 1 and "repetition_penalty" in out


class TestDaemonPromptLookup:
    def test_spec_batches_through_engine_with_counters(self, daemon):
        """Speculative requests ride the shared engine now: after a
        prompt_lookup request the SAME engine's generate_stats exposes
        the new verify counters (spec_rounds/spec_accepted), and an
        over-window draft_k refuses loudly instead of compiling a new
        shape."""
        status, _ = _raw_request_bytes(
            daemon,
            b'{"lab": "generate", "config": {"steps": 12, '
            b'"prompt_lookup": true}}',
            b"abcabcabcabc")
        assert status == 0
        s, st = _raw_request_bytes(daemon, b'{"lab": "generate_stats"}', b"")
        stats = json.loads(st)
        assert s == 0 and stats.get("spec_rounds", 0) > 0, stats
        assert stats.get("verify_passes", 0) > 0
        status, err = _raw_request(
            daemon,
            b'{"lab": "generate", "config": {"steps": 2, '
            b'"prompt_lookup": true, "draft_k": 9}}', b"x")
        assert status == 1 and "verify window" in err

    def test_prompt_lookup_over_wire_is_lossless(self, daemon):
        plain = _raw_request_bytes(
            daemon, b'{"lab": "generate", "config": {"steps": 8}}', b"lkp")
        lkp = _raw_request_bytes(
            daemon,
            b'{"lab": "generate", "config": {"steps": 8, '
            b'"prompt_lookup": true}}',
            b"lkp")
        assert plain[0] == 0 and lkp[0] == 0
        assert lkp[1] == plain[1]
        status, err = _raw_request(
            daemon,
            b'{"lab": "generate", "config": {"steps": 2, '
            b'"prompt_lookup": true, "speculative": true}}', b"x")
        assert status == 1 and "greedy" in err
