"""Observability layer (tpulab.obs): registry, tracer, and the wiring.

Covers the round-10 ISSUE checklist:
  * histogram bucket math and percentile estimation (the shared
    interpolation rule);
  * Prometheus text exposition, parseable line-by-line;
  * Chrome trace JSON validity + monotonic ordering; ring-buffer
    wraparound; disabled-tracer no-ops;
  * copy-on-read snapshots — a scrape racing ``observe`` can never see
    a torn histogram (the daemon used to read stats outside any lock);
  * engine wiring: latency histograms populate from a live run, stats
    and outputs are BIT-IDENTICAL with observability on vs off, and the
    ``overlap=1`` transfer-guard / flat-``h2d_ticks`` contract of the
    PR 2–4 tests holds with observability enabled;
  * daemon surfaces: the ``metrics`` request returns valid Prometheus
    text with ttft/itl/e2e populated by a live generate, ``trace_dump``
    returns loadable Chrome trace JSON, and the wave-line/stats lint —
    every ``engine.stats()`` key has a registered ``engine_*`` metric
    AND a docs entry, and every wave-log key exists in stats().
"""

import json
import re
import threading

import jax
import numpy as np
import pytest

from tpulab import obs
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs.registry import Registry, percentile_from_buckets
from tpulab.obs.tracer import Tracer

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


# ------------------------------------------------------------- registry
def test_histogram_bucket_math():
    r = Registry()
    h = r.histogram("h_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    # le is inclusive (0.001 lands in the 0.001 bucket), overflow last
    assert snap["counts"] == [2, 1, 1, 2]
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(5.5565)
    assert h.count == 6


def test_histogram_rejects_bad_buckets():
    r = Registry()
    with pytest.raises(ValueError, match="increasing"):
        r.histogram("bad", buckets=(0.1, 0.1))
    with pytest.raises(ValueError, match="invalid metric name"):
        r.counter("0bad")
    r.counter("ok_total")
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("ok_total")
    # get-or-create must not silently hand back DIFFERENT buckets
    r.histogram("lat_seconds", buckets=(0.1, 1.0))
    with pytest.raises(ValueError, match="conflicting"):
        r.histogram("lat_seconds", buckets=(0.001, 0.01))
    # same buckets (or unspecified) re-fetch the same instance
    assert (r.histogram("lat_seconds", buckets=(0.1, 1.0))
            is r.histogram("lat_seconds"))
    # a one-shot iterator registers cleanly (normalized once up front)
    h = r.histogram("iter_seconds", buckets=iter((0.1, 1.0)))
    assert h.bounds == (0.1, 1.0)


def test_percentile_estimation_interpolates():
    # 10 observations uniformly inside (1, 2]: p50 interpolates to 1.5
    assert percentile_from_buckets((1.0, 2.0, 4.0), (0, 10, 0, 0),
                                   0.5) == pytest.approx(1.5)
    # first bucket interpolates from 0
    assert percentile_from_buckets((1.0, 2.0), (10, 0, 0),
                                   0.5) == pytest.approx(0.5)
    # overflow ranks clamp to the last finite bound
    assert percentile_from_buckets((1.0, 2.0), (0, 0, 5), 0.99) == 2.0
    # empty histogram reports 0
    assert percentile_from_buckets((1.0,), (0, 0), 0.5) == 0.0
    with pytest.raises(ValueError, match="counts"):
        percentile_from_buckets((1.0,), (0,), 0.5)
    with pytest.raises(ValueError, match="q must be"):
        percentile_from_buckets((1.0,), (0, 0), 1.5)


def test_percentile_from_buckets_edge_cases():
    """The round-12 satellite: the shared interpolation rule now also
    backs the goodput math (tools/goodput_gate.py window percentiles),
    so its edge cases get direct coverage — empty histogram, all mass
    in one bucket, p0/p100, overflow-bucket clamping, and ranks landing
    exactly on bucket boundaries."""
    bounds = (1.0, 2.0, 4.0)
    # empty histogram: 0.0 at EVERY quantile, including the extremes
    for q in (0.0, 0.5, 1.0):
        assert percentile_from_buckets(bounds, (0, 0, 0, 0), q) == 0.0
    # all mass in a single interior bucket: every quantile interpolates
    # inside (1, 2], p100 reaches exactly its upper bound
    counts = (0, 8, 0, 0)
    assert percentile_from_buckets(bounds, counts, 0.25) == pytest.approx(1.25)
    assert percentile_from_buckets(bounds, counts, 1.0) == 2.0
    # p0 resolves to the lower edge of the first OCCUPIED bucket (rank
    # 0 skips the empty leading bucket, never reports below the mass)
    assert percentile_from_buckets(bounds, counts, 0.0) == 1.0
    # all mass in the FIRST bucket interpolates down from 0
    assert percentile_from_buckets(bounds, (10, 0, 0, 0), 0.1) == pytest.approx(0.1)
    # p100 with overflow mass clamps to the last finite bound — the
    # estimate can never exceed what the buckets resolve
    assert percentile_from_buckets(bounds, (1, 0, 0, 3), 1.0) == 4.0
    assert percentile_from_buckets(bounds, (0, 0, 0, 5), 0.5) == 4.0
    # rank landing EXACTLY on a bucket boundary returns the bound (5 of
    # 10 observations <= 1.0, so p50 == 1.0, no bleed into (1, 2])
    assert percentile_from_buckets(bounds, (5, 5, 0, 0), 0.5) == 1.0
    # ...and just past the boundary it moves into the next bucket
    assert percentile_from_buckets(bounds, (5, 5, 0, 0), 0.6
                                   ) == pytest.approx(1.2)
    # single-bound histogram, overflow-only mass
    assert percentile_from_buckets((0.5,), (0, 2), 0.9) == 0.5


def test_histogram_percentile_method():
    r = Registry()
    h = r.histogram("p_seconds", buckets=tuple(float(i) for i in
                                               range(1, 101)))
    for v in range(1, 101):
        h.observe(v - 0.5)  # one observation per unit bucket
    assert h.percentile(0.5) == pytest.approx(50.0, rel=0.03)
    assert h.percentile(0.99) == pytest.approx(99.0, rel=0.03)


def test_counter_and_gauge():
    r = Registry()
    c = r.counter("reqs_total", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = r.gauge("depth")
    g.set(3.5)
    assert g.value == 3.5
    # get-or-create returns the SAME instance
    assert r.counter("reqs_total") is c


_PROM_LINE = re.compile(
    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(_bucket\{le="[^"]+"\})? -?[0-9.e+\-inf]+'
    r'( # \{rid="\d+"\} -?[0-9.e+\-inf]+)?)$')


def test_prometheus_exposition_parses_line_by_line():
    r = Registry()
    r.counter("c_total", "a counter").inc(7)
    r.gauge("g_now").set(-1.25)
    h = r.histogram("lat_seconds", "latency", buckets=(0.001, 1.0))
    h.observe(0.0001)
    h.observe(0.5)
    h.observe(50.0)
    text = r.render()
    assert text.endswith("\n")
    for line in text.splitlines():
        assert _PROM_LINE.match(line), line
    # histogram exposition: cumulative buckets, +Inf == count
    assert 'lat_seconds_bucket{le="0.001"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    assert "c_total 7" in text
    assert "g_now -1.25" in text
    # round 21: exemplar-free output is byte-identical to the above;
    # an rid-carrying observe adds the OpenMetrics exemplar suffix to
    # exactly its bucket line, and the line still lints
    h.observe(0.0002, rid=42)
    text = r.render()
    for line in text.splitlines():
        assert _PROM_LINE.match(line), line
    assert 'lat_seconds_bucket{le="0.001"} 2 # {rid="42"} 0.0002' in text
    assert 'lat_seconds_bucket{le="1"} 3\n' in text  # no exemplar here


def test_snapshot_is_copy_on_read_never_torn():
    """The round-10 small fix: a scrape racing observe() must see a
    CONSISTENT histogram — count equals the bucket total, and (all
    observations being the same value) sum equals count * value
    exactly.  A torn read (count advanced, sum or a bucket not) fails
    one of the equalities."""
    r = Registry()
    h = r.histogram("torn_seconds", buckets=(1.0,))
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            h.observe(0.5)  # exactly representable: sum stays exact

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for _ in range(300):
            snap = h.snapshot()
            assert sum(snap["counts"]) == snap["count"]
            assert snap["sum"] == snap["count"] * 0.5
    finally:
        stop.set()
        t.join()


# --------------------------------------------------------------- tracer
def test_chrome_trace_valid_and_monotonic():
    tr = Tracer(64)
    with tr.span("outer"):
        tr.event("mark", 7)
        with tr.span("inner"):
            pass
    dump = tr.chrome_trace()
    json.loads(json.dumps(dump))  # round-trips as strict JSON
    ev = dump["traceEvents"]
    assert [e["ph"] for e in ev] == ["B", "i", "B", "E", "E"]
    assert [e["name"] for e in ev] == ["outer", "mark", "inner", "inner",
                                       "outer"]
    ts = [e["ts"] for e in ev]
    assert ts == sorted(ts) and ts[0] == 0.0
    instant = ev[1]
    assert instant["s"] == "t" and instant["args"] == {"arg": 7}
    assert all({"pid", "tid", "ts", "ph", "name"} <= set(e) for e in ev)
    assert dump["otherData"] == {"recorded": 5, "dropped": 0}


def test_tracer_kwargs_event_and_span_reuse():
    tr = Tracer(16)
    tr.event("rich", rid=3, why="test")
    ev = tr.chrome_trace()["traceEvents"]
    assert ev[0]["args"] == {"rid": 3, "why": "test"}
    # span handles are cached per name (zero-allocation steady state)
    assert tr.span("s") is tr.span("s")


def test_ring_buffer_wraparound():
    tr = Tracer(8)
    for i in range(20):
        tr.event("e", i)
    dump = tr.chrome_trace()
    ev = dump["traceEvents"]
    assert len(ev) == 8
    # the RETAINED window is the most recent 8, still in order
    assert [e["args"]["arg"] for e in ev] == list(range(12, 20))
    assert dump["otherData"] == {"recorded": 20, "dropped": 12}
    # export does not disturb recording: the next event still lands
    tr.event("e", 20)
    assert tr.chrome_trace()["otherData"]["recorded"] == 21


def test_disabled_tracer_noops():
    tr = Tracer(0)
    assert not tr.enabled
    with tr.span("x"):
        tr.event("y", 1)
    assert tr.chrome_trace()["traceEvents"] == []
    with pytest.raises(ValueError, match="capacity"):
        Tracer(-1)


def test_configure_tracer_resizes_global():
    prior = obs.TRACER.capacity
    try:
        obs.configure_tracer(4)
        assert obs.TRACER.capacity == 4 and obs.TRACER.enabled
        obs.configure_tracer(0)
        assert not obs.TRACER.enabled
    finally:
        obs.configure_tracer(prior)


# -------------------------------------------------------- engine wiring
def _run_wave(params, obs_on):
    eng = PagedEngine(params, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, obs=obs_on)
    r1 = eng.submit(_cycle_prompt(4), max_new=10)
    r2 = eng.submit(_cycle_prompt(6), max_new=8, temperature=1.5, seed=3)
    out = eng.run()
    return (out[r1], out[r2]), eng.stats()


def test_engine_histograms_populate(trained):
    before = {n: obs.REGISTRY.get(n).count
              for n in ("queue_wait_seconds", "prefill_seconds",
                        "ttft_seconds", "itl_seconds", "e2e_seconds")}
    (_, _), st = _run_wave(trained, True)
    reg = obs.REGISTRY
    for name in ("queue_wait_seconds", "prefill_seconds", "ttft_seconds",
                 "e2e_seconds"):
        assert reg.get(name).count == before[name] + 2, name
    # ITL: one observation per token after the first, per request
    assert (reg.get("itl_seconds").count
            == before["itl_seconds"] + st["tokens_out"] - 2)


def test_engine_obs_off_records_nothing(trained):
    names = ("queue_wait_seconds", "prefill_seconds", "ttft_seconds",
             "itl_seconds", "e2e_seconds")
    before = {n: obs.REGISTRY.get(n).count for n in names}
    _run_wave(trained, False)
    for n in names:
        assert obs.REGISTRY.get(n).count == before[n], n


def test_engine_stats_and_stream_bit_identical_obs_on_off(trained):
    """Observability must be a pure observer: the token streams AND
    every engine counter — including the transfer-guard contract pair
    ``host_syncs``/``h2d_ticks`` — are bit-identical with obs on vs off
    under the default ``overlap=1``."""
    (a1, a2), st_on = _run_wave(trained, True)
    (b1, b2), st_off = _run_wave(trained, False)
    assert np.array_equal(a1, b1) and np.array_equal(a2, b2)
    assert st_on == st_off
    assert np.array_equal(a1, generate(
        trained, _cycle_prompt(4)[None, :], CFG, steps=10,
        temperature=0.0)[0])


def test_steady_state_zero_transfers_with_obs_on(trained):
    """The PR 2 acceptance test, re-run with observability ENABLED and
    the global tracer recording: a steady-state tick still moves
    nothing host<->device implicitly, and ``h2d_ticks``/``host_syncs``
    stay flat — timestamps and ring appends are host-only by
    construction."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, obs=True)
    eng.submit(_cycle_prompt(4), max_new=30)
    eng.submit(_cycle_prompt(5), max_new=30, repetition_penalty=4.0)
    for _ in range(4):  # admission + compile happen OUTSIDE the guard
        eng.step()
    before = eng.stats()
    assert before["inflight_depth"] == 1  # the async window is open
    with jax.transfer_guard("disallow"):
        for _ in range(8):
            eng.step()
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 8
    assert st["h2d_ticks"] == before["h2d_ticks"], "obs tick uploaded"
    assert st["host_syncs"] == before["host_syncs"], "obs tick synced"
    out = eng.run()
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=30,
                    temperature=0.0)[0]
    assert np.array_equal(out[0], want)


def test_engine_trace_events_recorded(trained):
    prior = obs.TRACER.capacity
    try:
        obs.configure_tracer(1 << 12)  # fresh, private window
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64, prefill_chunk=8)
        rid = eng.submit(_cycle_prompt(20), max_new=4)
        eng.run()
        names = {e["name"] for e in obs.TRACER.chrome_trace()["traceEvents"]}
        assert {"engine.admit", "engine.prefill_chunk",
                "engine.first_token", "engine.retire"} <= names
    finally:
        obs.configure_tracer(prior)


# -------------------------------------------------------- daemon wiring
def test_daemon_metrics_and_trace_dump(trained):
    """Acceptance: the ``metrics`` request returns valid Prometheus text
    including ttft/itl/e2e histograms populated by a live generate, and
    ``trace_dump`` returns Chrome-trace JSON with monotonic
    timestamps."""
    from tpulab import daemon
    from tpulab.daemon import _GenerateService, handle_request

    svc = _GenerateService()
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64)
    out = svc.generate(eng, _cycle_prompt(4), 8)
    assert len(out) == 8
    key = (None, "gather", "native", 1, 0, "")
    daemon._ENGINES[key] = (None, eng, None)
    try:
        text = handle_request({"lab": "metrics"}, b"").decode("utf-8")
    finally:
        daemon._ENGINES.pop(key, None)
    for line in text.splitlines():
        assert _PROM_LINE.match(line), line
    for name in ("ttft_seconds", "itl_seconds", "e2e_seconds"):
        m = re.search(rf"^{name}_count (\d+)$", text, re.M)
        assert m and int(m.group(1)) > 0, name
    # the warm engine's stats ride along as engine_* gauges
    assert re.search(r"^engine_tokens_out \d+$", text, re.M)
    dump = json.loads(handle_request({"lab": "trace_dump"}, b""))
    ts = [e["ts"] for e in dump["traceEvents"]]
    assert ts == sorted(ts)


def test_daemon_metrics_aggregates_across_engines(trained):
    """With SEVERAL warm engines the unlabeled engine_* gauges must
    report the key-wise SUM (process totals), not whichever engine
    published last."""
    from tpulab import daemon
    from tpulab.daemon import handle_request

    engines = []
    for i in range(2):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64)
        eng.submit(_cycle_prompt(4), max_new=2 + i)
        eng.run()
        engines.append(eng)
    keys = [(None, "gather", "native", 1, i, "") for i in range(2)]
    for key, eng in zip(keys, engines):
        daemon._ENGINES[key] = (None, eng, None)
    try:
        text = handle_request({"lab": "metrics"}, b"").decode("utf-8")
    finally:
        for key in keys:
            daemon._ENGINES.pop(key, None)
    want = sum(e.stats()["tokens_out"] for e in engines)
    m = re.search(r"^engine_tokens_out (\d+)$", text, re.M)
    assert m and int(m.group(1)) == want, (m, want)
    # once the engines are gone, a scrape must ZERO the mirror rather
    # than freeze the dead engines' final values forever
    text = handle_request({"lab": "metrics"}, b"").decode("utf-8")
    m = re.search(r"^engine_tokens_out (\d+)$", text, re.M)
    assert m and int(m.group(1)) == 0, m


def test_wave_line_helper_and_stats_lint(trained):
    """The dedup satellite + the registry/docs lint: the wave-log
    formatter reads the same stats() snapshot as generate_stats, every
    wave key exists in stats(), and every stats() key has BOTH a
    registered ``engine_<key>`` metric (after publish_metrics) and a
    docs entry in docs/ARCHITECTURE.md."""
    import pathlib

    from tpulab.daemon import _WAVE_KEYS, _counters_line, _engine_stats

    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(4), max_new=2)
    eng.run()
    assert _engine_stats(eng) == eng.stats()  # the one snapshot source
    row = eng.publish_metrics()
    assert set(_WAVE_KEYS) <= set(row), "wave line names a missing key"
    line = _counters_line(row)
    for k in _WAVE_KEYS:
        assert f"{k}={row[k]}" in line
    docs = (pathlib.Path(__file__).resolve().parent.parent
            / "docs" / "ARCHITECTURE.md").read_text()
    for k in row:
        assert obs.REGISTRY.get(f"engine_{k}") is not None, (
            f"stats() key {k!r} has no registered engine_ metric")
        assert f"engine_{k}" in docs, (
            f"stats() key {k!r} has no docs/ARCHITECTURE.md entry")


def test_trainer_metrics_line():
    """train.py records dispatch/loss-lag histograms and emits the
    periodic [train] metrics line (here: the end-of-run emission)."""
    from tpulab.train import train

    before = obs.REGISTRY.get("train_dispatch_seconds")
    n0 = before.count if before else 0
    lines = []
    train(steps=3, batch=2, seq=16, log=lines.append)
    h = obs.REGISTRY.get("train_dispatch_seconds")
    assert h is not None and h.count == n0 + 3
    metrics_lines = [ln for ln in lines if ln.startswith("[train] metrics ")]
    assert metrics_lines, lines
    assert re.search(r"dispatch_ms_p50=[\d.]+ dispatch_ms_p99=[\d.]+ "
                     r"loss_lag_ms_p50=[\d.]+ loss_lag_ms_p99=[\d.]+ "
                     r"blocks=\d+", metrics_lines[-1])


# ------------------------------------------------------- report tooling
def test_obs_report_parses_and_summarizes():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "obs_report", pathlib.Path(__file__).resolve().parent.parent
        / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    r = Registry()
    h = r.histogram("ttft_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 0.05):
        h.observe(v)
    r.counter("engine_ticks").inc(3)
    parsed = rep.parse_prometheus(r.render())
    assert parsed["engine_ticks"]["value"] == 3
    assert parsed["ttft_seconds"]["count"] == 4
    p50 = rep.histogram_percentile(parsed["ttft_seconds"], 0.5)
    assert 0.001 < p50 <= 0.1
    rows = rep.summarize(parsed)
    assert rows and rows[0]["metric"] == "ttft_seconds"
    assert rows[0]["count"] == 4
    with pytest.raises(ValueError, match="unparseable"):
        rep.parse_prometheus("!! not prometheus")


@pytest.mark.slow
def test_obs_overhead_bench_under_budget():
    """The obs_overhead microbench: runs the real A/B windows and
    asserts the <3% budget internally (wall-clock sensitive — slow
    tier; the committed baselines.json row gates the CPU-proxy number
    round over round)."""
    from tpulab.bench import bench_obs_overhead

    # default window size on purpose: shorter windows amplify
    # scheduler noise past the retry-merge's ability to absorb it
    row = bench_obs_overhead(reps=2)
    assert row["metric"] == "obs_overhead_4slots_ticks_per_s"
    assert row["value"] > 0 and row["off_ticks_per_s"] > 0
    assert "overhead_pct_best" in row
