"""Telemetry over time, part 2: the alert rule engine
(tpulab.obs.alerts), its fleet-health wiring, and the ops console
rendering.

Round-15 checklist covered here:
  * the pending -> firing -> resolved state machine, ``for_s`` hold,
    ``keep_firing_s`` flap hysteresis, pending cancellation;
  * burn-rate arithmetic against hand-built histogram windows —
    including the exact-threshold boundary and the two-window AND;
  * threshold aggregate variants (gauge / ratio-with-zero-denominator
    gating / rate / delta / windowed percentile), absence/staleness
    rules, and probe-error containment;
  * ``obs_alerts_*`` counters/gauges + tracer transition events +
    page-severity flight-recorder bundles (and the bundle's firing-
    alert set satellite + retention pruning hardening);
  * the docs lint: every SHIPPED rule name and every ``obs_alerts_*``
    metric has a docs/ARCHITECTURE.md entry;
  * ``ReplicaHealth.note_alert`` (alert-wired SUSPECT: demote, hold,
    release) and the daemon glue (``_ensure_replica_rules`` /
    ``_apply_fleet_alerts`` / the ``alerts`` request);
  * END-TO-END CHAOS: a scoped fault wedges one replica; the windowed
    burn alert fires BEFORE the health machine's crash path runs, the
    router steers placement off the suspect replica, the eventual
    crash migrates the stream bit-identically, and the alert resolves
    after recovery.
"""

import json
import pathlib
import threading
import time

import numpy as np
import pytest

import tpulab.daemon as daemon_mod
from tpulab import faults, obs, router
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs import alerts as A
from tpulab.obs import history as H
from tpulab.obs import flightrec
from tpulab.obs.registry import Registry

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)
ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


class _FlagRule(A.Rule):
    """Test rule driven by an external flag."""

    def __init__(self, name="flag", **kw):
        super().__init__(name, **kw)
        self.active = False

    def probe(self, ctx):
        return self.active, 1.0 if self.active else 0.0, "flag"


def _hist_with_samples(n=2, t0=0.0, dt=1.0):
    reg = Registry()
    hist = H.MetricsHistory(64)
    for i in range(n):
        hist.sample(reg, now=t0 + i * dt)
    return hist


# -------------------------------------------------------- state machine
def test_state_machine_pending_firing_resolved():
    hist = _hist_with_samples()
    r = _FlagRule(for_s=2.0, keep_firing_s=3.0)
    m = A.AlertManager([r])
    m.evaluate(hist, now=10.0)
    assert m.get_state("flag").state == A.OK
    r.active = True
    tr = m.evaluate(hist, now=11.0)
    assert tr == [{"rule": "flag", "from": A.OK, "to": A.PENDING}]
    m.evaluate(hist, now=12.0)  # 1s held < for_s
    assert m.get_state("flag").state == A.PENDING
    tr = m.evaluate(hist, now=13.0)  # held 2s == for_s -> fires
    assert tr == [{"rule": "flag", "from": A.PENDING, "to": A.FIRING}]
    st = m.get_state("flag")
    assert st.fired_at == 13.0 and st.fires == 1
    # condition clears: firing HOLDS through keep_firing_s...
    r.active = False
    m.evaluate(hist, now=14.0)
    assert m.get_state("flag").state == A.FIRING
    # ...a flap back to active resets the clear timer (hysteresis)
    r.active = True
    m.evaluate(hist, now=15.0)
    r.active = False
    m.evaluate(hist, now=17.0)
    assert m.get_state("flag").state == A.FIRING  # only 2s clear
    tr = m.evaluate(hist, now=20.0)  # 3s continuously clear
    assert tr == [{"rule": "flag", "from": A.FIRING, "to": A.RESOLVED}]
    # resolved is sticky until the next activation
    m.evaluate(hist, now=21.0)
    assert m.get_state("flag").state == A.RESOLVED
    r.active = True
    m.evaluate(hist, now=22.0)
    assert m.get_state("flag").state == A.PENDING


def test_pending_cancels_without_firing():
    hist = _hist_with_samples()
    r = _FlagRule(for_s=5.0)
    m = A.AlertManager([r])
    r.active = True
    m.evaluate(hist, now=0.0)
    r.active = False
    tr = m.evaluate(hist, now=1.0)
    assert tr == [{"rule": "flag", "from": A.PENDING, "to": A.OK}]
    assert m.get_state("flag").fires == 0


def test_for_s_zero_fires_in_one_pass_and_counters_move():
    hist = _hist_with_samples()
    r = _FlagRule(for_s=0.0, keep_firing_s=0.0)
    m = A.AlertManager([r])
    fired0 = A.C_FIRED.value
    resolved0 = A.C_RESOLVED.value
    prior = obs.TRACER.capacity
    try:
        obs.configure_tracer(1 << 10)
        r.active = True
        tr = m.evaluate(hist, now=0.0)
        assert tr == [{"rule": "flag", "from": A.OK, "to": A.FIRING}]
        assert A.C_FIRED.value == fired0 + 1
        assert A.G_FIRING.value == 1
        r.active = False
        m.evaluate(hist, now=1.0)
        assert A.C_RESOLVED.value == resolved0 + 1
        assert A.G_FIRING.value == 0
        names = [e["name"] for e in
                 obs.TRACER.chrome_trace()["traceEvents"]]
        assert "alert.firing" in names and "alert.resolved" in names
    finally:
        obs.configure_tracer(prior)


def test_probe_error_contained_in_detail():
    class Broken(A.Rule):
        def probe(self, ctx):
            raise RuntimeError("kaput")

    hist = _hist_with_samples()
    m = A.AlertManager([Broken("broken"), _FlagRule()])
    m.evaluate(hist, now=0.0)  # does not raise
    row = [r for r in m.snapshot()["alerts"] if r["rule"] == "broken"][0]
    assert "kaput" in row["detail"] and row["state"] == A.OK


# ---------------------------------------------------- burn-rate windows
def _burn_hist(bad_long, good_long, bad_short, good_short,
               budget=0.1):
    """History whose 60s window holds long+short counts and whose 15s
    window holds only the short counts ('bad' observations land at
    4x budget, 'good' at budget/2).  The middle sample sits at EXACTLY
    t = 60 - 15: the short window's base resolves to a sample on its
    precise boundary — the window-boundary arithmetic the round-15
    checklist calls out."""
    reg = Registry()
    h = reg.histogram("ttft_seconds", buckets=(budget, 2 * budget,
                                               8 * budget))
    hist = H.MetricsHistory(64)
    hist.sample(reg, now=0.0)      # base of the 60s window
    for _ in range(good_long):
        h.observe(budget / 2)
    for _ in range(bad_long):
        h.observe(budget * 4)
    hist.sample(reg, now=45.0)     # base of the 15s window, exactly
    for _ in range(good_short):
        h.observe(budget / 2)
    for _ in range(bad_short):
        h.observe(budget * 4)
    hist.sample(reg, now=60.0)     # newest edge
    return hist


def test_burn_rate_arithmetic_exact():
    # long window: 60 obs, 10 bad -> err 1/6; short: 15 obs, 5 bad
    hist = _burn_hist(bad_long=5, good_long=40, bad_short=5,
                      good_short=10)
    r = A.BurnRateRule("b", objective=0.9, metric="ttft_seconds",
                       budget_s=0.1, long_s=60, short_s=15, burn=1.0)
    ctx = A._Ctx(hist, 60.0)
    bl, bs, nl, ns = r.burn_rates(ctx)
    assert nl == 60 and ns == 15
    assert bl == pytest.approx((10 / 60) / 0.1)
    assert bs == pytest.approx((5 / 15) / 0.1)


def test_burn_rate_two_window_and_gate():
    # long window burns, short window is CLEAN -> must not fire (the
    # incident is over; don't page on the long tail)
    hist = _burn_hist(bad_long=30, good_long=0, bad_short=0,
                      good_short=20)
    r = A.BurnRateRule("b", objective=0.9, metric="ttft_seconds",
                       budget_s=0.1, long_s=60, short_s=15, burn=2.0,
                       for_s=0)
    m = A.AlertManager([r])
    m.evaluate(hist, now=60.0)
    assert m.get_state("b").state == A.OK
    # both windows burning -> fires
    hist = _burn_hist(bad_long=10, good_long=10, bad_short=10,
                      good_short=0)
    r2 = A.BurnRateRule("b2", objective=0.9, metric="ttft_seconds",
                        budget_s=0.1, long_s=60, short_s=15, burn=2.0,
                        for_s=0)
    m2 = A.AlertManager([r2])
    m2.evaluate(hist, now=60.0)
    assert m2.get_state("b2").state == A.FIRING


def test_burn_rate_exact_threshold_boundary_fires():
    """burn == threshold is >= — firing at exactly the configured
    rate, not one observation past it."""
    # err 0.2 of budget 0.1 -> burn exactly 2.0 in both windows
    hist = _burn_hist(bad_long=2, good_long=8, bad_short=2,
                      good_short=8)
    r = A.BurnRateRule("b", objective=0.9, metric="ttft_seconds",
                       budget_s=0.1, long_s=60, short_s=15, burn=2.0,
                       for_s=0)
    ctx = A._Ctx(hist, 60.0)
    bl, bs, _, _ = r.burn_rates(ctx)
    assert bl == pytest.approx(2.0) and bs == pytest.approx(2.0)
    active, _, _ = r.probe(ctx)
    assert active


def test_burn_rate_empty_window_never_fires():
    hist = _hist_with_samples(n=3, dt=30.0)
    r = A.BurnRateRule("b", objective=0.99, metric="ttft_seconds",
                       budget_s=0.1, long_s=60, short_s=15, burn=1.0)
    active, _, detail = r.probe(A._Ctx(hist, 60.0))
    assert not active  # no traffic burns no budget


def test_burn_rate_ratio_mode():
    reg = Registry()
    bad = reg.counter("daemon_shed_requests")
    good = reg.counter("engine_requests_done")
    hist = H.MetricsHistory(64)
    hist.sample(reg, now=0.0)
    bad.inc(5)
    good.inc(5)
    hist.sample(reg, now=45.0)  # the 15s window's base, exactly
    bad.inc(5)
    good.inc(5)
    hist.sample(reg, now=60.0)
    r = A.BurnRateRule("shed", objective=0.9,
                       bad_metric="daemon_shed_requests",
                       good_metric="engine_requests_done",
                       long_s=60, short_s=15, burn=2.0)
    bl, bs, nl, ns = r.burn_rates(A._Ctx(hist, 60.0))
    assert bl == pytest.approx(0.5 / 0.1) and bs == pytest.approx(5.0)
    assert nl == 20 and ns == 10


def test_burn_rate_validation():
    with pytest.raises(ValueError, match="exactly one"):
        A.BurnRateRule("x", metric="m", budget_s=1,
                       bad_metric="b", good_metric="g")
    with pytest.raises(ValueError, match="short_s"):
        A.BurnRateRule("x", metric="m", budget_s=1, long_s=10,
                       short_s=10)
    with pytest.raises(ValueError, match="objective"):
        A.BurnRateRule("x", metric="m", budget_s=1, objective=1.0)


# ----------------------------------------------------- threshold rules
def test_threshold_agg_variants():
    reg = Registry()
    reg.gauge("g").set(10.0)
    reg.gauge("lim").set(0.0)
    c = reg.counter("c")
    h = reg.histogram("lat_seconds", buckets=(0.1, 0.2, 0.4))
    hist = H.MetricsHistory(8)
    hist.sample(reg, now=0.0)
    c.inc(30)
    h.observe(0.3)
    h.observe(0.3)
    hist.sample(reg, now=10.0)
    ctx = A._Ctx(hist, 10.0)
    assert A.ThresholdRule("a", "g", ">", 5).probe(ctx)[0]
    # gauge ratio with zero denominator: INACTIVE, not div-by-zero —
    # the CPU proxy publishes engine_hbm_bytes_limit=0
    active, v, detail = A.ThresholdRule(
        "b", "g", ">", 0.5, denom_metric="lim").probe(ctx)
    assert not active and v is None and "n/a" in detail
    assert A.ThresholdRule("c1", "c", ">", 2.0, agg="rate",
                           window_s=10).probe(ctx)[0]
    assert A.ThresholdRule("d", "c", ">=", 30, agg="delta",
                           window_s=10).probe(ctx)[0]
    active, v, _ = A.ThresholdRule("e", "lat_seconds", ">", 0.2,
                                   agg="p99", window_s=10).probe(ctx)
    assert active and 0.2 < v <= 0.4
    # under min_count the percentile aggregate stays inactive
    assert not A.ThresholdRule("f", "lat_seconds", ">", 0.0, agg="p99",
                               window_s=10, min_count=5).probe(ctx)[0]
    with pytest.raises(ValueError, match="agg"):
        A.ThresholdRule("x", "g", ">", 1, agg="median")
    with pytest.raises(ValueError, match="op"):
        A.ThresholdRule("x", "g", "!=", 1)


def test_absence_and_staleness_rules():
    reg = Registry()
    c = reg.counter("heartbeat")
    hist = H.MetricsHistory(64)
    c.inc()
    for i in range(6):
        hist.sample(reg, now=float(i))
    ctx = A._Ctx(hist, 5.0)
    assert A.AbsenceRule("gone", "never_registered").probe(ctx)[0]
    assert not A.AbsenceRule("here", "heartbeat").probe(ctx)[0]
    # unchanged for 5s with stale_s=3 and the ring spanning enough
    active, age, _ = A.AbsenceRule("stale", "heartbeat",
                                   stale_s=3.0).probe(ctx)
    assert active and age == pytest.approx(5.0)
    # a change inside the threshold resets the clock
    c.inc()
    hist.sample(reg, now=6.0)
    assert not A.AbsenceRule("stale", "heartbeat",
                             stale_s=3.0).probe(A._Ctx(hist, 6.0))[0]
    # ring too short to prove staleness: inactive
    short = H.MetricsHistory(64)
    short.sample(reg, now=0.0)
    short.sample(reg, now=1.0)
    assert not A.AbsenceRule("stale", "heartbeat",
                             stale_s=3.0).probe(A._Ctx(short, 1.0))[0]


def test_sampler_stale_rule():
    hist = _hist_with_samples(n=1, t0=100.0)
    hist.interval_s = 1.0
    r = A.SamplerStaleRule(max_age_s=30.0, age_intervals=10.0)
    active, age, _ = r.probe(A._Ctx(hist, 105.0))
    assert not active
    active, age, _ = r.probe(A._Ctx(hist, 115.0))  # 15s > 10*1s
    assert active and age == pytest.approx(15.0)


# ------------------------------------------- page bundles + flight rec
def test_page_alert_records_postmortem_with_alert_row(tmp_path):
    flightrec.configure_flightrec(tmp_path)
    try:
        hist = _hist_with_samples()
        r = _FlagRule("page_probe", severity="page", for_s=0)
        m = A.AlertManager([r], page_postmortems=True)
        r.active = True
        m.evaluate(hist, now=0.0)
        bundles = flightrec.list_bundles()
        assert len(bundles) == 1
        b = json.loads(bundles[0].read_text())
        assert b["reason"] == "alert_page:page_probe"
        assert b["extra"]["alert"]["rule"] == "page_probe"
        # without the opt-in, no bundle (the default for library users)
        m2 = A.AlertManager([_FlagRule("quiet", severity="page")])
        m2._rules["quiet"].active = True
        m2.evaluate(hist, now=0.0)
        assert len(flightrec.list_bundles()) == 1
    finally:
        flightrec.configure_flightrec(None)


def test_postmortem_bundle_carries_global_firing_set(tmp_path):
    """The round-15 flight-recorder satellite: every crash bundle
    snapshots what was ALREADY alerting when the process died."""
    flightrec.configure_flightrec(tmp_path)
    r = _FlagRule("already_burning", severity="warn", for_s=0)
    obs.ALERTS.add(r, replace=True)
    try:
        r.active = True
        obs.ALERTS.evaluate(_hist_with_samples(), now=0.0)
        path = flightrec.record_postmortem("test_crash",
                                           err=RuntimeError("boom"))
        b = json.loads(path.read_text())
        assert [a["rule"] for a in b["alerts"]] == ["already_burning"]
    finally:
        obs.ALERTS.remove("already_burning")
        flightrec.configure_flightrec(None)


def test_retention_prunes_oldest_first_and_never_raises(tmp_path,
                                                        monkeypatch):
    flightrec.configure_flightrec(tmp_path)
    try:
        for i in range(6):
            (tmp_path / f"postmortem_{1000 + i}_1_{i:04d}.json"
             ).write_text("{}")
        removed = flightrec.prune(keep=3)
        assert removed == 3
        left = [p.name for p in flightrec.list_bundles()]
        # newest three survive (list_bundles is newest-first)
        assert left == [f"postmortem_{1000 + i}_1_{i:04d}.json"
                        for i in (5, 4, 3)]
        # unlink failures are tolerated, and the count stays honest
        monkeypatch.setattr(pathlib.Path, "unlink",
                            lambda self: (_ for _ in ()).throw(
                                OSError("ro")))
        assert flightrec.prune(keep=0) == 0
        assert len(flightrec.list_bundles()) == 3
        monkeypatch.undo()
        # record_postmortem itself keeps the bound
        monkeypatch.setattr(flightrec, "KEEP", 2)
        p = flightrec.record_postmortem("bounded")
        assert p is not None and len(flightrec.list_bundles()) == 2
    finally:
        flightrec.configure_flightrec(None)


# ------------------------------------------------------------ the lint
def test_every_shipped_rule_and_alert_metric_documented():
    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for rule in A.default_rules():
        assert f"`{rule.name}`" in docs, (
            f"shipped alert rule {rule.name!r} has no "
            f"docs/ARCHITECTURE.md entry")
    # the per-replica dynamic rule documents its base name
    assert "`replica_degraded`" in docs
    for metric in ("obs_alerts_evals", "obs_alerts_fired",
                   "obs_alerts_resolved", "obs_alerts_firing",
                   "obs_alerts_pending"):
        assert obs.REGISTRY.get(metric) is not None, metric
        assert f"`{metric}`" in docs, (
            f"alert-engine metric {metric!r} has no docs entry")
    # shipped severities are the documented vocabulary
    assert all(r.severity in A.SEVERITIES for r in A.default_rules())


# --------------------------------------------- router note_alert wiring
def test_replica_health_note_alert_demotes_holds_releases():
    h = router.ReplicaHealth(slow_tick_s=0.1, suspect_after=3,
                             recover_after=2)
    h.note_alert(True)
    assert h.state == router.SUSPECT and h.suspects == 1
    # fast ticks do NOT promote while the alert holds
    h.note_tick(0.01)
    h.note_tick(0.01)
    h.note_tick(0.01)
    assert h.state == router.SUSPECT
    # release: the normal hysteresis finishes recovery
    h.note_alert(False)
    h.note_tick(0.01)
    assert h.state == router.SUSPECT  # streak restarted at release
    h.note_tick(0.01)
    assert h.state == router.HEALTHY
    # crash/rebuild lifecycle clears the hold
    h.note_alert(True)
    h.note_crash()
    h.note_rebuild_start()
    h.note_rebuilt()
    assert h.state == router.HEALTHY and not h.alert_firing
    assert h.snapshot()["alert_firing"] is False


# --------------------------------------------------- daemon glue + wire
def test_daemon_alerts_request_evaluates_and_reports():
    from tpulab.daemon import handle_request

    r = _FlagRule("wire_probe", for_s=0)
    obs.ALERTS.add(r, replace=True)
    try:
        r.active = True
        snap = json.loads(handle_request({"lab": "alerts"}, b""))
        row = [x for x in snap["alerts"]
               if x["rule"] == "wire_probe"][0]
        assert row["state"] == A.FIRING  # the request evaluated
        assert snap["firing"] >= 1
        # no_evaluate returns the table as-is
        r.active = False
        snap2 = json.loads(handle_request(
            {"lab": "alerts", "config": {"no_evaluate": True}}, b""))
        row2 = [x for x in snap2["alerts"]
                if x["rule"] == "wire_probe"][0]
        assert row2["state"] == A.FIRING  # unchanged without evaluate
    finally:
        obs.ALERTS.remove("wire_probe")


def test_ensure_replica_rules_and_apply(trained):
    svc = daemon_mod._FleetService()
    fleet = daemon_mod._make_fleet(
        lambda: (PagedEngine(trained, CFG, slots=2, n_blocks=32,
                             block_size=8, max_seq=64), None), 2)
    key = ("alerts-glue-test",)
    daemon_mod._FLEETS[key] = (None, fleet)
    f = fleet.fid
    try:
        daemon_mod._ensure_replica_rules()
        names = {r.name for r in obs.ALERTS.rules}
        # rules are FLEET-scoped: two warm fleets' same-index replicas
        # must never share a degradation verdict
        assert {f"fleet{f}_replica0_degraded",
                f"fleet{f}_replica1_degraded"} <= names
        # force replica1's alert FIRING and apply -> SUSPECT
        st = obs.ALERTS.get_state(f"fleet{f}_replica1_degraded")
        st.state = A.FIRING
        daemon_mod._apply_fleet_alerts()
        with fleet.cv:
            assert fleet.replicas[1].health.state == router.SUSPECT
            assert fleet.replicas[0].health.state == router.HEALTHY
        st.state = A.RESOLVED
        daemon_mod._apply_fleet_alerts()
        with fleet.cv:
            assert not fleet.replicas[1].health.alert_firing
    finally:
        daemon_mod._FLEETS.pop(key, None)
        obs.ALERTS.remove(f"fleet{f}_replica0_degraded")
        obs.ALERTS.remove(f"fleet{f}_replica1_degraded")


# -------------------------------------------------------- console/render
def test_render_single_engine_no_fleet_and_sparkline():
    from tpulab.obs import render as R

    reg = Registry()
    reg.gauge("engine_ticks").set(12)
    reg.gauge("engine_tokens_out").set(40)
    reg.gauge("engine_requests_done").set(3)
    metrics = R.parse_prometheus(reg.render())
    # no fleet + engine gauges: the single-engine row (the obs_report
    # satellite — no per-replica assumption anywhere)
    txt = R.format_fleet({"replicas": 0, "replica": []}, metrics)
    assert "engine (no fleet)" in txt and "tokens_out=40" in txt
    assert "-" in txt  # absent gauges render as dashes, not KeyError
    assert "none warm" in R.format_fleet(None, {})
    # a fleet row missing per-replica load fields renders dashes
    txt = R.format_fleet({"replicas": 1, "replica": [
        {"replica": 0, "health": "rebuilding", "dead": True}]})
    assert "pending=-" in txt and "dead" in txt
    assert R.sparkline([], 8) == " " * 8
    s = R.sparkline([0, 1, 2, 4], 4)
    assert len(s) == 4 and s[-1] == "█" and s[0] == " "
    assert len(R.sparkline(list(range(100)), 16)) == 16


def test_console_frame_renders_all_sections():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_console", ROOT / "tools" / "obs_console.py")
    con = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(con)
    reg = Registry()
    h = reg.histogram("ttft_seconds", buckets=(0.01, 0.1))
    h.observe(0.05)
    scr = {
        "metrics": reg.render(),
        "fleet": {"replicas": 1, "replica": [
            {"replica": 0, "health": "healthy", "pending": 0,
             "active": 1, "requests_done": 5, "generation": 0,
             "restarts": 0, "parked": 0}]},
        "history": {"samples": 3, "capacity": 900,
                    "sampler": {"running": True, "interval_s": 1.0},
                    "window": {"seconds": 30.0, "rates": {},
                               "histograms": {"ttft_seconds": {
                                   "count": 1, "p50_ms": 50.0,
                                   "p90_ms": 50.0, "p99_ms": 50.0}}},
                    "series": {"engine_tokens_out": [[-1.0, 3.0],
                                                     [0.0, 5.0]]}},
        "alerts": {"rules": 2, "firing": 1, "pending": 0, "alerts": [
            {"rule": "ttft_burn_fast", "severity": "page",
             "state": "firing", "value": 20.0, "detail": "burning",
             "fires": 1, "firing_for_s": 12.0},
            {"rule": "sampler_stale", "severity": "warn",
             "state": "ok", "value": 0.1, "detail": "", "fires": 0}]},
        "slowlog": {"recorded": 1, "worst": [
            {"rid": 7, "tag": "t", "e2e_ms": 9.0, "ttft_ms": 1.0,
             "itl_max_ms": 2.0, "itl_max_at_token": 3,
             "queue_wait_ms": 0.1, "prefill_chunks": 1,
             "tokens": 8}]},
    }
    frame = con.render_frame(scr)
    for needle in ("ops console", "ttft_seconds", "replica0",
                   "ttft_burn_fast", "firing", "history:", "rid=7",
                   "tokens_out"):
        assert needle in frame, needle
    # degraded daemon: every surface None still renders a frame
    frame = con.render_frame({"metrics": None, "errors": ["metrics: x"]})
    assert "unavailable" in frame and "scrape errors" in frame


# ----------------------------------------------------- end-to-end chaos
def _quiesce(fleet, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        busy = False
        for r in fleet.replicas:
            with r.cond:
                eng = r.engine
                if (r.dead or r.stepper_alive or eng.pending
                        or eng.inflight_depth
                        or any(a is not None for a in eng.active)):
                    busy = True
            with fleet.cv:
                if r.health.state in (router.QUARANTINED,
                                      router.REBUILDING):
                    busy = True
        if not busy:
            return
        time.sleep(0.02)
    raise AssertionError("fleet never quiesced")


def test_chaos_alert_fires_before_crash_steers_then_resolves(trained):
    """THE round-15 acceptance: a scoped fault wedges replica1 (slow
    ticks), the windowed replica-degradation alert fires while the
    replica is merely degraded — BEFORE the health machine's crash
    path ever runs — placement steers off it, the eventual injected
    crash migrates the stream bit-identically to a fault-free run, and
    after recovery the alert resolves and the replica returns to
    placement."""
    svc = daemon_mod._FleetService()
    fleet = daemon_mod._make_fleet(
        lambda: (PagedEngine(trained, CFG, slots=2, n_blocks=32,
                             block_size=8, max_seq=64), None), 2)
    key = ("alerts-chaos-test",)
    daemon_mod._FLEETS[key] = (None, fleet)
    # tight windows so resolve happens inside the test: 2 s of tick
    # evidence, >= 2 ticks, half slow; hold firing 0.3 s after clear
    f = fleet.fid
    rule1 = f"fleet{f}_replica1_degraded"
    obs.ALERTS.add(A.ReplicaStallRule(1, fleet_id=f, window_s=2.0,
                                      min_ticks=2, slow_frac=0.5,
                                      for_s=0, keep_firing_s=0.3),
                   replace=True)
    obs.ALERTS.add(A.ReplicaStallRule(0, fleet_id=f, window_s=2.0,
                                      min_ticks=2, slow_frac=0.5,
                                      for_s=0, keep_firing_s=0.3),
                   replace=True)
    obs.HISTORY.clear()
    prompt_a = _cycle_prompt(5)
    prompt_b = _cycle_prompt(6)
    # deterministic per-replica schedule: replica1's engine ticks run
    # 300 ms slow (>= the router's 0.25 s slow-tick threshold, so each
    # one is ALSO windowed slow-tick evidence) for its first 10 ticks,
    # then its 12th tick CRASHES.  replica0 is untouched.
    faults.configure([
        {"site": "paged.tick@replica1", "kind": "slow_ms", "at": 1,
         "count": 10, "arg": 300.0},
        {"site": "paged.tick@replica1", "kind": "raise", "at": 12},
    ])
    results = {}

    def run(name, prompt, steps):
        results[name] = svc.generate(fleet, prompt, steps)

    ta = threading.Thread(target=run, args=("a", prompt_a, 40))
    ta.start()
    # wait until replica0 is busy so the next request places on 1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with fleet.replicas[0].cond:
            if any(x is not None for x in
                   fleet.replicas[0].engine.active):
                break
        time.sleep(0.005)
    tb = threading.Thread(target=run, args=("b", prompt_b, 24))
    tb.start()
    # the sampler loop (what the daemon's _HistorySampler does), driven
    # here for determinism: sample -> evaluate -> apply to fleet health
    fired_at = None
    crashes_at_fire = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        obs.HISTORY.sample()
        obs.ALERTS.evaluate(obs.HISTORY)
        daemon_mod._apply_fleet_alerts()
        st = obs.ALERTS.get_state(rule1)
        if st is not None and st.state == A.FIRING:
            fired_at = time.monotonic()
            with fleet.cv:
                crashes_at_fire = fleet.replicas[1].health.crashes
                state_at_fire = fleet.replicas[1].health.state
            break
        time.sleep(0.1)
    assert fired_at is not None, "degradation alert never fired"
    # BEFORE the crash path: zero crashes when the alert fired, and the
    # alert-wired SUSPECT demotion is in place
    assert crashes_at_fire == 0
    assert state_at_fire == router.SUSPECT
    # placement steers off the suspect replica even for a prompt whose
    # prefix lives there (non-SUSPECT is strictly preferred)
    placed = svc._place(fleet, prompt_b)
    assert placed is not None and placed.index == 0
    # let the crash land and both requests finish — the migrated stream
    # is bit-identical to a fault-free run
    ta.join(timeout=120)
    tb.join(timeout=120)
    assert not ta.is_alive() and not tb.is_alive()
    want_a = generate(trained, prompt_a[None, :], CFG, steps=40,
                      temperature=0.0)[0]
    want_b = generate(trained, prompt_b[None, :], CFG, steps=24,
                      temperature=0.0)[0]
    assert np.array_equal(results["a"], want_a)
    assert np.array_equal(results["b"], want_b)
    assert faults.INJECTOR.fired().get("paged.tick@replica1", 0) >= 11
    with fleet.cv:
        assert fleet.replicas[1].health.crashes == 1
    faults.disable()
    _quiesce(fleet)
    # recovery: keep sampling until the alert resolves (slow ticks age
    # out of the 2 s window) and the hold on replica1 releases
    deadline = time.monotonic() + 30
    resolved = False
    while time.monotonic() < deadline:
        obs.HISTORY.sample()
        obs.ALERTS.evaluate(obs.HISTORY)
        daemon_mod._apply_fleet_alerts()
        st = obs.ALERTS.get_state(rule1)
        if st.state in (A.RESOLVED, A.OK):
            resolved = True
            break
        time.sleep(0.1)
    assert resolved, "alert never resolved after recovery"
    with fleet.cv:
        assert fleet.replicas[1].health.placeable
        assert not fleet.replicas[1].health.alert_firing
    # replica1 is back in rotation: an idle fleet places on it once
    # replica0 carries load again
    out = svc.generate(fleet, prompt_b, 4)
    assert len(out) == 4
    _quiesce(fleet)
    daemon_mod._FLEETS.pop(key, None)
    obs.ALERTS.remove(f"fleet{f}_replica0_degraded")
    obs.ALERTS.remove(rule1)
    obs.HISTORY.clear()
