"""Compiler & device observability (round 14).

Covers the round-14 ISSUE checklist:

  * the compile ledger (tpulab.obs.compilestats): per-program compile
    counts / compile-seconds via the executable-cache delta,
    cost_analysis snapshots at first compile, thread-filtered event
    bracketing, memory_analysis under the opt-in flag;
  * the RECOMPILE TRIPWIRE, proven BOTH WAYS (the acceptance pair): a
    steady-state decode window with spec + interleave + overlap ON
    records ZERO recompiles under strict(), and a deliberately
    bucket-busting prompt mix records a nonzero ``engine_recompiles``
    (and raises under strict at the offending tick);
  * MFU/roofline (tpulab.obs.roofline): the shared analytic-FLOPs
    implementation (tpulab.bench and tools/train_mfu_probe re-import
    it), compute- vs bandwidth-bound classification against the
    generation peaks, the engine_mfu/train_mfu gauges, and the
    CPU-proxy caveat (0 / "unknown", never a fabricated number);
  * HBM/KV occupancy: blocks used/free arithmetic, pool bytes, prefix
    cache bytes, the device-memory gauges' estimate fallback, and the
    per-program compile-bucket census gauges (census warn-once
    preserved — tests/test_paged_interleave.py keeps that assert);
  * the crash flight recorder (tpulab.obs.flightrec), exercised END TO
    END on the chaos path: an injected ``paged.step`` crash produces a
    bundle whose trace slice contains the failing request's rid-linked
    events and whose compile-stats table matches the live scrape, with
    zero leaked blocks after the supervised replay;
  * runtime/device info paths (device_info / ici_topology /
    generation_limits) on the CPU backend — they feed the roofline
    peak lookup and were previously untested;
  * the daemon's ``compile_stats``/``postmortem`` requests and
    tools/obs_report.py's ``--roofline``/``--postmortem`` renderers;
  * standing contracts re-certified with the new instrumentation ON:
    the transfer-guard flat-h2d steady window runs INSIDE strict()
    (obs on/off bit-equality and the obs_overhead <3% budget keep
    their existing certifications in tests/test_obs.py, which now run
    with the compile wrappers active).
"""

import importlib.util
import json
import pathlib
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab import faults, obs
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs import compilestats as cstats
from tpulab.obs import flightrec, roofline
from tpulab.obs.compilestats import COMPILESTATS, CompileStats, RecompileError

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)
ROOT = pathlib.Path(__file__).resolve().parent.parent

#: explicit TPU-shaped peaks for gauge/classification tests (the CPU
#: proxy has none by design)
PEAKS = {"device_kind": "test", "peak_tflops": 100.0, "peak_gbps": 1000.0}


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


@pytest.fixture(autouse=True)
def _injector_always_reset():
    yield
    faults.disable()


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


# ------------------------------------------------------- compile ledger
def test_instrument_counts_compiles_and_snapshots_cost():
    cs = CompileStats()
    fn = cs.instrument("probe", jax.jit(lambda x: x * 2 + 1))
    c0 = cs.seq()
    fn(jnp.ones((4,)))               # compile 1
    fn(jnp.ones((4,)))               # cache hit
    snap = cs.snapshot()["probe"]
    assert snap["compiles"] == 1
    assert snap["compile_seconds"] > 0
    # first-compile cost_analysis snapshot: FLOPs + bytes from the
    # lowered module, no second backend compile needed
    assert snap["flops"] and snap["flops"] > 0
    assert snap["bytes_accessed"] and snap["bytes_accessed"] > 0
    fn(jnp.ones((8,)))               # new shape -> compile 2
    assert cs.snapshot()["probe"]["compiles"] == 2
    assert cs.names_since(c0) == ["probe", "probe"]
    assert cs.seq() == c0 + 2
    assert cs.total_compiles() == 2
    assert cs.total_compile_seconds() > 0
    # analytic model-FLOPs registration rides the same ledger
    cs.set_model_flops("probe", 123.0)
    assert cs.model_flops("probe") == 123.0
    assert cs.model_flops("never-registered") is None


def test_instrument_forwards_attrs_and_reregisters_into_one_row():
    cs = CompileStats()
    base = jax.jit(lambda x: x + 1)
    fn = cs.instrument("twice", base)
    fn2 = cs.instrument("twice", jax.jit(lambda x: x + 2))
    fn(jnp.ones(3))
    fn2(jnp.ones(3))
    assert cs.snapshot()["twice"]["compiles"] == 2  # ONE accumulated row
    # attribute proxying: the wrapper is call-transparent
    assert fn.__wrapped__ is base
    assert callable(fn.lower)


def test_reset_does_not_orphan_wrappers():
    """reset() must not blind the ledger: wrappers resolve their row
    BY NAME per compile, so a post-reset compile re-creates the row
    (the review finding: a cached ProgramStats object survived reset
    and swallowed every later compile)."""
    cs = CompileStats()
    fn = cs.instrument("reborn", jax.jit(lambda x: x * 3))
    fn(jnp.ones((2,)))
    assert cs.snapshot()["reborn"]["compiles"] == 1
    cs.reset()
    assert cs.snapshot() == {} and cs.seq() == 0
    fn(jnp.ones((3,)))                     # fresh shape -> fresh compile
    assert cs.snapshot()["reborn"]["compiles"] == 1
    assert cs.names_since(0) == ["reborn"]


def test_names_since_filters_by_thread():
    """A compile triggered on ANOTHER thread (a peer replica's warmup)
    must not appear in this thread's bracket — the property that stops
    fleet warmup from tripping a steady engine's wire."""
    cs = CompileStats()
    fn = cs.instrument("other-thread", jax.jit(lambda x: x - 1))
    c0 = cs.seq()
    t = threading.Thread(target=lambda: fn(jnp.ones((5,))))
    t.start()
    t.join()
    assert cs.seq() == c0 + 1
    assert cs.names_since(c0) == []          # not OUR thread's compile
    assert cs.names_since(c0, thread_id=t.ident) == ["other-thread"]


def test_strict_raises_and_production_counts():
    cs = CompileStats()
    cs.note_steady_recompile(["paged_tick"])          # production: count
    assert cs.steady_recompiles == 1
    cs.strict = True
    with pytest.raises(RecompileError, match="paged_tick"):
        cs.note_steady_recompile(["paged_tick"])
    assert cs.steady_recompiles == 2                  # counted BEFORE raise
    # the module-level context manager arms/restores the global ledger
    assert not COMPILESTATS.strict
    with cstats.strict():
        assert COMPILESTATS.strict
    assert not COMPILESTATS.strict


def test_memory_analysis_capture_opt_in(monkeypatch):
    """TPULAB_COMPILESTATS_MEMORY=1 additionally snapshots
    memory_analysis (arg/output/temp bytes) at first compile — works on
    the CPU backend, costs one extra backend compile, off by default."""
    monkeypatch.setattr(cstats, "CAPTURE_MEMORY", True)
    cs = CompileStats()
    fn = cs.instrument("mem", jax.jit(lambda x: x @ x.T))
    fn(jnp.ones((4, 4)))
    mem = cs.snapshot()["mem"]["memory"]
    assert mem is not None
    assert mem["argument_size_in_bytes"] > 0
    assert "temp_size_in_bytes" in mem and "output_size_in_bytes" in mem


# ------------------------------------- recompile tripwire (acceptance)
def test_steady_decode_window_zero_recompiles(trained):
    """Acceptance, direction 1: a steady-state decode window with
    speculative verify + interleaved chunked prefill + the async
    overlap window all ON records ZERO recompiles — asserted the hard
    way, with strict() armed so any compile raises at the tick."""
    eng = PagedEngine(trained, CFG, slots=4, n_blocks=32, block_size=8,
                      max_seq=64, prefill_chunk=8, interleave=True,
                      overlap=1, spec_k=2)
    for i in range(4):
        # budget outlasts warm + window even at spec_k+1 tokens/tick
        eng.submit(_cycle_prompt(4 + i), max_new=56,
                   spec="lookup" if i % 2 == 0 else "off")
    for _ in range(12):   # admission + every program compile
        eng.step()
    assert eng._steady, "engine never reached the steady state"
    r0 = eng.counters["recompiles"]
    with cstats.strict():
        for _ in range(16):
            eng.step()
    assert eng.counters["recompiles"] == r0 == 0
    assert eng.stats()["recompiles"] == 0


def test_bucket_busting_mix_records_nonzero_recompiles(trained):
    """Acceptance, direction 2: an unchunked engine gone steady on
    short prompts is hit with a prompt from an UNSEEN dense bucket —
    the fresh prefill compile lands inside a steady step, increments
    ``engine_recompiles``, and raises under strict() at that tick.
    Unique pool geometry (block_size=4) guarantees the compile is
    genuinely fresh regardless of what earlier tests compiled."""
    def mk():
        return PagedEngine(trained, CFG, slots=3, n_blocks=48,
                           block_size=4, max_seq=64, prefill_chunk=0,
                           interleave=True)

    eng = mk()
    eng.submit(_cycle_prompt(4), max_new=40)
    for _ in range(8):
        eng.step()
    assert eng._steady
    assert eng.counters["recompiles"] == 0
    eng.submit(_cycle_prompt(34), max_new=4)    # dense bucket 64, unseen
    with pytest.raises(RecompileError):
        with cstats.strict():
            for _ in range(30):
                eng.step()
    assert eng.counters["recompiles"] > 0
    st = eng.stats()
    assert st["recompiles"] > 0
    assert st["compile_buckets_dense"] >= 2     # the census saw both
    # production mode (no strict): the same mix only counts — the wave
    # completes and the counter reaches the scrape
    eng2 = mk()
    eng2.submit(_cycle_prompt(4), max_new=40)
    for _ in range(8):
        eng2.step()
    assert eng2._steady
    eng2.submit(_cycle_prompt(30), max_new=4)   # bucket 32, fresh for bs=4
    eng2.run()
    assert eng2.stats()["recompiles"] > 0
    row = eng2.publish_metrics()
    assert obs.REGISTRY.get("engine_recompiles").value == row["recompiles"]


def test_steady_window_transfer_guard_inside_strict(trained):
    """Standing contract: the tripwire accounting itself is host-only —
    a steady window under jax.transfer_guard('disallow') AND strict()
    moves nothing and compiles nothing, h2d_ticks/host_syncs flat."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(4), max_new=30)
    eng.submit(_cycle_prompt(5), max_new=30, temperature=1.1, seed=5)
    for _ in range(4):
        eng.step()
    before = eng.stats()
    with cstats.strict():
        with jax.transfer_guard("disallow"):
            for _ in range(8):
                eng.step()
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 8
    assert st["h2d_ticks"] == before["h2d_ticks"]
    assert st["host_syncs"] == before["host_syncs"]
    assert st["recompiles"] == 0


# ------------------------------------------------------- MFU / roofline
def test_flops_math_is_shared_single_copy():
    import tpulab.bench as bench

    assert bench.labformer_fwd_flops is roofline.labformer_fwd_flops
    assert bench._mfu_fields is roofline.mfu_fields
    # per-token decode FLOPs == the fwd per-token matmul term
    class _Cfg:
        d_model, d_ff, n_layers, vocab = 8, 16, 2, 10
    per_tok = roofline.per_token_flops(_Cfg)
    assert per_tok == 2 * 2 * (4 * 64 + 2 * 8 * 16) + 2 * 8 * 10
    # fwd(b=1, s=1, causal=False) = per_tok + the s^2 attention term
    assert (roofline.labformer_fwd_flops(_Cfg, 1, 1, causal=False)
            == per_tok + 2 * 4 * 8 // 2 * 2)  # n_layers*4*1*1*d


def test_mfu_pct_and_cpu_caveat():
    assert roofline.mfu_pct(50e12, 1.0, PEAKS) == pytest.approx(50.0)
    assert roofline.mfu_pct(50e12, 1.0, {"peak_tflops": None}) == 0.0
    # the attached device is the CPU proxy: no peak, never a number
    assert roofline.device_peaks()["peak_tflops"] is None
    assert roofline.device_peaks()["peak_gbps"] is None
    assert roofline.device_peaks(device_kind="TPU v4")["peak_gbps"] == 1228


def test_roofline_classification():
    # intensity 200 F/B vs ridge 100 -> compute-bound at full peak
    c = roofline.classify(2e12, 1e10, PEAKS)
    assert c["bound"] == "compute-bound"
    assert c["ceiling_tflops"] == PEAKS["peak_tflops"]
    assert c["ridge_flops_per_byte"] == pytest.approx(100.0)
    # intensity 2 F/B -> bandwidth-bound, ceiling = intensity * bw
    c = roofline.classify(2e10, 1e10, PEAKS)
    assert c["bound"] == "bandwidth-bound"
    assert c["ceiling_tflops"] == pytest.approx(2e10 / 1e10 * 1000 / 1e3)
    # no peaks (CPU proxy): says so instead of fabricating
    assert "unknown" in roofline.classify(2e10, 1e10, {})["bound"]
    assert roofline.classify(None, 1e10, PEAKS)["bound"] == "unknown"


def test_roofline_rows_from_snapshot():
    rows = roofline.roofline_rows(
        {"p1": {"compiles": 2, "compile_seconds": 1.5, "flops": 2e12,
                "bytes_accessed": 1e10, "model_flops": None}},
        PEAKS)
    assert rows[0]["program"] == "p1"
    assert rows[0]["bound"] == "compute-bound"
    assert rows[0]["compiles"] == 2


def test_engine_mfu_gauge_from_itl_and_registered_flops(trained):
    """A served wave populates itl_seconds and registers the engine's
    per-tick analytic FLOPs; with explicit TPU-shaped peaks the gauge
    computes, and with the real (CPU) peaks it publishes 0 — the
    documented caveat."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(4), max_new=8)
    eng.run()
    assert (COMPILESTATS.model_flops("paged_tick")
            == 2 * roofline.per_token_flops(CFG))
    got = roofline.update_mfu_gauges(PEAKS)
    assert got["engine_mfu"] > 0
    assert obs.REGISTRY.get("engine_mfu").value == got["engine_mfu"]
    assert roofline.update_mfu_gauges()["engine_mfu"] == 0.0  # CPU proxy


def test_train_mfu_accumulates_windows():
    roofline.note_train_window(5e12, 1.0)
    got = roofline.update_mfu_gauges(PEAKS)
    assert got["train_mfu"] > 0
    assert obs.REGISTRY.get("train_mfu").value == got["train_mfu"]


# ------------------------------------------------- HBM / KV occupancy
def test_capacity_stats_and_memory_gauges(trained):
    from tpulab.models.paged import _pool_nbytes

    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64)
    st0 = eng.stats()
    assert st0["blocks_used"] == 0
    assert st0["blocks_used"] + st0["blocks_free"] == st0["blocks_total"]
    assert st0["kv_pool_bytes"] == (_pool_nbytes(eng.kpool)
                                    + _pool_nbytes(eng.vpool))
    assert st0["cache_bytes"] == 0
    # a prompt long enough to register a block-aligned prefix
    eng.submit(_cycle_prompt(17), max_new=4)
    eng.run()
    st = eng.stats()
    assert st["cache_entries"] == 1 and st["cache_bytes"] > 0
    assert st["cache_bytes"] % (st["kv_pool_bytes"] // 32) == 0
    assert st["blocks_used"] + st["blocks_free"] == st["blocks_total"]
    # device estimate covers pools + params + per-slot state
    est = eng.device_bytes_estimate()
    assert est > st["kv_pool_bytes"]
    assert eng.device_bytes_estimate() == est  # cached
    # the scrape-path gauges: CPU backend has no memory_stats -> the
    # in-use gauge falls back to the estimate, limit publishes 0
    got = roofline.update_device_memory_gauges(est)
    assert got["engine_hbm_bytes_in_use"] == est
    assert obs.REGISTRY.get("engine_hbm_bytes_in_use").value == est
    assert obs.REGISTRY.get("engine_hbm_bytes_limit").value == 0


def test_int8_pool_bytes_include_scales(trained):
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=16, block_size=8,
                      max_seq=64, kv_dtype="int8")
    data, scale = eng.kpool
    assert (eng.stats()["kv_pool_bytes"]
            == 2 * (data.nbytes + scale.nbytes))


def test_compile_bucket_census_per_program(trained):
    """The promoted census gauges: dense whole-prompt buckets and
    chunk-0 whole-tail extend buckets count separately per program
    (the warn-once over the union is asserted where it always was,
    tests/test_paged_interleave.py)."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, prefill_chunk=0)
    eng.submit(_cycle_prompt(5), max_new=2)    # dense bucket 16
    eng.run()
    eng.submit(_cycle_prompt(20), max_new=2)   # dense bucket 32
    eng.run()
    st = eng.stats()
    assert st["compile_buckets_dense"] == 2
    assert st["compile_buckets_extend"] == 0
    # a prefix-hit admission on the unchunked engine runs the chunk-0
    # whole-tail extend window -> the EXTEND census counts it
    eng.submit(_cycle_prompt(20), max_new=2)   # shares the cached prefix
    eng.run()
    st = eng.stats()
    assert st["prefix_hits"] >= 1
    assert st["compile_buckets_extend"] >= 1
    assert st["compile_buckets_dense"] == 2


# ------------------------------------------------- device info (CPU)
def test_generation_limits_lookup_and_bandwidth():
    from tpulab.runtime.device import generation_limits

    v4 = generation_limits("TPU v4")
    assert v4["bf16_peak_tflops_per_chip"] == 275
    assert v4["hbm_gbps_per_chip"] == 1228
    # substring matching, case-insensitive, against real kind strings
    assert generation_limits("TPU v5 lite chip")["hbm_gbps_per_chip"] == 819
    assert generation_limits("tpu v5e")["bf16_peak_tflops_per_chip"] == 197
    assert generation_limits("Intel Xeon") == {}
    assert generation_limits("") == {}
    # mutating the returned dict must not poison the table
    v4["bf16_peak_tflops_per_chip"] = -1
    assert generation_limits("TPU v4")["bf16_peak_tflops_per_chip"] == 275


def test_device_info_cpu_backend():
    from tpulab.runtime.device import (device_info, format_device_info,
                                       ici_topology)

    info = device_info()
    assert info["platform"] == "cpu"
    assert info["num_devices"] == jax.device_count()
    assert info["num_local_devices"] == jax.local_device_count()
    assert info["num_processes"] == 1 and info["process_index"] == 0
    assert "id" in info and "device_kind" in info
    # CPU has no generation-limit or memory_stats fields
    assert "bf16_peak_tflops_per_chip" not in info
    topo = ici_topology()
    assert topo["num_chips"] == jax.device_count()
    assert info["ici_num_chips"] == topo["num_chips"]
    text = format_device_info()
    assert "platform: cpu" in text
    assert len(text.splitlines()) == len(info)


def test_resolve_and_commit_paths():
    from tpulab.runtime.device import (backend_name, cpu_device,
                                       resolve_device)

    assert backend_name() == "cpu"
    assert resolve_device(None).platform == "cpu"
    assert resolve_device("auto") is resolve_device("default")
    assert resolve_device("cpu") == jax.devices("cpu")[0]
    assert cpu_device() is cpu_device()  # cached


# ------------------------------------------------- flight recorder
def test_flightrec_roundtrip_and_retention(tmp_path):
    flightrec.configure_flightrec(tmp_path)
    try:
        p = flightrec.record_postmortem(
            "unit", err=ValueError("boom"), extra={"k": (1, 2)})
        assert p is not None and p.is_file()
        bundle = json.loads(p.read_text())
        assert bundle["schema"] == 1 and bundle["reason"] == "unit"
        assert bundle["error"] == {"type": "ValueError",
                                   "message": "boom"}
        assert bundle["extra"] == {"k": [1, 2]}
        assert "metrics" in bundle and "compile_stats" in bundle
        assert bundle["faults"]["enabled"] is False
        latest = flightrec.latest_postmortem()
        assert latest["path"] == str(p)
        # bounded retention: KEEP newest survive, oldest deleted
        for i in range(flightrec.KEEP + 3):
            flightrec.record_postmortem(f"r{i}")
        assert len(flightrec.list_bundles()) == flightrec.KEEP
        assert flightrec.latest_postmortem()["reason"] == (
            f"r{flightrec.KEEP + 2}")
        # a corrupt newest bundle is skipped, not fatal
        flightrec.list_bundles()[0].write_text("{corrupt")
        assert flightrec.latest_postmortem()["reason"] == (
            f"r{flightrec.KEEP + 1}")
    finally:
        flightrec.configure_flightrec(None)


def _no_leaks(eng):
    cache_blocks = {b for blocks in eng.prefix_cache.values()
                    for b in blocks}
    assert len(eng.free) + len(cache_blocks) == eng.n_usable_blocks
    assert len(set(eng.free)) == len(eng.free)
    assert all(eng.block_refs[b] == 0 for b in eng.free)


def test_flight_recorder_end_to_end_on_chaos_path(trained, tmp_path):
    """Acceptance: an injected ``paged.step`` crash rides the PR-6
    supervisor, and the bundle it leaves behind is self-explaining —
    the failing request's rid-linked trace events are in the slice,
    the compile-stats table matches the live scrape, the armed fault
    schedule is recorded, and the replayed wave completes with zero
    leaked blocks."""
    from tpulab.daemon import _GenerateService, _handle_compile_stats

    flightrec.configure_flightrec(tmp_path)
    prior = obs.TRACER.capacity
    try:
        obs.configure_tracer(1 << 12)  # fresh, private trace window
        svc = _GenerateService()

        def mk():
            e = PagedEngine(trained, CFG, slots=2, n_blocks=32,
                            block_size=8, max_seq=64)
            e._rebuild = lambda: (mk(), None)
            e._build_stamp = "test-stamp"
            return e

        eng = mk()
        pm0 = obs.REGISTRY.get("daemon_postmortems").value
        rid_lo = obs.next_rid()
        with faults.active([{"site": "paged.step", "kind": "raise",
                             "at": 4}]):
            out = svc.generate(eng, _cycle_prompt(4), 12)
            # read fired() INSIDE the context: disable() clears rules
            assert faults.INJECTOR.fired() == {"paged.step": 1}
        rid_hi = obs.next_rid()
        # the replayed stream is bit-identical to a fault-free run
        want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                        temperature=0.0)[0]
        assert np.array_equal(out, want)
        assert obs.REGISTRY.get("daemon_postmortems").value == pm0 + 1
        bundle = flightrec.latest_postmortem()
        assert bundle["reason"] == "engine_quarantine"
        assert bundle["error"]["type"] == "InjectedFault"
        assert bundle["engine"]["build_stamp"] == "test-stamp"
        assert bundle["engine"]["stats"]["ticks"] >= 1
        # the armed schedule travelled into the bundle
        sites = [r["site"] for r in bundle["faults"]["rules"]]
        assert "paged.step" in sites
        # rid linkage: the failing request's submit AND admit events
        # (same rid, allocated between our two fenceposts) are in the
        # trace slice
        by_name = {}
        for e in bundle["trace"]["events"]:
            arg = (e.get("args") or {}).get("arg")
            if arg is not None and rid_lo < arg < rid_hi:
                by_name.setdefault(e["name"], set()).add(arg)
        assert by_name.get("engine.submit"), by_name
        rid = next(iter(by_name["engine.submit"]))
        assert rid in by_name.get("engine.admit", set())
        # compile-stats table matches the live scrape (same program
        # set; the crash froze counts the scrape can only meet or
        # exceed — the replay re-uses the already-compiled programs)
        live = json.loads(_handle_compile_stats({}))["programs"]
        assert set(bundle["compile_stats"]) == set(live)
        for name, row in bundle["compile_stats"].items():
            assert live[name]["compiles"] >= row["compiles"]
        assert bundle["compile_stats"]["paged_tick"]["compiles"] >= 1
        # zero leaked blocks on the engine that served the replay
        _no_leaks(svc._state_for(eng).engine)
    finally:
        obs.configure_tracer(prior)
        flightrec.configure_flightrec(None)


# ------------------------------------------- daemon + report surfaces
def test_daemon_compile_stats_request(trained):
    from tpulab.daemon import handle_request

    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(4), max_new=2)
    eng.run()
    payload = json.loads(handle_request({"lab": "compile_stats"}, b""))
    assert "paged_tick" in payload["programs"]
    assert payload["programs"]["paged_tick"]["compiles"] >= 1
    assert payload["peaks"]["peak_tflops"] is None  # CPU proxy
    assert set(payload["mfu"]) == {"engine_mfu", "train_mfu"}
    assert payload["total_compile_seconds"] > 0


def test_daemon_postmortem_request(tmp_path):
    from tpulab.daemon import handle_request

    flightrec.configure_flightrec(tmp_path)
    try:
        assert json.loads(handle_request({"lab": "postmortem"}, b"")) == {
            "bundles": 0}
        flightrec.record_postmortem("wire-test", err=RuntimeError("x"))
        got = json.loads(handle_request({"lab": "postmortem"}, b""))
        assert got["reason"] == "wire-test" and got["bundles"] == 1
        assert got["path"].startswith(str(tmp_path))
    finally:
        flightrec.configure_flightrec(None)


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", ROOT / "tools" / "obs_report.py")
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    return rep


def test_obs_report_roofline_and_postmortem_renderers():
    rep = _load_obs_report()
    payload = {
        "programs": {"paged_tick": {
            "compiles": 3, "compile_seconds": 2.25, "flops": 2e12,
            "bytes_accessed": 1e10, "model_flops": 1e9}},
        "peaks": PEAKS,
        "mfu": {"engine_mfu": 12.5, "train_mfu": 0.0},
        "steady_recompiles": 0, "total_compile_seconds": 2.25,
    }
    text = rep.format_roofline(payload)
    assert "paged_tick" in text and "compute-bound" in text
    assert "engine=12.5%" in text
    empty = rep.format_roofline({"programs": {}, "peaks": {}, "mfu": {}})
    assert "no programs compiled" in empty
    assert "no post-mortem" in rep.format_postmortem({"bundles": 0})
    pm = rep.format_postmortem({
        "reason": "engine_quarantine", "bundles": 2, "path": "/x.json",
        "error": {"type": "InjectedFault", "message": "boom"},
        "engine": {"build_key": None, "build_stamp": "s",
                   "replica_index": 1,
                   "stats": {"ticks": 9, "recompiles": 0}},
        "faults": {"rules": [{"site": "paged.step", "kind": "raise",
                              "at": 4, "fired": 1}]},
        "compile_stats": {"paged_tick": {"compiles": 2}},
        "trace": {"events": [1, 2, 3], "dropped": 0},
        "slowlog": {"worst": [{"rid": 7, "tag": "t", "e2e_ms": 5.0,
                               "tokens": 3, "resubmits": 1}]},
    })
    assert "engine_quarantine" in pm and "InjectedFault" in pm
    assert "paged.step raise at=4 fired=1" in pm
    assert "paged_tickx2" in pm and "rid=7" in pm


def test_device_tier_gauges_registered_and_documented(trained):
    """The round-14 lint extension (tests/test_obs.py pattern): the
    non-stats device-tier gauges and the postmortem counter are
    registered AND documented — a new gauge cannot silently miss the
    scrape surface or the docs catalog."""
    PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                max_seq=64).publish_metrics()
    import tpulab.daemon  # noqa: F401  (registers daemon_postmortems)

    docs = (ROOT / "docs" / "ARCHITECTURE.md").read_text()
    for name in ("engine_mfu", "train_mfu", "engine_hbm_bytes_in_use",
                 "engine_hbm_bytes_limit", "daemon_postmortems"):
        assert obs.REGISTRY.get(name) is not None, name
        assert name in docs, f"{name} missing from docs/ARCHITECTURE.md"


def test_bench_registry_has_decode_recompiles():
    import inspect

    from tpulab.bench import bench_decode_recompiles, run_benchmarks

    src = inspect.getsource(run_benchmarks)
    assert "decode_recompiles" in src
    row = bench_decode_recompiles(slots=2, steps=12, spec_k=2)
    assert row["metric"] == "decode_steady_recompiles"
    assert row["value"] == 0, row
