"""Telemetry over time, part 1: the metrics history ring
(tpulab.obs.history) and its daemon wiring.

Round-15 checklist covered here:
  * windowed histogram-bucket differencing — including counter resets
    (a cleared registry / an evicted engine's re-zeroed gauge mirror:
    the new counts ARE the delta) — so ``percentile_from_buckets``
    works over "the last 30 s" instead of process lifetime;
  * window selection at exact sample boundaries, windows longer than
    the ring's span, wraparound, and the single-sample degenerate case;
  * ``fraction_le`` (the SLO error-rate input) edge cases;
  * the background :class:`~tpulab.obs.history.Sampler` (tick cadence,
    error containment, stop);
  * the daemon's ``history`` request and the WINDOWED shed signal —
    ``_queue_wait_p99_ms`` reads a live-edged history window when the
    sampler is active and decays past congestion, and falls back to
    the legacy two-mark path (behavior-compatible) when not;
  * standing contracts re-certified with the sampler RUNNING: engine
    streams/stats bit-identical obs on/off, and the transfer-guard
    flat-``h2d_ticks`` steady window.
"""

import json
import time

import jax
import numpy as np
import pytest

from tpulab import obs
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine
from tpulab.obs import history as H
from tpulab.obs.registry import Registry

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


# ----------------------------------------------------------- delta math
def test_counts_delta_basic_and_scratch_reuse():
    out = H.counts_delta([5, 3, 1], [2, 3, 0])
    assert out == [3, 0, 1]
    # scratch reuse: same list object comes back, contents replaced
    same = H.counts_delta([9, 9, 9], [1, 2, 3], out)
    assert same is out and out == [8, 7, 6]


def test_counts_delta_reset_rules():
    # any bucket going backwards == restart: new counts ARE the delta
    assert H.counts_delta([2, 0, 0], [5, 0, 0]) == [2, 0, 0]
    assert H.counts_delta([7, 1, 0], [7, 2, 0]) == [7, 1, 0]
    # absent-from-old (metric created inside the window) == reset
    assert H.counts_delta([4, 4], None) == [4, 4]
    # length mismatch (bucket layout changed) == reset, not ValueError
    assert H.counts_delta([1, 2, 3], [1, 2]) == [1, 2, 3]


def test_value_delta_reset_clamp():
    assert H.value_delta(10.0, 4.0) == 6.0
    assert H.value_delta(3.0, 7.0) == 3.0   # went backwards: restart
    assert H.value_delta(3.0, None) == 3.0


def test_fraction_le_edges():
    bounds = (0.1, 0.2, 0.4)
    # empty window: no observations -> no violations
    assert H.fraction_le(bounds, [0, 0, 0, 0], 0.2) == 1.0
    # all mass in one bucket, x at its exact upper boundary
    assert H.fraction_le(bounds, [4, 0, 0, 0], 0.1) == 1.0
    # interpolation inside the first bucket (lo=0)
    assert H.fraction_le(bounds, [4, 0, 0, 0], 0.05) == pytest.approx(0.5)
    # x below every bound with mass above it
    assert H.fraction_le(bounds, [0, 4, 0, 0], 0.1) == 0.0
    # interpolation inside an inner bucket
    assert H.fraction_le(bounds, [2, 2, 0, 0], 0.15) == pytest.approx(
        (2 + 2 * 0.5) / 4)
    # overflow mass: x past the last finite bound clamps to 1.0
    assert H.fraction_le(bounds, [0, 0, 0, 3], 0.4) == 1.0


# ------------------------------------------------------------- the ring
def _mk(capacity=8):
    reg = Registry()
    c = reg.counter("reqs")
    h = reg.histogram("lat_seconds", buckets=(0.1, 0.2, 0.4))
    g = reg.gauge("depth")
    return reg, c, h, g, H.MetricsHistory(capacity)


def test_ring_wraparound_keeps_newest():
    reg, c, _, _, hist = _mk(capacity=4)
    for i in range(7):
        c.inc()
        hist.sample(reg, now=float(i))
    assert hist.samples == 4 and hist.total_samples == 7
    times = [t for t, _ in hist.retained()]
    assert times == [3.0, 4.0, 5.0, 6.0]  # oldest first, newest kept
    assert hist.latest()[0] == 6.0


def test_window_boundary_selection_is_exact():
    reg, c, _, _, hist = _mk()
    for i in range(8):
        c.inc(2)
        hist.sample(reg, now=float(i))
    # newest sample t=7 is the end; target 7-3=4 hits a sample exactly
    w = hist.window(3.0)
    assert (w.t0, w.t1) == (4.0, 7.0)
    assert w.delta("reqs") == 6 and w.rate("reqs") == pytest.approx(2.0)
    # a window BETWEEN samples bases on the newest sample at/before it
    w = hist.window(2.5)
    assert w.t0 == 4.0  # 7-2.5=4.5 -> sample at 4.0
    # longer than the ring's span: falls back to the oldest retained
    w = hist.window(100.0)
    assert w.t0 == 0.0 and w.delta("reqs") == 14


def test_single_sample_window_and_empty():
    reg, c, _, _, hist = _mk()
    assert hist.window(10.0) is None
    c.inc(5)
    hist.sample(reg, now=1.0)
    w = hist.window(10.0)
    assert w.old is None and w.delta("reqs") == 5  # since-start view


def test_histogram_differencing_across_reset():
    """The engine-eviction / registry-restart case: bucket counts go
    BACKWARDS between samples, and the window must report the new
    life's counts instead of negative garbage."""
    reg, _, h, _, hist = _mk()
    for v in (0.05, 0.05, 0.3):
        h.observe(v)
    hist.sample(reg, now=1.0)
    # a fresh registry under the same names == the evicted-engine shape
    reg2 = Registry()
    h2 = reg2.histogram("lat_seconds", buckets=(0.1, 0.2, 0.4))
    reg2.counter("reqs").inc()
    h2.observe(0.15)
    hist.sample(reg2, now=2.0)
    w = hist.window(1.0)
    assert w.count("lat_seconds") == 1
    assert w.percentile("lat_seconds", 0.5) == pytest.approx(0.15, abs=0.05)
    assert w.delta("reqs") == 1  # counter reset-clamped, not negative


def test_window_percentile_matches_direct_math():
    reg, _, h, _, hist = _mk()
    for v in (0.05,) * 10:
        h.observe(v)
    hist.sample(reg, now=0.0)
    for v in (0.3,) * 10:  # only these land inside the window
        h.observe(v)
    hist.sample(reg, now=10.0)
    w = hist.window(5.0)
    assert w.count("lat_seconds") == 10
    # all windowed mass in the (0.2, 0.4] bucket
    assert 0.2 < w.percentile("lat_seconds", 0.5) <= 0.4
    # lifetime percentile would say the p50 is in the first bucket —
    # the whole point of windowing
    assert h.percentile(0.5) <= 0.1
    assert w.fraction_le("lat_seconds", 0.2) == 0.0


def test_absent_metric_accessors_are_tolerant():
    reg, c, _, _, hist = _mk()
    c.inc()
    hist.sample(reg, now=0.0)
    hist.sample(reg, now=1.0)
    w = hist.window(1.0)
    assert w.delta("nope") == 0.0 and w.rate("nope") == 0.0
    assert w.percentile("nope", 0.99) == 0.0
    assert w.hist_delta("nope") is None
    assert w.fraction_le("nope", 1.0) == 1.0
    assert w.gauge("nope", default=7.0) == 7.0


def test_series_rates_and_reset():
    reg, c, _, _, hist = _mk()
    for i, inc in enumerate((2, 2, 2, 2)):
        c.inc(inc)
        hist.sample(reg, now=float(i))
    s = hist.series("reqs", 10.0, rate=True)
    assert [v for _, v in s] == pytest.approx([2.0, 2.0, 2.0])
    # restart mid-series: rate clamps to the new value, never negative
    reg2 = Registry()
    reg2.counter("reqs").inc(1)
    hist.sample(reg2, now=4.0)
    s = hist.series("reqs", 10.0, rate=True)
    assert s[-1][1] == pytest.approx(1.0)
    assert all(v >= 0 for _, v in s)


def test_report_shape():
    reg, c, h, _, hist = _mk()
    c.inc(4)
    h.observe(0.05)
    hist.sample(reg, now=0.0)
    c.inc(4)
    h.observe(0.3)
    hist.sample(reg, now=2.0)
    rep = hist.report(2.0, series=["reqs"])
    assert rep["samples"] == 2 and rep["capacity"] == 8
    assert rep["window"]["rates"]["reqs"] == pytest.approx(2.0)
    hrow = rep["window"]["histograms"]["lat_seconds"]
    assert hrow["count"] == 1 and hrow["p99_ms"] > 100
    assert rep["series"]["reqs"][-1][1] == pytest.approx(2.0)
    json.dumps(rep)  # wire-serializable as-is


def test_live_window_counts_post_sample_observations():
    reg, _, h, _, hist = _mk()
    hist.sample(reg, now=time.monotonic())
    h.observe(0.3)  # lands AFTER the newest ring sample
    w = hist.live_window(60.0, reg)
    assert w.count("lat_seconds") == 1


# ------------------------------------------------------------- sampler
def test_sampler_thread_ticks_and_stops():
    reg = Registry()
    reg.counter("x").inc()
    hist = H.MetricsHistory(16)
    hooks = {"n": 0}

    def boom():
        hooks["n"] += 1
        if hooks["n"] == 1:
            raise RuntimeError("one bad tick")

    s = H.Sampler(hist, 0.01, on_sample=boom, registry=reg)
    s.start()
    deadline = time.monotonic() + 5.0
    while hist.total_samples < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    s.stop()
    assert hist.total_samples >= 3
    assert s.errors >= 1 and hooks["n"] >= 3  # survived the bad tick
    n = hist.total_samples
    time.sleep(0.05)
    assert hist.total_samples == n  # actually stopped
    assert not s.running
    with pytest.raises(ValueError, match="interval_s"):
        H.Sampler(hist, 0.0)


# ------------------------------------------------------- daemon wiring
def test_daemon_history_request_reports_window():
    from tpulab.daemon import handle_request

    obs.HISTORY.clear()
    try:
        obs.REGISTRY.counter("hist_req_probe").inc(3)
        obs.HISTORY.sample(now=time.monotonic() - 5.0)
        obs.REGISTRY.counter("hist_req_probe").inc(3)
        obs.HISTORY.sample()
        rep = json.loads(handle_request(
            {"lab": "history",
             "config": {"seconds": 30, "series": ["hist_req_probe"]}},
            b""))
        assert rep["samples"] == 2
        assert rep["window"]["rates"]["hist_req_probe"] > 0
        assert rep["series"]["hist_req_probe"]
        assert rep["sampler"]["running"] is False  # none started here
        with pytest.raises(ValueError, match="seconds"):
            handle_request({"lab": "history",
                            "config": {"seconds": -1}}, b"")
    finally:
        obs.HISTORY.clear()


def test_shed_p99_uses_history_window_and_decays(monkeypatch):
    """The round-15 shed upgrade: with an active sampler the
    queue-wait p99 comes from a live-edged history window — old
    congestion DECAYS out once it leaves the window — and without one
    the legacy two-mark path still answers (behavior compatibility)."""
    import tpulab.daemon as daemon_mod

    svc = daemon_mod._GenerateService()
    qw = obs.REGISTRY.histogram("queue_wait_seconds")
    obs.HISTORY.clear()
    monkeypatch.setattr(daemon_mod, "_sampler_active", lambda: True)
    try:
        # congestion BEFORE the window base: must not shed forever
        for _ in range(50):
            qw.observe(3.0)
        obs.HISTORY.sample(
            now=time.monotonic() - daemon_mod.QUEUE_WAIT_WINDOW_S - 5)
        obs.HISTORY.sample()  # fresh edge: congestion is outside
        assert svc._queue_wait_p99_ms() == 0.0
        # fresh congestion INSIDE the window (after the newest sample:
        # the live edge must see it without waiting for the sampler)
        for _ in range(50):
            qw.observe(1.0)
        p99 = svc._queue_wait_p99_ms()
        assert 500.0 <= p99 <= 2000.0
    finally:
        obs.HISTORY.clear()
    # sampler inactive -> legacy marks path (fresh service: the first
    # call primes the mark at current cumulative counts, so the old
    # observations above are invisible — same decay discipline)
    monkeypatch.setattr(daemon_mod, "_sampler_active", lambda: False)
    svc2 = daemon_mod._GenerateService()
    svc2.prime_queue_wait()
    assert svc2._queue_wait_p99_ms() == 0.0


def test_start_sampler_clamps_bad_capacity_and_zero_interval():
    """TPULAB_DAEMON_HISTORY=0 (or any < 1) must degrade to the
    smallest ring, not kill the daemon before it binds its socket;
    interval 0 disables cleanly."""
    import tpulab.daemon as daemon_mod
    from tpulab.obs import alerts as A2

    prior_cap = obs.HISTORY.capacity
    assert daemon_mod.start_sampler(interval_s=0) is None
    s = daemon_mod.start_sampler(interval_s=0.05, capacity=0)
    try:
        assert s is not None and s.running
        assert obs.HISTORY.capacity == 1
    finally:
        daemon_mod.stop_sampler()
        # start_sampler installed the default catalog + page bundles on
        # the GLOBAL manager: restore a clean slate for later tests
        A2.ALERTS.clear()
        A2.ALERTS.page_postmortems = False
        obs.configure_history(prior_cap)
        obs.HISTORY.clear()


def test_sampler_active_requires_fresh_samples(monkeypatch):
    import tpulab.daemon as daemon_mod

    class FakeSampler:
        interval_s = 0.5
        running = True

    obs.HISTORY.clear()
    monkeypatch.setattr(daemon_mod, "_SAMPLER", None)
    assert not daemon_mod._sampler_active()
    monkeypatch.setattr(daemon_mod, "_SAMPLER", FakeSampler())
    assert not daemon_mod._sampler_active()  # no samples yet
    obs.HISTORY.sample()
    try:
        assert daemon_mod._sampler_active()
    finally:
        obs.HISTORY.clear()


@pytest.mark.slow
def test_obs_history_overhead_bench_under_budget():
    """The round-15 overhead A/B: obs + history sampler + full alert
    catalog ON vs everything OFF, asserting the <3% budget internally
    (wall-clock sensitive — slow tier; the committed baselines row
    gates the CPU-proxy number round over round)."""
    from tpulab.bench import bench_obs_history_overhead

    row = bench_obs_history_overhead(reps=2)
    assert row["metric"] == "obs_history_overhead_4slots_ticks_per_s"
    assert row["value"] > 0 and row["off_ticks_per_s"] > 0
    assert row["history_samples"] > 0 and row["alert_rules"] >= 10
    assert "overhead_pct_best" in row


# --------------------------------------- standing contracts, sampler ON
def _run_wave(params, obs_on):
    eng = PagedEngine(params, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, obs=obs_on)
    r1 = eng.submit(_cycle_prompt(4), max_new=10)
    r2 = eng.submit(_cycle_prompt(6), max_new=8, temperature=1.5, seed=3)
    out = eng.run()
    return (out[r1], out[r2]), eng.stats()


def test_bit_equality_and_transfer_guard_with_sampler_running(trained):
    """The obs on/off bit-equality AND the zero-transfer steady window,
    re-certified while a real sampler thread hammers the registry at
    10 ms cadence: history is a pure READER of state the hot paths
    already write, so neither contract may move."""
    hist = H.MetricsHistory(64)
    s = H.Sampler(hist, 0.01).start()
    try:
        (a1, a2), st_on = _run_wave(trained, True)
        (b1, b2), st_off = _run_wave(trained, False)
        assert np.array_equal(a1, b1) and np.array_equal(a2, b2)
        assert st_on == st_off
        assert np.array_equal(a1, generate(
            trained, _cycle_prompt(4)[None, :], CFG, steps=10,
            temperature=0.0)[0])
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32,
                          block_size=8, max_seq=64, obs=True)
        eng.submit(_cycle_prompt(4), max_new=30)
        eng.submit(_cycle_prompt(5), max_new=30, repetition_penalty=4.0)
        for _ in range(4):  # admission + compile outside the guard
            eng.step()
        before = eng.stats()
        with jax.transfer_guard("disallow"):
            for _ in range(8):
                eng.step()
        st = eng.stats()
        assert st["ticks"] == before["ticks"] + 8
        assert st["h2d_ticks"] == before["h2d_ticks"]
        assert st["host_syncs"] == before["host_syncs"]
        eng.run()
        assert hist.total_samples > 0  # the sampler really ran
    finally:
        s.stop()
