"""Paged KV cache + continuous-batching engine (tpulab.models.paged).

Headline property: the engine's greedy output per request equals the
plain dense-cache ``generate`` greedy stream, while requests of mixed
lengths share a block pool smaller than the rectangular cache would
need, with blocks recycled across waves through a fixed slot count.
"""

import jax
import numpy as np
import pytest

from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine, TRASH

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


def test_engine_matches_plain_generate(trained):
    """Mixed prompt lengths, more requests than slots (two waves), tiny
    pool: every request's tokens must equal its solo greedy decode."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=24, block_size=8,
                      max_seq=64)
    reqs = {}
    for p, n in [(3, 6), (5, 9), (9, 4), (2, 7), (12, 5)]:
        rid = eng.submit(_cycle_prompt(p), max_new=n)
        reqs[rid] = (p, n)
    out = eng.run()
    assert set(out) == set(reqs)
    for rid, (p, n) in reqs.items():
        want = generate(trained, _cycle_prompt(p)[None, :], CFG, steps=n,
                        temperature=0.0)[0]
        assert np.array_equal(out[rid], want), (rid, p, n)


def test_blocks_recycled(trained):
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                      max_seq=64)
    total_free = len(eng.free)
    for _ in range(3):
        eng.submit(_cycle_prompt(4), max_new=3)
    out = eng.run()
    assert len(out) == 3
    assert sorted(eng.free) == list(range(1, 8))  # every block returned
    assert len(eng.free) == total_free
    assert np.all(eng.tables == TRASH)


def test_pool_capacity_gates_admission(trained):
    # pool holds 3 usable blocks of 8; two requests of 2 blocks each
    # cannot run concurrently — the engine must serialize, not corrupt
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=4, block_size=8,
                      max_seq=32)
    a = eng.submit(_cycle_prompt(6), max_new=8)   # 2 blocks
    b = eng.submit(_cycle_prompt(6), max_new=8)   # 2 blocks
    out = eng.run()
    for rid in (a, b):
        want = generate(trained, _cycle_prompt(6)[None, :], CFG, steps=8,
                        temperature=0.0)[0]
        assert np.array_equal(out[rid], want)


def test_oversized_request_rejected(trained):
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=4, block_size=8,
                      max_seq=32)
    with pytest.raises(ValueError, match="capacity"):
        eng.submit(_cycle_prompt(20), max_new=20)


def test_gqa_engine():
    """The paged path honors grouped K/V (narrow pools)."""
    cfg = LabformerConfig(
        d_model=32, n_heads=4, n_kv_heads=2, n_layers=2, d_ff=64, max_seq=128
    )
    from tpulab.models.labformer import init_train_state

    params, opt, step = init_train_state(cfg, None, seed=0)
    tok = np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))
    for _ in range(60):
        params, opt, _ = step(params, opt, tok)
    params = jax.device_get(params)
    eng = PagedEngine(params, cfg, slots=2, n_blocks=16, block_size=8,
                      max_seq=64)
    assert eng.kpool.shape[3] == 2  # kv heads, not n_heads
    rid = eng.submit(_cycle_prompt(5), max_new=6)
    out = eng.run()
    want = generate(params, _cycle_prompt(5)[None, :], cfg, steps=6,
                    temperature=0.0)[0]
    assert np.array_equal(out[rid], want)


def test_single_token_prompt(trained):
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                      max_seq=32)
    rid = eng.submit(_cycle_prompt(1), max_new=4)
    out = eng.run()
    want = generate(trained, _cycle_prompt(1)[None, :], CFG, steps=4,
                    temperature=0.0)[0]
    assert np.array_equal(out[rid], want)


def test_engine_reusable_across_runs(trained):
    """A second run() returns only the second wave's results."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                      max_seq=32)
    a = eng.submit(_cycle_prompt(3), max_new=3)
    first = eng.run()
    b = eng.submit(_cycle_prompt(4), max_new=3)
    second = eng.run()
    assert set(first) == {a} and set(second) == {b}


class TestPrefixSharing:
    def _sys_prompt(self, tail):
        # 17-token "system prompt" (2 full blocks at BS=8) + unique tail
        return np.concatenate(
            [(np.arange(17) % 7).astype(np.int32),
             np.asarray(tail, np.int32)]
        )

    def test_concurrent_requests_share_prefix_blocks(self, trained):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64)
        a = eng.submit(self._sys_prompt([1, 2]), max_new=5)
        b = eng.submit(self._sys_prompt([3, 4]), max_new=5)
        eng._admit()
        # both slots' first two blocks (the full shared region) are the
        # SAME physical blocks, refcounted
        assert np.array_equal(eng.tables[0][:2], eng.tables[1][:2])
        shared = [int(x) for x in eng.tables[0][:2]]
        assert all(eng.block_refs[x] >= 2 for x in shared)
        out = eng.run()
        for rid, tail in ((a, [1, 2]), (b, [3, 4])):
            want = generate(trained, self._sys_prompt(tail)[None, :], CFG,
                            steps=5, temperature=0.0)[0]
            assert np.array_equal(out[rid], want), rid

    def test_prefix_survives_across_waves(self, trained):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64)
        eng.submit(self._sys_prompt([1]), max_new=3)
        eng.run()
        cached = list(eng.prefix_cache.values())[0]
        rid = eng.submit(self._sys_prompt([5]), max_new=4)
        eng._admit()
        assert [int(x) for x in eng.tables[0][:2]] == cached
        out = eng.run()
        want = generate(trained, self._sys_prompt([5])[None, :], CFG,
                        steps=4, temperature=0.0)[0]
        assert np.array_equal(out[rid], want)

    def test_eviction_under_pressure_stays_correct(self, trained):
        # pool of 3 usable blocks == exactly one request's need, so
        # EVERY admission after the first must evict the previous
        # request's cached prefix (the pin-against-own-eviction guard is
        # unit-tested directly in test_pin_protects_matched_entry —
        # stop-early eviction makes it unreachable from this sequence)
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=4, block_size=8,
                          max_seq=64)
        evictions = 0
        orig = eng._evict_prefixes

        def counting(want_free):
            nonlocal evictions
            evictions += 1
            return orig(want_free)

        eng._evict_prefixes = counting
        reqs = {}
        for seed in (0, 1, 2, 0):
            prompt = ((np.arange(12) * (seed + 1)) % 7).astype(np.int32)
            rid = eng.submit(prompt, max_new=4)
            reqs[rid] = prompt
        out = eng.run()
        assert evictions > 0, "pool pressure never triggered eviction"
        for rid, prompt in reqs.items():
            want = generate(trained, prompt[None, :], CFG, steps=4,
                            temperature=0.0)[0]
            assert np.array_equal(out[rid], want), rid

    def test_refcounts_balance(self, trained):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64)
        for tail in ([1], [2], [3]):
            eng.submit(self._sys_prompt(tail), max_new=3)
        eng.run()
        # only the cache's own refs remain; evicting everything frees all
        eng._evict_prefixes(want_free=eng.n_usable_blocks)
        assert sorted(eng.free) == list(range(1, 32))
        assert int(eng.block_refs.sum()) == 0

    def test_pin_protects_matched_entry(self, trained):
        """The invariant _admit's pin provides: blocks of a matched
        prefix entry must NOT reach the free list while pinned, even if
        the entry itself is evicted (otherwise they could be handed out
        as fresh blocks while still referenced by the admitting
        request's `shared` list)."""
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64)
        eng.submit(self._sys_prompt([1]), max_new=3)
        eng.run()
        shared, pos = eng._lookup_prefix(self._sys_prompt([9]))
        assert pos == 16 and len(shared) == 2
        for b in shared:            # _admit's pin phase
            eng.block_refs[b] += 1
        eng._evict_prefixes(want_free=eng.n_usable_blocks)  # drop everything
        assert not eng.prefix_cache
        assert all(b not in eng.free for b in shared)       # pin held
        for b in shared:            # _admit's unpin (break path)
            eng._deref(b)
        assert all(b in eng.free for b in shared)           # now released
        assert int(eng.block_refs.sum()) == 0

    def test_cache_hit_skips_dense_prefill(self, trained, monkeypatch):
        """The compute-reuse claim: on a prefix-cache hit the dense
        prefill must not run at all — only paged_extend over the tail."""
        import tpulab.models.paged as paged_mod

        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64)
        first = eng.submit(self._sys_prompt([1]), max_new=4)
        out1 = eng.run()
        calls = {"n": 0}
        real = paged_mod._prefill

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(paged_mod, "_prefill", counting)
        rid = eng.submit(self._sys_prompt([5, 3]), max_new=5)
        out = eng.run()
        assert calls["n"] == 0, "dense prefill ran despite a cache hit"
        want = generate(trained, self._sys_prompt([5, 3])[None, :], CFG,
                        steps=5, temperature=0.0)[0]
        assert np.array_equal(out[rid], want)


class TestChunkedPrefillAndStats:
    def test_chunked_prefill_matches_whole_tail(self, trained):
        """prefill_chunk splits admission into fixed windows through
        paged_extend; tokens must stay bit-equal to solo greedy."""
        prompt = (np.arange(30) % 7).astype(np.int32)
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64, prefill_chunk=8)
        rid = eng.submit(prompt, max_new=6)
        out = eng.run()
        want = generate(trained, prompt[None, :], CFG, steps=6,
                        temperature=0.0)[0]
        assert np.array_equal(out[rid], want)

    def test_stats_counters(self, trained):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64)
        sys_prompt = (np.arange(17) % 7).astype(np.int32)
        eng.submit(sys_prompt, max_new=3)
        eng.run()
        eng.submit(np.concatenate([sys_prompt, [2]]).astype(np.int32),
                   max_new=4)
        eng.run()
        st = eng.stats()
        assert st["prefix_misses"] == 1 and st["prefix_hits"] == 1
        assert st["requests_done"] == 2 and st["tokens_out"] == 7
        assert st["ticks"] >= 4
        # after run() drains, only cache-pinned blocks remain in use
        cached = sum(len(b) for b in eng.prefix_cache.values())
        assert st["blocks_free"] == st["blocks_total"] - cached
        assert st["cache_entries"] >= 1


def test_tp_mesh_engine_matches_single_device(trained):
    """Tensor-parallel serving: the engine over a {'tp': 2} mesh (params
    tp-sharded, pools sharded on the kv-head axis, GSPMD partitioning
    the same decode program) must emit the same tokens as the
    single-device engine."""
    from tpulab.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 2})
    reqs = [(3, 6), (5, 8), (9, 4)]
    outs = []
    for m in (None, mesh):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=24, block_size=8,
                          max_seq=64, mesh=m)
        rids = [eng.submit(_cycle_prompt(p), max_new=n) for p, n in reqs]
        got = eng.run()
        outs.append([got[r] for r in rids])
    for a, b, (p, n) in zip(outs[0], outs[1], reqs):
        assert np.array_equal(a, b), (p, n)


def test_tp_mesh_gqa_engine():
    """tp=2 over kv_heads=2: one kv head per shard."""
    from tpulab.parallel.mesh import make_mesh

    cfg = LabformerConfig(
        d_model=32, n_heads=4, n_kv_heads=2, n_layers=2, d_ff=64, max_seq=128
    )
    from tpulab.models.labformer import init_train_state

    params, opt, step = init_train_state(cfg, None, seed=0)
    tok = np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))
    for _ in range(60):
        params, opt, _ = step(params, opt, tok)
    params = jax.device_get(params)
    single = PagedEngine(params, cfg, slots=1, n_blocks=16, block_size=8,
                         max_seq=64)
    a = single.submit(_cycle_prompt(5), max_new=6)
    want = single.run()[a]
    sharded = PagedEngine(params, cfg, slots=1, n_blocks=16, block_size=8,
                          max_seq=64, mesh=make_mesh({"tp": 2}))
    b = sharded.submit(_cycle_prompt(5), max_new=6)
    assert np.array_equal(sharded.run()[b], want)


def test_tp_mesh_rejects_indivisible_heads(trained):
    from tpulab.parallel.mesh import make_mesh

    cfg = LabformerConfig(
        d_model=32, n_heads=4, n_kv_heads=1, n_layers=2, d_ff=64, max_seq=128
    )
    with pytest.raises(ValueError, match="tp=2 must divide kv_heads=1"):
        PagedEngine(trained, cfg, mesh=make_mesh({"tp": 2}))


class TestPerSlotSampling:
    def test_same_seed_reproduces_and_seeds_differ(self, trained):
        def run(seed):
            eng = PagedEngine(trained, CFG, slots=1, n_blocks=16,
                              block_size=8, max_seq=64)
            rid = eng.submit(_cycle_prompt(4), max_new=12,
                             temperature=1.5, seed=seed)
            return eng.run()[rid]

        a, b, c = run(7), run(7), run(8)
        assert np.array_equal(a, b)          # one deterministic stream
        assert not np.array_equal(a, c)      # seeds diverge (w.h.p.)

    def test_greedy_slot_unperturbed_by_sampled_neighbor(self, trained):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=24, block_size=8,
                          max_seq=64)
        g = eng.submit(_cycle_prompt(5), max_new=8)  # greedy
        s = eng.submit(_cycle_prompt(3), max_new=8, temperature=2.0, seed=1)
        out = eng.run()
        want = generate(trained, _cycle_prompt(5)[None, :], CFG, steps=8,
                        temperature=0.0)[0]
        assert np.array_equal(out[g], want)
        assert len(out[s]) == 8

    def test_negative_temperature_rejected(self, trained):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=8, block_size=8,
                          max_seq=32)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit(_cycle_prompt(3), max_new=2, temperature=-1.0)


def test_sample_tokens_applies_per_slot_penalty():
    """The discriminating guard on the engine's penalty plumbing: with
    crafted logits, a penalized slot's greedy argmax flips to the best
    UNSEEN token while an unpenalized slot with identical logits keeps
    the raw argmax (slot isolation).  (The trained cycle model is
    structurally penalty-invariant — once every candidate is seen, all
    get divided and the order survives — so stream-level assertions
    cannot distinguish applied from ignored.)"""
    import jax.numpy as jnp
    from tpulab.models.paged import _sample_tokens

    logits = jnp.asarray([[4.0, 3.0, -1.0, -2.0],
                          [4.0, 3.0, -1.0, -2.0]])
    seen = jnp.asarray([[True, False, False, False],
                        [True, False, False, False]])
    penalties = jnp.asarray([2.0, 1.0], jnp.float32)  # slot1 off
    temps = jnp.zeros(2, jnp.float32)                 # greedy
    keys = jnp.zeros((2, 2), jnp.uint32)
    toks, _ = _sample_tokens(logits, temps, keys, penalties, seen)
    toks = np.asarray(toks)
    assert toks[0] == 1, toks  # 4/2=2 < 3: best unseen wins
    assert toks[1] == 0, toks  # untouched raw argmax


def test_penalized_requests_match_generate(trained):
    """Per-request repetition penalty in the engine must equal the base
    generate path token-for-token, including a penalized request batched
    next to an unpenalized one."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=24, block_size=8,
                      max_seq=64)
    r_pen = eng.submit(_cycle_prompt(4), max_new=8, repetition_penalty=4.0)
    r_plain = eng.submit(_cycle_prompt(4), max_new=8)
    out = eng.run()
    want_pen = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=8,
                        temperature=0.0, repetition_penalty=4.0)[0]
    want_plain = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=8,
                          temperature=0.0)[0]
    assert np.array_equal(out[r_pen], want_pen), out[r_pen]
    assert np.array_equal(out[r_plain], want_plain), out[r_plain]


def test_stop_byte_finishes_early_and_frees_slot(trained):
    """A stop-byte request ends right after emitting the byte (it is the
    final token), releases its blocks, and the slot serves the next
    request normally."""
    # discover a byte the greedy stream emits mid-way
    ref = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=8,
                   temperature=0.0)[0].tolist()
    stop = ref[3]
    first = ref.index(stop)
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    free0 = len(eng.free)
    rid = eng.submit(_cycle_prompt(4), max_new=8, stop_byte=stop)
    rid2 = eng.submit(_cycle_prompt(4), max_new=4)  # queued behind
    out = eng.run()
    got = out[rid].tolist()
    assert got == ref[:first + 1], (got, ref, stop)
    assert len(eng.free) == free0, "blocks not fully recycled"
    assert np.array_equal(
        out[rid2],
        generate(trained, _cycle_prompt(4)[None, :], CFG, steps=4,
                 temperature=0.0)[0])


def test_service_on_progress_early_cancel_frees_slot(trained):
    """A streaming consumer that reports 'done' (on_progress returns
    truthy — e.g. the BPE-decoded stop byte already went out) cancels
    the request: the call returns the tokens so far instead of decoding
    the full budget, and the slot + blocks recycle (round-4 advisor)."""
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    free0 = len(eng.free)
    ticks = []

    def on_progress(inc):
        ticks.append(list(inc))
        return len(ticks) >= 2  # consumer satisfied after 2 ticks

    out = svc.generate(eng, _cycle_prompt(4), 48, on_progress=on_progress)
    assert 2 <= len(out) < 48, len(out)  # cancelled well short of budget
    # the request finished through the NORMAL path, so by the time
    # generate() returned the stepper had already freed slot + blocks
    assert all(r is None for r in eng.active)
    assert len(eng.free) == free0, "blocks not fully recycled"


def test_engine_rejects_bad_penalty_and_stop(trained):
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    with pytest.raises(ValueError, match="repetition_penalty"):
        eng.submit(_cycle_prompt(3), max_new=2, repetition_penalty=0.0)
    with pytest.raises(ValueError, match="stop_byte"):
        eng.submit(_cycle_prompt(3), max_new=2, stop_byte=256)
