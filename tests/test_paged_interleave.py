"""Stall-free admission: interleaved chunked prefill
(tpulab.models.paged, ``PagedEngine(interleave=...)``).

Headline properties:
  * greedy output is BIT-IDENTICAL with interleaved admission on vs
    off, for chunked (``prefill_chunk=16``) and whole-tail/dense
    (``prefill_chunk=0``) admission, across prefix-hit, sampled,
    penalized, stop-byte, and speculative-lookup requests — only the
    tick a request's first token appears on moves;
  * ZERO stalls: while one slot's multi-chunk prefill is in flight,
    every decoding slot emits a token on every engine tick
    (``stall_ticks == 0``; the synchronous path charges its inline
    chunk loop), and admission never drains the async overlap window
    (``host_syncs == 0``);
  * ``ticks == tokens`` still holds for decoding slots — prefilling
    slots consume no decode dispatch;
  * the steady-state transfer-guard zero-upload window still passes
    after an interleaved admission (h2d settles back to flat);
  * cancel-mid-prefill releases the admitted blocks exactly, without
    emitting, and without perturbing the other slots' streams;
  * the dense-prefill compile-bucket census warns once past 4 buckets.
"""

import numpy as np
import pytest

import tpulab.models.paged as paged_mod
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


REP = np.tile(np.arange(7, dtype=np.int32), 4)  # lookup-friendly period-7
SYS = (np.arange(16) % 7).astype(np.int32)      # 2 full blocks at BS=8


@pytest.mark.parametrize("chunk", [16, 0])
def test_bit_equality_interleave_on_off(trained, chunk):
    """The satellite matrix: interleave on/off x chunk {16, 0} over
    prefix-hit, sampled, penalized, stop-byte, and spec-lookup
    requests — every request's stream bit-equal across modes, and the
    deterministic ones equal the dense ``generate`` goldens."""
    ref = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=10,
                   temperature=0.0)[0].tolist()
    stop = ref[3]
    jobs = [
        dict(prompt=np.concatenate([SYS, [1, 2]]).astype(np.int32),
             max_new=10),                                # prefix miss, long
        dict(prompt=np.concatenate([SYS, [3]]).astype(np.int32),
             max_new=8),                                 # prefix HIT
        dict(prompt=_cycle_prompt(40), max_new=8),       # multi-chunk
        dict(prompt=_cycle_prompt(5), max_new=10,
             temperature=1.5, seed=3),                   # sampled slot
        dict(prompt=_cycle_prompt(4), max_new=10,
             stop_byte=int(stop)),                       # stop byte
        dict(prompt=_cycle_prompt(6), max_new=8,
             repetition_penalty=4.0),                    # penalized
        dict(prompt=REP, max_new=12, spec="lookup"),     # speculative
    ]

    def run(interleave):
        eng = PagedEngine(trained, CFG, slots=3, n_blocks=48, block_size=8,
                          max_seq=64, prefill_chunk=chunk, spec_k=4,
                          interleave=interleave)
        rids = [eng.submit(j["prompt"], max_new=j["max_new"],
                           temperature=j.get("temperature", 0.0),
                           seed=j.get("seed", 0),
                           repetition_penalty=j.get(
                               "repetition_penalty", 1.0),
                           stop_byte=j.get("stop_byte", -1),
                           spec=j.get("spec", "off"))
                for j in jobs]
        out = eng.run()
        return [out[r] for r in rids], eng.stats()

    on, st_on = run(True)
    off, st_off = run(False)
    for i, (a, b) in enumerate(zip(on, off)):
        assert np.array_equal(a, b), (i, a, b)
    # deterministic goldens (the dense path never saw a paged pool)
    assert np.array_equal(on[2], generate(
        trained, _cycle_prompt(40)[None, :], CFG, steps=8,
        temperature=0.0)[0])
    assert np.array_equal(on[6], generate(
        trained, REP[None, :], CFG, steps=12, temperature=0.0)[0])
    assert st_on["stall_ticks"] == 0, st_on
    assert st_on["prefix_hits"] >= 1 and st_on["spec_rounds"] > 0
    assert st_on["admissions"] == st_off["admissions"] == len(jobs)


def test_zero_stall_twelve_chunk_admission(trained):
    """ISSUE acceptance: while a 12-chunk prompt admits against 3
    decoding slots, every decoding slot emits a token on EVERY engine
    tick (stall_ticks == 0, one chunk rides each tick) and admission
    never drains the overlap window.  The synchronous path, by
    contrast, charges its 12 serialized inline chunks."""
    prompt96 = _cycle_prompt(97)  # 96 prefill positions = 12 chunks of 8

    def run(interleave):
        eng = PagedEngine(trained, CFG, slots=4, n_blocks=48, block_size=8,
                          max_seq=128, prefill_chunk=8,
                          interleave=interleave)
        decs = [eng.submit(_cycle_prompt(4 + i), max_new=40)
                for i in range(3)]
        for _ in range(6):
            eng.step()  # decoders mid-wave; window open
        eng.submit(prompt96, max_new=4)
        pre = {r: None for r in decs}
        st0 = eng.stats()
        # drive until the long prompt's prefill completes
        steps = 0
        while eng.stats()["prefill_inflight"] == 0:
            eng.step()  # admission happens on the next step
            steps += 1
            assert steps < 4, "long prompt never entered prefill"
        reqs = {r.req_id: r for r in eng.active if r is not None}
        base = {rid: len(reqs[rid].out) for rid in decs}
        t_base = eng.stats()["ticks"]
        while eng.stats()["prefill_inflight"]:
            eng.step()
        st = eng.stats()
        ticks_elapsed = st["ticks"] - t_base
        assert ticks_elapsed >= 11  # 12 chunks, one per tick
        for rid in decs:
            # every tick emitted for every decoding slot (the one-tick
            # overlap window may hold the newest token in flight)
            got = len(reqs[rid].out)
            assert got - base[rid] >= ticks_elapsed - 1, (
                rid, got, base[rid], ticks_elapsed)
        assert st["stall_ticks"] == 0, st
        assert st["host_syncs"] == st0["host_syncs"], st  # no admission drain
        out = eng.run()
        return out, eng.stats()

    out_on, st_on = run(True)
    assert st_on["stall_ticks"] == 0, st_on
    # the synchronous engine charges the inline chunk loop
    eng = PagedEngine(trained, CFG, slots=4, n_blocks=48, block_size=8,
                      max_seq=128, prefill_chunk=8, interleave=False)
    for i in range(3):
        eng.submit(_cycle_prompt(4 + i), max_new=40)
    for _ in range(6):
        eng.step()
    eng.submit(prompt96, max_new=4)
    eng.run()
    assert eng.stats()["stall_ticks"] >= 11, eng.stats()
    # and the long request's stream is identical in both modes
    want = generate(trained, prompt96[None, :], CFG, steps=4,
                    temperature=0.0)[0]
    long_on = [v for v in out_on.values() if len(v) == 4]
    assert any(np.array_equal(v, want) for v in long_on)


def test_ticks_equal_tokens_excluding_prefill(trained):
    """Counter economy under interleave: a solo request spends exactly
    max_new decode ticks regardless of how many prefill chunks its
    admission needed — prefill chunks are counted separately and never
    consume a decode dispatch."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=128, prefill_chunk=8)
    rid = eng.submit(_cycle_prompt(40), max_new=10)  # 5 prefill chunks
    out = eng.run()
    st = eng.stats()
    assert len(out[rid]) == 10
    assert st["ticks"] == 10, st          # decode dispatches == tokens
    assert st["tokens_out"] == 10
    assert st["prefill_chunks"] == 5, st  # 39 positions in windows of 8
    assert st["stall_ticks"] == 0, st     # no decoder was waiting


def test_transfer_guard_window_after_interleaved_admission(trained):
    """The PR-2 zero-upload contract survives: admission ticks upload
    (chunks + activation scatter), but once the admitted slot is
    decoding the steady-state window is flat again — enforced with
    jax.transfer_guard, the jnp.asarray tripwire, and h2d_ticks."""
    import jax

    from tests.test_paged_overlap import _NoUpload

    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=128, prefill_chunk=8)
    a = eng.submit(_cycle_prompt(4), max_new=30)
    for _ in range(4):
        eng.step()
    b = eng.submit(_cycle_prompt(40), max_new=20)  # interleaved admission
    while (eng.pending or eng.stats()["prefill_inflight"]
           or any(r is not None and not r.out for r in eng.active)):
        eng.step()  # admission window: h2d ticks expected here
    before = eng.stats()
    jnp_real = paged_mod.jnp
    paged_mod.jnp = _NoUpload()
    try:
        with jax.transfer_guard("disallow"):
            for _ in range(6):
                eng.step()
    finally:
        paged_mod.jnp = jnp_real
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 6
    assert st["h2d_ticks"] == before["h2d_ticks"], "steady tick uploaded"
    assert st["host_syncs"] == before["host_syncs"], "steady tick synced"
    out = eng.run()
    assert np.array_equal(out[a], generate(
        trained, _cycle_prompt(4)[None, :], CFG, steps=30,
        temperature=0.0)[0])
    assert np.array_equal(out[b], generate(
        trained, _cycle_prompt(40)[None, :], CFG, steps=20,
        temperature=0.0)[0])


def test_cancel_mid_prefill_releases_blocks_exactly(trained):
    """A request cancelled while its interleaved prefill is still in
    flight releases every block admission claimed, emits nothing, and
    leaves the neighbouring stream bit-identical."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=128, prefill_chunk=8)
    a = eng.submit(_cycle_prompt(5), max_new=20)
    for _ in range(3):
        eng.step()
    free_mid = len(eng.free)
    victim = eng.submit(_cycle_prompt(80), max_new=8)  # 10 chunks
    for _ in range(3):
        eng.step()  # admit + a few chunks
    assert eng.stats()["prefill_inflight"] == 1
    assert len(eng.free) < free_mid          # its blocks are claimed
    assert eng.cancel(victim) == "active"
    out = eng.run()
    assert len(out[victim]) == 0             # no token was ever produced
    assert np.array_equal(out[a], generate(
        trained, _cycle_prompt(5)[None, :], CFG, steps=20,
        temperature=0.0)[0])
    # every non-cache block returned (request a finished too)
    cached = sum(len(b) for b in eng.prefix_cache.values())
    assert len(eng.free) == eng.n_usable_blocks - cached
    assert int(eng.block_refs.sum()) == cached


def test_prefix_registers_only_after_prefill_completes(trained):
    """A same-prefix request submitted while the first is still
    prefilling must MISS (sharing half-written blocks would attend
    garbage) — and still decode correctly; once the first completes,
    later requests hit."""
    long_sys = _cycle_prompt(64)

    def tail_prompt(t):
        return np.concatenate([long_sys, [t]]).astype(np.int32)

    eng = PagedEngine(trained, CFG, slots=2, n_blocks=48, block_size=8,
                      max_seq=128, prefill_chunk=8)
    r1 = eng.submit(tail_prompt(1), max_new=4)
    eng.step()  # admit r1; prefill begins
    assert eng.stats()["prefill_inflight"] == 1
    r2 = eng.submit(tail_prompt(2), max_new=4)
    for _ in range(2):
        eng.step()  # r2 admits while r1 still owes chunks
    out = eng.run()
    st = eng.stats()
    assert st["prefix_misses"] == 2, st  # no half-written share
    for rid, t in ((r1, 1), (r2, 2)):
        assert np.array_equal(out[rid], generate(
            trained, tail_prompt(t)[None, :], CFG, steps=4,
            temperature=0.0)[0]), rid
    r3 = eng.submit(tail_prompt(3), max_new=4)
    out3 = eng.run()
    assert eng.stats()["prefix_hits"] == 1  # registered at completion
    assert np.array_equal(out3[r3], generate(
        trained, tail_prompt(3)[None, :], CFG, steps=4,
        temperature=0.0)[0])


def test_spec_draft_prefill_chunk_scheduled(trained):
    """Dense-draft speculative slots chunk-schedule the DRAFT prefill
    too: one draft-cache window per tick next to the target chunk, and
    the stream stays lossless (bit-equal to plain greedy) while a
    neighbour decodes."""
    from tpulab.models.quant import quantize_decode_params

    draft = quantize_decode_params(trained, CFG)

    def run(interleave):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=128, prefill_chunk=8, spec_k=4,
                          interleave=interleave)
        eng.set_draft(draft, CFG)
        a = eng.submit(_cycle_prompt(5), max_new=16)
        for _ in range(3):
            eng.step()
        b = eng.submit(REP, max_new=12, spec="draft")
        out = eng.run()
        return out[a], out[b], eng.stats()

    a_on, b_on, st_on = run(True)
    a_off, b_off, _ = run(False)
    assert np.array_equal(a_on, a_off)
    assert np.array_equal(b_on, b_off)
    assert np.array_equal(b_on, generate(
        trained, REP[None, :], CFG, steps=12, temperature=0.0)[0])
    assert st_on["stall_ticks"] == 0, st_on
    # the draft windows were chunk-scheduled (target 3 chunks + draft
    # 4 windows for the 27-position REP prompt, plus slot a's chunks)
    assert st_on["spec_rounds"] > 0


def test_dense_bucket_census_warns_past_four(trained):
    """Satellite: chunk-0 engines warn ONCE when the dense prefill has
    compiled more than 4 prompt-length buckets (the bound chunked
    prefill exists to enforce)."""
    rng = np.random.default_rng(5)

    def fresh_prompt(p):  # no shared block-aligned prefixes: every
        return rng.integers(0, 7, (p,)).astype(np.int32)  # admission is
        # a genuine dense prefill, not a prefix-cache hit

    eng = PagedEngine(trained, CFG, slots=1, n_blocks=40, block_size=8,
                      max_seq=256, prefill_chunk=0)
    lengths = (3, 18, 34, 66)  # buckets 16, 32, 64, 128
    for p in lengths:
        eng.submit(fresh_prompt(p), max_new=1)
    eng.run()
    assert not eng._dense_warned
    with pytest.warns(RuntimeWarning, match="prompt-length buckets"):
        eng.submit(fresh_prompt(10), max_new=1)   # bucket 16 is cached
        eng.submit(fresh_prompt(130), max_new=1)  # bucket 256: the 5th
        eng.run()
    assert eng._dense_warned
    # chunked engines never grow the census
    eng2 = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                       max_seq=128, prefill_chunk=16)
    for p in lengths:
        eng2.submit(fresh_prompt(p), max_new=1)
    eng2.run()
    assert not eng2._dense_buckets


def test_daemon_defaults_to_chunked_interleaved_engine():
    """The daemon's serving default IS the stall-free path: engines
    build with the module-wide PREFILL_CHUNK window and interleave on
    (chunk 0 stays reachable per-request via config)."""
    from tpulab import daemon

    assert daemon.PREFILL_CHUNK > 0
    # the argparse surface accepts the satellite's knob
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--prefill-chunk", type=int,
                    default=daemon.PREFILL_CHUNK)
    assert ap.parse_args([]).prefill_chunk == daemon.PREFILL_CHUNK


def test_service_streams_through_interleaved_admission(trained):
    """The daemon's generate service over an interleaved engine: a
    long-prompt request admitted mid-wave streams every token exactly
    once and matches the golden — the prefill phase just delays the
    first increment."""
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=128, prefill_chunk=8)
    import threading

    bg_out = {}
    bg = threading.Thread(
        target=lambda: bg_out.setdefault(
            "a", svc.generate(eng, _cycle_prompt(4), 24)))
    bg.start()
    chunks = []
    out = svc.generate(eng, _cycle_prompt(40), 8,
                       on_progress=lambda inc: chunks.append(list(inc)))
    bg.join()
    want = generate(trained, _cycle_prompt(40)[None, :], CFG, steps=8,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    assert [t for c in chunks for t in c] == list(want)
    assert np.array_equal(bg_out["a"], generate(
        trained, _cycle_prompt(4)[None, :], CFG, steps=24,
        temperature=0.0)[0])
    assert eng.inflight_depth == 0
