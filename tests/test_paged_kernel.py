"""Pallas paged-attention decode kernel (ops/pallas/paged).

Claims under test (interpret mode on CPU; compiled Mosaic runs in
tools/pallas_tpu_parity.py):
  * numerical parity with the XLA gather path across MHA / GQA /
    sliding-window / ragged lengths — same masking, same f32 softmax;
  * the padded group rows (sublane floor) never leak into outputs;
  * the engine produces BIT-IDENTICAL greedy tokens with attn="pallas"
    vs attn="gather" under continuous batching;
  * invalid configurations refuse loudly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.labformer import LabformerConfig, init_params
from tpulab.models.paged import PagedEngine, _paged_attend
from tpulab.ops.pallas.paged import paged_attend_pallas


def _case(S=3, M=4, BS=16, d=64, P=32, h=8, kvh=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((S, 1, h, d)), dtype)
    kp = jnp.asarray(rng.standard_normal((P, BS, kvh, d)), dtype)
    vp = jnp.asarray(rng.standard_normal((P, BS, kvh, d)), dtype)
    tables = jnp.asarray(
        rng.choice(P, (S, M), replace=False).reshape(S, M), jnp.int32)
    return q, kp, vp, tables


@pytest.mark.parametrize("h,kvh,window", [(8, 8, 0), (8, 2, 0), (8, 2, 5),
                                          (4, 4, 0), (16, 4, 7),
                                          # g=12: above the sublane floor
                                          # but not a multiple of 8 — the
                                          # pad must round UP to G=16, not
                                          # floor at max(g, 8)=12
                                          (24, 2, 0), (24, 2, 9)])
def test_kernel_matches_gather(h, kvh, window):
    q, kp, vp, tables = _case(h=h, kvh=kvh)
    lengths = jnp.asarray([1, 30, 64], jnp.int32)
    want = np.asarray(_paged_attend(q, kp, vp, tables, lengths, 16, window))
    got = np.asarray(paged_attend_pallas(q, kp, vp, tables, lengths, 16,
                                         window))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_kernel_matches_gather_bf16():
    q, kp, vp, tables = _case(dtype=jnp.bfloat16)
    lengths = jnp.asarray([7, 33, 50], jnp.int32)
    want = np.asarray(_paged_attend(q, kp, vp, tables, lengths, 16),
                      np.float32)
    got = np.asarray(paged_attend_pallas(q, kp, vp, tables, lengths, 16),
                     np.float32)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


def test_block_boundary_lengths():
    """Lengths at exact block edges: no off-by-one at the mask seam."""
    q, kp, vp, tables = _case()
    for lens in ([16, 32, 48], [15, 17, 64], [1, 1, 1]):
        lengths = jnp.asarray(lens, jnp.int32)
        want = np.asarray(_paged_attend(q, kp, vp, tables, lengths, 16))
        got = np.asarray(paged_attend_pallas(q, kp, vp, tables, lengths, 16))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5, err_msg=str(lens))


def test_pool_block_size_mismatch_refused():
    q, kp, vp, tables = _case()
    with pytest.raises(ValueError, match="block size"):
        paged_attend_pallas(q, kp, vp, tables, jnp.asarray([1, 2, 3]), 8)


def _trained_params(cfg, steps=40):
    from tpulab.models.labformer import init_train_state

    params, opt, step = init_train_state(cfg, mesh=None, seed=0)
    cyc = np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))
    for _ in range(steps):
        params, opt, _ = step(params, opt, cyc)
    return jax.device_get(params)


def test_engine_tokens_bit_equal_across_attn_impls():
    """Continuous batching with attn='pallas' emits the gather engine's
    exact greedy tokens (sharpened model so argmax ties can't flip)."""
    cfg = LabformerConfig(d_model=64, n_heads=8, n_kv_heads=4, n_layers=2,
                          d_ff=128, max_seq=64)
    params = _trained_params(cfg)
    prompts = [(np.arange(5) % 7).astype(np.int32),
               (np.arange(9) % 7).astype(np.int32),
               (np.ones(3) * 2).astype(np.int32)]
    outs = {}
    for attn in ("gather", "pallas"):
        eng = PagedEngine(params, cfg, slots=2, n_blocks=16, block_size=8,
                          max_seq=64, attn=attn)
        rids = [eng.submit(p, max_new=6) for p in prompts]
        got = eng.run()
        outs[attn] = [np.asarray(got[r]) for r in rids]
    for a, b in zip(outs["gather"], outs["pallas"]):
        assert np.array_equal(a, b), (a, b)


def test_int8_kv_cache_tokens_and_memory():
    """kv_dtype='int8': greedy tokens survive the quantization on a
    sharpened model (incl. prefix-shared blocks) and the pool data
    really is int8 at half the bf16 bytes."""
    cfg = LabformerConfig(d_model=64, n_heads=8, n_kv_heads=4, n_layers=2,
                          d_ff=128, max_seq=64, dtype=jnp.bfloat16)
    params = _trained_params(cfg)
    shared = (np.arange(16) % 7).astype(np.int32)  # 2 full blocks shared
    prompts = [np.concatenate([shared, (np.arange(4) % 5).astype(np.int32)]),
               np.concatenate([shared, (np.ones(3) * 3).astype(np.int32)])]
    outs = {}
    for kv in ("native", "int8"):
        eng = PagedEngine(params, cfg, slots=2, n_blocks=16, block_size=8,
                          max_seq=64, kv_dtype=kv)
        rids = [eng.submit(p, max_new=6) for p in prompts]
        got = eng.run()
        outs[kv] = [np.asarray(got[r]) for r in rids]
        if kv == "int8":
            data, scale = eng.kpool
            assert data.dtype == jnp.int8 and scale.dtype == jnp.float32
            assert data.nbytes == scale.size * cfg.head_dim  # 1 byte/elt
        else:
            assert eng.kpool.dtype == jnp.bfloat16
    for a, b in zip(outs["native"], outs["int8"]):
        assert np.array_equal(a, b), (a, b)


def test_int8_kv_logits_close():
    """Quantization error bound on raw decode logits, random model."""
    from tpulab.models.paged import init_pools, paged_decode_step

    cfg = LabformerConfig(d_model=64, n_heads=4, n_layers=2, d_ff=128,
                          max_seq=64)
    params = init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    tables = jnp.asarray(rng.choice(np.arange(1, 9), (2, 4), replace=False)
                         .reshape(2, 4), jnp.int32)
    lengths = jnp.asarray([5, 11], jnp.int32)
    toks = jnp.asarray([3, 4], jnp.int32)
    outs = {}
    for kv in ("native", "int8"):
        kp, vp = init_pools(cfg, 16, 8, kv)
        # warm the pools with a few decode steps so the attended keys
        # are real (quantized-on-write) values, not zeros
        l = lengths - 3
        for i in range(3):
            logits, kp, vp = paged_decode_step(
                params, toks + i, kp, vp, tables, l + i, cfg, 8)
        outs[kv] = np.asarray(logits, np.float32)
    err = np.max(np.abs(outs["native"] - outs["int8"]))
    spread = np.ptp(outs["native"])
    assert err < 0.05 * spread, (err, spread)


def test_int8_kv_refusals():
    from tpulab.models.paged import init_pools

    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=64)
    with pytest.raises(ValueError, match="expected"):
        init_pools(cfg, 8, 8, "fp4")


def test_kernel_matches_gather_int8_pools():
    """The kernel's in-kernel dequantization must agree with the gather
    path's _pool_gather recipe on the SAME quantized pools — int8 KV no
    longer forces the gather path."""
    from tpulab.models.paged import _kv_quant, _paged_attend

    rng = np.random.default_rng(5)
    S, M, BS, d, P, h, kvh = 3, 4, 16, 64, 32, 8, 2
    q = jnp.asarray(rng.standard_normal((S, 1, h, d)), jnp.bfloat16)
    kf = rng.standard_normal((P, BS, kvh, d)).astype(np.float32)
    vf = rng.standard_normal((P, BS, kvh, d)).astype(np.float32)
    kp = tuple(jnp.asarray(a) for a in _kv_quant(jnp.asarray(kf)))
    vp = tuple(jnp.asarray(a) for a in _kv_quant(jnp.asarray(vf)))
    tables = jnp.asarray(
        rng.choice(P, (S, M), replace=False).reshape(S, M), jnp.int32)
    for window in (0, 11):
        lengths = jnp.asarray([1, 30, 64], jnp.int32)
        want = np.asarray(_paged_attend(q, kp, vp, tables, lengths, BS,
                                        window), np.float32)
        got = np.asarray(paged_attend_pallas(q, kp, vp, tables, lengths,
                                             BS, window), np.float32)
        np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2,
                                   err_msg=f"window={window}")


def test_engine_pallas_int8_matches_gather_int8():
    """Engine tokens through pallas+int8 == gather+int8 (the serving
    matrix's last cell): same quantize-on-write pools, two read paths."""
    cfg = LabformerConfig(d_model=64, n_heads=8, n_kv_heads=4, n_layers=2,
                          d_ff=128, max_seq=64, dtype=jnp.bfloat16)
    params = _trained_params(cfg)
    prompt = (np.arange(5) % 7).astype(np.int32)

    def tokens(attn):
        eng = PagedEngine(params, cfg, slots=2, n_blocks=16, block_size=8,
                          max_seq=64, attn=attn, kv_dtype="int8")
        rid = eng.submit(prompt, max_new=6)
        return eng.run()[rid]

    assert np.array_equal(tokens("pallas"), tokens("gather"))


def test_cancel_releases_exactly_what_admission_allocated():
    """Cancelling an active request must free the same blocks a
    run-to-completion request frees (admission allocated for
    prompt + max_new; a cancel that shrank max_new used to leak the
    difference — ~30 aborted streams exhausted the daemon's pool)."""
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=64)
    params = init_params(cfg, seed=0)
    prompt = (np.arange(3) % 7).astype(np.int32)

    def free_after(cancel_after_ticks):
        eng = PagedEngine(params, cfg, slots=1, n_blocks=16, block_size=8,
                          max_seq=64)
        rid = eng.submit(prompt, max_new=40)
        if cancel_after_ticks is None:
            eng.run()
        else:
            for _ in range(cancel_after_ticks):
                eng.step()
            assert eng.cancel(rid) == "active"
            fin = eng.step()
            assert rid in fin
            assert eng.cancel(rid) == "gone"
        return len(eng.free)

    assert free_after(1) == free_after(None)
    assert free_after(3) == free_after(None)


def test_cancel_pending_before_admission():
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=64)
    params = init_params(cfg, seed=0)
    eng = PagedEngine(params, cfg, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    r1 = eng.submit((np.arange(3) % 7).astype(np.int32), max_new=8)
    r2 = eng.submit((np.arange(4) % 7).astype(np.int32), max_new=8)
    # slot count is 1: r2 queues un-admitted; cancelling it drops it
    assert eng.cancel(r2) == "pending"
    done = eng.run()
    assert r1 in done and r2 not in done


def test_sliding_window_retires_blocks_mid_decode():
    """attn_window serving holds O(window) KV per slot: blocks wholly
    behind the window free DURING decode (not just at finish), tokens
    stay equal to the solo windowed decode, and finish accounting still
    balances."""
    from tpulab.models.generate import generate

    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=128, attn_window=6)
    params = _trained_params(cfg, steps=20)
    prompt = (np.arange(10) % 7).astype(np.int32)

    eng = PagedEngine(params, cfg, slots=1, n_blocks=32, block_size=8,
                      max_seq=128)
    free0 = len(eng.free)
    rid = eng.submit(prompt, max_new=40)
    eng.step()
    free_early = len(eng.free)
    mid_frees = []
    out = None
    while out is None:
        fin = eng.step()
        mid_frees.append(len(eng.free))
        if rid in fin:
            out = eng._done.pop(rid)
    # blocks were retired while decoding (free pool grew mid-flight)
    assert max(mid_frees[:-1] or [free_early]) > free_early, mid_frees
    assert eng.counters["blocks_retired"] > 0
    # accounting balances at finish (minus any prefix-cached blocks)
    cached = sum(len(b) for b in eng.prefix_cache.values())
    assert len(eng.free) == free0 - cached
    # and the tokens are the solo windowed decode's, exactly
    want = generate(params, prompt[None, :], cfg, steps=40,
                    temperature=0.0)[0]
    assert np.array_equal(out, np.asarray(want))


def test_window_retirement_keeps_shared_prefix_cached():
    """Retiring a slot's reference must not free prefix-cache blocks:
    a later request with the same prompt still hits the cache."""
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=128, attn_window=6)
    params = _trained_params(cfg, steps=20)
    shared = (np.arange(16) % 7).astype(np.int32)  # 2 full blocks

    eng = PagedEngine(params, cfg, slots=1, n_blocks=32, block_size=8,
                      max_seq=128)
    r1 = eng.submit(shared, max_new=24)  # decode far past the window
    out1 = eng.run()[r1]
    assert eng.counters["blocks_retired"] > 0
    r2 = eng.submit(shared, max_new=24)
    out2 = eng.run()[r2]
    assert eng.counters["prefix_hits"] >= 1
    assert np.array_equal(out1, out2)


def test_engine_refuses_pallas_with_mesh():
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=64)
    params = init_params(cfg, seed=0)

    class FakeMesh:  # never touched: the refusal fires first
        pass

    with pytest.raises(ValueError, match="mesh"):
        PagedEngine(params, cfg, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, mesh=FakeMesh(), attn="pallas")
    with pytest.raises(ValueError, match="expected"):
        PagedEngine(params, cfg, slots=1, n_blocks=8, block_size=8,
                    max_seq=32, attn="wat")
