"""Device-resident decode state: fused ``paged_tick`` + one-tick async
overlap (tpulab.models.paged).

Headline properties:
  * steady-state decode performs ZERO implicit host<->device transfers
    per tick — enforced three ways at once: ``jax.transfer_guard
    ("disallow")`` around the ticks, a tripwire on the module's
    ``jnp.asarray`` (the engine's only host-upload idiom), and the
    ``h2d_ticks`` counter staying flat while ``ticks`` climbs;
  * greedy output is BIT-IDENTICAL with ``overlap=1`` vs ``overlap=0``
    vs the pre-change goldens (plain dense ``generate``) for plain,
    sampled, penalized, and speculative slots, under both
    ``attn="gather"`` and ``attn="pallas"``;
  * the new overlap counters (``host_syncs`` / ``h2d_ticks`` /
    ``inflight_depth``) surface in ``engine.stats()``;
  * ``run()``'s convergence guard is no longer consumed by empty ticks,
    and a genuinely stuck engine raises immediately instead of spinning.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpulab.models.paged as paged_mod
from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig
from tpulab.models.paged import PagedEngine

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


REP = np.tile(np.arange(7, dtype=np.int32), 3)  # lookup-friendly period-7


class _NoUpload:
    """jnp stand-in whose ``asarray`` (the engine's one host-upload
    idiom) raises: catches numpy uploads the CPU backend's zero-copy
    paths hide from ``jax.transfer_guard``."""

    def __getattr__(self, name):
        return getattr(jnp, name)

    def asarray(self, *a, **kw):  # noqa: D102 - tripwire
        raise AssertionError("host->device upload in steady-state decode")


def test_steady_state_zero_transfers(trained, monkeypatch):
    """ISSUE acceptance: a steady-state tick (no admission, no release)
    moves NOTHING between host and device implicitly — for plain,
    sampled, AND penalized slots in one batch.  ``jax.transfer_guard``
    catches scalar/array transfers, the ``jnp.asarray`` tripwire
    catches zero-copy numpy uploads, and ``h2d_ticks`` must stay flat
    while ``ticks`` advances.  The drain's ``jax.device_get`` is the
    one EXPLICIT d2h, which "disallow" (implicit-only) permits."""
    eng = PagedEngine(trained, CFG, slots=3, n_blocks=32, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(4), max_new=30)
    eng.submit(_cycle_prompt(6), max_new=30, temperature=1.5, seed=3)
    eng.submit(_cycle_prompt(5), max_new=30, repetition_penalty=4.0)
    for _ in range(4):  # admission + compile happen OUTSIDE the guard
        eng.step()
    before = eng.stats()
    assert before["inflight_depth"] == 1  # the async window is open
    monkeypatch.setattr(paged_mod, "jnp", _NoUpload())
    with jax.transfer_guard("disallow"):
        for _ in range(8):
            eng.step()
    monkeypatch.undo()
    st = eng.stats()
    assert st["ticks"] == before["ticks"] + 8
    assert st["h2d_ticks"] == before["h2d_ticks"], "steady tick uploaded"
    assert st["host_syncs"] == before["host_syncs"], "steady tick synced"
    out = eng.run()  # finish normally; the greedy slot still matches
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=30,
                    temperature=0.0)[0]
    assert np.array_equal(out[0], want)


def test_overlap_bit_equality_plain_sampled_penalized(trained):
    """Greedy/sampled/penalized streams are bit-identical with the
    async window on vs off, and the deterministic ones equal the
    pre-change goldens (plain dense generate)."""
    jobs = [
        dict(prompt=_cycle_prompt(4), max_new=12),
        dict(prompt=_cycle_prompt(6), max_new=12, temperature=1.5, seed=7),
        dict(prompt=_cycle_prompt(5), max_new=10, repetition_penalty=4.0),
    ]

    def run(overlap):
        eng = PagedEngine(trained, CFG, slots=3, n_blocks=32, block_size=8,
                          max_seq=64, overlap=overlap)
        rids = [eng.submit(j["prompt"], max_new=j["max_new"],
                           temperature=j.get("temperature", 0.0),
                           seed=j.get("seed", 0),
                           repetition_penalty=j.get(
                               "repetition_penalty", 1.0))
                for j in jobs]
        out = eng.run()
        return [out[r] for r in rids]

    on, off = run(1), run(0)
    for i, (a, b) in enumerate(zip(on, off)):
        assert np.array_equal(a, b), i
    assert np.array_equal(on[0], generate(
        trained, jobs[0]["prompt"][None, :], CFG, steps=12,
        temperature=0.0)[0])
    assert np.array_equal(on[2], generate(
        trained, jobs[2]["prompt"][None, :], CFG, steps=10,
        temperature=0.0, repetition_penalty=4.0)[0])


def test_overlap_bit_equality_speculative(trained):
    """Speculative slots (which force the sync barrier) coexist with an
    overlapping plain slot: both streams bit-equal overlap on vs off vs
    goldens, and the verify counters still fire."""
    def run(overlap):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64, spec_k=4, overlap=overlap)
        rs = eng.submit(REP, max_new=16, spec="lookup")
        rp = eng.submit(_cycle_prompt(5), max_new=12)
        out = eng.run()
        return out[rs], out[rp], eng.stats()

    (s_on, p_on, st_on), (s_off, p_off, _) = run(1), run(0)
    assert np.array_equal(s_on, s_off)
    assert np.array_equal(p_on, p_off)
    assert np.array_equal(s_on, generate(trained, REP[None, :], CFG,
                                         steps=16, temperature=0.0)[0])
    assert np.array_equal(p_on, generate(
        trained, _cycle_prompt(5)[None, :], CFG, steps=12,
        temperature=0.0)[0])
    assert st_on["spec_rounds"] > 0 and st_on["verify_passes"] > 0


@pytest.mark.parametrize("knob", [dict(attn="pallas"),
                                  dict(kv_dtype="int8")])
def test_overlap_bit_equality_engine_knobs(trained, knob):
    """The fused tick serves both attention paths and int8 KV pools,
    with plain, sampled, and penalized slots in one batch: overlap on
    == overlap off, bit for bit (and the plain slot == the golden)."""
    def run(overlap):
        eng = PagedEngine(trained, CFG, slots=3, n_blocks=32, block_size=8,
                          max_seq=64, overlap=overlap, **knob)
        a = eng.submit(_cycle_prompt(5), max_new=10)
        b = eng.submit(_cycle_prompt(9), max_new=8,
                       temperature=1.5, seed=11)
        c = eng.submit(_cycle_prompt(4), max_new=8,
                       repetition_penalty=4.0)
        out = eng.run()
        return out[a], out[b], out[c]

    on, off = run(1), run(0)
    for x, y in zip(on, off):
        assert np.array_equal(x, y)
    # the trained model's margins absorb both the kernel's and int8's
    # tiny logit perturbations (same bar test_paged_kernel holds)
    assert np.array_equal(on[0], generate(
        trained, _cycle_prompt(5)[None, :], CFG, steps=10,
        temperature=0.0)[0])


def test_overlap_counters_and_tick_economy(trained):
    """Counter semantics: a solo greedy request spends exactly max_new
    ticks (the skip-dispatch rule keeps the async window from burning a
    wasted tick per wave), h2d_ticks counts only admission ticks, and
    the window closes (inflight_depth 0) when the engine goes idle."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    rid = eng.submit(_cycle_prompt(4), max_new=10)
    out = eng.run()
    st = eng.stats()
    assert len(out[rid]) == 10
    assert st["ticks"] == 10, st
    assert st["tokens_out"] == 10
    assert 1 <= st["h2d_ticks"] < st["ticks"]
    assert st["host_syncs"] == 0  # solo wave: pipelined pops only
    assert st["inflight_depth"] == 0
    for key in ("host_syncs", "h2d_ticks", "inflight_depth"):
        assert key in st


def test_admission_mid_wave_sync_only_without_interleave(trained):
    """Interleaved admission (the default) no longer drains the async
    window at all — a request admitted while another slot is mid-decode
    keeps host_syncs at zero.  ``interleave=False`` restores the
    pre-change structural barrier (host_syncs counts it), and both
    modes emit identical streams."""
    def run(interleave):
        eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                          max_seq=64, interleave=interleave)
        a = eng.submit(_cycle_prompt(4), max_new=4)    # finishes first
        b = eng.submit(_cycle_prompt(6), max_new=16)   # keeps decoding
        c = eng.submit(_cycle_prompt(5), max_new=4)    # pending behind
        out = eng.run()
        return [out[r] for r in (a, b, c)], eng.stats()

    on, st_on = run(True)
    off, st_off = run(False)
    for x, y in zip(on, off):
        assert np.array_equal(x, y)
    assert len(on) == 3
    assert st_on["host_syncs"] == 0, st_on     # no admission barrier left
    assert st_off["host_syncs"] >= 1, st_off   # the sync path still syncs
    # and neither mode drains every tick
    assert st_off["host_syncs"] < st_off["ticks"] // 2, st_off


def test_block_starved_pending_head_keeps_window_open(trained):
    """A pending head that cannot FIT (blocks, not slots) must not
    drain the async window every tick: the admission barrier is gated
    on feasibility, so overlap survives the starved period and the
    head still admits (and decodes correctly) once blocks free up."""
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=64)  # 7 usable blocks
    a = eng.submit(_cycle_prompt(4), max_new=20)   # 3 blocks, 20 ticks
    b = eng.submit(_cycle_prompt(4), max_new=36)   # 5 blocks: starved
    out = eng.run()
    st = eng.stats()
    assert len(out[a]) == 20 and len(out[b]) == 36
    assert np.array_equal(out[b], generate(
        trained, _cycle_prompt(4)[None, :], CFG, steps=36,
        temperature=0.0)[0])
    # ~20 starved ticks; an every-tick barrier would sync each one
    assert st["host_syncs"] <= 3, st


def test_prefix_pinned_starved_head_keeps_window_open(trained):
    """The gate must simulate _admit's PIN: a head whose matched
    shared-prefix blocks are the only evictable credit sits in the
    window where the naive gate passes (pre-pin credit) while _admit
    declines (post-pin the blocks aren't evictable) — that must not
    turn into an every-tick sync storm."""
    sysp = (np.arange(17) % 7).astype(np.int32)   # 2 full blocks at BS=8
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=8, block_size=8,
                      max_seq=64)  # 7 usable blocks
    r0 = eng.submit(sysp, max_new=2)              # caches the 2 blocks
    eng.run()
    long = eng.submit(_cycle_prompt(4), max_new=20)   # 3 fresh blocks
    head = eng.submit(np.concatenate([sysp, [5]]).astype(np.int32),
                      max_new=15)  # needs 5, shares 2: need_new 3 >
    out = eng.run()                # free (2) + post-pin evictable (0)
    st = eng.stats()
    assert len(out[long]) == 20 and len(out[head]) == 15
    assert np.array_equal(out[head], generate(
        trained, np.concatenate([sysp, [5]])[None, :].astype(np.int32),
        CFG, steps=15, temperature=0.0)[0])
    assert st["host_syncs"] <= 4, st  # no 1:1 sync-per-starved-tick


def test_overlap_streaming_service_one_tick_late(trained):
    """The daemon's generate service over an overlapping engine: the
    stream still carries every token exactly once (one tick late is
    invisible to the consumer) and the full output matches the golden."""
    from tpulab.daemon import _GenerateService

    svc = _GenerateService()
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    chunks = []
    out = svc.generate(eng, _cycle_prompt(4), 12,
                       on_progress=lambda inc: chunks.append(list(inc)))
    want = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                    temperature=0.0)[0]
    assert np.array_equal(out, want)
    assert [t for c in chunks for t in c] == list(want)
    assert eng.inflight_depth == 0  # stepper drained the window


def test_empty_ticks_do_not_consume_guard(trained):
    """Satellite fix: ticks that admit nothing and dispatch nothing no
    longer count against run()'s 100k guard — and a state that can
    never progress raises IMMEDIATELY instead of spinning it down."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    # an empty step is free: no tick, no guard-relevant state change
    assert eng.step() == []
    assert eng.stats()["ticks"] == 0
    eng.submit(_cycle_prompt(3), max_new=2)
    calls = {"n": 0}
    eng._admit = lambda: calls.__setitem__("n", calls["n"] + 1)  # admits 0
    with pytest.raises(RuntimeError, match="cannot make progress"):
        eng.run()
    assert calls["n"] == 1, "run() spun instead of failing fast"


def test_run_guard_still_bounds_real_work(trained):
    """The guard still exists for DISPATCHED ticks: an engine whose
    step keeps reporting device work without ever finishing its
    requests trips the 100k bound rather than looping forever."""
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64)
    eng.submit(_cycle_prompt(3), max_new=8)
    real_step = eng.step

    def stuck_step():
        if eng.counters["ticks"] >= 4:  # simulate non-convergence:
            eng.counters["ticks"] += 1  # "dispatches" but never finishes
            return []
        return real_step()

    eng.step = stuck_step
    with pytest.raises(RuntimeError, match="did not converge"):
        eng.run()
