"""Batched speculative decoding inside the paged engine
(tpulab.models.paged.paged_verify + PagedEngine spec_k mode).

Headline property (the lossless bar): with ``spec_k > 0`` every GREEDY
request's token stream is bit-identical to the same engine at
``spec_k = 0`` — across prefix-cache hits, chunked prefill, stop bytes,
repetition penalty, sliding-window attention, and sampled slots
coexisting in the batch — while the engine spends measurably fewer
target forward passes (ticks) per generated token.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.generate import generate
from tpulab.models.labformer import LabformerConfig, init_train_state
from tpulab.models.paged import (PagedEngine, init_pools, paged_decode_step,
                                 paged_verify)

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


def _cycle_prompt(p):
    return (np.arange(p) % 7).astype(np.int32)


REP = np.tile(np.arange(7, dtype=np.int32), 3)  # lookup-friendly period-7


def test_paged_verify_rows_match_sequential_decode(trained):
    """Verify-window logits row j == the batched decode-step logits
    after feeding the window prefix token-by-token — the paged analog of
    test_speculative.TestForwardWindow."""
    toks = np.array([[1, 2, 3, 4], [2, 4, 6, 1]], np.int32)
    tables = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    lengths = np.zeros(2, np.int32)
    kp, vp = init_pools(CFG, 16, 8)
    vlogits, _, _ = paged_verify(
        trained, jnp.asarray(toks), kp, vp, jnp.asarray(tables),
        jnp.asarray(lengths), jnp.asarray(np.full(2, 3, np.int32)),
        CFG, 8, 4)
    vlogits = np.asarray(vlogits)
    kp, vp = init_pools(CFG, 16, 8)
    for j in range(4):
        lg, kp, vp = paged_decode_step(
            trained, jnp.asarray(toks[:, j]), kp, vp, jnp.asarray(tables),
            jnp.asarray(np.full(2, j, np.int32)), CFG, 8)
        np.testing.assert_allclose(vlogits[:, j], np.asarray(lg),
                                   atol=1e-5), j


def test_spec_lookup_lossless_and_fewer_passes(trained):
    """Measured-speedup proxy (ISSUE acceptance): on lookup-friendly
    text, target forward passes per generated token drop >= 2x vs
    spec_k=0, with a bit-identical stream — asserted via the new
    engine.stats() counters."""
    def run(spec):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64, spec_k=4)
        rid = eng.submit(REP, max_new=24, spec=spec)
        return eng.run()[rid], eng.stats()

    out_s, st_s = run("lookup")
    out_p, st_p = run("off")
    assert np.array_equal(out_s, out_p)
    assert st_s["tokens_out"] == st_p["tokens_out"] == 24
    assert st_p["ticks"] == 24  # plain: one target pass per token
    assert 2 * st_s["ticks"] <= st_p["ticks"], st_s
    assert st_s["verify_passes"] == st_s["ticks"]
    assert st_s["spec_rounds"] > 0
    assert st_s["spec_accepted"] / st_s["spec_rounds"] > 1.0
    assert st_s["spec_tokens"] == 24


def test_spec_equals_nonspec_mixed_batch(trained):
    """THE lossless-equivalence bar: a mixed batch exercising
    prefix-cache hits, chunked prefill, stop bytes, and a coexisting
    sampled slot — spec_k>0 output bit-identical to spec_k=0 per
    request (sampled stream included: keys advance once per tick and
    sampled slots commit one token per tick in both modes)."""
    sysp = (np.arange(17) % 7).astype(np.int32)  # 2 full blocks at BS=8
    ref = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                   temperature=0.0)[0].tolist()
    stop = ref[3]
    jobs = [
        dict(prompt=np.concatenate([sysp, [1, 2]]).astype(np.int32),
             max_new=12),                                  # prefix miss
        dict(prompt=np.concatenate([sysp, [3]]).astype(np.int32),
             max_new=10),                                  # prefix HIT
        dict(prompt=REP, max_new=16),                      # lookup-friendly
        dict(prompt=(np.arange(30) % 7).astype(np.int32),
             max_new=8),                                   # chunked prefill
        dict(prompt=_cycle_prompt(5), max_new=12,
             temperature=1.5, seed=3),                     # sampled slot
        dict(prompt=_cycle_prompt(4), max_new=12,
             stop_byte=int(stop)),                         # stop byte
        dict(prompt=_cycle_prompt(6), max_new=9,
             repetition_penalty=4.0),                      # penalized
    ]

    def run(spec_k):
        eng = PagedEngine(trained, CFG, slots=3, n_blocks=48, block_size=8,
                          max_seq=64, prefill_chunk=8, spec_k=spec_k)
        rids = [
            eng.submit(j["prompt"], max_new=j["max_new"],
                       temperature=j.get("temperature", 0.0),
                       seed=j.get("seed", 0),
                       repetition_penalty=j.get("repetition_penalty", 1.0),
                       stop_byte=j.get("stop_byte", -1),
                       spec="lookup" if spec_k else "off")
            for j in jobs
        ]
        out = eng.run()
        return [out[r] for r in rids], eng.stats()

    got_spec, st = run(4)
    got_plain, _ = run(0)
    for i, (a, b) in enumerate(zip(got_spec, got_plain)):
        assert np.array_equal(a, b), (i, a, b)
    assert st["prefix_hits"] >= 1
    assert st["spec_rounds"] > 0 and st["spec_accepted"] > 0


def test_spec_draft_mode_lossless_and_accepting(trained):
    """Opt-in dense-draft proposer (int8-quantized target, per-slot
    vmapped propose): lossless next to a plain slot, and the sharp int8
    draft accepts most proposals."""
    from tpulab.models.quant import quantize_decode_params

    eng = PagedEngine(trained, CFG, slots=2, n_blocks=24, block_size=8,
                      max_seq=64, spec_k=4)
    eng.set_draft(quantize_decode_params(trained, CFG))
    rd = eng.submit(_cycle_prompt(5), max_new=16, spec="draft")
    rp = eng.submit(_cycle_prompt(9), max_new=8)   # plain rides along
    out = eng.run()
    want_d = generate(trained, _cycle_prompt(5)[None, :], CFG, steps=16,
                      temperature=0.0)[0]
    want_p = generate(trained, _cycle_prompt(9)[None, :], CFG, steps=8,
                      temperature=0.0)[0]
    assert np.array_equal(out[rd], want_d)
    assert np.array_equal(out[rp], want_p)
    st = eng.stats()
    assert st["spec_accepted"] / st["spec_rounds"] > 2.0, st


def test_spec_draft_constructor_and_single_token_prompt(trained):
    """Draft via the constructor; a 1-token prompt (no draft prefill at
    all) still decodes losslessly."""
    from tpulab.models.quant import quantize_decode_params

    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64, spec_k=3,
                      draft_params=quantize_decode_params(trained, CFG))
    rid = eng.submit(_cycle_prompt(1), max_new=8, spec="draft")
    out = eng.run()
    want = generate(trained, _cycle_prompt(1)[None, :], CFG, steps=8,
                    temperature=0.0)[0]
    assert np.array_equal(out[rid], want)


def test_spec_with_attention_window(trained_small_cfg):
    """Sliding-window attention + spec: lossless, and window block
    retirement still fires mid-spec."""
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                          max_seq=128, attn_window=8)
    params, opt, step = init_train_state(cfg, None, seed=0)
    tok = np.tile(np.arange(33, dtype=np.int32) % 7, (8, 1))
    for _ in range(60):
        params, opt, _ = step(params, opt, tok)
    params = jax.device_get(params)
    eng = PagedEngine(params, cfg, slots=1, n_blocks=16, block_size=8,
                      max_seq=64, spec_k=3)
    rid = eng.submit(REP, max_new=20, spec="lookup")
    out = eng.run()
    want = generate(params, REP[None, :], cfg, steps=20,
                    temperature=0.0)[0]
    assert np.array_equal(out[rid], want)
    assert eng.stats()["blocks_retired"] > 0


def test_spec_stop_byte_frees_blocks(trained):
    """A stop byte landing inside a multi-token commit truncates the
    stream right after it and recycles every block."""
    ref = generate(trained, _cycle_prompt(4)[None, :], CFG, steps=12,
                   temperature=0.0)[0].tolist()
    stop = ref[3]
    first = ref.index(stop)
    eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                      max_seq=64, spec_k=4)
    free0 = len(eng.free)
    rid = eng.submit(_cycle_prompt(4), max_new=12, stop_byte=int(stop),
                     spec="lookup")
    out = eng.run()
    assert out[rid].tolist() == ref[:first + 1]
    assert len(eng.free) == free0, "blocks not fully recycled"


def test_spec_int8_kv_pool(trained):
    """spec over int8-quantized KV pools: the verify writes/gathers go
    through the same one-quantize-site helpers."""
    def run(spec_k):
        eng = PagedEngine(trained, CFG, slots=1, n_blocks=16, block_size=8,
                          max_seq=64, kv_dtype="int8", spec_k=spec_k)
        rid = eng.submit(REP, max_new=12,
                         spec="lookup" if spec_k else "off")
        return eng.run()[rid]

    assert np.array_equal(run(4), run(0))


def test_spec_validation():
    cfg = CFG
    from tpulab.models.labformer import init_params

    params = init_params(cfg, seed=0)
    with pytest.raises(ValueError, match="spec_k must be >= 0"):
        PagedEngine(params, cfg, spec_k=-1)
    with pytest.raises(ValueError, match="gather"):
        PagedEngine(params, cfg, spec_k=2, attn="pallas")
    eng0 = PagedEngine(params, cfg, slots=1, n_blocks=8, block_size=8,
                       max_seq=32)
    with pytest.raises(ValueError, match="spec_k > 0"):
        eng0.submit(_cycle_prompt(3), max_new=2, spec="lookup")
    eng = PagedEngine(params, cfg, slots=1, n_blocks=8, block_size=8,
                      max_seq=32, spec_k=2)
    with pytest.raises(ValueError, match="set_draft"):
        eng.submit(_cycle_prompt(3), max_new=2, spec="draft")
    with pytest.raises(ValueError, match="spec_k must be in"):
        eng.submit(_cycle_prompt(3), max_new=2, spec="lookup", spec_k=9)
    with pytest.raises(ValueError, match="expected 'off'"):
        eng.submit(_cycle_prompt(3), max_new=2, spec="ngram")
    with pytest.raises(ValueError, match="spec_k=0"):
        eng0.set_draft(params)


def test_concurrent_spec_clients_interleave(trained):
    """Satellite: two simultaneous speculative daemon clients on ONE
    engine make interleaved progress (both resident in the same batch —
    no global-lock serialization) and both streams match their
    single-client outputs."""
    from tpulab.daemon import _GenerateService

    prompts = {"a": REP, "b": (np.arange(12) % 5).astype(np.int32)}
    solo = {}
    for name, pr in prompts.items():
        e = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                        max_seq=64, spec_k=4)
        rid = e.submit(pr, max_new=20, spec="lookup")
        solo[name] = e.run()[rid]

    svc = _GenerateService()
    eng = PagedEngine(trained, CFG, slots=2, n_blocks=32, block_size=8,
                      max_seq=64, spec_k=4)
    # co-residency evidence: record the active-slot count right after
    # every admission — a serialized path would never see 2
    peak = {"n": 0}
    orig_admit = eng._admit

    def counting_admit():
        orig_admit()
        peak["n"] = max(peak["n"],
                        sum(1 for r in eng.active if r is not None))

    eng._admit = counting_admit
    barrier = threading.Barrier(2)
    results = {}
    errors = []

    def client(name, pr):
        try:
            barrier.wait()
            results[name] = svc.generate(eng, pr, 20, spec="lookup")
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append((name, e))

    threads = [threading.Thread(target=client, args=(n, p))
               for n, p in prompts.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert np.array_equal(results["a"], solo["a"])
    assert np.array_equal(results["b"], solo["b"])
    assert peak["n"] == 2, "spec clients never co-resided in the batch"
