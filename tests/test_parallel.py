"""Multi-device tier tests on the virtual 8-device CPU mesh.

The distributed implementations must agree with their single-device
twins (and NumPy oracles) exactly — the same bar the golden-file tier
sets for the lab kernels (SURVEY.md section 4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.ops.mahalanobis import ClassStats, class_statistics, classify
from tpulab.ops.roberts import roberts_edges
from tpulab.parallel import (
    all_gather_op,
    best_factorization,
    classify_sharded,
    distributed_mean,
    distributed_reduce,
    distributed_sort,
    make_mesh,
    reduce_scatter_op,
    roberts_sharded,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh({"x": 8})


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh({"x": 4})


def test_mesh_factorization():
    assert best_factorization(8, ("dp", "tp")) == {"dp": 2, "tp": 4}
    assert best_factorization(8, ("x",)) == {"x": 8}
    sizes = best_factorization(12, ("a", "b", "c"))
    assert sizes["a"] * sizes["b"] * sizes["c"] == 12
    assert best_factorization(1, ("dp", "tp")) == {"dp": 1, "tp": 1}


def test_make_mesh_shapes():
    m = make_mesh({"dp": 2, "tp": 4})
    assert m.shape == {"dp": 2, "tp": 4}
    m1 = make_mesh(n_devices=8, axes=("x",))
    assert m1.shape["x"] == 8


class TestServingMeshHelpers:
    """Round-19 engine-facing mesh helpers (tpulab/parallel/mesh.py):
    the 2D ("batch", "model") serving layout, the "AxB" spec grammar,
    and the axis resolvers that keep the legacy 1D tp mesh working
    through the same engine code path."""

    def test_parse_mesh_spec(self):
        from tpulab.parallel import parse_mesh_spec

        assert parse_mesh_spec("2x4") == (2, 4)
        assert parse_mesh_spec("1x1") == (1, 1)
        assert parse_mesh_spec("8X1") == (8, 1)  # case-insensitive

    @pytest.mark.parametrize("bad", ["", "8", "2x", "x4", "2x4x2",
                                     "axb", "2.5x4", "0x4", "2x-1"])
    def test_parse_mesh_spec_rejects(self, bad):
        from tpulab.parallel import parse_mesh_spec

        with pytest.raises(ValueError):
            parse_mesh_spec(bad)

    def test_serving_mesh_axes(self):
        from tpulab.parallel import serving_mesh

        m = serving_mesh(2, 4)
        assert m.axis_names == ("batch", "model")
        assert m.shape == {"batch": 2, "model": 4}
        assert serving_mesh(1, 1).shape == {"batch": 1, "model": 1}
        with pytest.raises(ValueError):
            serving_mesh(0, 4)

    def test_axis_resolvers(self):
        from tpulab.parallel import serving_mesh
        from tpulab.parallel.mesh import axis_size, batch_axis, model_axis

        sm = serving_mesh(2, 4)
        tp = make_mesh({"tp": 4})
        plain = make_mesh({"x": 8})
        assert model_axis(sm) == "model" and batch_axis(sm) == "batch"
        assert model_axis(tp) == "tp" and batch_axis(tp) is None
        assert model_axis(plain) is None and batch_axis(plain) is None
        assert model_axis(None) is None and batch_axis(None) is None
        assert axis_size(sm, "model") == 4
        assert axis_size(sm, "batch") == 2
        assert axis_size(tp, None) == 1
        assert axis_size(None, "model") == 1

    def test_specs(self):
        from jax.sharding import PartitionSpec as P

        from tpulab.parallel import serving_mesh
        from tpulab.parallel.mesh import (pool_scale_spec, pool_spec,
                                          slot_spec)

        sm = serving_mesh(2, 4)
        tp = make_mesh({"tp": 4})
        assert pool_spec(sm) == P(None, None, None, "model", None)
        assert pool_spec(tp) == P(None, None, None, "tp", None)
        assert pool_scale_spec(sm) == P(None, None, None, "model")
        assert slot_spec(sm, 1) == P("batch")
        assert slot_spec(sm, 2) == P("batch", None)
        # legacy tp mesh has no batch axis: state stays replicated
        assert slot_spec(tp, 2) == P(None, None)

    def test_serving_param_spec_translation(self):
        from jax.sharding import PartitionSpec as P

        from tpulab.parallel import serving_mesh
        from tpulab.parallel.mesh import serving_param_spec

        sm = serving_mesh(2, 4)
        # training spec ("pp", None, "tp"): pp drops (absent), tp
        # renames to model — params never shard on batch
        assert (serving_param_spec(P("pp", None, "tp"), sm)
                == P(None, None, "model"))
        assert serving_param_spec(P(None, "tp"), sm) == P(None, "model")
        # legacy tp mesh: rename is a no-op, pp still drops
        tp = make_mesh({"tp": 4})
        assert (serving_param_spec(P("pp", "tp", None), tp)
                == P(None, "tp", None))
        # replicated entries stay replicated
        assert serving_param_spec(P(None, None), sm) == P(None, None)


class TestDistributedReduce:
    @pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
    def test_int_ops_match_numpy(self, mesh8, op, rng):
        vals = rng.integers(1, 5, size=37).astype(np.int32)
        got = distributed_reduce(vals, op, mesh=mesh8)
        want = {"sum": np.sum, "min": np.min, "max": np.max, "prod": np.prod}[op](
            vals.astype(np.int64)
        )
        assert int(got) == int(want)

    @pytest.mark.parametrize("op", ["sum", "min", "max"])
    def test_float_ops(self, mesh8, op, rng):
        vals = rng.normal(size=64).astype(np.float32)
        got = distributed_reduce(vals, op, mesh=mesh8)
        want = {"sum": np.sum, "min": np.min, "max": np.max}[op](vals)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_matches_single_device_reduce(self, mesh8):
        # the lab5 fixture pattern: descending 0,9,8,...,1 (SURVEY.md 2.3)
        vals = np.array([0, 9, 8, 7, 6, 5, 4, 3, 2, 1], np.int32)
        from tpulab.ops.reduction import reduce_op

        assert int(distributed_reduce(vals, "sum", mesh=mesh8)) == int(
            reduce_op(vals, "sum", backend="cpu")
        )

    def test_mean(self, mesh8, rng):
        vals = rng.normal(size=19)
        got = distributed_mean(vals, mesh=mesh8)
        np.testing.assert_allclose(float(got), vals.mean(), rtol=1e-12)


class TestGatherScatter:
    def test_all_gather_identity(self, mesh4, rng):
        vals = rng.normal(size=16).astype(np.float32)
        got = np.asarray(all_gather_op(vals, mesh=mesh4))
        np.testing.assert_array_equal(got, vals)

    def test_reduce_scatter_is_column_sum(self, mesh4, rng):
        mat = rng.normal(size=(4, 8)).astype(np.float32)
        got = np.asarray(reduce_scatter_op(mat, mesh=mesh4))
        np.testing.assert_allclose(got, mat.sum(axis=0), rtol=1e-5)


class TestHaloStencil:
    @pytest.mark.parametrize("shape", [(16, 16), (37, 23), (5, 9), (8, 128)])
    def test_matches_single_device(self, mesh8, rng, shape):
        img = rng.integers(0, 256, size=(*shape, 4)).astype(np.uint8)
        want = np.asarray(roberts_edges(jnp.asarray(img)))
        got = roberts_sharded(img, mesh=mesh8)
        np.testing.assert_array_equal(got, want)

    def test_height_smaller_than_mesh(self, mesh8, rng):
        img = rng.integers(0, 256, size=(3, 7, 4)).astype(np.uint8)
        want = np.asarray(roberts_edges(jnp.asarray(img)))
        np.testing.assert_array_equal(roberts_sharded(img, mesh=mesh8), want)


class TestDistributedSort:
    @pytest.mark.parametrize("n", [10, 64, 1000, 1021])
    def test_float(self, mesh8, rng, n):
        vals = rng.normal(size=n).astype(np.float32)
        np.testing.assert_array_equal(distributed_sort(vals, mesh=mesh8), np.sort(vals))

    def test_int_with_duplicates(self, mesh8, rng):
        vals = rng.integers(0, 10, size=200).astype(np.int32)
        np.testing.assert_array_equal(distributed_sort(vals, mesh=mesh8), np.sort(vals))

    def test_uint8_lab5_fixture_pattern(self, mesh8):
        vals = np.array([1, 2, 3, 1, 2, 3, 1, 2, 3, 4], np.uint8)  # lab5/data/uchar10
        got = distributed_sort(vals, mesh=mesh8)
        assert got.dtype == np.uint8
        np.testing.assert_array_equal(got, np.sort(vals))

    def test_already_sorted_and_reversed(self, mesh4):
        vals = np.arange(100, dtype=np.float64)
        np.testing.assert_array_equal(distributed_sort(vals, mesh=mesh4), vals)
        np.testing.assert_array_equal(distributed_sort(vals[::-1], mesh=mesh4), vals)

    def test_inf_and_negatives(self, mesh8):
        # +inf must survive: a naive finfo.max padding sentinel sorts
        # below +inf and the count-based trim would drop the real infs
        vals = np.array([1.0, np.inf, -3.0, 2.0, -np.inf, 0.0] * 5, np.float32)
        np.testing.assert_array_equal(distributed_sort(vals, mesh=mesh8), np.sort(vals))

    def test_nan_sorts_last(self, mesh8, rng):
        vals = rng.normal(size=37).astype(np.float32)
        vals[[3, 17, 30]] = np.nan
        got = distributed_sort(vals, mesh=mesh8)
        np.testing.assert_array_equal(got, np.sort(vals))  # NaNs last, like np.sort

    def test_finfo_max_values_survive(self, mesh8):
        vals = np.array([np.finfo(np.float32).max, 0.0, -1.0] * 4, np.float32)
        np.testing.assert_array_equal(distributed_sort(vals, mesh=mesh8), np.sort(vals))


class TestShardedClassify:
    def test_matches_single_device(self, mesh8, rng):
        img = rng.integers(0, 256, size=(32, 16, 4)).astype(np.uint8)
        classes = [
            np.array([[0, 0], [1, 0], [2, 1], [3, 2]]),
            np.array([[10, 20], [11, 21], [12, 22], [13, 23]]),
        ]
        stats = class_statistics(img, classes)
        want = np.asarray(classify(img, stats, backend="cpu", compute_dtype=jnp.float32))
        got = classify_sharded(img, stats, mesh=mesh8, compute_dtype=jnp.float32)
        np.testing.assert_array_equal(got, want)
