"""GPipe pipeline tests: stage-parallel result == sequential scan."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.parallel.pipeline import make_pipeline_train_step, pipeline_apply


def mlp_layer(x, layer):
    return jnp.tanh(x @ layer["w"] + layer["b"])


def sequential(params, x):
    def step(a, layer):
        return mlp_layer(a, layer), None

    out, _ = jax.lax.scan(step, jnp.asarray(x), params)
    return np.asarray(out)


def _params(rng, n_layers, d):
    return {
        "w": jnp.asarray(rng.standard_normal((n_layers, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_layers, d)) * 0.1, jnp.float32),
    }


class TestPipeline:
    @pytest.mark.parametrize("stages,n_micro", [(2, 2), (4, 4), (8, 2), (4, 1)])
    def test_matches_sequential(self, rng, stages, n_micro):
        mesh = cpu_test_mesh({"pp": stages})
        params = _params(rng, n_layers=stages * 2, d=16)
        x = rng.standard_normal((8, 16)).astype(np.float32)
        got = np.asarray(pipeline_apply(mlp_layer, params, x, mesh=mesh, n_micro=n_micro))
        np.testing.assert_allclose(got, sequential(params, x), rtol=1e-5, atol=1e-6)

    def test_single_stage(self, rng):
        mesh = cpu_test_mesh({"pp": 1})
        params = _params(rng, n_layers=3, d=8)
        x = rng.standard_normal((4, 8)).astype(np.float32)
        got = np.asarray(pipeline_apply(mlp_layer, params, x, mesh=mesh, n_micro=2))
        np.testing.assert_allclose(got, sequential(params, x), rtol=1e-5, atol=1e-6)

    def test_layers_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"pp": 4})
        params = _params(rng, n_layers=6, d=8)
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(mlp_layer, params, np.zeros((4, 8), np.float32), mesh=mesh)

    def test_batch_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"pp": 2})
        params = _params(rng, n_layers=2, d=8)
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(
                mlp_layer, params, np.zeros((5, 8), np.float32), mesh=mesh, n_micro=4
            )


class TestPipelineBackward:
    """The GPipe schedule is a training feature: grads flow backwards
    through the reverse-replayed scan with transposed ppermutes."""

    @pytest.mark.parametrize("stages,n_micro", [(2, 2), (4, 4)])
    def test_gradients_match_sequential(self, rng, stages, n_micro):
        mesh = cpu_test_mesh({"pp": stages})
        params = _params(rng, n_layers=stages * 2, d=16)
        x = rng.standard_normal((8, 16)).astype(np.float32)

        def loss_pipe(p):
            out = pipeline_apply(mlp_layer, p, x, mesh=mesh, n_micro=n_micro)
            return jnp.sum(out * out)

        def loss_seq(p):
            def step(a, layer):
                return mlp_layer(a, layer), None

            out, _ = jax.lax.scan(step, jnp.asarray(x), p)
            return jnp.sum(out * out)

        got = jax.grad(loss_pipe)(params)
        want = jax.grad(loss_seq)(params)
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(got[key]), np.asarray(want[key]), rtol=1e-4, atol=1e-5
            )

    def test_train_step_matches_single_device(self, rng):
        import optax

        d, n_layers, steps = 8, 4, 3
        params0 = _params(rng, n_layers=n_layers, d=d)
        x = rng.standard_normal((8, d)).astype(np.float32)
        y = rng.standard_normal((8, d)).astype(np.float32)
        loss_head = lambda out, tgt: jnp.mean((out - tgt) ** 2)

        mesh = cpu_test_mesh({"pp": 2})
        optimizer = optax.sgd(0.1)
        step_pipe = make_pipeline_train_step(
            mlp_layer, loss_head, optimizer, mesh=mesh, n_micro=2
        )
        params = jax.tree_util.tree_map(jnp.copy, params0)
        opt_state = optimizer.init(params)
        for _ in range(steps):
            params, opt_state, loss_p = step_pipe(params, opt_state, x, y)

        # single-device oracle: sequential scan + identical optimizer
        def loss_seq(p, x, tgt):
            def step(a, layer):
                return mlp_layer(a, layer), None

            out, _ = jax.lax.scan(step, jnp.asarray(x), p)
            return loss_head(out, tgt)

        ref = jax.tree_util.tree_map(jnp.copy, params0)
        ref_opt = optimizer.init(ref)
        for _ in range(steps):
            loss_s, grads = jax.value_and_grad(loss_seq)(ref, x, y)
            updates, ref_opt = optimizer.update(grads, ref_opt, ref)
            ref = optax.apply_updates(ref, updates)

        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-5)
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(params[key]), np.asarray(ref[key]), rtol=1e-4, atol=1e-5
            )
