"""GPipe pipeline tests: stage-parallel result == sequential scan."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.parallel.pipeline import pipeline_apply


def mlp_layer(x, layer):
    return jnp.tanh(x @ layer["w"] + layer["b"])


def sequential(params, x):
    def step(a, layer):
        return mlp_layer(a, layer), None

    out, _ = jax.lax.scan(step, jnp.asarray(x), params)
    return np.asarray(out)


def _params(rng, n_layers, d):
    return {
        "w": jnp.asarray(rng.standard_normal((n_layers, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_layers, d)) * 0.1, jnp.float32),
    }


class TestPipeline:
    @pytest.mark.parametrize("stages,n_micro", [(2, 2), (4, 4), (8, 2), (4, 1)])
    def test_matches_sequential(self, rng, stages, n_micro):
        mesh = cpu_test_mesh({"pp": stages})
        params = _params(rng, n_layers=stages * 2, d=16)
        x = rng.standard_normal((8, 16)).astype(np.float32)
        got = np.asarray(pipeline_apply(mlp_layer, params, x, mesh=mesh, n_micro=n_micro))
        np.testing.assert_allclose(got, sequential(params, x), rtol=1e-5, atol=1e-6)

    def test_single_stage(self, rng):
        mesh = cpu_test_mesh({"pp": 1})
        params = _params(rng, n_layers=3, d=8)
        x = rng.standard_normal((4, 8)).astype(np.float32)
        got = np.asarray(pipeline_apply(mlp_layer, params, x, mesh=mesh, n_micro=2))
        np.testing.assert_allclose(got, sequential(params, x), rtol=1e-5, atol=1e-6)

    def test_layers_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"pp": 4})
        params = _params(rng, n_layers=6, d=8)
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(mlp_layer, params, np.zeros((4, 8), np.float32), mesh=mesh)

    def test_batch_not_divisible_raises(self, rng):
        mesh = cpu_test_mesh({"pp": 2})
        params = _params(rng, n_layers=2, d=8)
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(
                mlp_layer, params, np.zeros((5, 8), np.float32), mesh=mesh, n_micro=4
            )
