"""Property-based tests (hypothesis): invariants the fixed-seed suite
samples only pointwise.

The reference's own test strategy is generate→run→compare against an
oracle (SURVEY.md section 4, mechanism 2 — lab1's commented-out
allclose); hypothesis turns that pattern into searched invariants over
the input space, shrinking any counterexample it finds.
"""

import os

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

MAX_EXAMPLES = 25  # each example runs real codec/kernel code — keep tight


def _rgba(h, w, seed):
    return np.random.default_rng(seed).integers(0, 256, (h, w, 4), np.uint8)


class TestCodecRoundTrips:
    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(h=st.integers(1, 9), w=st.integers(1, 9), seed=st.integers(0, 2**31))
    def test_pack_unpack_identity(self, h, w, seed):
        from tpulab.io.imagefile import pack_image, unpack_image

        px = _rgba(h, w, seed)
        assert np.array_equal(unpack_image(pack_image(px)), px)

    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(h=st.integers(1, 9), w=st.integers(1, 9), seed=st.integers(0, 2**31))
    def test_hex_identity(self, h, w, seed):
        from tpulab.io.imagefile import bytes_to_hex, hex_to_bytes, pack_image

        blob = pack_image(_rgba(h, w, seed))
        assert hex_to_bytes(bytes_to_hex(blob)) == blob


class TestKernelOracles:
    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(h=st.integers(1, 12), w=st.integers(1, 12), seed=st.integers(0, 2**31))
    def test_roberts_matches_c_oracle(self, h, w, seed):
        """XLA Roberts == the independent per-pixel C-semantics oracle,
        bit-exact, for ANY image shape including 1-pixel edges."""
        from tests.test_lab2 import roberts_oracle_c
        from tpulab.ops.roberts import roberts_edges

        px = _rgba(h, w, seed)
        got = np.asarray(roberts_edges(jnp.asarray(px)))
        assert np.array_equal(got, roberts_oracle_c(px))

    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(n=st.integers(1, 300), seed=st.integers(0, 2**31))
    def test_subtract_matches_oracle(self, n, seed):
        from tpulab.ops.elementwise import subtract, subtract_oracle

        rng = np.random.default_rng(seed)
        a = rng.uniform(-1e100, 1e100, n)
        b = rng.uniform(-1e100, 1e100, n)
        got = np.asarray(subtract(a, b))
        assert np.array_equal(got, subtract_oracle(a, b))

    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(seed=st.integers(0, 2**31), nc=st.integers(1, 6))
    def test_classify_labels_in_range_or_reference_sentinel(self, seed, nc):
        """Labels are valid class ids — or 255 exactly when a pixel saw
        only NaN distances (degenerate covariances), which is the
        reference's own ``best_class = -1`` → uchar alpha semantics
        (lab3/src/main.cu:47,73).  Found by hypothesis: 3 random sample
        points are often rank-deficient in color space."""
        from tpulab.ops.mahalanobis import class_statistics, classify_labels

        rng = np.random.default_rng(seed)
        img = _rgba(8, 8, seed)
        classes = [
            np.stack([rng.integers(0, 8, 3), rng.integers(0, 8, 3)], axis=1)
            for _ in range(nc)
        ]
        stats = class_statistics(img, classes)
        labels = np.asarray(
            classify_labels(jnp.asarray(img), jnp.asarray(stats.mean),
                            jnp.asarray(stats.inv_cov))
        )
        assert labels.shape == (8, 8)
        ok = (labels < nc) | (labels == 255)
        assert ok.all(), labels
        if np.isfinite(stats.inv_cov).all():
            # every class usable -> the sentinel must not appear
            assert (labels < nc).all()


class TestQuantBounds:
    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(rows=st.integers(1, 24), cols=st.integers(1, 16),
           scale=st.floats(1e-3, 1e3), seed=st.integers(0, 2**31))
    def test_dequant_error_bound(self, rows, cols, scale, seed):
        """|w - q*s| <= s/2 elementwise for any magnitude distribution."""
        from tpulab.models.quant import quantize_tensor

        w = (np.random.default_rng(seed).standard_normal((rows, cols))
             * scale).astype(np.float32)
        qt = quantize_tensor(w, axis=0)
        deq = np.asarray(qt.q, np.float32) * np.asarray(qt.s)[None, :]
        bound = np.asarray(qt.s)[None, :] / 2 * (1 + 1e-6) + 1e-12
        assert (np.abs(deq - w) <= bound).all()


class TestSortTotalOrder:
    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(n=st.integers(1, 200), seed=st.integers(0, 2**31),
           specials=st.booleans())
    def test_sort_matches_numpy_with_specials(self, n, seed, specials):
        """sort_ascending == np.sort for any float mix incl. ±inf/NaN
        (NaNs sort last, matching numpy's IEEE total-order behavior)."""
        from tpulab.ops.sortops import sort_ascending

        rng = np.random.default_rng(seed)
        x = rng.standard_normal(n).astype(np.float32)
        if specials and n >= 4:
            x[rng.integers(0, n, 2)] = [np.inf, -np.inf]
            x[rng.integers(0, n)] = np.nan
        got = np.asarray(sort_ascending(jnp.asarray(x)))
        want = np.sort(x)
        assert np.array_equal(got, want, equal_nan=True)


class TestPagedEngineInvariants:
    """Randomized serving workloads: whatever the mix of prompt lengths,
    budgets, shared prefixes, slot counts, and chunked prefill, every
    request's greedy tokens must equal its solo decode, and the pool
    must account for every block afterward."""

    # Each example draws a full engine workload + per-request solo decode
    # oracle (~7s on the one-core box), and the 4-way (window, attn)
    # parametrize multiplies every max_examples value by 4: the default
    # TPULAB_PAGED_EXAMPLES=2 runs 8 property executions per suite;
    # =25 runs 100 (~4x the time documented below).
    # The default is a wall-time choice, not a coverage ceiling:
    # the full 25-example sweep passes (verified 2026-07-31, 79.5 s on
    # the 8-device CPU mesh) — raise via TPULAB_PAGED_EXAMPLES to re-run
    # the wide sweep.
    # (window, attn) are pytest params, NOT hypothesis draws: under a
    # small example budget a random draw could leave a combination
    # (e.g. pallas+windowed) entirely unexercised — parametrize
    # guarantees all four combos run every time, hypothesis varies the
    # workload WITHIN each.
    @pytest.mark.parametrize("window,attn", [
        (0, "gather"), (0, "pallas"), (5, "gather"), (5, "pallas")])
    @settings(max_examples=int(os.environ.get("TPULAB_PAGED_EXAMPLES", "2")),
              deadline=None)
    @given(
        data=st.data(),
        slots=st.integers(1, 3),
        n_reqs=st.integers(1, 6),
        chunk=st.sampled_from([0, 8]),
        seed=st.integers(0, 2**31),
    )
    def test_random_workload_matches_solo_decode(
        self, trained_small, trained_small_cfg, window, attn, data, slots,
        n_reqs, chunk, seed,
    ):
        import dataclasses

        from tpulab.models.generate import generate
        from tpulab.models.paged import PagedEngine

        # window and attention impl are pure function/engine knobs over
        # the SAME weights: every combination must match its own solo
        # windowed decode (and windowed runs exercise mid-decode block
        # retirement under the same accounting assertions)
        cfg = dataclasses.replace(trained_small_cfg, attn_window=window)
        rng = np.random.default_rng(seed)
        shared = (np.arange(17) % 7).astype(np.int32)
        jobs = []
        for _ in range(n_reqs):
            if data.draw(st.booleans(), label="share"):
                tail = rng.integers(0, 7, rng.integers(1, 5)).astype(np.int32)
                prompt = np.concatenate([shared, tail])
            else:
                prompt = rng.integers(
                    0, 7, rng.integers(1, 21)).astype(np.int32)
            jobs.append((prompt, int(rng.integers(1, 8))))

        eng = PagedEngine(trained_small, cfg, slots=slots, n_blocks=32,
                          block_size=8, max_seq=64, prefill_chunk=chunk,
                          attn=attn)
        rids = [eng.submit(p, max_new=n) for p, n in jobs]
        out = eng.run()
        for rid, (prompt, n) in zip(rids, jobs):
            want = generate(trained_small, prompt[None, :], cfg, steps=n,
                            temperature=0.0)[0]
            assert np.array_equal(out[rid], want), (prompt.tolist(), n)
        # block accounting: everything not held by the prefix cache is free
        cached = sum(len(b) for b in eng.prefix_cache.values())
        assert len(eng.free) == eng.n_usable_blocks - cached
        assert int(eng.block_refs.sum()) == cached


class TestBPERoundTrip:
    """BPE is byte-faithful by construction (ids 0..255 stay raw
    bytes): encode∘decode must be the identity for ANY corpus and ANY
    input, trained-on or not."""

    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(
        corpus=st.binary(min_size=4, max_size=2000),
        data=st.binary(min_size=0, max_size=500),
        vocab=st.integers(min_value=256, max_value=320),
    )
    def test_roundtrip_identity(self, corpus, data, vocab):
        from tpulab.io.bpe import train_bpe

        tok = train_bpe(corpus, vocab)
        assert tok.decode(tok.encode(data)) == data
        # and the corpus itself round-trips through its own table
        assert tok.decode(tok.encode(corpus)) == corpus

    @settings(max_examples=MAX_EXAMPLES, deadline=None)
    @given(corpus=st.binary(min_size=16, max_size=2000))
    def test_merges_are_well_formed(self, corpus):
        """Every merged id expands to <= max_token_bytes bytes and
        references only earlier ids (the table is a DAG by rank)."""
        from tpulab.io.bpe import train_bpe

        tok = train_bpe(corpus, 320, max_token_bytes=8)
        for i, (a, b) in enumerate(tok.merges):
            assert a < 256 + i and b < 256 + i
            assert len(tok.decode([256 + i])) <= 8
