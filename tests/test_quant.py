"""int8 weight-only quantized decode: numerics + end-to-end agreement."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpulab.models.generate import generate_jit
from tpulab.models.labformer import LabformerConfig, init_params
from tpulab.models.quant import (
    QTensor,
    qmat,
    quantize_decode_params,
    quantize_tensor,
    unembed,
)

CFG = LabformerConfig(d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128,
                      dtype=jnp.float32)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


class TestQuantizeTensor:
    def test_error_bound(self, rng):
        w = rng.standard_normal((32, 16)).astype(np.float32)
        qt = quantize_tensor(w, axis=0)
        deq = np.asarray(qt.q, np.float32) * np.asarray(qt.s)[None, :]
        bound = np.asarray(qt.s)[None, :] / 2 + 1e-7
        assert (np.abs(deq - w) <= bound).all()

    def test_zero_channel_safe(self):
        w = np.zeros((8, 4), np.float32)
        qt = quantize_tensor(w, axis=0)
        assert np.asarray(qt.q).max() == 0 and np.isfinite(np.asarray(qt.s)).all()

    def test_qmat_matches_dequantized_matmul(self, rng):
        w = rng.standard_normal((32, 16)).astype(np.float32)
        x = jnp.asarray(rng.standard_normal((4, 32)).astype(np.float32))
        qt = quantize_tensor(w, axis=0)
        got = np.asarray(qmat(x, qt))
        deq = np.asarray(qt.q, np.float32) * np.asarray(qt.s)[None, :]
        np.testing.assert_allclose(got, np.asarray(x) @ deq, rtol=1e-5, atol=1e-5)

    def test_unembed_per_row(self, rng):
        e = rng.standard_normal((16, 8)).astype(np.float32)
        x = jnp.asarray(rng.standard_normal((2, 8)).astype(np.float32))
        qt = quantize_tensor(e, axis=1)
        deq = np.asarray(qt.q, np.float32) * np.asarray(qt.s)[:, None]
        np.testing.assert_allclose(
            np.asarray(unembed(x, qt)), np.asarray(x) @ deq.T, rtol=1e-5, atol=1e-5
        )


class TestQuantizedDecode:
    def test_greedy_decode_matches_fp_on_trained_model(self, rng):
        """On a briefly-trained model (peaked logits — a random init's
        near-tied logits flip argmax on any noise), weight-only int8
        must reproduce the full-precision greedy decode almost exactly,
        through the same jitted loop."""
        from tpulab.models.labformer import init_train_state

        params, opt_state, step = init_train_state(CFG, mesh=None, seed=0)
        corpus = rng.integers(0, 64, (4, 33)).astype(np.int32)  # memorizable
        for _ in range(120):
            params, opt_state, _ = step(params, opt_state, jnp.asarray(corpus))
        qparams = quantize_decode_params(params, CFG)
        prompt = jnp.asarray(corpus[:2, :8])
        key = jax.random.PRNGKey(0)
        fp = np.asarray(generate_jit(params, prompt, key, CFG, 24, 0.0))
        q8 = np.asarray(generate_jit(qparams, prompt, key, CFG, 24, 0.0))
        agree = (fp == q8).mean()
        assert agree > 0.9, f"token agreement {agree}"

    def test_moe_rejected(self):
        import dataclasses

        moe = dataclasses.replace(CFG, n_experts=4)
        with pytest.raises(NotImplementedError):
            quantize_decode_params(init_params(moe, seed=0), moe)

    def test_qtensor_is_pytree(self, rng):
        qt = quantize_tensor(rng.standard_normal((8, 4)).astype(np.float32))
        leaves = jax.tree_util.tree_leaves(qt)
        assert len(leaves) == 2  # scan/jit can carry and slice it
