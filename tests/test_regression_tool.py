"""tools/check_regression.py: the machine-readable baseline diff."""

import json
import subprocess
import sys
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(tmp_path, rows, baselines, extra=()):
    bench = tmp_path / "bench.jsonl"
    bench.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bfile = tmp_path / "baselines.json"
    bfile.write_text(json.dumps({"baselines": baselines}))
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "check_regression.py"),
         str(bench), "--baselines", str(bfile), *extra],
        capture_output=True, text=True,
    )
    return proc, bfile


BASE = {
    "m_ms": {"value": 1.0, "tol_rel": 0.2, "direction": "lower",
             "measured": "r2"},
    "m_tps": {"value": 100.0, "tol_rel": 0.2, "direction": "higher",
              "measured": "r2"},
}


def test_ok_and_missing_pass(tmp_path):
    proc, _ = _run(tmp_path, [{"metric": "m_ms", "value": 1.1}], BASE)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ok] m_ms" in proc.stdout
    assert "[missing] m_tps" in proc.stdout


def test_regression_fails_both_directions(tmp_path):
    proc, _ = _run(tmp_path, [
        {"metric": "m_ms", "value": 1.5},     # slower: regressed
        {"metric": "m_tps", "value": 70.0},   # fewer tok/s: regressed
    ], BASE)
    assert proc.returncode == 1
    assert proc.stdout.count("[regressed]") == 2


def test_zero_baseline_matches_and_regresses(tmp_path):
    """Round-14 fix: a ref == 0 baseline (decode_steady_recompiles,
    expected 0) must pass when the measurement is also 0 — the old
    unconditional inf ratio reported a perfect 0-vs-0 match as
    regressed — and any positive value must still fail the gate."""
    base = {"zero_count": {"value": 0, "tol_rel": 0.0,
                           "direction": "lower", "measured": "r14"}}
    proc, _ = _run(tmp_path, [{"metric": "zero_count", "value": 0}], base)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[ok] zero_count" in proc.stdout
    proc, _ = _run(tmp_path, [{"metric": "zero_count", "value": 1}], base)
    assert proc.returncode == 1
    assert "[regressed] zero_count" in proc.stdout


def test_committed_gate_catches_20pct_tokens_regression(tmp_path):
    """Round-4 verdict weak #2 / next #3: with the COMMITTED baseline
    table, a synthetic -20% injection on every tokens/s metric must
    fail the gate — the old tol_rel=0.3 let a 22% real regression pass."""
    table = json.loads((ROOT / "results" / "baselines.json").read_text())
    tps = {m: spec for m, spec in table["baselines"].items()
           if m.endswith("_tokens_per_s")}
    assert tps, "no tokens/s metrics under the gate?"
    assert all(spec["tol_rel"] <= 0.15 for spec in tps.values()), tps
    rows = [{"metric": m, "value": spec["value"] * 0.8}
            for m, spec in tps.items()]
    proc, _ = _run(tmp_path, rows, table["baselines"])
    assert proc.returncode == 1
    assert proc.stdout.count("[regressed]") == len(rows), proc.stdout


def test_update_ratchets_only_improvements(tmp_path):
    proc, bfile = _run(tmp_path, [
        {"metric": "m_ms", "value": 0.5},     # 2x faster: improved
        {"metric": "m_tps", "value": 95.0},   # within tol: ok
    ], BASE, extra=("--update", "--date", "r4"))
    assert proc.returncode == 0
    new = json.loads(bfile.read_text())["baselines"]
    assert new["m_ms"]["value"] == 0.5 and new["m_ms"]["measured"] == "r4"
    assert new["m_tps"]["value"] == 100.0  # untouched


def test_null_and_garbage_rows_ignored(tmp_path):
    proc, _ = _run(tmp_path, [
        {"metric": "m_ms", "value": None, "error": "relay down"},
    ], BASE)
    assert proc.returncode == 0
    assert "[missing] m_ms" in proc.stdout


def test_unknown_metric_surfaces(tmp_path):
    proc, _ = _run(tmp_path, [
        {"metric": "m_ms", "value": 1.0},
        {"metric": "renamed_metric_ms", "value": 9.9},
    ], BASE)
    assert proc.returncode == 0
    assert "[unknown] renamed_metric_ms" in proc.stdout


def test_update_requires_date(tmp_path):
    proc, _ = _run(tmp_path, [{"metric": "m_ms", "value": 0.5}], BASE,
                   extra=("--update",))
    assert proc.returncode == 2
    assert "--date" in proc.stderr


def test_unusable_inputs_exit_2(tmp_path):
    import subprocess
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "check_regression.py"),
         str(tmp_path / "absent.jsonl")],
        capture_output=True, text=True)
    assert proc.returncode == 2, proc.stderr
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    bench = tmp_path / "b.jsonl"
    bench.write_text('{"metric": "m_ms", "value": 1.0}\n')
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "check_regression.py"),
         str(bench), "--baselines", str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 2, proc.stderr


def test_update_refuses_worse_direction_without_note(tmp_path):
    """VERDICT round-5 #6: --update must never move a baseline in the
    worse direction unless the update carries explicit provenance."""
    proc, bfile = _run(tmp_path, [{"metric": "m_ms", "value": 1.5}], BASE,
                       extra=("--update", "--date", "r6"))
    assert proc.returncode == 1
    assert "NOT ratcheting" in proc.stderr
    new = json.loads(bfile.read_text())["baselines"]
    assert new["m_ms"]["value"] == 1.0  # untouched
    assert "regression_accepted" not in new["m_ms"]


def test_accept_regression_moves_baseline_with_provenance(tmp_path):
    """With --accept-regression NOTE the regressed entry moves AND
    records the note; co-improving metrics ratchet in the same pass."""
    proc, bfile = _run(tmp_path, [
        {"metric": "m_ms", "value": 1.5},     # regressed: accepted
        {"metric": "m_tps", "value": 140.0},  # improved: ratchets
    ], BASE, extra=("--update", "--date", "r6",
                    "--accept-regression", "relay rebuilt, new floor"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    new = json.loads(bfile.read_text())["baselines"]
    assert new["m_ms"]["value"] == 1.5
    assert new["m_ms"]["measured"] == "r6"
    assert new["m_ms"]["regression_accepted"] == "relay rebuilt, new floor"
    assert new["m_tps"]["value"] == 140.0
    assert "regression_accepted" not in new["m_tps"]


def test_later_improvement_clears_accepted_note(tmp_path):
    """A clean ratchet supersedes an earlier accepted regression: the
    stale regression_accepted note must not survive onto the improved
    value (false provenance)."""
    base = {"m_ms": {"value": 1.5, "tol_rel": 0.2, "direction": "lower",
                     "measured": "r6", "regression_accepted": "relay"}}
    proc, bfile = _run(tmp_path, [{"metric": "m_ms", "value": 0.9}], base,
                       extra=("--update", "--date", "r7"))
    assert proc.returncode == 0
    new = json.loads(bfile.read_text())["baselines"]
    assert new["m_ms"]["value"] == 0.9 and new["m_ms"]["measured"] == "r7"
    assert "regression_accepted" not in new["m_ms"]


def test_accept_regression_requires_update(tmp_path):
    proc, _ = _run(tmp_path, [{"metric": "m_ms", "value": 1.5}], BASE,
                   extra=("--accept-regression", "note"))
    assert proc.returncode == 2
    assert "--update" in proc.stderr


def test_update_refuses_on_mixed_run(tmp_path):
    proc, bfile = _run(tmp_path, [
        {"metric": "m_ms", "value": 0.5},    # improved
        {"metric": "m_tps", "value": 10.0},  # regressed
    ], BASE, extra=("--update", "--date", "r4"))
    assert proc.returncode == 1
    assert "NOT ratcheting" in proc.stderr
    new = json.loads(bfile.read_text())["baselines"]
    assert new["m_ms"]["value"] == 1.0  # untouched
