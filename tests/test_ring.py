"""Sequence-parallel attention tests: ring + Ulysses vs dense oracle.

Runs on the 8-virtual-device CPU mesh (conftest).  The oracle is plain
dense softmax attention in f32 NumPy — independent of the JAX paths.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpulab.parallel.mesh import cpu_test_mesh
from tpulab.parallel.ring import attention_reference, ring_attention, ulysses_attention


def oracle(q, k, v, causal=True):
    b, s, h, d = q.shape
    out = np.empty_like(q, dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            s_mat = (q[bi, :, hi] / np.sqrt(d)) @ k[bi, :, hi].T  # (s, s)
            if causal:
                mask = np.tril(np.ones((s, s), bool))
                s_mat = np.where(mask, s_mat, -1e30)
            s_mat = s_mat - s_mat.max(axis=-1, keepdims=True)
            p = np.exp(s_mat)
            p /= p.sum(axis=-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi]
    return out


@pytest.fixture(scope="module")
def mesh_sp():
    return cpu_test_mesh({"sp": 8})


def _qkv(rng, b=2, s=64, h=8, d=16):
    shape = (b, s, h, d)
    return (
        rng.standard_normal(shape).astype(np.float32),
        rng.standard_normal(shape).astype(np.float32),
        rng.standard_normal(shape).astype(np.float32),
    )


class TestReference:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_numpy_oracle(self, rng, causal):
        q, k, v = _qkv(rng)
        got = np.asarray(attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(got, oracle(q, k, v, causal), rtol=1e-5, atol=1e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_oracle(self, mesh_sp, rng, causal):
        q, k, v = _qkv(rng)
        got = np.asarray(ring_attention(q, k, v, mesh=mesh_sp, causal=causal))
        np.testing.assert_allclose(got, oracle(q, k, v, causal), rtol=1e-4, atol=1e-5)

    def test_long_sequence(self, mesh_sp, rng):
        q, k, v = _qkv(rng, b=1, s=512, h=2, d=8)
        got = np.asarray(ring_attention(q, k, v, mesh=mesh_sp))
        np.testing.assert_allclose(got, oracle(q, k, v), rtol=1e-4, atol=1e-5)

    def test_seq_not_divisible_raises(self, mesh_sp, rng):
        q, k, v = _qkv(rng, s=30)
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, k, v, mesh=mesh_sp)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_oracle(self, mesh_sp, rng, causal):
        q, k, v = _qkv(rng)
        got = np.asarray(ulysses_attention(q, k, v, mesh=mesh_sp, causal=causal))
        np.testing.assert_allclose(got, oracle(q, k, v, causal), rtol=1e-4, atol=1e-5)

    def test_heads_not_divisible_raises(self, mesh_sp, rng):
        q, k, v = _qkv(rng, h=6)
        with pytest.raises(ValueError, match="heads"):
            ulysses_attention(q, k, v, mesh=mesh_sp)

    def test_ring_and_ulysses_agree(self, mesh_sp, rng):
        q, k, v = _qkv(rng, b=1, s=128, h=8, d=32)
        a = np.asarray(ring_attention(q, k, v, mesh=mesh_sp))
        b = np.asarray(ulysses_attention(q, k, v, mesh=mesh_sp))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestUlyssesFlashLocal:
    """Ulysses with the Pallas flash kernel as the per-head-group local
    attention: O(seq) memory on the gathered sequence, trainable via the
    kernel's custom_vjp."""

    def test_matches_dense_local(self, mesh_sp, rng):
        q, k, v = _qkv(rng, s=64)
        a = np.asarray(ulysses_attention(q, k, v, mesh=mesh_sp, local_impl="flash"))
        b = np.asarray(ulysses_attention(q, k, v, mesh=mesh_sp, local_impl="dense"))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_gradient_flows_through_flash_local(self, mesh_sp, rng):
        import jax
        import jax.numpy as jnp

        q, k, v = _qkv(rng, s=64)

        def loss(q):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh=mesh_sp, local_impl="flash") ** 2
            )

        g = np.asarray(jax.grad(loss)(jnp.asarray(q)))
        def dense_loss(q):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh=mesh_sp, local_impl="dense") ** 2
            )
        gd = np.asarray(jax.grad(dense_loss)(jnp.asarray(q)))
        np.testing.assert_allclose(g, gd, rtol=2e-4, atol=2e-4)


class TestRingFlashLocal:
    """Ring attention with flash as the per-step block attention:
    O(seq/p * d) memory per device, (o, lse) partials merged across the
    ring, trainable through the kernel's custom_vjp."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_oracle(self, mesh_sp, rng, causal):
        q, k, v = _qkv(rng)
        got = np.asarray(
            ring_attention(q, k, v, mesh=mesh_sp, causal=causal, local_impl="flash")
        )
        np.testing.assert_allclose(got, oracle(q, k, v, causal), rtol=1e-4, atol=1e-5)

    def test_grads_match_dense_ring(self, mesh_sp, rng):
        import jax
        import jax.numpy as jnp

        q, k, v = _qkv(rng)

        def loss(impl):
            return lambda q: jnp.sum(
                ring_attention(q, k, v, mesh=mesh_sp, local_impl=impl) ** 2
            )

        gf = np.asarray(jax.grad(loss("flash"))(jnp.asarray(q)))
        gd = np.asarray(jax.grad(loss("dense"))(jnp.asarray(q)))
        np.testing.assert_allclose(gf, gd, rtol=2e-4, atol=2e-4)


class TestWindowedRing:
    """Sliding-window ring attention (causal): the flash path unrolls
    only the live rotations (comm and compute O(window)); the dense path
    masks by global position.  Oracle: the windowed dense reference."""

    # windows chosen to exercise: within one shard (5 < 8), exactly at
    # the shard edge (8), spanning two shards (13), spanning most of the
    # ring (40), covering everything (64 == seq), and the self-only
    # degenerate window (1)
    @pytest.mark.parametrize("window", [1, 5, 8, 13, 40, 64])
    @pytest.mark.parametrize("impl", ["dense", "flash"])
    def test_matches_windowed_reference(self, mesh_sp, rng, window, impl):
        q, k, v = _qkv(rng)
        want = np.asarray(attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
            window=window))
        got = np.asarray(ring_attention(
            q, k, v, mesh=mesh_sp, causal=True, local_impl=impl,
            window=window))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                   err_msg=f"{impl} w={window}")

    def test_flash_grads_match_dense(self, mesh_sp, rng):
        import jax

        q, k, v = _qkv(rng)

        def loss(impl):
            return lambda q: jnp.sum(ring_attention(
                q, k, v, mesh=mesh_sp, local_impl=impl, window=13) ** 2)

        gf = np.asarray(jax.grad(loss("flash"))(jnp.asarray(q)))
        gd = np.asarray(jax.grad(loss("dense"))(jnp.asarray(q)))
        np.testing.assert_allclose(gf, gd, rtol=2e-4, atol=2e-4)

    def test_window_requires_causal(self, mesh_sp, rng):
        q, k, v = _qkv(rng)
        with pytest.raises(NotImplementedError, match="causal"):
            ring_attention(q, k, v, mesh=mesh_sp, causal=False, window=4)

    def test_live_rotation_count(self):
        """The shared dense/flash rotation bound: step t's nearest
        (q, k) pair is (t-1)*shard + 1 apart — brute-force cross-check."""
        from tpulab.parallel.ring import n_live_rotations

        for shard in (1, 4, 8):
            for p in (2, 4, 8):
                for window in (0, 1, 2, shard, shard + 1, 3 * shard, 10**6):
                    if not window:
                        continue  # windowless rings use n_steps = p
                    # true brute force: enumerate every (i, j) pair of
                    # every visiting step against the ring bodies' mask
                    # condition (keep iff 0 <= reach < window); a step
                    # is live iff ANY pair survives
                    live = [
                        t for t in range(1, p)
                        if any(0 <= t * shard + i - j < window
                               for i in range(shard) for j in range(shard))
                    ]
                    # liveness is contiguous from t=1, so count == max t
                    assert live == list(range(1, len(live) + 1))
                    assert n_live_rotations(window, shard, p) == len(live), (
                        window, shard, p, live)

    def test_matches_windowed_ulysses(self, mesh_sp, rng):
        """The two windowed sp paths must agree with each other too."""
        q, k, v = _qkv(rng)
        got_r = np.asarray(ring_attention(q, k, v, mesh=mesh_sp,
                                          window=11, local_impl="flash"))
        got_u = np.asarray(ulysses_attention(q, k, v, mesh=mesh_sp,
                                             window=11))
        np.testing.assert_allclose(got_r, got_u, rtol=1e-4, atol=1e-5)


class TestZigzagRing:
    """Load-balanced causal ring: zigzag layout (device i owns sequence
    half-blocks i and 2p-1-i) equalizes causal work per device and skips
    dead (q, k) pairs instead of masking them.  Exactness vs the same
    independent NumPy oracle as the plain ring."""

    def test_matches_oracle(self, mesh_sp, rng):
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng)
        got = np.asarray(zigzag_ring_attention(q, k, v, mesh=mesh_sp))
        np.testing.assert_allclose(got, oracle(q, k, v, True), rtol=1e-4, atol=1e-5)

    def test_matches_plain_ring(self, mesh_sp, rng):
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng, s=48, h=4, d=8)  # seq 48 = 2*8 * 3: non-power-of-2 halves
        zz = np.asarray(zigzag_ring_attention(q, k, v, mesh=mesh_sp))
        pr = np.asarray(ring_attention(q, k, v, mesh=mesh_sp, causal=True))
        np.testing.assert_allclose(zz, pr, rtol=1e-4, atol=1e-5)

    def test_small_mesh_and_single_device(self, rng):
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng, s=32)
        for p in (1, 2, 4):
            mesh = cpu_test_mesh({"sp": p})
            got = np.asarray(zigzag_ring_attention(q, k, v, mesh=mesh))
            np.testing.assert_allclose(
                got, oracle(q, k, v, True), rtol=1e-4, atol=1e-5,
                err_msg=f"p={p}")

    def test_grads_match_plain_ring(self, mesh_sp, rng):
        import jax
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng, s=32, h=4, d=8)

        def loss(fn):
            return lambda q: jnp.sum(fn(q) ** 2)

        gz = np.asarray(jax.grad(loss(
            lambda q: zigzag_ring_attention(q, k, v, mesh=mesh_sp)))(jnp.asarray(q)))
        gr = np.asarray(jax.grad(loss(
            lambda q: ring_attention(q, k, v, mesh=mesh_sp, causal=True)))(jnp.asarray(q)))
        np.testing.assert_allclose(gz, gr, rtol=2e-4, atol=2e-4)

    def test_seq_not_divisible_raises(self, mesh_sp, rng):
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng, s=40)  # 40 % 16 != 0
        with pytest.raises(ValueError, match="zigzag"):
            zigzag_ring_attention(q, k, v, mesh=mesh_sp)


class TestZigzagFlashLocal:
    """Zigzag with flash local attends: every block pair decomposes into
    equal-length (hl x hl) flash calls whose (o, lse) partials merge via
    logaddexp — O(seq/p * d) memory with the zigzag balance."""

    def test_matches_oracle(self, mesh_sp, rng):
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng)
        got = np.asarray(
            zigzag_ring_attention(q, k, v, mesh=mesh_sp, local_impl="flash"))
        np.testing.assert_allclose(got, oracle(q, k, v, True), rtol=1e-4, atol=1e-5)

    def test_grads_match_dense_zigzag(self, mesh_sp, rng):
        import jax
        from tpulab.parallel.ring import zigzag_ring_attention

        q, k, v = _qkv(rng, s=32, h=4, d=8)

        def loss(impl):
            return lambda q: jnp.sum(
                zigzag_ring_attention(q, k, v, mesh=mesh_sp, local_impl=impl) ** 2)

        gf = np.asarray(jax.grad(loss("flash"))(jnp.asarray(q)))
        gd = np.asarray(jax.grad(loss("dense"))(jnp.asarray(q)))
        np.testing.assert_allclose(gf, gd, rtol=2e-4, atol=2e-4)
