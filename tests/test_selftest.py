"""`tpulab selftest` — the one-minute user-facing sanity command."""

from tpulab.selftest import main


def test_selftest_passes(capsys):
    # the heavy tiers (train, serving) have their own suites — skipping
    # them keeps this a wiring/kernel check, not a duplicate
    rc = main(["--skip", "train", "--skip", "serving"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("pass") == 4 and out.count("SKIP") == 2
    assert "OK (4/4 run, 2 skipped)" in out


def test_selftest_reports_failure(capsys, monkeypatch):
    import tpulab.selftest as st

    def boom():
        raise AssertionError("synthetic")

    monkeypatch.setattr(
        st, "CHECKS", [("ok", lambda: None), ("bad", boom)])
    rc = main([])
    out = capsys.readouterr().out
    assert rc == 1 and "FAIL  bad" in out and "FAILED (1/2 run)" in out
