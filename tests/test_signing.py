"""Detached-signature workflow (tools/sign_artifacts.py) — the analog of
the reference's GPG-signed submissions (reference README.md:17-21,
hw1/src/main.c.asc): sign writes armored detached signatures + the
public key; verify succeeds from a FRESH keyring holding only the
committed pubkey; tampering any signed byte fails verification."""

import pathlib
import shutil
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("gpg") is None,
                                reason="gpg not installed")


def _run(cmd, root):
    return subprocess.run(
        [sys.executable, str(ROOT / "tools" / "sign_artifacts.py"),
         cmd, "--root", str(root)],
        capture_output=True, text=True,
    )


@pytest.fixture(scope="module")
def signed_tree(tmp_path_factory):
    """A miniature repo tree with two manifest entries present."""
    root = tmp_path_factory.mktemp("signroot")
    (root / "results").mkdir()
    (root / "results" / "baselines.json").write_text('{"baselines": {}}\n')
    (root / "tpulab" / "ops" / "pallas").mkdir(parents=True)
    (root / "tpulab" / "ops" / "pallas" / "attention.py").write_text(
        "def f():\n    return 1\n")
    r = _run("sign", root)
    assert r.returncode == 0, r.stdout + r.stderr
    return root


def test_sign_emits_armored_sigs_and_pubkey(signed_tree):
    sig_dir = signed_tree / "results" / "signing"
    pub = (sig_dir / "pubkey.asc").read_text()
    assert "BEGIN PGP PUBLIC KEY BLOCK" in pub
    sig = (sig_dir / "results__baselines.json.asc").read_text()
    assert "BEGIN PGP SIGNATURE" in sig
    # absent manifest entries are skipped, not failed
    assert not (sig_dir / "bench.py.asc").exists()
    # the PRIVATE key never leaves the gitignored homedir
    assert (signed_tree / ".gnupg").exists()
    assert "PRIVATE KEY" not in pub


def test_verify_from_pubkey_only(signed_tree):
    r = _run("verify", signed_tree)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 failed" in r.stdout


def test_tampering_fails_verification(signed_tree, tmp_path):
    tampered = tmp_path / "copy"
    # verify needs only the tree + signatures — NOT .gnupg (whose
    # gpg-agent sockets break copytree, and whose absence is the point:
    # a third party never has the signer's homedir)
    shutil.copytree(signed_tree, tampered,
                    ignore=shutil.ignore_patterns(".gnupg"))
    f = tampered / "results" / "baselines.json"
    f.write_text(f.read_text() + " ")
    r = _run("verify", tampered)
    assert r.returncode == 1
    assert "BAD SIGNATURE" in r.stderr


def test_deleted_signature_fails_verification(signed_tree, tmp_path):
    """Tamper-by-deletion: stripping a file's .asc (or all of them) must
    fail — a present manifest file with no signature is never a skip."""
    tampered = tmp_path / "copy"
    shutil.copytree(signed_tree, tampered,
                    ignore=shutil.ignore_patterns(".gnupg"))
    (tampered / "results" / "signing" / "results__baselines.json.asc").unlink()
    r = _run("verify", tampered)
    assert r.returncode == 1
    assert "MISSING SIGNATURE" in r.stderr
    # stripping everything is a vacuous (= failed) verification
    for p in (tampered / "results" / "signing").glob("*.asc"):
        if p.name != "pubkey.asc":
            p.unlink()
    r2 = _run("verify", tampered)
    assert r2.returncode == 1


def test_committed_signatures_verify():
    """The signatures committed in THIS repo must verify for a third
    party holding only the tree (skips until the first sign run)."""
    if not (ROOT / "results" / "signing" / "pubkey.asc").exists():
        pytest.skip("repo not yet signed")
    r = _run("verify", ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
