"""Speculative decoding (tpulab.models.speculative).

The greedy variant is LOSSLESS: output must be bit-identical to the
target model decoding alone, for any draft — a perfect draft (the
target itself), a quantized draft, or an adversarial one (different
random init).  Plus the windowed-forward machinery it rides on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.generate import (
    _forward_step,
    _forward_window,
    generate,
    init_kv_cache,
)
from tpulab.models.labformer import LabformerConfig, init_params
from tpulab.models.speculative import speculative_generate

CFG = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=128)


def _prompt(rng, b=2, p=5):
    return rng.integers(0, CFG.vocab, (b, p)).astype(np.int32)


@pytest.fixture(scope="module")
def trained(trained_small, trained_small_cfg):
    assert CFG == trained_small_cfg  # shared-model drift fails loudly
    return trained_small


class TestForwardWindow:
    def test_window_matches_sequential_steps(self, rng):
        """One (b, w) window pass == w sequential single-token steps."""
        params = init_params(CFG, seed=0)
        toks = rng.integers(0, CFG.vocab, (2, 4)).astype(np.int32)
        kc, vc = init_kv_cache(CFG, batch=2, max_seq=32)
        win_logits, _, _ = _forward_window(
            params, jnp.asarray(toks), kc, vc, 0, CFG
        )
        kc2, vc2 = init_kv_cache(CFG, batch=2, max_seq=32)
        for i in range(4):
            step_logits, kc2, vc2 = _forward_step(
                params, jnp.asarray(toks[:, i]), kc2, vc2, i, CFG
            )
            assert np.allclose(
                np.asarray(win_logits[:, i]), np.asarray(step_logits),
                atol=1e-5,
            ), i

    def test_stale_cache_is_masked(self, rng):
        """KV garbage past the window must not influence the output —
        the no-rollback invariant of speculative decode."""
        params = init_params(CFG, seed=0)
        toks = rng.integers(0, CFG.vocab, (1, 3)).astype(np.int32)
        kc, vc = init_kv_cache(CFG, batch=1, max_seq=32)
        clean, _, _ = _forward_window(params, jnp.asarray(toks), kc, vc, 0, CFG)
        dirty_k = kc.at[:, :, 10:].set(99.0)
        dirty_v = vc.at[:, :, 10:].set(-7.0)
        dirty, _, _ = _forward_window(
            params, jnp.asarray(toks), dirty_k, dirty_v, 0, CFG
        )
        assert np.array_equal(np.asarray(clean), np.asarray(dirty))


class TestSpeculative:
    def test_perfect_draft_accepts_everything(self, trained):
        params = trained
        prompt = np.tile(np.arange(5, dtype=np.int32) % 7, (2, 1))
        toks, acc = speculative_generate(
            params, CFG, params, CFG, prompt, steps=12, k=4
        )
        want = generate(params, prompt, CFG, steps=12, temperature=0.0)
        assert np.array_equal(toks, want)
        assert acc == 4.0  # a sharp target always agrees with itself

    def test_adversarial_draft_still_lossless(self, rng):
        target = init_params(CFG, seed=0)
        draft = init_params(CFG, seed=99)  # unrelated model
        prompt = _prompt(rng)
        toks, acc = speculative_generate(
            draft, CFG, target, CFG, prompt, steps=12, k=4
        )
        want = generate(target, prompt, CFG, steps=12, temperature=0.0)
        assert np.array_equal(toks, want)
        assert 0.0 <= acc <= 4.0

    def test_quantized_draft_lossless_and_accepting(self, trained):
        from tpulab.models.quant import quantize_decode_params

        target = trained
        draft = quantize_decode_params(target, CFG)
        prompt = np.tile(np.arange(5, dtype=np.int32) % 7, (1, 1))
        toks, acc = speculative_generate(
            draft, CFG, target, CFG, prompt, steps=16, k=4
        )
        want = generate(target, prompt, CFG, steps=16, temperature=0.0)
        assert np.array_equal(toks, want)
        # int8 of the same sharp weights should agree most of the time
        assert acc > 2.0, acc

    def test_smaller_draft_model(self, rng):
        """A draft with a different architecture (fewer layers) — only
        the vocab must match."""
        target = init_params(CFG, seed=0)
        small = LabformerConfig(
            d_model=16, n_heads=2, n_layers=1, d_ff=32, max_seq=128
        )
        draft = init_params(small, seed=0)
        prompt = _prompt(rng)
        toks, _ = speculative_generate(
            draft, small, target, CFG, prompt, steps=10, k=3
        )
        want = generate(target, prompt, CFG, steps=10, temperature=0.0)
        assert np.array_equal(toks, want)

    def test_vocab_mismatch_rejected(self):
        a = LabformerConfig(vocab=128, d_model=16, n_heads=2, n_layers=1,
                            d_ff=32)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(
                init_params(a), a, init_params(CFG), CFG,
                np.zeros((1, 3), np.int32), steps=4,
            )


class TestPromptLookup:
    """Draft-free speculation: n-gram proposals from the committed
    sequence, verified through the same windowed target pass."""

    def test_lossless_vs_plain_greedy(self, trained_small,
                                      trained_small_cfg):
        from tpulab.models.generate import generate
        from tpulab.models.speculative import prompt_lookup_generate

        # period-7 cycle — the exact pattern trained_small was trained
        # on, so the continuation repeats it and lookups extend right
        prompt = np.tile(np.arange(7, dtype=np.int32), 3)[None, :]
        want = generate(trained_small, prompt, trained_small_cfg,
                        steps=24, temperature=0.0)
        got, acc = prompt_lookup_generate(trained_small, trained_small_cfg,
                                          prompt, steps=24, k=4)
        assert np.array_equal(got, np.asarray(want))
        assert acc > 1.0, acc

    def test_lossless_on_nonrepetitive_prompt(self, trained_small,
                                              trained_small_cfg):
        from tpulab.models.generate import generate
        from tpulab.models.speculative import prompt_lookup_generate

        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 7, (1, 11)).astype(np.int32)
        want = generate(trained_small, prompt, trained_small_cfg,
                        steps=12, temperature=0.0)
        got, acc = prompt_lookup_generate(trained_small, trained_small_cfg,
                                          prompt, steps=12, k=3, ngram=4)
        assert np.array_equal(got, np.asarray(want))  # acc may be ~0

    def test_ngram_validation(self, trained_small, trained_small_cfg):
        from tpulab.models.speculative import prompt_lookup_generate

        with pytest.raises(ValueError, match="ngram"):
            prompt_lookup_generate(trained_small, trained_small_cfg,
                                   np.zeros((1, 4), np.int32), ngram=0)

    def test_lookup_propose_semantics(self):
        from tpulab.models.speculative import _lookup_propose

        hist = np.array([1, 2, 3, 9, 9, 1, 2, 3], np.int32)
        # last 3 = [1,2,3]; earlier match at 0 -> continuation [9, 9, 1]
        got = _lookup_propose(hist, k=3, ngram=3)
        assert got.tolist() == [9, 9, 1]
        # no match -> repeat last token
        got = _lookup_propose(np.array([1, 2, 3, 4], np.int32), 2, 3)
        assert got.tolist() == [4, 4]
