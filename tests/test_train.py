"""Training-loop tests: loss progress, checkpoint/resume parity, failure
detection, tracing, and mesh training.  CPU backend (conftest)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpulab.models.labformer import LabformerConfig
from tpulab.train import batches, train

TINY = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=32)


def _quiet(*a, **k):
    pass


class TestLoop:
    def test_loss_decreases(self):
        _, first = train(steps=1, batch=4, seq=32, cfg=TINY, log=_quiet)
        _, last = train(steps=12, batch=4, seq=32, cfg=TINY, log=_quiet)
        assert last < first

    def test_deterministic_batches(self):
        b = batches(256, 4, 16, seed=7)
        np.testing.assert_array_equal(b(3), b(3))
        assert not np.array_equal(b(3), b(4))


class TestCheckpointResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        """save@10 -> resume -> 20 must equal straight-through 20."""
        d1 = str(tmp_path / "interrupted")
        train(steps=10, batch=4, seq=32, cfg=TINY, ckpt_dir=d1, save_every=10, log=_quiet)
        _, resumed = train(
            steps=20, batch=4, seq=32, cfg=TINY, ckpt_dir=d1, save_every=10,
            resume=True, log=_quiet,
        )
        _, straight = train(steps=20, batch=4, seq=32, cfg=TINY, log=_quiet)
        assert abs(resumed - straight) < 1e-5, (resumed, straight)

    def test_resume_refuses_changed_config(self, tmp_path):
        """Resuming with a flag that differs from the sidecar must fail
        loudly: the trainer would otherwise use the new value while
        serving reads the stale sidecar — silent train/serve divergence
        (round-4 advisor)."""
        d = str(tmp_path / "ck")
        train(steps=4, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=4,
              log=_quiet)
        # attn_window changes behavior but not param shapes — exactly
        # the divergence class the check exists for
        changed = LabformerConfig(d_model=32, n_heads=4, n_layers=2,
                                  d_ff=64, max_seq=32, attn_window=8)
        with pytest.raises(ValueError, match="resume config mismatch"):
            train(steps=8, batch=2, seq=32, cfg=changed, ckpt_dir=d,
                  save_every=4, resume=True, log=_quiet)
        # matching flags still resume fine
        train(steps=8, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=4,
              resume=True, log=_quiet)

    def test_resume_tolerates_pre_field_sidecar(self, tmp_path):
        """A sidecar recorded before a config field existed must keep
        resuming as long as this invocation leaves the field at its
        dataclass default; an explicit non-default value for the
        unrecorded field still refuses loudly (round-5 advisor: the
        old all-keys diff hard-failed every pre-field checkpoint
        forever)."""
        d = str(tmp_path / "ck")
        train(steps=4, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=4,
              log=_quiet)
        sc = os.path.join(d, "tpulab_config.json")
        with open(sc) as f:
            sidecar = json.load(f)
        sidecar["config"].pop("attn_window")  # pretend pre-window era
        with open(sc, "w") as f:
            json.dump(sidecar, f)
        # default attn_window == 0: the missing key matches
        train(steps=8, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=4,
              resume=True, log=_quiet)
        changed = LabformerConfig(d_model=32, n_heads=4, n_layers=2,
                                  d_ff=64, max_seq=32, attn_window=8)
        with pytest.raises(ValueError, match="not recorded"):
            train(steps=12, batch=2, seq=32, cfg=changed, ckpt_dir=d,
                  save_every=4, resume=True, log=_quiet)

    def test_fresh_run_clears_stale_dir(self, tmp_path):
        d = str(tmp_path / "ck")
        train(steps=5, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=5, log=_quiet)
        # non-resume run must not restore from the stale snapshot
        train(steps=5, batch=2, seq=32, cfg=TINY, ckpt_dir=d, save_every=5, log=_quiet)
        assert os.path.isdir(d)


class TestFailureDetection:
    def test_nonfinite_loss_raises(self):
        """A diverging run (lr=1e38 overflows f32 in a few steps) must
        fail fast with FloatingPointError — the CSC-macro analog."""
        import optax

        with pytest.raises(FloatingPointError, match="non-finite loss"):
            train(
                steps=8, batch=2, seq=32, cfg=TINY, log=_quiet,
                optimizer=optax.sgd(1e38),
            )


class TestTracing:
    def test_trace_dir_written(self, tmp_path):
        d = str(tmp_path / "trace")
        train(steps=2, batch=2, seq=32, cfg=TINY, trace_dir=d, log=_quiet)
        assert os.path.isdir(d) and any(os.scandir(d))

    def test_event_log(self, tmp_path):
        # canonical home since the round-14 fold (tpulab.obs.profiler);
        # the runtime.trace shim must keep re-exporting it unchanged
        from tpulab.obs import EventLog as ObsEventLog
        from tpulab.runtime.trace import EventLog

        assert EventLog is ObsEventLog

        p = str(tmp_path / "events.jsonl")
        log = EventLog(p, echo=False)
        log.event("Experiment", "run started", k_times=3)
        with log.timed("Kernel", "lab2"):
            pass
        log.close()
        lines = [json.loads(l) for l in open(p)]
        assert lines[0]["tag"] == "Experiment" and lines[0]["k_times"] == 3
        assert "elapsed_ms" in lines[1]


class TestMeshTraining:
    def test_train_on_8dev_mesh(self):
        _, loss = train(steps=3, batch=4, seq=32, cfg=TINY, mesh_devices=8, log=_quiet)
        assert np.isfinite(loss)

    def test_windowed_ring_train_matches_single_device(self):
        """The FULL train step through the windowed ring sp path (flash
        custom_vjp inside the unrolled O(window) rotation loop) must
        reproduce the single-device windowed loss trajectory — the
        topology changes the schedule, never the function."""
        import dataclasses

        wcfg = dataclasses.replace(TINY, attn_window=8, sp_impl="ring",
                                   attn_impl="flash")
        _, mesh_loss = train(steps=4, batch=4, seq=32, cfg=wcfg,
                             mesh_devices=8, log=_quiet)
        _, solo_loss = train(
            steps=4, batch=4, seq=32,
            cfg=dataclasses.replace(wcfg, attn_impl="dense"), log=_quiet)
        assert np.isfinite(mesh_loss)
        assert abs(mesh_loss - solo_loss) < 1e-4, (mesh_loss, solo_loss)


class TestMemoryLevers:
    def test_remat_matches_plain(self):
        """jax.checkpoint changes memory, not math: losses must agree."""
        import dataclasses

        _, plain = train(steps=4, batch=4, seq=32, cfg=TINY, log=_quiet)
        _, remat = train(
            steps=4, batch=4, seq=32,
            cfg=dataclasses.replace(TINY, remat=True), log=_quiet,
        )
        assert abs(plain - remat) < 1e-5, (plain, remat)

    def test_grad_accumulation_matches_full_batch(self):
        """accum=4 microbatches of 2 == one batch of 8 (mean CE over the
        same token set; adamw sees the averaged gradient)."""
        _, full = train(steps=4, batch=8, seq=32, cfg=TINY, log=_quiet)
        _, accum = train(steps=4, batch=8, seq=32, cfg=TINY, accum=4, log=_quiet)
        assert abs(full - accum) < 1e-4, (full, accum)


class TestCLI:
    def test_cli_smoke(self, tmp_path):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        r = subprocess.run(
            [sys.executable, "-m", "tpulab", "train", "--steps", "2", "--batch", "2",
             "--seq", "32"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["final_step"] == 2


class TestVisionModel:
    """The train driver runs the labvision family with the same
    checkpoint/resume machinery as the labformer."""

    _CFG = None

    @classmethod
    def _cfg(cls):
        from tpulab.models.labvision import LabvisionConfig

        if cls._CFG is None:
            cls._CFG = LabvisionConfig(n_classes=4, img_size=16, channels=(8, 16))
        return cls._CFG

    def test_loss_decreases(self):
        _, l20 = train(model="labvision", steps=20, batch=32, cfg=self._cfg(),
                       log=_quiet)
        _, l1 = train(model="labvision", steps=1, batch=32, cfg=self._cfg(),
                      log=_quiet)
        assert l20 < l1

    def test_resume_matches_uninterrupted(self, tmp_path):
        d = str(tmp_path / "vck")
        train(model="labvision", steps=4, batch=8, cfg=self._cfg(), ckpt_dir=d,
              save_every=4, log=_quiet)
        _, resumed = train(model="labvision", steps=8, batch=8, cfg=self._cfg(),
                           ckpt_dir=d, save_every=4, resume=True, log=_quiet)
        _, straight = train(model="labvision", steps=8, batch=8, cfg=self._cfg(),
                            log=_quiet)
        assert abs(resumed - straight) < 1e-5, (resumed, straight)

    def test_dp_mesh(self):
        _, loss = train(model="labvision", steps=2, batch=16, cfg=self._cfg(),
                        mesh_devices=8, log=_quiet)
        assert np.isfinite(loss)

    def test_unknown_model_raises(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="unknown model"):
            train(model="labaudio", steps=1, log=_quiet)


class TestEval:
    def test_eval_lines_logged_and_finite(self):
        lines = []
        train(steps=4, batch=2, seq=32, cfg=TINY, eval_every=2, eval_batches=2,
              log=lines.append)
        evals = [l for l in lines if l.startswith("[eval]")]
        assert len(evals) == 2, lines
        vals = [float(l.split()[-1]) for l in evals]
        assert all(np.isfinite(v) for v in vals)

    def test_eval_on_mesh(self):
        lines = []
        train(steps=2, batch=4, seq=32, cfg=TINY, mesh_devices=8, eval_every=2,
              eval_batches=1, log=lines.append)
        assert any(l.startswith("[eval]") for l in lines)

    def test_vision_eval(self):
        from tpulab.models.labvision import LabvisionConfig

        cfg = LabvisionConfig(n_classes=4, img_size=16, channels=(8, 16))
        lines = []
        train(model="labvision", steps=2, batch=8, cfg=cfg, eval_every=2,
              eval_batches=2, log=lines.append)
        evals = [l for l in lines if l.startswith("[eval]")]
        assert len(evals) == 1 and np.isfinite(float(evals[0].split()[-1]))


class TestOptimizerStack:
    def test_warmup_cosine_trains(self):
        _, loss = train(steps=6, batch=2, seq=32, cfg=TINY, lr=3e-4,
                        warmup_steps=2, schedule="cosine", clip_norm=1.0,
                        log=_quiet)
        assert np.isfinite(loss)

    def test_clip_norm_bounds_update(self):
        """With an absurdly tiny clip norm the params barely move."""
        import jax

        from tpulab.models.labformer import init_params, init_train_state
        from tpulab.train import build_optimizer

        opt = build_optimizer(lr=1.0, steps=5, clip_norm=1e-8)
        params, opt_state, step = init_train_state(TINY, None, seed=0,
                                                   optimizer=opt)
        before = np.asarray(jax.device_get(params["blocks"]["wq"])).copy()
        tok = np.random.default_rng(0).integers(0, 256, (2, 33)).astype(np.int32)
        params, opt_state, _ = step(params, opt_state, tok)
        after = np.asarray(jax.device_get(params["blocks"]["wq"]))
        # adamw normalizes per-param scale, but the clipped gradient is
        # ~1e-8 of its natural size -> second-moment ratios stay sane and
        # the single-step delta is tiny relative to lr=1.0
        assert np.abs(after - before).max() < 1.5

    def test_unknown_schedule_raises(self):
        from tpulab.train import build_optimizer

        import pytest as _pytest

        with _pytest.raises(ValueError, match="unknown schedule"):
            build_optimizer(lr=1e-3, steps=5, schedule="triangle")


class TestElasticResume:
    def test_mesh_checkpoint_restores_on_single_device(self, tmp_path):
        """A snapshot taken while training on the 8-device mesh must
        restore and continue on a single device (and vice versa) — the
        elastic-topology half of checkpoint/resume."""
        d = str(tmp_path / "elastic")
        train(steps=4, batch=4, seq=32, cfg=TINY, mesh_devices=8, ckpt_dir=d,
              save_every=4, log=_quiet)
        _, resumed_single = train(
            steps=8, batch=4, seq=32, cfg=TINY, mesh_devices=0, ckpt_dir=d,
            save_every=8, resume=True, log=_quiet,
        )
        _, straight = train(steps=8, batch=4, seq=32, cfg=TINY, log=_quiet)
        assert abs(resumed_single - straight) < 1e-4, (resumed_single, straight)

    def test_single_checkpoint_restores_on_mesh(self, tmp_path):
        d = str(tmp_path / "elastic2")
        train(steps=4, batch=4, seq=32, cfg=TINY, ckpt_dir=d, save_every=4,
              log=_quiet)
        _, resumed_mesh = train(
            steps=8, batch=4, seq=32, cfg=TINY, mesh_devices=8, ckpt_dir=d,
            save_every=8, resume=True, log=_quiet,
        )
        _, straight = train(steps=8, batch=4, seq=32, cfg=TINY, log=_quiet)
        assert abs(resumed_mesh - straight) < 1e-4, (resumed_mesh, straight)


class TestElasticRecovery:
    """--recover: roll back to the latest snapshot on a non-finite loss
    and continue (bounded budget); --inject-fault exercises it with a
    one-shot transient (SURVEY.md section 5.3's fault-injection tier)."""

    def test_injected_fault_recovers_bit_identical(self, tmp_path):
        """A transient fault at step 7 (snapshot at 5) must roll back,
        replay deterministically, and land EXACTLY where the fault-free
        run lands — rollback loses no information beyond the replay."""
        d = str(tmp_path / "rec")
        msgs = []
        _, recovered = train(
            steps=10, batch=4, seq=32, cfg=TINY, ckpt_dir=d, save_every=5,
            recover=2, inject_fault=(7,), log=lambda m: msgs.append(str(m)),
        )
        _, straight = train(steps=10, batch=4, seq=32, cfg=TINY, log=_quiet)
        assert any("[fault]" in m for m in msgs), msgs
        assert any("[recover]" in m and "snapshot 5" in m for m in msgs), msgs
        assert abs(recovered - straight) < 1e-6, (recovered, straight)

    def test_budget_exhaustion_fails_fast(self, tmp_path):
        """Faults at more steps than the budget covers must surface the
        original FloatingPointError, not loop forever."""
        d = str(tmp_path / "rec")
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            train(
                steps=10, batch=4, seq=32, cfg=TINY, ckpt_dir=d,
                save_every=5, recover=1, inject_fault=(6, 7), log=_quiet,
            )

    def test_fault_before_any_snapshot_fails_fast(self, tmp_path):
        """No snapshot to roll back to -> the pre-recovery contract."""
        d = str(tmp_path / "rec")
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            train(
                steps=10, batch=4, seq=32, cfg=TINY, ckpt_dir=d,
                save_every=50, recover=3, inject_fault=(2,), log=_quiet,
            )

    def test_recover_requires_ckpt_dir(self):
        with pytest.raises(ValueError, match="recover"):
            train(steps=2, batch=2, seq=32, cfg=TINY, recover=1, log=_quiet)


class TestRematPolicy:
    def test_dots_policy_matches_full_remat_loss(self):
        """remat_policy only changes WHAT the backward recomputes, never
        the math: losses agree bitwise-ish across none/dots/no-remat."""
        import dataclasses

        import jax.numpy as jnp

        from tpulab.models.labformer import LabformerConfig, init_train_state

        base = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                               max_seq=64)
        toks = np.tile(np.arange(33, dtype=np.int32) % 7, (2, 1))
        losses = {}
        for name, kw in (("plain", {}),
                         ("remat", dict(remat=True)),
                         ("dots", dict(remat=True, remat_policy="dots"))):
            cfg = dataclasses.replace(base, **kw)
            p, o, step = init_train_state(cfg, mesh=None, seed=0)
            for _ in range(3):
                p, o, loss = step(p, o, jnp.asarray(toks))
            losses[name] = float(loss)
        assert np.isclose(losses["plain"], losses["remat"], atol=1e-5)
        assert np.isclose(losses["plain"], losses["dots"], atol=1e-5)

    def test_dots_policy_actually_applies(self, monkeypatch):
        """The 'dots' knob must reach jax.checkpoint as the saveable
        policy — losses are equal across policies by design, so only
        the call itself can pin that the branch works."""
        import jax

        from tpulab.models.labformer import LabformerConfig, forward, init_params

        seen = []
        real = jax.checkpoint

        def spy(fn, *a, **kw):
            seen.append(kw.get("policy"))
            return real(fn, *a, **kw)

        monkeypatch.setattr(jax, "checkpoint", spy)
        toks = np.zeros((1, 9), np.int32)
        for kw, want in ((dict(remat=True), None),
                         (dict(remat=True, remat_policy="dots"),
                          jax.checkpoint_policies.dots_with_no_batch_dims_saveable)):
            seen.clear()
            cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                                  max_seq=64, **kw)
            forward(init_params(cfg, seed=0), toks, cfg)
            assert seen and seen[0] is want, (kw, seen)

    def test_policy_validated(self):
        import pytest as _pytest

        from tpulab.models.labformer import LabformerConfig

        with _pytest.raises(ValueError, match="remat_policy"):
            LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                            max_seq=64, remat=True,
                            remat_policy="everything")
        # a policy without remat would silently do nothing: refused
        with _pytest.raises(ValueError, match="requires remat"):
            LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64,
                            max_seq=64, remat_policy="dots")
