"""Device-resident training step: donated state, K-step fused dispatch,
one-step-async loop (tpulab/train.py + the labformer step builders).

Headline properties (the training analog of tests/test_paged_overlap.py):
  * the (step, loss) trajectory is BIT-IDENTICAL across overlap on/off
    and steps_per_call K in {1, 4}, for the synthetic stream, the
    native-loader corpus stream, and the LoRA finetune path;
  * steady-state steps perform ZERO implicit host<->device transfers
    (``jax.transfer_guard("disallow")``; the batch upload is an
    EXPLICIT device_put) and the live-buffer count stays flat — the
    donated step aliases params/opt_state instead of copying;
  * re-using a donated params/opt_state tree raises (the donation
    tripwire);
  * ``--inject-fault`` + ``--recover`` rollback replays bit-identically
    under the async window: late NaN detection discards the in-flight
    block and lands on the same final params as a fault-free run;
  * ``--log-every`` thins [train] lines while preserving exact
    step/loss pairing from the delayed drain, and the batched eval
    fetch reports bit-identical val_loss.
"""

import re

import jax
import numpy as np
import pytest

from tpulab.models.labformer import LabformerConfig, init_train_state
from tpulab.train import batches, device_resident, train

TINY = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=32)


def _run(**kw):
    lines = []
    kw.setdefault("batch", 4)
    kw.setdefault("seq", 32)
    kw.setdefault("cfg", TINY)
    _, loss = train(log=lines.append, **kw)
    return lines, loss


def _pairs(lines):
    """Exact (step, loss-string) pairing of the emitted [train] lines."""
    out = []
    for l in lines:
        m = re.match(r"\[train\] step (\d+) loss (\S+) ", l)
        if m:
            out.append((int(m.group(1)), m.group(2)))
    return out


def _counters(lines):
    for l in lines:
        if l.startswith("[train] counters"):
            return dict(kv.split("=") for kv in l.split()[2:])
    raise AssertionError(f"no counters line in {lines}")


class TestTrajectoryBitIdentical:
    def test_synthetic_overlap_and_k(self):
        """ISSUE acceptance: overlap on/off x K in {1, 4} all reproduce
        the synchronous K=1 trajectory — same (step, loss) lines, same
        final loss bit for bit.  steps=9 exercises the K=4 remainder
        (two fused blocks + a K=1 tail)."""
        base_lines, base_loss = _run(steps=9, overlap=0)
        for kw in (dict(overlap=1),
                   dict(overlap=1, steps_per_call=4),
                   dict(overlap=0, steps_per_call=4)):
            lines, loss = _run(steps=9, **kw)
            assert _pairs(lines) == _pairs(base_lines), kw
            assert loss == base_loss, kw

    def test_step_k_bit_identical_machinery(self):
        """The fused K-step program IS the single step scanned: per-step
        losses and the advanced params agree bit for bit with K
        sequential calls of the donated 1-step program."""
        batch_at = batches(TINY.vocab, 4, 32, seed=3)
        toks = np.stack([batch_at(i) for i in range(8)])

        p1, o1, step = init_train_state(TINY, None, seed=0, donate=True)
        p1, o1 = device_resident(p1), device_resident(o1)
        seq_losses = []
        for i in range(8):
            p1, o1, l = step(p1, o1, jax.device_put(toks[i]))
            seq_losses.append(float(jax.device_get(l)))

        p2, o2, step2 = init_train_state(TINY, None, seed=0, donate=True)
        p2, o2 = device_resident(p2), device_resident(o2)
        k_losses = []
        for i in (0, 4):
            p2, o2, ls = step2.step_k(p2, o2, jax.device_put(toks[i:i + 4]))
            k_losses.extend(np.asarray(jax.device_get(ls)).tolist())

        assert k_losses == seq_losses
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p1)),
                        jax.tree_util.tree_leaves(jax.device_get(p2))):
            assert np.array_equal(a, b)

    def test_corpus_overlap_and_k(self, tmp_path):
        """The native-loader corpus stream (strictly sequential cursor)
        survives K-blocking and the async window: identical windows in
        identical order, bit-identical trajectory."""
        d = tmp_path / "corpus"
        d.mkdir()
        rng = np.random.default_rng(0)
        for i in range(2):
            (d / f"f{i}.bin").write_bytes(rng.integers(
                0, 256, 4096, dtype=np.uint8).tobytes())
        base_lines, base_loss = _run(steps=8, overlap=0,
                                     data_dir=str(d))
        for kw in (dict(overlap=1),
                   dict(overlap=1, steps_per_call=4)):
            lines, loss = _run(steps=8, data_dir=str(d), **kw)
            assert _pairs(lines) == _pairs(base_lines), kw
            assert loss == base_loss, kw

    def test_lora_overlap_and_k(self):
        """The LoRA finetune step (adapter-only grads, donated base
        pass-through) holds the same bit-identity bar."""
        base_lines, base_loss = _run(steps=9, overlap=0, lora_rank=2)
        for kw in (dict(overlap=1),
                   dict(overlap=1, steps_per_call=4)):
            lines, loss = _run(steps=9, lora_rank=2, **kw)
            assert _pairs(lines) == _pairs(base_lines), kw
            assert loss == base_loss, kw

    def test_vision_overlap(self):
        """The labvision family shares the donated async loop (K stays
        1 — token-block fusion is labformer-only)."""
        from tpulab.models.labvision import LabvisionConfig

        cfg = LabvisionConfig(n_classes=4, img_size=16, channels=(8, 16))
        _, on = train(model="labvision", steps=4, batch=8, cfg=cfg,
                      overlap=1, log=lambda *a: None)
        _, off = train(model="labvision", steps=4, batch=8, cfg=cfg,
                       overlap=0, log=lambda *a: None)
        assert on == off


class TestRecovery:
    def test_fault_rollback_bit_identical_params(self, tmp_path):
        """A fault detected ONE BLOCK LATE (async window open, K=4
        elsewhere; the fault step itself runs as a forced K=1 call)
        discards the in-flight dispatch, rolls back to the snapshot and
        replays to EXACTLY the fault-free final params and loss."""
        import os

        import orbax.checkpoint as ocp

        def load_params(d):
            mgr = ocp.CheckpointManager(os.path.abspath(d))
            step = mgr.latest_step()
            r = mgr.restore(step, args=ocp.args.Composite(
                state=ocp.args.StandardRestore()))
            return r["state"]["params"], step

        d_fault = str(tmp_path / "fault")
        d_clean = str(tmp_path / "clean")
        msgs = []
        _, recovered = train(
            steps=10, batch=4, seq=32, cfg=TINY, ckpt_dir=d_fault,
            save_every=5, recover=2, inject_fault=(7,), overlap=1,
            steps_per_call=4, log=lambda m: msgs.append(str(m)),
        )
        clean_lines, straight = _run(steps=10, overlap=0,
                                     ckpt_dir=d_clean, save_every=5)
        assert any("[fault]" in m for m in msgs), msgs
        assert any("[recover]" in m and "snapshot 5" in m for m in msgs), msgs
        assert recovered == straight
        # the replayed tail of the trajectory matches the fault-free one
        assert _pairs(msgs)[-5:] == _pairs(clean_lines)[-5:]
        pf, sf = load_params(d_fault)
        pc, sc = load_params(d_clean)
        assert sf == sc == 10
        for a, b in zip(jax.tree_util.tree_leaves(pf),
                        jax.tree_util.tree_leaves(pc)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_budget_exhaustion_still_fails_fast_under_overlap(self, tmp_path):
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            train(steps=10, batch=4, seq=32, cfg=TINY,
                  ckpt_dir=str(tmp_path / "rec"), save_every=5, recover=1,
                  inject_fault=(6, 7), overlap=1, steps_per_call=4,
                  log=lambda *a: None)


class TestDeviceResidency:
    def test_donation_tripwire(self):
        """Re-using a donated params/opt_state tree must raise — the
        buffers were aliased into the update, not copied."""
        p, o, step = init_train_state(TINY, None, seed=0, donate=True)
        p, o = device_resident(p), device_resident(o)
        tok = batches(TINY.vocab, 2, 32, seed=0)(0)
        old_p, old_o = p, o
        p, o, _ = step(p, o, tok)
        leaf = jax.tree_util.tree_leaves(old_p)[0]
        assert leaf.is_deleted()
        # jaxlib raises RuntimeError on direct array use and ValueError
        # (INVALID_ARGUMENT) when a deleted buffer enters a jit call
        with pytest.raises((RuntimeError, ValueError), match="deleted"):
            step(old_p, old_o, tok)

    def test_steady_state_zero_uploads_flat_buffers(self):
        """ISSUE acceptance: a steady-state train step moves NOTHING
        implicitly between host and device — params/opt_state are
        device-resident and ALIASED through every call (flat live-array
        count), the token batch rides one EXPLICIT device_put, and the
        loss fetch is an explicit device_get after the guarded window.
        Covers the 1-step and the fused K-step programs."""
        p, o, step = init_train_state(TINY, None, seed=0, donate=True)
        p, o = device_resident(p), device_resident(o)
        batch_at = batches(TINY.vocab, 2, 32, seed=1)
        toks = [jax.device_put(batch_at(i)) for i in range(10)]
        block = jax.device_put(np.stack([batch_at(10 + j) for j in range(4)]))
        warm_block = jax.device_put(
            np.stack([batch_at(20 + j) for j in range(4)]))
        # compile both programs OUTSIDE the guard
        p, o, l = step(p, o, toks[0])
        p, o, l = step.step_k(p, o, warm_block)
        n0 = len(jax.live_arrays())
        with jax.transfer_guard("disallow"):
            for t in toks[1:7]:
                p, o, l = step(p, o, t)
            p, o, lk = step.step_k(p, o, block)
        n1 = len(jax.live_arrays())
        # 6 single steps + 1 fused call: state aliased in place, only
        # the rebound loss outputs differ -> the census stays flat
        assert n1 <= n0 + 2, (n0, n1)
        assert np.all(np.isfinite(jax.device_get(lk)))


class TestLoggingAndEval:
    def test_log_every_preserves_pairing(self):
        """Thinned lines are an exact subset: same (step, loss) pairs
        from the delayed-loss queue, every other step."""
        full_lines, _ = _run(steps=6, overlap=1)
        thin_lines, _ = _run(steps=6, overlap=1, log_every=2)
        full = _pairs(full_lines)
        assert _pairs(thin_lines) == [p for p in full if p[0] % 2 == 0]

    def test_eval_batched_fetch_bit_identical(self):
        """[eval] lines (dispatch-all, fetch-once) agree across the
        async window and K-fusion — eval boundaries end blocks, so the
        evaluated params are per-step exact."""
        base, _ = _run(steps=8, overlap=0, eval_every=4, eval_batches=3)
        want = [l for l in base if l.startswith("[eval]")]
        assert len(want) == 2
        for kw in (dict(overlap=1),
                   dict(overlap=1, steps_per_call=4)):
            lines, _ = _run(steps=8, eval_every=4, eval_batches=3, **kw)
            assert [l for l in lines if l.startswith("[eval]")] == want, kw

    def test_counters_and_remainder_accounting(self, tmp_path):
        """K=4 over 10 steps with a save boundary at 5: fused blocks
        0-3 and 5-8, forced K=1 remainders at 4 and 9 (the driver
        compiles exactly two programs), checkpoints land on schedule,
        and the boundary drains show up as host_syncs."""
        import orbax.checkpoint as ocp

        d = str(tmp_path / "ck")
        lines, _ = _run(steps=10, overlap=1, steps_per_call=4,
                        ckpt_dir=d, save_every=5)
        c = _counters(lines)
        assert c["fused_calls"] == "2", c
        assert c["dispatches"] == "4", c
        assert int(c["host_syncs"]) >= 1, c
        mgr = ocp.CheckpointManager(d)
        assert mgr.latest_step() == 10
        assert _pairs(lines) == _pairs(_run(steps=10, overlap=0)[0])


class TestRefusals:
    def test_steps_per_call_needs_labformer(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            train(model="labvision", steps=2, steps_per_call=4,
                  log=lambda *a: None)

    def test_bad_knobs_refused(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            train(steps=2, cfg=TINY, steps_per_call=0, log=lambda *a: None)
        with pytest.raises(ValueError, match="log_every"):
            train(steps=2, cfg=TINY, log_every=0, log=lambda *a: None)
        with pytest.raises(ValueError, match="overlap"):
            train(steps=2, cfg=TINY, overlap=-1, log=lambda *a: None)
