"""ZeRO-1 optimizer-state sharding (labformer.make_train_step zero1=True).

The reference world does optimizer-state sharding with hand-written
reduce-scatter/all-gather (ZeRO stage 1 over NCCL); here the same
schedule is a GSPMD sharding constraint on the Adam moments.  These
tests pin (a) the memory claim — each dp rank holds 1/dp of every
moment leaf — and (b) numerical equivalence with the replicated
optimizer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpulab.models.labformer import (
    LabformerConfig,
    _map_moment_trees,
    _zero1_spec,
    init_train_state,
    zero1_shardings,
)
from tpulab.parallel.mesh import make_mesh

from jax.sharding import PartitionSpec as P


def _tokens(cfg, batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab, size=(batch, seq)).astype(np.int32)


def _moment_leaves(opt_state, params):
    """All optimizer leaves whose shape matches some param's (mu and nu)."""
    shapes = {np.shape(p) for p in jax.tree_util.tree_leaves(params)}
    return [
        l for l in jax.tree_util.tree_leaves(opt_state)
        if getattr(l, "ndim", 0) > 0 and np.shape(l) in shapes
    ]


def test_zero1_spec_adds_dp_on_first_free_axis():
    mesh = make_mesh({"dp": 4, "tp": 2})
    # (L, d, ff) sharded ("pp", None, "tp") -> pp missing from mesh, d gets dp
    sp = _zero1_spec((2, 8, 16), P("pp", None, "tp"), mesh)
    assert sp == P(None, "dp", "tp")
    # axis not divisible by dp: falls through to the next free axis
    sp = _zero1_spec((2, 6, 16), P(None, None, None), mesh)
    assert sp == P(None, None, "dp")
    # dp already consumed (MoE expert axis): spec unchanged
    sp = _zero1_spec((2, 8, 16), P(None, ("dp", "sp"), None), mesh)
    assert sp == P(None, ("dp",), None)


def test_zero1_moments_are_dp_sharded():
    mesh = make_mesh({"dp": 8})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    params, opt_state, _ = init_train_state(cfg, mesh, seed=0, zero1=True)
    moments = _moment_leaves(opt_state, params)
    assert moments, "no moment leaves recognized"
    sharded = 0
    for leaf in moments:
        shard = leaf.addressable_shards[0].data
        if shard.size < leaf.size:
            assert shard.size * 8 == leaf.size, (leaf.shape, shard.shape)
            sharded += 1
    # every moment big enough to split must actually be split
    splittable = [l for l in moments if any(d % 8 == 0 and d >= 8 for d in l.shape)]
    assert sharded == len(splittable) and sharded > 0


def test_zero1_matches_replicated_training():
    mesh = make_mesh({"dp": 4})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    p0, s0, step0 = init_train_state(cfg, mesh, seed=0)
    p1, s1, step1 = init_train_state(cfg, mesh, seed=0, zero1=True)
    # Two steps cover every distinct code path by construction: step 1
    # updates from freshly-zeroed (sharded) moments; step 2 consumes
    # moments produced sharded in step 1 AND params produced through the
    # gather, i.e. the full sharded-state -> next-step feedback cycle.
    # Step 3+ re-runs the step-2 path with different numbers — parity
    # there is implied by per-leaf equality after step 2 (checked below)
    # plus determinism of the jitted step.
    for i in range(2):
        tok = _tokens(cfg, 8, 32, seed=i)
        p0, s0, l0 = step0(p0, s0, tok)
        p1, s1, l1 = step1(p1, s1, tok)
        assert np.allclose(float(l0), float(l1), atol=1e-5), i
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_zero1_state_stays_sharded_across_steps():
    mesh = make_mesh({"dp": 8})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    params, opt_state, step = init_train_state(cfg, mesh, seed=0, zero1=True)
    params, opt_state, _ = step(params, opt_state, _tokens(cfg, 8, 32))
    params, opt_state, _ = step(params, opt_state, _tokens(cfg, 8, 32, seed=1))
    moments = _moment_leaves(opt_state, params)
    splittable = [l for l in moments if any(d % 8 == 0 and d >= 8 for d in l.shape)]
    for leaf in splittable:
        assert leaf.addressable_shards[0].data.size * 8 == leaf.size


def test_zero1_layouts_survive_shape_collision():
    # d_ff == d_model makes wq/wk/wv/w1 and wo/w2 share a shape while
    # their tp layouts are transposed; structure-based matching must
    # still land every moment on its OWN param's ZeRO-1 sharding
    mesh = make_mesh({"dp": 2, "tp": 4})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=32, max_seq=64)
    params, opt_state, step = init_train_state(cfg, mesh, seed=0, zero1=True)
    params, opt_state, _ = step(params, opt_state, _tokens(cfg, 4, 32))
    want = zero1_shardings(params, cfg, mesh)
    checked = []
    def check(leaf, sh):
        # is_equivalent_to: trailing-None specs normalize (P('dp') vs
        # P('dp', None) place identically)
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim), (
            leaf.shape, leaf.sharding, sh)
        checked.append(leaf)
        return leaf
    _map_moment_trees(opt_state, params, want, check)
    # adamw carries mu and nu: two full params-shaped moment trees
    n_params = len(jax.tree_util.tree_leaves(params))
    assert len(checked) == 2 * n_params


def test_zero1_refuses_meshless_and_labvision():
    from tpulab.train import train

    with pytest.raises(ValueError, match="mesh"):
        train(steps=1, zero1=True, mesh_devices=0)
    with pytest.raises(ValueError, match="labformer"):
        train(steps=1, zero1=True, mesh_devices=8, model="labvision")


def test_zero1_noop_without_dp_axis():
    mesh = make_mesh({"tp": 4})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    params, opt_state, step = init_train_state(cfg, mesh, seed=0, zero1=True)
    params, opt_state, loss = step(params, opt_state, _tokens(cfg, 4, 32))
    assert np.isfinite(float(loss))


def test_zero1_with_moe_dispatch():
    # expert axis already consumes dp: zero1 must skip those leaves and
    # still shard the dense ones; the step must run end to end
    mesh = make_mesh({"dp": 4, "sp": 2})
    cfg = LabformerConfig(
        d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64,
        n_experts=8, moe_impl="dispatch",
    )
    # seq 33: the loss shifts tokens/targets, so the attended length is
    # seq-1, which must divide the sp axis
    params, opt_state, step = init_train_state(cfg, mesh, seed=0, zero1=True)
    params, opt_state, loss = step(params, opt_state, _tokens(cfg, 8, 33))
    assert np.isfinite(float(loss))


def test_zero2_matches_replicated_training():
    """ZeRO-2 (grads reduce-scattered over dp, sharded moment update,
    all-gathered parameter updates) must be numerically identical to
    replicated training — the sharding constraint changes the schedule,
    not the math."""
    mesh = make_mesh({"dp": 4})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    p0, s0, step0 = init_train_state(cfg, mesh, seed=0)
    p2, s2, step2 = init_train_state(cfg, mesh, seed=0, zero2=True)
    for i in range(2):
        tok = _tokens(cfg, 8, 32, seed=i)
        p0, s0, l0 = step0(p0, s0, tok)
        p2, s2, l2 = step2(p2, s2, tok)
        assert np.allclose(float(l0), float(l2), atol=1e-5), i
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p2)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_zero2_with_grad_accumulation():
    """The sharded microbatch accumulator (zeros + per-microbatch grads
    constrained to the dp shard) must equal zero2 on the full batch."""
    mesh = make_mesh({"dp": 4})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    pa, sa, step_accum = init_train_state(cfg, mesh, seed=0, zero2=True, accum=2)
    pf, sf, step_full = init_train_state(cfg, mesh, seed=0, zero2=True)
    tok = _tokens(cfg, 8, 32, seed=0)
    pa, sa, la = step_accum(pa, sa, tok)
    pf, sf, lf = step_full(pf, sf, tok)
    assert np.allclose(float(la), float(lf), atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pa), jax.tree_util.tree_leaves(pf)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_zero2_implies_zero1_sharded_state():
    """zero2=True alone must still produce dp-sharded moments."""
    mesh = make_mesh({"dp": 8})
    cfg = LabformerConfig(d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64)
    params, opt_state, _ = init_train_state(cfg, mesh, seed=0, zero2=True)
    moments = _moment_leaves(opt_state, params)
    sharded = [l for l in moments
               if l.addressable_shards[0].data.size < l.size]
    assert sharded, "zero2 did not shard the optimizer state"
