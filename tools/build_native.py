#!/usr/bin/env python
"""Build the native tier: the C++ daemon client and the C codec extension.

Artifacts land in ``native/bin/`` (client) and next to the package as an
importable extension (``native/lib/_tpulab_fastcodec*.so``, appended to
sys.path by tpulab.io.imagefile when present).

Usage: ``python tools/build_native.py [--clean]``
Requires g++ (baked into the image); no network access needed.
"""

from __future__ import annotations

import argparse
import pathlib
import shutil
import subprocess
import sys
import sysconfig

ROOT = pathlib.Path(__file__).resolve().parent.parent
NATIVE = ROOT / "native"
BIN = NATIVE / "bin"
LIB = NATIVE / "lib"


def build_client() -> pathlib.Path:
    BIN.mkdir(parents=True, exist_ok=True)
    out = BIN / "tpulab_client"
    src = NATIVE / "client" / "tpulab_client.cpp"
    cmd = ["g++", "-std=c++17", "-O2", "-Wall", "-o", str(out), str(src)]
    subprocess.run(cmd, check=True)
    return out


def build_fastcodec() -> pathlib.Path:
    LIB.mkdir(parents=True, exist_ok=True)
    src = NATIVE / "fastcodec" / "fastcodecmodule.c"
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = LIB / f"_tpulab_fastcodec{suffix}"
    include = sysconfig.get_paths()["include"]
    cmd = [
        "gcc",
        "-shared",
        "-fPIC",
        "-O2",
        "-Wall",
        f"-I{include}",
        "-o",
        str(out),
        str(src),
    ]
    subprocess.run(cmd, check=True)
    return out


def build_loader() -> pathlib.Path:
    LIB.mkdir(parents=True, exist_ok=True)
    out = LIB / "libtpulab_loader.so"
    src = NATIVE / "loader" / "tpulab_loader.cpp"
    cmd = [
        "g++", "-std=c++17", "-shared", "-fPIC", "-O2", "-Wall",
        "-pthread", "-o", str(out), str(src),
    ]
    subprocess.run(cmd, check=True)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clean", action="store_true")
    args = ap.parse_args(argv)
    if args.clean:
        for d in (BIN, LIB):
            shutil.rmtree(d, ignore_errors=True)
        print("cleaned")
        return 0
    client = build_client()
    ext = build_fastcodec()
    loader = build_loader()
    print(f"built {client.relative_to(ROOT)}")
    print(f"built {ext.relative_to(ROOT)}")
    print(f"built {loader.relative_to(ROOT)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
