#!/usr/bin/env python
"""Standalone checker for the lab3 stdin grammar (reference
``lab3/src/test_read_input.c:4-66`` parity tool, component N9).

Reads the lab3 input from stdin — input path, output path, ``nc``, then
per class ``np`` and ``np`` coordinate pairs — and echoes the parsed
structure back in the same shape, so a malformed payload is caught
before it reaches a workload.  Usage::

    python tools/check_lab3_input.py [--sweep] < input.txt
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sweep", action="store_true", help="expect the to_plot launch prefix")
    args = ap.parse_args(argv)

    from tpulab.io.protocol import parse_lab3

    try:
        inp = parse_lab3(sys.stdin.read(), sweep=args.sweep)
    except Exception as exc:
        print(f"PARSE ERROR: {exc}", file=sys.stderr)
        return 1

    if inp.launch:
        print(f"launch: {inp.launch[0]} {inp.launch[1]}")
    print(f"input_path: {inp.input_path}")
    print(f"output_path: {inp.output_path}")
    print(f"nc: {len(inp.classes)}")
    for i, cls in enumerate(inp.classes):
        pts = " ".join(f"{x} {y}" for x, y in cls.points)
        print(f"class {i}: np={len(cls.points)} {pts}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
