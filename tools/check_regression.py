"""Compare a bench run against the committed performance baselines.

Closes the loop VERDICT round-2 weak #7 opened: measured numbers used to
live only in RESULTS.md prose, so no later round could mechanically
regress against them.  ``results/baselines.json`` is the machine-readable
table; this tool diffs a ``bench.py`` output (JSONL file or stdin)
against it.

Usage:
    python bench.py | tee /tmp/bench.jsonl
    python tools/check_regression.py /tmp/bench.jsonl
    python tools/check_regression.py --update /tmp/bench.jsonl  # accept new numbers

Exit codes: 0 = no regressions (missing metrics are reported but don't
fail — a CPU smoke run covers few), 1 = at least one metric regressed
beyond its tolerance, 2 = input unusable.

A regression means: direction "lower" and value > baseline*(1+tol_rel),
or direction "higher" and value < baseline*(1-tol_rel).  Improvements
are reported; ``--update`` rewrites the baseline entry for any metric
that improved beyond tolerance (ratcheting), stamping the provided
``--date`` (timestamps are injected, never read from the clock, so runs
are reproducible).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
BASELINES = ROOT / "results" / "baselines.json"


def load_rows(path: str):
    text = (sys.stdin.read() if path == "-"
            else pathlib.Path(path).read_text())
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if "metric" in row:
            rows.append(row)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bench", help="bench.py JSONL output file, or - for stdin")
    ap.add_argument("--baselines", default=str(BASELINES))
    ap.add_argument("--update", action="store_true",
                    help="ratchet baselines for metrics that improved "
                         "beyond tolerance")
    ap.add_argument("--date", default=None,
                    help="date stamp recorded with --update entries")
    ap.add_argument("--accept-regression", default=None, metavar="NOTE",
                    help="with --update: ALSO move baselines for "
                         "regressed metrics, recording NOTE as the "
                         "entry's regression_accepted provenance.  "
                         "Without it, --update refuses to move any "
                         "baseline in the worse direction (and, as "
                         "before, refuses to ratchet a mixed run).")
    args = ap.parse_args(argv)
    if args.update and not args.date:
        ap.error("--update requires --date (provenance must move with "
                 "the ratcheted value)")
    if args.accept_regression and not args.update:
        ap.error("--accept-regression only makes sense with --update")

    try:
        table = json.loads(pathlib.Path(args.baselines).read_text())
        base = table["baselines"]
    except (OSError, ValueError, KeyError) as e:
        print(f"baselines unusable ({args.baselines}): {e}", file=sys.stderr)
        return 2
    try:
        rows = load_rows(args.bench)
    except OSError as e:
        print(f"bench input unusable: {e}", file=sys.stderr)
        return 2
    if not rows:
        print("no bench rows found", file=sys.stderr)
        return 2

    got = {}
    for r in rows:
        if r.get("value") is not None:
            got[r["metric"]] = float(r["value"])

    regressed, improved, ok, missing = [], [], [], []
    for metric, spec in base.items():
        if metric not in got:
            missing.append(metric)
            continue
        val, ref, tol = got[metric], spec["value"], spec["tol_rel"]
        lower_is_better = spec["direction"] == "lower"
        # ref == 0 baselines (e.g. decode_steady_recompiles, expected
        # 0): matching 0 is OK, any positive value is infinitely worse
        # for a lower-is-better metric — the old unconditional inf made
        # a 0-vs-0 match report as regressed
        ratio = val / ref if ref else (float("inf") if val > 0 else 1.0)
        if lower_is_better:
            state = ("regressed" if ratio > 1 + tol
                     else "improved" if ratio < 1 - tol else "ok")
        else:
            state = ("regressed" if ratio < 1 - tol
                     else "improved" if ratio > 1 + tol else "ok")
        line = (f"[{state}] {metric}: {val:.6g} vs baseline {ref:.6g} "
                f"(x{ratio:.2f}, tol {tol:.0%}, {spec['direction']} is better)")
        print(line)
        {"regressed": regressed, "improved": improved, "ok": ok}[state].append(metric)
        if state == "improved" and args.update:
            spec["value"] = val
            if args.date:
                spec["measured"] = args.date
            # a clean improvement supersedes any earlier accepted
            # regression: leaving the note would attach false
            # provenance to the ratcheted value
            spec.pop("regression_accepted", None)
        elif state == "regressed" and args.update and args.accept_regression:
            # moving a baseline in the WORSE direction is only legal
            # with explicit provenance: the note travels with the entry
            # so later rounds can see the regression was accepted, not
            # laundered in by a half-broken run (VERDICT round-5 #6)
            spec["value"] = val
            spec["measured"] = args.date
            spec["regression_accepted"] = args.accept_regression
    for m in missing:
        print(f"[missing] {m}: not in this bench run")
    for m in sorted(set(got) - set(base)):
        # surface name drift loudly: a renamed metric would otherwise
        # silently stop being checked
        print(f"[unknown] {m}: measured but not in the baseline table")

    if args.update and (improved or regressed):
        if regressed and not args.accept_regression:
            # a half-broken run must not permanently tighten baselines
            # for the metrics that happened to look good — and must
            # NEVER move one in the worse direction without provenance
            print("NOT ratcheting: this run contains regressions — fix, "
                  "rerun, or pass --accept-regression NOTE before "
                  "--update", file=sys.stderr)
        else:
            pathlib.Path(args.baselines).write_text(
                json.dumps(table, indent=2) + "\n")
            moved = len(improved) + (len(regressed)
                                     if args.accept_regression else 0)
            print(f"updated {moved} baseline(s) -> {args.baselines}"
                  + (f" ({len(regressed)} regression(s) accepted: "
                     f"{args.accept_regression})"
                     if args.accept_regression and regressed else ""))

    print(f"summary: {len(ok)} ok, {len(improved)} improved, "
          f"{len(regressed)} regressed, {len(missing)} missing")
    # accepted-and-recorded regressions are a deliberate baseline move,
    # not a gate failure
    if regressed and args.update and args.accept_regression:
        return 0
    return 1 if regressed else 0


if __name__ == "__main__":
    raise SystemExit(main())
