"""On-silicon proof that flash-attention training works end to end.

Round-2 verdict task #2: the claim "flash attention is trainable"
rested on interpret-mode tests; the Mosaic lowering of the custom_vjp
backward (``ops/pallas/attention.py``) had never produced a gradient on
the real chip.  This tool makes the measured claim:

  1. **Grad parity on chip**: at small seq, d(loss)/d(params) through
     ``attn_impl="flash"`` vs ``attn_impl="dense"`` on identical
     params/batch — max relative leaf error within tolerance proves the
     compiled backward computes the same mathematics.
  2. **Training run through flash**: a short labformer run at seq past
     the flash threshold (the step differentiates THROUGH the Pallas
     kernels); a strictly-decreasing-trend, finite loss curve is the
     working-training evidence.  Loss curve + timings land in the
     artifact.

Writes ``results/flash_train_tpu.json``.

Usage: python tools/flash_train_proof.py [--steps 30]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys
import time

import numpy as np

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def grad_parity(seq: int = 512, b: int = 2):
    """Max relative grad-leaf error, flash vs dense, same params/batch."""
    import jax
    import jax.numpy as jnp

    from tpulab.models.labformer import LabformerConfig, init_params, loss_fn

    errs = {}
    base = dict(d_model=256, n_heads=4, n_layers=2, d_ff=512, max_seq=seq,
                dtype=jnp.bfloat16)
    cfg_f = LabformerConfig(**base, attn_impl="flash")
    cfg_d = LabformerConfig(**base, attn_impl="dense")
    params = init_params(cfg_d, seed=0)
    tokens = np.random.default_rng(0).integers(
        0, cfg_d.vocab, (b, seq + 1)).astype(np.int32)

    g_f = jax.jit(jax.grad(lambda p: loss_fn(p, tokens, cfg_f, None)))(params)
    g_d = jax.jit(jax.grad(lambda p: loss_fn(p, tokens, cfg_d, None)))(params)
    flat_f = jax.tree_util.tree_leaves_with_path(g_f)
    flat_d = jax.tree_util.tree_leaves(g_d)
    for (path, lf), ld in zip(flat_f, flat_d):
        a = np.asarray(lf, np.float32)
        bb = np.asarray(ld, np.float32)
        denom = max(float(np.abs(bb).max()), 1e-6)
        errs[jax.tree_util.keystr(path)] = float(
            np.abs(a - bb).max() / denom
        )
    return errs


def train_through_flash(steps: int, seq: int, b: int):
    """Short real-chip training run whose step differentiates through
    the Pallas flash kernels (seq past the auto threshold)."""
    from tpulab.train import train

    losses = []
    t0 = time.perf_counter()
    train(
        model="labformer", steps=steps, batch=b, seq=seq,
        log=lambda msg: losses.append(msg) if "[train]" in str(msg) else None,
    )
    wall = time.perf_counter() - t0
    curve = []
    for line in losses:
        # "[train] step N loss X (Y ms)"
        parts = line.split()
        try:
            curve.append({"step": int(parts[2]), "loss": float(parts[4]),
                          "ms": float(parts[5].lstrip("("))})
        except (IndexError, ValueError):
            pass
    return curve, wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--tol", type=float, default=0.06,
                    help="max relative grad-leaf error (bf16 model: flash "
                         "and dense round differently through exp/matmuls)")
    ap.add_argument("--out", default=str(ROOT / "results" / "flash_train_tpu.json"))
    args = ap.parse_args(argv)

    import jax

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print("refusing: this artifact certifies the compiled Mosaic "
              "backward on real hardware", file=sys.stderr)
        return 2

    errs = grad_parity()
    worst = max(errs.values())
    curve, wall = train_through_flash(args.steps, args.seq, args.batch)
    finite = all(np.isfinite(r["loss"]) for r in curve)
    # trend: mean of last 5 below mean of first 5
    head = np.mean([r["loss"] for r in curve[:5]]) if len(curve) >= 10 else None
    tail = np.mean([r["loss"] for r in curve[-5:]]) if len(curve) >= 10 else None
    report = {
        "device_kind": dev.device_kind,
        "grad_parity": {
            "seq": 512, "worst_rel_err": worst, "tol": args.tol,
            "ok": bool(worst < args.tol),
            "n_leaves": len(errs),
        },
        "train": {
            "steps": args.steps, "seq": args.seq, "batch": args.batch,
            "wall_s": round(wall, 2),
            "finite": finite,
            "loss_first5_mean": head, "loss_last5_mean": tail,
            "decreasing": bool(head is not None and tail < head),
            "curve": curve,
        },
        # the loss trend IS the working-training evidence: with >=10 points
        # a finite but flat/diverging curve must not certify ok
        "ok": bool(worst < args.tol and finite
                   and (head is None or tail < head)),
    }
    out = pathlib.Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps({k: v for k, v in report.items() if k != "train"},
                     indent=2))
    print(f"train: {len(curve)} steps, finite={finite}, "
          f"first5={head} last5={tail}")
    print(f"wrote {out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
