"""Generate the framework's committed test/benchmark fixtures.

Creates deterministic tiny images under ``data/lab2/data`` and
``data/lab3/data`` plus golden outputs under the sibling ``data_out_gt``
dirs.  Goldens are produced by the framework's own CPU f64/f32 reference
paths, which are bit-exact against the reference suite's committed
goldens (tests/test_lab2.py, tests/test_lab3.py prove that equivalence);
the pixel content is original to this repo.

Run from the repo root:  python tools/gen_fixtures.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"

# The env var alone is NOT enough: the container's sitecustomize calls
# jax.config.update("jax_platforms", "axon,cpu") at interpreter startup,
# which outranks it — goldens would silently be computed on the TPU f32
# path.  Override the config itself before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tpulab.io import save_image  # noqa: E402
from tpulab.harness.processors.lab3 import PINNED_CLASS_POINTS  # noqa: E402
from tpulab.ops.mahalanobis import class_statistics, classify  # noqa: E402
from tpulab.ops.roberts import roberts_edges  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LAB3_CLASS_POINTS = {k: v for k, v in PINNED_CLASS_POINTS.items() if k != "test_01_lab3"}


def lab2_images(rng):
    imgs = {}
    imgs["grad_3x3"] = np.stack(
        [
            np.tile(np.arange(3, dtype=np.uint8)[None, :] * 40, (3, 1)),
            np.tile(np.arange(3, dtype=np.uint8)[:, None] * 60, (1, 3)),
            np.full((3, 3), 128, np.uint8),
            np.full((3, 3), 255, np.uint8),
        ],
        axis=-1,
    )
    imgs["spot_1x5"] = np.zeros((1, 5, 4), np.uint8)
    imgs["spot_1x5"][0, 2] = [200, 100, 50, 3]
    imgs["noise_4x4"] = rng.integers(0, 256, size=(4, 4, 4), dtype=np.uint8)
    imgs["rings_16x16"] = np.zeros((16, 16, 4), np.uint8)
    yy, xx = np.mgrid[0:16, 0:16]
    r = np.sqrt((yy - 7.5) ** 2 + (xx - 7.5) ** 2)
    imgs["rings_16x16"][..., 0] = ((np.sin(r * 1.7) * 0.5 + 0.5) * 255).astype(np.uint8)
    imgs["rings_16x16"][..., 1] = ((np.cos(r) * 0.5 + 0.5) * 255).astype(np.uint8)
    imgs["rings_16x16"][..., 2] = (r * 16).astype(np.uint8)
    imgs["rings_16x16"][..., 3] = 255
    return imgs


def lab3_images(rng):
    imgs = {}
    checker = np.zeros((6, 6, 4), np.uint8)
    checker[..., 0] = np.where((np.indices((6, 6)).sum(0) % 2) == 0, 220, 30)
    checker[..., 1] = np.where((np.indices((6, 6)).sum(0) % 2) == 0, 40, 200)
    checker[..., 2] = 128
    # per-pixel noise: a pure two-color checker gives every class a
    # rank-deficient covariance (NaN inverse -> all labels 255); noise
    # keeps the class statistics full-rank and the golden meaningful
    noise = rng.integers(0, 24, size=(6, 6, 3), dtype=np.uint8)
    checker[..., :3] = np.clip(checker[..., :3].astype(int) + noise, 0, 255).astype(np.uint8)
    checker[..., 3] = 255
    imgs["checker_6x6"] = checker
    blobs = rng.integers(0, 80, size=(8, 8, 4), dtype=np.uint8)
    blobs[:2, :2, 0] += 170
    blobs[6:, 6:, 1] += 170
    blobs[:2, 6:, 2] += 170
    blobs[..., 3] = 255
    imgs["blobs_8x8"] = blobs
    return imgs


def main() -> None:
    rng = np.random.default_rng(20240713)

    d2 = os.path.join(ROOT, "data/lab2/data")
    g2 = os.path.join(ROOT, "data/lab2/data_out_gt")
    os.makedirs(d2, exist_ok=True)
    os.makedirs(g2, exist_ok=True)
    for name, img in lab2_images(rng).items():
        ext = ".txt" if img.size <= 16 * 16 * 4 else ".data"
        save_image(os.path.join(d2, name + ext), img)
        save_image(os.path.join(g2, name + ext), np.asarray(roberts_edges(img)))
        print(f"lab2 fixture {name}{ext} + golden")

    d3 = os.path.join(ROOT, "data/lab3/data")
    g3 = os.path.join(ROOT, "data/lab3/data_out_gt")
    os.makedirs(d3, exist_ok=True)
    os.makedirs(g3, exist_ok=True)
    for name, img in lab3_images(rng).items():
        save_image(os.path.join(d3, name + ".txt"), img)
        stats = class_statistics(img, LAB3_CLASS_POINTS[name])
        out = np.asarray(classify(img, stats, backend="cpu"))
        save_image(os.path.join(g3, name + ".txt"), out)
        print(f"lab3 fixture {name}.txt + golden ({len(LAB3_CLASS_POINTS[name])} classes)")


if __name__ == "__main__":
    main()
