#!/usr/bin/env python
"""Goodput-under-SLO gate: replay a trace against a live daemon and
fail on regression.

The measurement layer ROADMAP item 5 asks for: instead of trusting
steady-state tokens/s, drive a live tpulab daemon with a seeded
trace (tpulab.loadgen — bursty arrivals, heavy-tail lengths,
multi-turn prefix reuse, mid-stream cancellations, per-class
deadline/priority mixes) and report **goodput**: the fraction of
requests completed within their class's TTFT/ITL/e2e budgets, and the
token throughput those good requests delivered.

What one run produces:

* per-class goodput-under-SLO + shed/cancel/error accounting from the
  client-observed outcomes (tpulab.loadgen.summarize);
* server-side latency percentiles for the replay WINDOW, computed by
  differencing the daemon's Prometheus scrape before vs after (the
  PR-5 histograms — cumulative, so the delta isolates this run);
* shed / preemption / replay / restart counter deltas from the same
  scrapes;
* the daemon's ``slowlog`` worst-N with per-request span summaries —
  each entry's ``rid`` links to the trace events, and its ``tag``
  names the trace row that produced it;
* bench-style JSONL rows (``goodput_<spec>_goodput_tokens_per_s``,
  ``goodput_<spec>_slo_attainment``) on stdout, gated against the
  signed ``results/baselines.json`` by ``--check-baselines`` (exit 1
  on regression — the ratchet lives in tools/check_regression.py).

Usage (host-only fast tier, as tools/onchip_queue_r12.sh runs it):

    python tools/goodput_gate.py --spawn-daemon --spec fast \
        --out results/goodput_r12.json --check-baselines

or against an already-running daemon: ``--socket /tmp/tpulab.sock``
(never spawn a daemon you don't own on a chip — the running one holds
the claim).

Chaos scenario (round 13, the fleet certification
tools/onchip_queue_r13.sh runs):

    python tools/goodput_gate.py --spawn-daemon --spec chaos \
        --replicas 3 --chaos --rolling-restart \
        --out results/goodput_chaos_r13.json --check-baselines

replays the trace twice — fault-free for reference outputs, then with
``CHAOS_SCHEDULE`` armed (replica1 crashes mid-trace, replica2 wedges)
— and gates: every non-cancelled request completes, streamed chunks
reassemble exactly, surviving outputs are BIT-IDENTICAL to the
reference (migration loses/duplicates zero tokens), and a full
rolling restart under steady load serves with zero shed requests.
The ``goodput_chaos_*`` rows ride the same baselines ratchet.

Disaggregated scenario (round 20, tools/onchip_queue_r20.sh runs):

    python tools/goodput_gate.py --spawn-daemon --spec disagg \
        --disagg --out results/goodput_disagg_r20.json \
        --check-baselines

replays the heavy-tail trace twice — against a unified single-engine
daemon (reference outputs + the decode-latency floor), then against a
phase-disaggregated fleet (``--pool-spec prefill=1..2,decode=1``)
where every prompt prefills in the prefill pool and hands its KV
blocks to the decode pool through the digest-keyed host tier — and
gates: handoffs fired, decode ITL p99 flat vs unified while the long
prefills saturate the prefill pool, attainment 1.0, every stream
bit-identical to unified serving, zero leaked blocks in both pools,
and the prefill pool scaling on its own queue-wait signal while the
decode pool holds its floor.  ``goodput_disagg_*`` rows ride the
same ratchet.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tpulab import loadgen  # noqa: E402
from tpulab.obs.journey import HANDOFF_PHASES  # noqa: E402
from tpulab.obs.registry import percentile_from_buckets  # noqa: E402


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", pathlib.Path(__file__).resolve().parent
        / "obs_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: counters whose before/after delta the report carries (the PR-6
#: fault-tolerance counters, the engine preemption mirror, and the
#: round-13 fleet-router counters)
_COUNTERS = ("daemon_shed_requests", "daemon_replays",
             "daemon_engine_restarts", "engine_preemptions",
             "daemon_migrations", "daemon_hedges", "daemon_hedge_wins",
             "daemon_drains",
             # round 17: the elastic-fleet surface
             "daemon_scale_outs", "daemon_scale_ins",
             "daemon_spot_preemptions", "daemon_brownout_steps",
             "daemon_brownout_reversals",
             # round 20: the disaggregated prefill/decode handoff
             "daemon_handoffs", "handoff_bytes")

#: the chaos fault schedule (--chaos, replayed via TPULAB_FAULTS in
#: the spawned daemon's environment): CRASH replica1 mid-trace (its
#: in-flight requests must migrate to healthy peers and complete
#: bit-identically) and WEDGE replica2 (long slow_ms drains — the
#: health checker marks it SUSPECT and placement routes around it).
#: Sites are replica-scoped (tpulab/faults.py round 13), so the
#: schedule is deterministic per replica regardless of how the
#: steppers interleave.
CHAOS_SCHEDULE = [
    {"site": "paged.tick@replica1", "kind": "raise", "at": 40},
    # 300ms stretched ticks: above the router's slow-tick threshold
    # (tpulab/router.py DEFAULT_SLOW_TICK_S = 0.25), so the wedge
    # actually drives HEALTHY -> SUSPECT and placement routes around
    # the wedged replica
    {"site": "paged.drain@replica2", "kind": "slow_ms", "at": 30,
     "count": 60, "arg": 300.0},
]

#: the elastic-fleet drill (--autoscale): one spot-preemption NOTICE
#: delivered to replica1 — the slot the first scale-out brings up — a
#: few dozen stepper ticks into its life (mid-burst), with a 2 s drain
#: deadline.  The replica migrates what the deadline allows, parks the
#: stragglers, and releases; the reconcile loop revives the slot
#: because provisioned fell below target.  Scoped, so it is
#: deterministic per replica regardless of stepper interleaving.
RAMP_PREEMPT_SCHEDULE = [
    {"site": "replica.preempt@replica1", "kind": "preempt", "at": 40,
     "arg": 2000.0},
]

#: histograms percentile-diffed over the replay window
_HISTOGRAMS = ("ttft_seconds", "itl_seconds", "e2e_seconds",
               "queue_wait_seconds", "prefill_seconds")


def _histogram_counts(metric: dict):
    """Scraped cumulative buckets -> (bounds, per-bucket counts)."""
    pairs = metric.get("buckets") or []
    if not pairs or pairs[-1][0] != float("inf"):
        return None
    bounds = tuple(le for le, _ in pairs[:-1])
    cums = [c for _, c in pairs]
    return bounds, [cums[0]] + [b - a for a, b in zip(cums, cums[1:])]


def window_percentiles(before: dict, after: dict) -> dict:
    """Server-side p50/p90/p99 for the replay WINDOW: per-bucket deltas
    of the cumulative scraped histograms (the process-lifetime scrape
    would fold warmup and any earlier traffic into the estimate)."""
    out = {}
    for name in _HISTOGRAMS:
        b, a = before.get(name), after.get(name)
        if not a or a.get("type") != "histogram":
            continue
        got = _histogram_counts(a)
        if got is None:
            continue
        bounds, counts = got
        got_b = _histogram_counts(b) if b else None
        if got_b is not None and got_b[0] == bounds:
            counts = [x - y for x, y in zip(counts, got_b[1])]
        n = sum(counts)
        if n <= 0:
            continue
        out[name] = {
            "count": n,
            "p50_ms": round(
                percentile_from_buckets(bounds, counts, 0.50) * 1e3, 3),
            "p90_ms": round(
                percentile_from_buckets(bounds, counts, 0.90) * 1e3, 3),
            "p99_ms": round(
                percentile_from_buckets(bounds, counts, 0.99) * 1e3, 3),
        }
    return out


def counter_deltas(before: dict, after: dict) -> dict:
    out = {}
    for name in _COUNTERS:
        a = after.get(name, {}).get("value")
        if a is None:
            continue
        b = before.get(name, {}).get("value") or 0
        out[name] = int(a - b)
    return out


def _spawn_daemon(sock: str, slowlog: int, trace_buffer: int,
                  replicas: int = 1, extra_env: dict | None = None,
                  extra_args: list | None = None):
    """Host-only convenience: spawn a private daemon for the replay and
    SIGTERM it afterwards.  CPU-tier only — an on-chip daemon holds the
    relay claim and must be driven, not owned, by this gate.
    ``replicas`` sizes the serving fleet; ``extra_env`` injects e.g.
    the TPULAB_FAULTS chaos schedule; ``extra_args`` appends daemon
    flags (e.g. ``--journal`` for the kill scenario)."""
    # a stale socket file from a killed earlier run would satisfy the
    # readiness poll before the child ever binds (skipping its crash
    # detection); the daemon unlinks on bind, so pre-clear it here too
    if os.path.exists(sock):
        os.unlink(sock)
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock,
         "--slowlog", str(slowlog), "--trace-buffer", str(trace_buffer),
         "--replicas", str(replicas)] + list(extra_args or ()),
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"spawned daemon exited rc={proc.returncode} before "
                f"its socket appeared")
        if os.path.exists(sock):
            return proc
        time.sleep(0.1)
    # orphan guard: never leave the stuck child running — SIGTERM alone
    # left a zombie/orphan when the socket never appeared (the raise
    # below abandons the handle without reaping it)
    _reap(proc)
    raise RuntimeError("spawned daemon socket never appeared")


def _reap(proc) -> None:
    """Make absolutely sure a spawned daemon is dead AND reaped: polite
    SIGTERM with a bounded wait, then SIGKILL + wait.  Every gate exit
    path — success, assertion failure, crash mid-trace — funnels
    through this, so no run can leak an orphaned daemon process."""
    if proc is None or proc.poll() is not None:
        if proc is not None:
            proc.wait()  # already exited: reap the zombie
        return
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def rolling_restart(rep, sock: str, n_replicas: int, log) -> dict:
    """Zero-shed rolling restart under steady load: background client
    threads keep firing small generates (RAW requests — a shed or park
    would surface as an error here, which is exactly what the gate
    must count) while each replica in turn is drained, rebuilt
    (generation advance observed via the ``fleet`` request), and
    undrained.  Returns the outcome tally; the caller gates on
    shed == rebuilding == errors == 0."""
    import threading

    stop = threading.Event()
    tally = {"ok": 0, "shed": 0, "rebuilding": 0, "errors": 0}
    lock = threading.Lock()

    def loader(i: int):
        j = 0
        while not stop.is_set():
            try:
                rep.request(sock, "generate",
                            {"steps": 4, "tag": f"roll:{i}"},
                            f"rolling restart load {i} {j}".encode())
                with lock:
                    tally["ok"] += 1
            except (RuntimeError, OSError, ConnectionError) as e:
                # classify through THE shed/park pattern
                # (loadgen.SHED_RE) rather than a private substring:
                # round 20's pool-scoped park frame ("rebuilding
                # pool=<role> retry_after_ms=N") must tally as
                # rebuilding, not as a hard error
                m = loadgen.SHED_RE.search(str(e))
                with lock:
                    if m is not None and m.group(1) == "shed":
                        tally["shed"] += 1
                    elif m is not None:
                        tally["rebuilding"] += 1
                    else:
                        tally["errors"] += 1
            j += 1

    threads = [threading.Thread(target=loader, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(n_replicas):
            base = json.loads(rep.request(sock, "fleet"))
            base_gen = base["replica"][i]["generation"]
            rep.request(sock, "drain", {"replica": i})
            log(f"[goodput_gate] rolling restart: drained replica{i}")
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                st = json.loads(rep.request(sock, "fleet"))["replica"][i]
                if (st["generation"] > base_gen
                        and st["health"] == "healthy"):
                    break
                time.sleep(0.2)
            else:
                raise RuntimeError(
                    f"replica{i} never rebuilt during rolling restart")
            rep.request(sock, "undrain", {"replica": i})
            log(f"[goodput_gate] rolling restart: replica{i} rebuilt "
                f"(generation {st['generation']}) and undrained")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    return tally


def leaked_blocks(after: dict) -> dict:
    """Per-replica leaked-block census from a QUIESCED after-scrape
    (--disagg): once every stream has completed, each engine's used
    blocks must be exactly the blocks its prefix cache holds references
    on — anything above that is a block the handoff path allocated and
    never released.  The scrape carries ``engine_blocks_used_replica<i>``
    plus the cache's byte footprint; block size falls out of the pool
    bytes (``blocks_total`` is the USABLE count — one block of the
    constructor's pool is reserved, hence the +1)."""
    import re as _re

    leaks = {}
    for key, metric in after.items():
        m = _re.match(r"engine_blocks_used_replica(\d+)$", key)
        if not m:
            continue
        i = m.group(1)

        def g(name):
            return int(after.get(f"engine_{name}_replica{i}",
                                 {}).get("value") or 0)

        used, total, pool = g("blocks_used"), g("blocks_total"), \
            g("kv_pool_bytes")
        if total <= 0 or pool <= 0:
            continue  # retired slot: the stale-gauge sweep zeroed it
        block_bytes = pool // (total + 1)
        cached = (g("cache_bytes") // block_bytes) if block_bytes else 0
        leaks[f"replica{i}"] = used - cached
    return leaks


def compare_streams(ref_results: list, chaos_results: list):
    """Greedy bit-equality across the fault-free and chaos replays:
    for every trace row that COMPLETED in both runs (scripted cancels
    excluded — a hang-up races completion, so a row can legitimately
    complete in one run and cancel in the other), the output shas must
    match — migration/hedging must not lose, duplicate, or alter one
    token."""
    compared = 0
    mismatches = []
    for a, b in zip(ref_results, chaos_results):
        if (a["ok"] and b["ok"]
                and not a["cancelled"] and not b["cancelled"]):
            compared += 1
            if a["sha"] != b["sha"]:
                mismatches.append(
                    {"i": a["i"], "tag": b["tag"],
                     "ref_sha": a["sha"], "chaos_sha": b["sha"]})
    return compared, mismatches


def settle_fleet(rep, sock: str, floor: int, log,
                 timeout_s: float = 180.0) -> dict:
    """Post-burst convergence poll (--autoscale): wait for the fleet
    to return to its ``floor`` serving replicas with the brownout
    ladder fully released.  This is the decay half of the elastic
    story — the scrape that follows captures the scale-in and reversal
    counters the acceptance block gates on."""
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < timeout_s:
        try:
            last = json.loads(rep.request(sock, "fleet"))
        except Exception:
            time.sleep(0.5)
            continue
        active = last.get("active")
        level = (last.get("brownout") or {}).get("level", 0)
        target = (last.get("autoscale") or {}).get("target")
        if active == floor and level == 0 and target == floor:
            waited = time.monotonic() - t0
            log(f"[goodput_gate] fleet settled at floor={floor}, "
                f"brownout level 0 after {waited:.1f}s")
            return {"settled": True, "waited_s": round(waited, 3),
                    "final": last}
        time.sleep(0.5)
    return {"settled": False,
            "waited_s": round(time.monotonic() - t0, 3), "final": last}


def run_replay(args, rep, trace, *, extra_env=None, extra_args=None,
               rolling=False, settle=None, label=""):
    """One full replay window against a (possibly spawned) daemon:
    warmup outside the window, before/after scrapes, trace replay,
    slowlog + fleet captures, optional rolling-restart phase.
    ``settle`` (the autoscale scenario) runs between the replay and
    the after-scrape, so convergence-phase counter movement lands in
    the deltas.  Returns every capture the report needs."""
    daemon_proc = None
    extra_args = list(extra_args or ())
    if getattr(args, "attribute", False):
        # arm a deep journey store: every trace row's journey must
        # still be resident when the attribution pass queries by tag
        extra_args += ["--journeys", "4096"]
    if args.spawn_daemon:
        daemon_proc = _spawn_daemon(
            args.socket, max(args.slowlog, 16), 1 << 16,
            replicas=args.replicas, extra_env=extra_env,
            extra_args=extra_args)
    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    try:
        # warmup OUTSIDE the measured window: the first request pays
        # engine build + XLA compile; a goodput number that charges
        # cold start to the first trace row measures the wrong thing
        for i in range(args.warmup):
            rep.request_with_retry(args.socket, "generate", {"steps": 4},
                                   b"goodput gate warmup",
                                   deadline_s=300.0)
        before = rep.parse_prometheus(
            rep.request(args.socket, "metrics").decode("utf-8"))
        results, wall_s = loadgen.replay(
            trace, args.socket, time_scale=args.time_scale,
            timeout_s=args.timeout_s,
            log=lambda m: log(f"{label}{m}"))
        settled = None
        if settle is not None:
            settled = settle(log)
        after = rep.parse_prometheus(
            rep.request(args.socket, "metrics").decode("utf-8"))
        slow = json.loads(rep.request(args.socket, "slowlog",
                                      {"n": args.slowlog}))
        try:
            fleet = json.loads(rep.request(args.socket, "fleet"))
        except Exception:
            fleet = None
        journeys = None
        if getattr(args, "attribute", False):
            journeys = capture_journeys(rep, args.socket, results, after)
        roll = None
        if rolling:
            roll = rolling_restart(rep, args.socket, args.replicas, log)
    finally:
        _reap(daemon_proc)
    return {"results": results, "wall_s": wall_s, "before": before,
            "after": after, "slow": slow, "fleet": fleet, "roll": roll,
            "settled": settled, "journeys": journeys}


def capture_journeys(rep, sock: str, results, after) -> dict:
    """Attribution captures taken while the replay daemon is still
    alive (``--attribute``): one stitched journey per trace row —
    joined on the wire tag, which the daemon threads into the journey
    store — the store stats, and every histogram exemplar from the
    after-scrape resolved back to a live journey rid.  The acceptance
    pass consumes these after the daemon is gone."""
    by_tag: dict = {}
    for r in results:
        tag = r.get("tag")
        if not tag or tag in by_tag:
            continue
        try:
            resp = json.loads(rep.request(sock, "journey", {"tag": tag}))
            by_tag[tag] = resp.get("journey")
        except Exception:
            by_tag[tag] = None
    exemplars = []
    resolved_rids: dict = {}
    for mname, m in sorted((after or {}).items()):
        for le, (rid, v) in sorted((m.get("exemplars") or {}).items()):
            if rid not in resolved_rids:
                try:
                    resp = json.loads(
                        rep.request(sock, "journey", {"rid": rid}))
                    resolved_rids[rid] = resp.get("journey") is not None
                except Exception:
                    resolved_rids[rid] = False
            exemplars.append(
                {"metric": mname,
                 "le": "+Inf" if le == float("inf") else le,
                 "rid": rid, "value": v,
                 "resolved": resolved_rids[rid]})
    try:
        stats = json.loads(
            rep.request(sock, "journey", {"n": 0})).get("stats")
    except Exception:
        stats = None
    return {"by_tag": by_tag, "exemplars": exemplars, "stats": stats}


def build_attribution(results, trace, jcap: dict, counters: dict,
                      slowlog) -> dict:
    """Per-phase SLO attribution (``--attribute``): fold the captured
    journeys into per-request phase breakdowns, verify each journey's
    internal invariants (contiguous + monotonic waterfall, handoff
    phases summing to the recorded ``handoff_ms``, agreement with the
    slowlog's entry for the same rid), and classify every SLO miss by
    its dominant phase.  Returns the report section; ``problems`` is
    the list of invariant violations the acceptance pass fails on."""
    classes = {c["name"]: c for c in trace.classes}
    by_tag = jcap.get("by_tag") or {}
    slow_by_rid = {e.get("rid"): e for e in (slowlog or [])}
    rows, misses, problems = [], [], []
    dominant: dict = {}
    handed = 0
    bytes_sum = 0
    for r in results:
        if (r.get("cancelled") or r.get("shed") or r.get("rebuilding")
                or not r.get("ok")):
            continue
        tag = r.get("tag")
        j = by_tag.get(tag)
        if not j:
            problems.append(f"{tag}: completed request has no resident "
                            f"journey")
            continue
        if not j.get("completed"):
            problems.append(f"{tag}: journey never saw its retire mark")
        phases = j.get("phases") or []
        if not phases:
            problems.append(f"{tag}: journey stitched zero phases")
            continue
        for a, b in zip(phases, phases[1:]):
            if a["t1_ms"] != b["t0_ms"]:
                problems.append(
                    f"{tag}: waterfall not contiguous — {a['phase']} "
                    f"ends at {a['t1_ms']}ms but {b['phase']} starts "
                    f"at {b['t0_ms']}ms")
        for p in phases:
            if p["ms"] < 0 or p["t1_ms"] < p["t0_ms"]:
                problems.append(f"{tag}: non-monotonic phase "
                                f"{p['phase']} ({p['ms']}ms)")
        hsum = round(sum(p["ms"] for p in phases
                         if p["phase"] in HANDOFF_PHASES), 3)
        if j.get("handoff_ms") is not None:
            handed += 1
            bytes_sum += int(j.get("handoff_bytes") or 0)
            if abs(hsum - j["handoff_ms"]) > 0.01:
                problems.append(
                    f"{tag}: handoff phases sum to {hsum}ms but the "
                    f"journey recorded handoff_ms={j['handoff_ms']}")
            sl = slow_by_rid.get(j.get("rid"))
            if (sl is not None and sl.get("handoff_ms") is not None
                    and abs(sl["handoff_ms"] - j["handoff_ms"]) > 0.01):
                problems.append(
                    f"{tag}: slowlog handoff_ms={sl['handoff_ms']} "
                    f"disagrees with journey {j['handoff_ms']}")
        dom = max(phases, key=lambda p: p["ms"])
        dominant[dom["phase"]] = dominant.get(dom["phase"], 0) + 1
        c = classes[r["cls"]]
        failed = []
        if r["ttft_ms"] is None or r["ttft_ms"] > c["ttft_ms"]:
            failed.append("ttft")
        if r["itl_max_ms"] > c["itl_ms"]:
            failed.append("itl")
        if r["e2e_ms"] is None or r["e2e_ms"] > c["e2e_ms"]:
            failed.append("e2e")
        row = {"tag": tag, "rid": j["rid"], "cls": r["cls"],
               "e2e_ms": j.get("e2e_ms"),
               "dominant_phase": dom["phase"], "dominant_ms": dom["ms"],
               "handoff_ms": j.get("handoff_ms"),
               "handoff_bytes": j.get("handoff_bytes"),
               "pools": j.get("pools"),
               "phases": {p["phase"]: p["ms"] for p in phases}}
        rows.append(row)
        if failed:
            misses.append(dict(row, failed=failed))
    misses_by_phase: dict = {}
    for m in misses:
        misses_by_phase[m["dominant_phase"]] = (
            misses_by_phase.get(m["dominant_phase"], 0) + 1)
    exemplars = jcap.get("exemplars") or []
    return {
        "requests": rows,
        "misses": misses,
        "misses_by_phase": misses_by_phase,
        "dominant_by_phase": dominant,
        "handed_off": handed,
        "handoff_bytes_sum": bytes_sum,
        "counter_daemon_handoffs": counters.get("daemon_handoffs", 0),
        "counter_handoff_bytes": counters.get("handoff_bytes", 0),
        "exemplars": exemplars,
        "exemplars_resolved": sum(1 for e in exemplars if e["resolved"]),
        "journey_stats": jcap.get("stats"),
        "problems": problems,
    }


def run_kill_replay(args, rep, trace, ref_wall_s: float,
                    label="[kill] "):
    """The crash-durability scenario (round 16): replay the trace
    against a journal-armed daemon, SIGKILL the daemon PROCESS
    mid-trace (``proc.kill()`` — no signal handler, no cleanup, the
    spot-preemption/OOM stand-in), restart it on the SAME socket and
    journal, and let the clients' reconnect-with-resume path carry
    every stream across the crash.  The restarted daemon replays
    incomplete journaled requests through ``PagedEngine.resubmit``, so
    surviving outputs must be bit-identical to the fault-free
    reference.  Returns the standard run captures plus the kill
    bookkeeping; counters scraped AFTER are absolute values from the
    restarted process (its registry starts at zero — deltas against the
    pre-kill scrape would be meaningless)."""
    import threading

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    fd, journal = tempfile.mkstemp(suffix=".journal.jsonl")
    os.close(fd)
    os.unlink(journal)  # the daemon creates it; mkstemp just named it
    holder = {"proc": None}
    kill_err = []
    killed = {"n": 0}
    kill_after_s = max(1.0, ref_wall_s * args.kill_at)

    def killer():
        try:
            time.sleep(kill_after_s)
            p = holder["proc"]
            p.kill()  # SIGKILL: the journal's whole reason to exist
            p.wait()
            killed["n"] += 1
            log(f"{label}[goodput_gate] SIGKILLed daemon pid={p.pid} "
                f"at t+{kill_after_s:.1f}s; restarting on the same "
                f"socket + journal")
            holder["proc"] = _spawn_daemon(
                args.socket, max(args.slowlog, 16), 1 << 16,
                replicas=args.replicas,
                extra_args=["--journal", journal])
        except BaseException as e:  # surfaced after the replay joins
            kill_err.append(e)

    holder["proc"] = _spawn_daemon(
        args.socket, max(args.slowlog, 16), 1 << 16,
        replicas=args.replicas, extra_args=["--journal", journal])
    try:
        for _ in range(args.warmup):
            rep.request_with_retry(args.socket, "generate", {"steps": 4},
                                   b"goodput gate warmup",
                                   deadline_s=300.0)
        before = rep.parse_prometheus(
            rep.request(args.socket, "metrics").decode("utf-8"))
        th = threading.Thread(target=killer, daemon=True)
        th.start()
        results, wall_s = loadgen.replay(
            trace, args.socket, time_scale=args.time_scale,
            timeout_s=args.timeout_s,
            log=lambda m: log(f"{label}{m}"))
        th.join(timeout=180)
        if kill_err:
            raise RuntimeError(
                f"kill/restart thread failed: {kill_err[0]!r}"
            ) from kill_err[0]
        # scrapes come from the RESTARTED process: absolute values
        after = rep.parse_prometheus(
            rep.request_with_retry(args.socket, "metrics",
                                   deadline_s=120.0).decode("utf-8"))
        slow = json.loads(rep.request(args.socket, "slowlog",
                                      {"n": args.slowlog}))
        try:
            fleet = json.loads(rep.request(args.socket, "fleet"))
        except Exception:
            fleet = None
    finally:
        _reap(holder["proc"])
        try:
            os.unlink(journal)
        except OSError:
            pass
    return {"results": results, "wall_s": wall_s, "before": before,
            "after": after, "slow": slow, "fleet": fleet, "roll": None,
            "killed": killed["n"], "kill_after_s": kill_after_s}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket", default="/tmp/tpulab.sock")
    ap.add_argument("--spawn-daemon", action="store_true",
                    help="spawn a private daemon on --socket for the "
                         "replay and stop it after (HOST tier only — "
                         "never own a chip-claiming daemon from here)")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="replay this committed trace JSON instead of "
                         "building one from --spec")
    ap.add_argument("--spec", default="fast",
                    help=f"built-in spec name ({sorted(loadgen.SPECS)}) "
                         f"when --trace is not given")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the spec seed")
    ap.add_argument("--n", type=int, default=None,
                    help="override the spec request count")
    ap.add_argument("--write-trace", default=None, metavar="FILE",
                    help="persist the built trace JSON (the run's exact "
                         "workload definition)")
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="multiply trace send times (0 = fire as fast "
                         "as possible)")
    ap.add_argument("--timeout-s", type=float, default=120.0,
                    help="per-request hard deadline during replay")
    ap.add_argument("--warmup", type=int, default=2, metavar="N",
                    help="generate requests sent before the measured "
                         "window (engine build + XLA compile must not "
                         "count against the first trace row's TTFT)")
    ap.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="fleet size for the spawned daemon "
                         "(--spawn-daemon); the chaos scenario needs "
                         ">= 3 (replica1 crashes, replica2 wedges)")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos-certify the fleet: replay the trace "
                         "FAULT-FREE first (reference outputs), then "
                         "again with CHAOS_SCHEDULE armed (crash one "
                         "replica mid-trace, wedge another) and gate: "
                         "every non-cancelled request completes, "
                         "streamed chunks reassemble exactly, and "
                         "completed outputs are bit-identical to the "
                         "reference (zero lost/duplicated tokens)")
    ap.add_argument("--kill-daemon", action="store_true",
                    help="crash-durability certification (round 16): "
                         "replay FAULT-FREE first against a journal-"
                         "armed daemon (reference outputs), then again "
                         "while SIGKILLing the daemon process "
                         "mid-trace; the restarted daemon recovers "
                         "from the write-ahead journal and clients "
                         "resume streams by rid — gate on every "
                         "non-cancelled request completing "
                         "bit-identical to the reference with zero "
                         "lost/duplicated tokens client-side")
    ap.add_argument("--autoscale", action="store_true",
                    help="elastic-fleet certification (round 17): "
                         "replay the trace FAULT-FREE against a fixed "
                         "one-replica daemon (reference outputs), then "
                         "again against an autoscaler-armed daemon "
                         "(floor 1, ceiling 3) with one spot "
                         "preemption injected on the scaled-out "
                         "replica — gate on scale-out engaging, "
                         "brownout steps firing AND fully reversing, "
                         "attainment 1.0, zero lost/duplicated client "
                         "bytes vs the reference, and the fleet "
                         "settling back to its floor (use with "
                         "--spec ramp)")
    ap.add_argument("--autoscale-max", type=int, default=3, metavar="N",
                    help="ceiling passed to the autoscaler-armed "
                         "daemon in the --autoscale scenario")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="hierarchical-cache certification (round 18): "
                         "replay the trace against an HBM-only daemon "
                         "(dict prefix index — the reference outputs "
                         "AND the hit-rate floor), then again against "
                         "a radix + host-RAM spill-tier daemon "
                         "(--prefix-index radix --spill-blocks N) and "
                         "gate: the trace's block-aligned working set "
                         "is >= 4x the 128-block HBM pool, the "
                         "spill-enabled hit rate is STRICTLY above "
                         "HBM-only, blocks actually spilled AND "
                         "prefetched, attainment >= the reference, and "
                         "every stream is bit-identical to the spill-"
                         "disabled reference (use with --spec prefix)")
    ap.add_argument("--spill-blocks", type=int, default=512, metavar="N",
                    help="host spill-tier capacity (blocks) for the "
                         "armed daemon in the --prefix-cache scenario "
                         "and BOTH daemons of the --disagg scenario")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated-serving certification (round "
                         "20): replay the trace against a UNIFIED "
                         "single-engine daemon (same radix + spill "
                         "config — the reference outputs and the "
                         "decode-latency floor), then again against a "
                         "phase-disaggregated fleet (--pool-spec) "
                         "where every request prefills in the prefill "
                         "pool and hands its KV blocks to the decode "
                         "pool over the host spill tier — gate on "
                         "handoffs actually firing, decode ITL p99 "
                         "staying flat vs the unified reference while "
                         "the heavy-tail prefills run, attainment 1.0, "
                         "every stream bit-identical to the reference, "
                         "zero leaked blocks in BOTH pools, and the "
                         "prefill pool scaling on its own signal while "
                         "the decode pool holds its floor (use with "
                         "--spec disagg)")
    ap.add_argument("--pool-spec", default="prefill=1..2,decode=1",
                    metavar="SPEC",
                    help="pool layout handed to the disaggregated "
                         "daemon in the --disagg scenario (the default "
                         "gives the prefill pool scale-out headroom "
                         "and pins the decode pool)")
    ap.add_argument("--attribute", action="store_true",
                    help="per-phase SLO attribution (round 21): arm a "
                         "deep journey store in the spawned daemon, "
                         "join every trace row to its stitched "
                         "cross-engine journey by wire tag, and gate "
                         "on the journey invariants — every completed "
                         "request has ONE journey whose phase "
                         "waterfall is contiguous and monotonic "
                         "across both pools, whose handoff phases sum "
                         "to its recorded handoff_ms, whose bytes "
                         "match the daemon_handoffs/handoff_bytes "
                         "counter deltas exactly (--disagg), and at "
                         "least one histogram exemplar resolves to a "
                         "live journey rid; every SLO miss is broken "
                         "down by its dominant phase in the report")
    ap.add_argument("--kill-at", type=float, default=0.4, metavar="F",
                    help="when to SIGKILL, as a fraction of the "
                         "reference replay's wall time (default 0.4)")
    ap.add_argument("--rolling-restart", action="store_true",
                    help="after the replay, roll every replica "
                         "(drain -> rebuild -> undrain) under steady "
                         "background load and gate on ZERO shed/"
                         "parked/errored requests")
    ap.add_argument("--slowlog", type=int, default=8, metavar="N",
                    help="worst-N slow-log entries to embed in the report")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the full JSON report here")
    ap.add_argument("--min-attainment", type=float, default=0.0,
                    help="hard floor on overall SLO attainment (exit 1 "
                         "below it)")
    ap.add_argument("--check-baselines", action="store_true",
                    help="gate the emitted rows against "
                         "results/baselines.json via check_regression "
                         "(exit 1 on regression)")
    ap.add_argument("--baselines", default=str(ROOT / "results"
                                               / "baselines.json"))
    args = ap.parse_args(argv)

    rep = _load_obs_report()
    if args.trace:
        trace = loadgen.Trace.load(args.trace)
    else:
        spec = loadgen.built_in_spec(args.spec)
        if args.seed is not None or args.n is not None:
            from dataclasses import replace

            spec = replace(
                spec,
                **({"seed": args.seed} if args.seed is not None else {}),
                **({"n_requests": args.n} if args.n is not None else {}))
        trace = loadgen.build_trace(spec)
    if args.write_trace:
        trace.save(args.write_trace)
    name = trace.spec.get("name", "trace")

    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    chaos = None
    kill = None
    autoscale = None
    prefix_cache = None
    disagg = None
    if args.autoscale and (args.chaos or args.kill_daemon):
        ap.error("--autoscale is its own scenario: run --chaos/"
                 "--kill-daemon as separate invocations")
    if args.prefix_cache and (args.chaos or args.kill_daemon
                              or args.autoscale):
        ap.error("--prefix-cache is its own scenario: run --chaos/"
                 "--kill-daemon/--autoscale as separate invocations")
    if args.disagg and (args.chaos or args.kill_daemon
                        or args.autoscale or args.prefix_cache):
        ap.error("--disagg is its own scenario: run --chaos/"
                 "--kill-daemon/--autoscale/--prefix-cache as "
                 "separate invocations")
    if args.attribute and not args.spawn_daemon:
        ap.error("--attribute needs --spawn-daemon (the attribution "
                 "pass queries the journey store of the daemon the "
                 "gate owns, before tearing it down)")
    if args.attribute and args.kill_daemon:
        ap.error("--attribute and --kill-daemon are incompatible: the "
                 "SIGKILL restart resets the journey store mid-window")
    if args.kill_daemon:
        if not args.spawn_daemon:
            ap.error("--kill-daemon needs --spawn-daemon (the gate "
                     "must own the process it kills)")
        if args.chaos:
            ap.error("--kill-daemon and --chaos are separate "
                     "scenarios: run them as separate invocations")
        if not 0.0 < args.kill_at < 1.0:
            ap.error("--kill-at must be in (0, 1)")
        # metric rows get their own name: the kill run's attainment is
        # NOT comparable to the chaos baselines (a full process restart
        # sits inside the measured window)
        name = "kill"
        # fault-free reference first, SAME journal-armed config: its
        # shas are what every stream resumed across the crash must
        # equal bit-for-bit
        fd, ref_journal = tempfile.mkstemp(suffix=".journal.jsonl")
        os.close(fd)
        os.unlink(ref_journal)
        try:
            ref = run_replay(args, rep, trace, label="[ref] ",
                             extra_args=["--journal", ref_journal])
        finally:
            try:
                os.unlink(ref_journal)
            except OSError:
                pass
        run = run_kill_replay(args, rep, trace, ref["wall_s"])
        compared, mismatches = compare_streams(ref["results"],
                                               run["results"])
        kill = {"compared": compared, "mismatches": mismatches,
                "killed": run["killed"],
                "kill_after_s": round(run["kill_after_s"], 3),
                "reference_wall_s": round(ref["wall_s"], 3)}
    elif args.chaos:
        if not args.spawn_daemon:
            ap.error("--chaos needs --spawn-daemon (the reference and "
                     "chaos replays each own a private daemon)")
        if args.replicas < 3:
            ap.error("--chaos targets replica1 (crash) and replica2 "
                     "(wedge): use --replicas >= 3")
        # fault-free REFERENCE replay first: its per-request output
        # shas are what the chaos run's surviving streams must equal
        ref = run_replay(args, rep, trace, label="[ref] ")
        fault_env = {"TPULAB_FAULTS": json.dumps(CHAOS_SCHEDULE)}
        run = run_replay(args, rep, trace, extra_env=fault_env,
                         rolling=args.rolling_restart, label="[chaos] ")
        compared, mismatches = compare_streams(ref["results"],
                                               run["results"])
        chaos = {"schedule": CHAOS_SCHEDULE, "compared": compared,
                 "mismatches": mismatches,
                 "reference_wall_s": round(ref["wall_s"], 3)}
    elif args.autoscale:
        if not args.spawn_daemon:
            ap.error("--autoscale needs --spawn-daemon (the reference "
                     "and elastic replays each own a private daemon)")
        if args.replicas != 1:
            ap.error("--autoscale starts at the fleet floor: use "
                     "--replicas 1")
        if args.autoscale_max < 2:
            ap.error("--autoscale-max must be >= 2 (the scenario must "
                     "have headroom to scale out)")
        # fault-free, fixed-size, autoscaler-DISARMED reference first:
        # its shas are the disabled-by-default contract — every stream
        # the elastic run serves (across scale-out, brownout, and the
        # preemption) must equal them bit-for-bit
        ref = run_replay(args, rep, trace, label="[ref] ")
        fault_env = {"TPULAB_FAULTS": json.dumps(RAMP_PREEMPT_SCHEDULE)}
        auto_args = ["--autoscale-min", "1",
                     "--autoscale-max", str(args.autoscale_max),
                     # a tighter control-loop cadence than the 1 s
                     # default: the trace's burst phase is short
                     "--metrics-interval", "0.5"]
        run = run_replay(
            args, rep, trace, extra_env=fault_env,
            extra_args=auto_args, label="[autoscale] ",
            settle=lambda log: settle_fleet(rep, args.socket, 1, log))
        compared, mismatches = compare_streams(ref["results"],
                                               run["results"])
        autoscale = {"schedule": RAMP_PREEMPT_SCHEDULE,
                     "ceiling": args.autoscale_max,
                     "compared": compared, "mismatches": mismatches,
                     "settled": run["settled"],
                     "reference_wall_s": round(ref["wall_s"], 3)}
    elif args.prefix_cache:
        if not args.spawn_daemon:
            ap.error("--prefix-cache needs --spawn-daemon (the "
                     "HBM-only and spill-enabled replays each own a "
                     "private daemon)")
        if args.spill_blocks < 1:
            ap.error("--spill-blocks must be >= 1")
        # The scenario only proves anything when the trace's shared-
        # prefix working set cannot fit on-chip: require >= 4x the
        # serving pool (128 blocks of 16 tokens each — the config
        # tpulab/daemon.py _build_engine hard-wires).  Prompts are
        # byte-level tokens, so the block-aligned working set is
        # countable from the trace alone; depth mirrors the engine's
        # prefill region (prompt minus the last token).
        srv_bs, srv_pool = 16, 128
        ws = set()
        for r in trace.requests:
            pb = r["prompt"].encode()
            for j in range(1, (len(pb) - 1) // srv_bs + 1):
                ws.add(pb[: srv_bs * j])
        if len(ws) < 4 * srv_pool:
            ap.error(f"trace working set {len(ws)} blocks < 4x the "
                     f"{srv_pool}-block HBM pool: use --spec prefix "
                     f"or a heavier shared-prefix trace")
        # HBM-only reference first: the default dict prefix index with
        # NO spill tier.  Its per-request output shas are the
        # bit-equality contract the hierarchical cache must honour,
        # and its hit rate is the floor it must strictly beat.
        ref = run_replay(args, rep, trace, label="[hbm] ")
        run = run_replay(
            args, rep, trace, label="[spill] ",
            extra_args=["--prefix-index", "radix",
                        "--spill-blocks", str(args.spill_blocks),
                        "--spill-dtype", "native"])
        compared, mismatches = compare_streams(ref["results"],
                                               run["results"])

        # engine_* stats are published as gauges holding cumulative
        # engine counters, NOT in counter_deltas' daemon counter set —
        # delta the scrapes directly
        def _gdelta(cap, gname):
            a = cap["after"].get(gname, {}).get("value") or 0
            b = cap["before"].get(gname, {}).get("value") or 0
            return int(a - b)

        def _rate(cap):
            h = _gdelta(cap, "engine_prefix_hits")
            m = _gdelta(cap, "engine_prefix_misses")
            return h, m, (h / (h + m) if h + m else 0.0)

        hbm_h, hbm_m, hbm_rate = _rate(ref)
        sp_h, sp_m, sp_rate = _rate(run)
        ref_overall = loadgen.summarize(
            ref["results"], trace, ref["wall_s"])["overall"]
        prefix_cache = {
            "working_set_blocks": len(ws), "pool_blocks": srv_pool,
            "spill_blocks": args.spill_blocks,
            "compared": compared, "mismatches": mismatches,
            "hbm_hits": hbm_h, "hbm_misses": hbm_m,
            "hbm_hit_rate": round(hbm_rate, 4),
            "spill_hits": sp_h, "spill_misses": sp_m,
            "spill_hit_rate": round(sp_rate, 4),
            "spilled_blocks": _gdelta(run, "engine_spill_spilled"),
            "prefetched_blocks": _gdelta(run, "engine_spill_prefetched"),
            "spill_admission_hits": _gdelta(run, "engine_spill_hits"),
            "reference_attainment": ref_overall["attainment"],
            "reference_wall_s": round(ref["wall_s"], 3)}
    elif args.disagg:
        if not args.spawn_daemon:
            ap.error("--disagg needs --spawn-daemon (the unified "
                     "reference and pooled replays each own a private "
                     "daemon)")
        if args.replicas != 1:
            ap.error("--disagg measures the pooled fleet against a "
                     "UNIFIED single-engine reference: use --replicas 1")
        if args.spill_blocks < 1:
            ap.error("--spill-blocks must be >= 1 (the handoff wire "
                     "format IS the host spill tier)")
        # UNIFIED reference first, with the SAME radix + spill config
        # the pooled fleet runs (the only variable under test is WHERE
        # each phase executes): its per-request shas are the
        # bit-equality contract and its decode ITL p99 is the
        # latency floor the disaggregated fleet must not degrade —
        # on the unified engine the heavy-tail prefills time-share the
        # one engine with every decoding stream, which is exactly the
        # interference disaggregation removes.
        cache_args = ["--prefix-index", "radix",
                      "--spill-blocks", str(args.spill_blocks)]
        ref = run_replay(args, rep, trace, label="[unified] ",
                         extra_args=cache_args)
        run = run_replay(
            args, rep, trace, label="[disagg] ",
            extra_args=cache_args + [
                "--pool-spec", args.pool_spec,
                # a tight control-loop cadence so the prefill pool's
                # queue-wait burn can act within the trace window
                "--metrics-interval", "0.5"])
        compared, mismatches = compare_streams(ref["results"],
                                               run["results"])
        ref_win = window_percentiles(ref["before"], ref["after"])
        run_win = window_percentiles(run["before"], run["after"])
        ref_itl = (ref_win.get("itl_seconds") or {}).get("p99_ms")
        run_itl = (run_win.get("itl_seconds") or {}).get("p99_ms")
        # "flat within noise": the CPU proxy's bucket-granular p99 and
        # scheduler jitter need both a relative band and an absolute
        # floor — a 2 ms reference p99 must not fail on a 3 ms reading
        itl_budget = (max(1.5 * ref_itl, ref_itl + 50.0)
                      if ref_itl is not None else None)
        ref_overall = loadgen.summarize(
            ref["results"], trace, ref["wall_s"])["overall"]
        disagg = {
            "pool_spec": args.pool_spec,
            "spill_blocks": args.spill_blocks,
            "compared": compared, "mismatches": mismatches,
            "reference_itl_p99_ms": ref_itl,
            "disagg_itl_p99_ms": run_itl,
            "itl_budget_ms": (round(itl_budget, 3)
                              if itl_budget is not None else None),
            "leaked_blocks": leaked_blocks(run["after"]),
            "reference_attainment": ref_overall["attainment"],
            "reference_wall_s": round(ref["wall_s"], 3)}
    else:
        run = run_replay(args, rep, trace,
                         rolling=args.rolling_restart)
    results, wall_s = run["results"], run["wall_s"]
    before, after, slow = run["before"], run["after"], run["slow"]

    goodput = loadgen.summarize(results, trace, wall_s)
    report = {
        "trace": {"name": name, "seed": trace.spec.get("seed"),
                  "n_requests": len(trace.requests),
                  "arrival": trace.spec.get("arrival"),
                  "source": args.trace or f"spec:{args.spec}"},
        "replicas": args.replicas,
        "goodput": goodput,
        "server_window": window_percentiles(before, after),
        "counters": counter_deltas(before, after),
        "slowlog": slow.get("worst", []),
        "fleet": run["fleet"],
        "results": results,
    }
    if chaos is not None:
        report["chaos"] = chaos
    if kill is not None:
        report["kill"] = kill
    if autoscale is not None:
        report["autoscale"] = autoscale
    if prefix_cache is not None:
        report["prefix_cache"] = prefix_cache
    if disagg is not None:
        report["disagg"] = disagg
    attribution = None
    if args.attribute and run.get("journeys") is not None:
        attribution = build_attribution(
            results, trace, run["journeys"], report["counters"],
            report["slowlog"])
        report["attribution"] = attribution
    if run["roll"] is not None:
        report["rolling_restart"] = run["roll"]
    if args.out:
        out = pathlib.Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2) + "\n")
        print(f"[goodput_gate] report -> {args.out}", file=sys.stderr,
              flush=True)

    overall = goodput["overall"]
    rows = [
        {"metric": f"goodput_{name}_goodput_tokens_per_s",
         "value": overall["goodput_tokens_per_s"], "unit": "tokens/s",
         "vs_baseline": None, "attainment": overall["attainment"],
         "completed": overall["completed"], "shed": overall["shed"],
         "rebuilding": overall["rebuilding"],
         "cancelled": overall["cancelled"], "errors": overall["errors"],
         "wall_s": overall["wall_s"]},
        {"metric": f"goodput_{name}_slo_attainment",
         "value": overall["attainment"], "unit": "fraction",
         "vs_baseline": None, "in_slo": overall["in_slo"],
         "eligible": overall["n"] - overall["cancelled"]},
    ]
    if prefix_cache is not None:
        rows.append(
            {"metric": "prefix_cache_hit_rate",
             "value": prefix_cache["spill_hit_rate"],
             "unit": "fraction", "vs_baseline": None,
             "hbm_hit_rate": prefix_cache["hbm_hit_rate"],
             "working_set_blocks": prefix_cache["working_set_blocks"],
             "spilled_blocks": prefix_cache["spilled_blocks"],
             "prefetched_blocks": prefix_cache["prefetched_blocks"]})
    for row in rows:
        print(json.dumps(row), flush=True)

    rc = 0
    if overall["errors"]:
        bad = [r for r in results if r["error"]][:3]
        print(f"[goodput_gate] FAIL: {overall['errors']} hard error(s), "
              f"e.g. {bad}", file=sys.stderr, flush=True)
        rc = 1
    if chaos is not None:
        # chaos acceptance: the fault schedule actually fired, every
        # non-cancelled request completed, streams reassembled exactly,
        # and surviving outputs are bit-identical to the reference
        counters = report["counters"]
        if counters.get("daemon_engine_restarts", 0) < 1:
            print("[goodput_gate] FAIL: chaos schedule never crashed a "
                  "replica (daemon_engine_restarts delta 0) — the run "
                  "proved nothing", file=sys.stderr, flush=True)
            rc = 1
        incomplete = [r for r in results
                      if not r["cancelled"] and not r["ok"]][:3]
        if incomplete:
            print(f"[goodput_gate] FAIL: non-cancelled request(s) did "
                  f"not complete under chaos, e.g. {incomplete}",
                  file=sys.stderr, flush=True)
            rc = 1
        torn = [r for r in results
                if r["ok"] and r.get("stream_ok") is False][:3]
        if torn:
            print(f"[goodput_gate] FAIL: streamed chunks do not "
                  f"reassemble to the terminal output (lost/duplicated "
                  f"tokens), e.g. {torn}", file=sys.stderr, flush=True)
            rc = 1
        if chaos["mismatches"]:
            print(f"[goodput_gate] FAIL: {len(chaos['mismatches'])} "
                  f"stream(s) diverged from the fault-free reference, "
                  f"e.g. {chaos['mismatches'][:3]}",
                  file=sys.stderr, flush=True)
            rc = 1
        print(f"[goodput_gate] chaos: {chaos['compared']} streams "
              f"bit-compared vs reference, "
              f"{counters.get('daemon_engine_restarts', 0)} restart(s), "
              f"{counters.get('daemon_migrations', 0)} migration(s)",
              file=sys.stderr, flush=True)
    if kill is not None:
        # kill acceptance: the process actually died, the restarted
        # daemon recovered journaled work and answered resumes, every
        # non-cancelled request completed, client-side streams carry
        # zero lost/duplicated bytes, and surviving outputs are
        # bit-identical to the fault-free reference.  Counters are
        # ABSOLUTE values from the restarted process (registry reset).
        if run["killed"] < 1:
            print("[goodput_gate] FAIL: the daemon was never killed — "
                  "the run proved nothing", file=sys.stderr, flush=True)
            rc = 1
        recov = int(run["after"].get("daemon_recoveries",
                                     {}).get("value") or 0)
        resumed = int(run["after"].get("daemon_resumed_streams",
                                       {}).get("value") or 0)
        if recov < 1:
            print("[goodput_gate] FAIL: the restarted daemon replayed "
                  "no journaled request (daemon_recoveries 0) — the "
                  "kill landed outside any in-flight window or "
                  "recovery is broken", file=sys.stderr, flush=True)
            rc = 1
        if resumed < 1:
            print("[goodput_gate] FAIL: no client stream was resumed "
                  "by rid (daemon_resumed_streams 0)",
                  file=sys.stderr, flush=True)
            rc = 1
        incomplete = [r for r in results
                      if not r["cancelled"] and not r["ok"]][:3]
        if incomplete:
            print(f"[goodput_gate] FAIL: non-cancelled request(s) did "
                  f"not complete across the kill, e.g. {incomplete}",
                  file=sys.stderr, flush=True)
            rc = 1
        torn = [r for r in results
                if r["ok"] and r.get("stream_ok") is False][:3]
        if torn:
            print(f"[goodput_gate] FAIL: resumed streams carry lost/"
                  f"duplicated bytes client-side, e.g. {torn}",
                  file=sys.stderr, flush=True)
            rc = 1
        if kill["mismatches"]:
            print(f"[goodput_gate] FAIL: {len(kill['mismatches'])} "
                  f"stream(s) diverged from the fault-free reference "
                  f"across the kill, e.g. {kill['mismatches'][:3]}",
                  file=sys.stderr, flush=True)
            rc = 1
        reconnected = sum(r.get("reconnects", 0) for r in results)
        print(f"[goodput_gate] kill: {kill['compared']} streams "
              f"bit-compared vs reference, {run['killed']} kill(s), "
              f"{recov} journal recover(ies), {resumed} resumed "
              f"stream(s), {reconnected} client reconnect(s)",
              file=sys.stderr, flush=True)
    if autoscale is not None:
        # elastic acceptance: the controller actually scaled out AND
        # back in, the brownout ladder engaged and FULLY reversed, the
        # injected preemption fired, the fleet settled at its floor,
        # attainment held at 1.0 through the ramp, streams reassembled
        # exactly, and every surviving output is bit-identical to the
        # disarmed reference (zero lost/duplicated client bytes).
        counters = report["counters"]
        if counters.get("daemon_scale_outs", 0) < 1:
            print("[goodput_gate] FAIL: the ramp never drove a "
                  "scale-out (daemon_scale_outs delta 0) — the run "
                  "proved nothing", file=sys.stderr, flush=True)
            rc = 1
        if counters.get("daemon_scale_ins", 0) < 1:
            print("[goodput_gate] FAIL: the decay never drove a "
                  "scale-in (daemon_scale_ins delta 0)",
                  file=sys.stderr, flush=True)
            rc = 1
        if counters.get("daemon_spot_preemptions", 0) < 1:
            print("[goodput_gate] FAIL: the injected spot preemption "
                  "never fired (daemon_spot_preemptions delta 0)",
                  file=sys.stderr, flush=True)
            rc = 1
        steps = counters.get("daemon_brownout_steps", 0)
        reversals = counters.get("daemon_brownout_reversals", 0)
        if steps < 1:
            print("[goodput_gate] FAIL: no brownout rung ever engaged "
                  "(daemon_brownout_steps delta 0)",
                  file=sys.stderr, flush=True)
            rc = 1
        if steps != reversals:
            print(f"[goodput_gate] FAIL: brownout did not fully "
                  f"reverse: {steps} engage(s) vs {reversals} "
                  f"release(s)", file=sys.stderr, flush=True)
            rc = 1
        if not (run["settled"] or {}).get("settled"):
            print(f"[goodput_gate] FAIL: fleet never settled back to "
                  f"its floor: {run['settled']}",
                  file=sys.stderr, flush=True)
            rc = 1
        if overall["attainment"] != 1.0:
            print(f"[goodput_gate] FAIL: attainment "
                  f"{overall['attainment']} != 1.0 through the ramp",
                  file=sys.stderr, flush=True)
            rc = 1
        incomplete = [r for r in results
                      if not r["cancelled"] and not r["ok"]][:3]
        if incomplete:
            print(f"[goodput_gate] FAIL: non-cancelled request(s) did "
                  f"not complete through the ramp, e.g. {incomplete}",
                  file=sys.stderr, flush=True)
            rc = 1
        torn = [r for r in results
                if r["ok"] and r.get("stream_ok") is False][:3]
        if torn:
            print(f"[goodput_gate] FAIL: streamed chunks do not "
                  f"reassemble to the terminal output (lost/duplicated "
                  f"bytes), e.g. {torn}", file=sys.stderr, flush=True)
            rc = 1
        if autoscale["mismatches"]:
            print(f"[goodput_gate] FAIL: {len(autoscale['mismatches'])} "
                  f"stream(s) diverged from the disarmed reference, "
                  f"e.g. {autoscale['mismatches'][:3]}",
                  file=sys.stderr, flush=True)
            rc = 1
        print(f"[goodput_gate] autoscale: {autoscale['compared']} "
              f"streams bit-compared vs reference, "
              f"{counters.get('daemon_scale_outs', 0)} scale-out(s), "
              f"{counters.get('daemon_scale_ins', 0)} scale-in(s), "
              f"{counters.get('daemon_spot_preemptions', 0)} "
              f"preemption(s), {steps} brownout step(s) / "
              f"{reversals} reversal(s), "
              f"{counters.get('daemon_migrations', 0)} migration(s)",
              file=sys.stderr, flush=True)
    if prefix_cache is not None:
        # hierarchical-cache acceptance: blocks actually crossed the
        # tier boundary in BOTH directions (spill out, prefetch back),
        # the spill-enabled hit rate is STRICTLY above HBM-only on the
        # same trace, attainment did not regress vs the spill-disabled
        # reference, and every stream is bit-identical to it — the
        # host tier may only ever change WHERE bytes live, never what
        # any client reads.
        pc = prefix_cache
        if pc["spilled_blocks"] < 1:
            print("[goodput_gate] FAIL: no block was ever spilled to "
                  "host (engine_spill_spilled delta 0) — the tier was "
                  "never exercised", file=sys.stderr, flush=True)
            rc = 1
        if pc["prefetched_blocks"] < 1:
            print("[goodput_gate] FAIL: no block was ever prefetched "
                  "back from host (engine_spill_prefetched delta 0) — "
                  "spilled prefixes were never re-used",
                  file=sys.stderr, flush=True)
            rc = 1
        if not pc["spill_hit_rate"] > pc["hbm_hit_rate"]:
            print(f"[goodput_gate] FAIL: spill-enabled hit rate "
                  f"{pc['spill_hit_rate']} is not strictly above the "
                  f"HBM-only floor {pc['hbm_hit_rate']}",
                  file=sys.stderr, flush=True)
            rc = 1
        ref_att = pc["reference_attainment"]
        if (overall["attainment"] is not None and ref_att is not None
                and overall["attainment"] < ref_att):
            print(f"[goodput_gate] FAIL: attainment "
                  f"{overall['attainment']} regressed below the "
                  f"spill-disabled reference {ref_att}",
                  file=sys.stderr, flush=True)
            rc = 1
        if pc["mismatches"]:
            print(f"[goodput_gate] FAIL: {len(pc['mismatches'])} "
                  f"stream(s) diverged from the spill-disabled "
                  f"reference, e.g. {pc['mismatches'][:3]}",
                  file=sys.stderr, flush=True)
            rc = 1
        print(f"[goodput_gate] prefix-cache: {pc['compared']} streams "
              f"bit-compared vs reference, working set "
              f"{pc['working_set_blocks']} blocks over a "
              f"{pc['pool_blocks']}-block pool, hit rate "
              f"{pc['hbm_hit_rate']} -> {pc['spill_hit_rate']}, "
              f"{pc['spilled_blocks']} spill(s) / "
              f"{pc['prefetched_blocks']} prefetch(es)",
              file=sys.stderr, flush=True)
    if disagg is not None:
        # disagg acceptance: KV actually crossed the engine boundary,
        # the decode pool's latency held flat against the unified
        # reference while the heavy-tail prefills ran, every stream is
        # bit-identical to unified serving, neither pool leaked a
        # block, and the prefill pool scaled on its OWN signal while
        # the decode pool held its fixed size.
        counters = report["counters"]
        if counters.get("daemon_handoffs", 0) < 1:
            print("[goodput_gate] FAIL: no request was ever handed "
                  "off (daemon_handoffs delta 0) — the pools never "
                  "exchanged work and the run proved nothing",
                  file=sys.stderr, flush=True)
            rc = 1
        if counters.get("handoff_bytes", 0) < 1:
            print("[goodput_gate] FAIL: no KV byte crossed the engine "
                  "boundary (handoff_bytes delta 0)",
                  file=sys.stderr, flush=True)
            rc = 1
        if counters.get("daemon_scale_outs", 0) < 1:
            print("[goodput_gate] FAIL: the prefill pool never scaled "
                  "out (daemon_scale_outs delta 0) — the heavy-tail "
                  "prefills never drove the pool's queue-wait burn",
                  file=sys.stderr, flush=True)
            rc = 1
        roles = [r.get("role") for r in
                 (run["fleet"] or {}).get("replica", [])
                 if not r.get("retired")]
        n_decode = sum(1 for r in roles if r == "decode")
        if n_decode != 1:
            print(f"[goodput_gate] FAIL: the fixed decode pool ended "
                  f"at {n_decode} replica(s), not 1 — pool scaling "
                  f"was not independent (roles: {roles})",
                  file=sys.stderr, flush=True)
            rc = 1
        if (disagg["itl_budget_ms"] is not None
                and disagg["disagg_itl_p99_ms"] is not None
                and disagg["disagg_itl_p99_ms"]
                > disagg["itl_budget_ms"]):
            print(f"[goodput_gate] FAIL: decode ITL p99 "
                  f"{disagg['disagg_itl_p99_ms']}ms is not flat vs "
                  f"the unified reference "
                  f"{disagg['reference_itl_p99_ms']}ms (budget "
                  f"{disagg['itl_budget_ms']}ms)",
                  file=sys.stderr, flush=True)
            rc = 1
        if overall["attainment"] != 1.0:
            print(f"[goodput_gate] FAIL: attainment "
                  f"{overall['attainment']} != 1.0 across the handoffs",
                  file=sys.stderr, flush=True)
            rc = 1
        incomplete = [r for r in results
                      if not r["cancelled"] and not r["ok"]][:3]
        if incomplete:
            print(f"[goodput_gate] FAIL: non-cancelled request(s) did "
                  f"not complete across the handoff, e.g. {incomplete}",
                  file=sys.stderr, flush=True)
            rc = 1
        torn = [r for r in results
                if r["ok"] and r.get("stream_ok") is False][:3]
        if torn:
            print(f"[goodput_gate] FAIL: streamed chunks do not "
                  f"reassemble to the terminal output (lost/duplicated "
                  f"tokens), e.g. {torn}", file=sys.stderr, flush=True)
            rc = 1
        if disagg["mismatches"]:
            print(f"[goodput_gate] FAIL: {len(disagg['mismatches'])} "
                  f"stream(s) diverged from unified serving, e.g. "
                  f"{disagg['mismatches'][:3]}",
                  file=sys.stderr, flush=True)
            rc = 1
        leaked = {k: v for k, v in disagg["leaked_blocks"].items()
                  if v != 0}
        if leaked:
            print(f"[goodput_gate] FAIL: leaked KV blocks after "
                  f"quiesce: {leaked}", file=sys.stderr, flush=True)
            rc = 1
        print(f"[goodput_gate] disagg: {disagg['compared']} streams "
              f"bit-compared vs unified, "
              f"{counters.get('daemon_handoffs', 0)} handoff(s) / "
              f"{counters.get('handoff_bytes', 0)} byte(s), ITL p99 "
              f"{disagg['reference_itl_p99_ms']}ms -> "
              f"{disagg['disagg_itl_p99_ms']}ms, "
              f"{counters.get('daemon_scale_outs', 0)} prefill "
              f"scale-out(s), decode pool fixed at {n_decode}",
              file=sys.stderr, flush=True)
    if args.attribute:
        # attribution acceptance: every completed request yielded one
        # journey whose waterfall holds its invariants, the journeys'
        # handoff accounting matches the daemon counters EXACTLY, and
        # the scraped histograms carry at least one exemplar that
        # resolves back to a real journey
        if attribution is None:
            print("[goodput_gate] FAIL: --attribute produced no "
                  "journey capture", file=sys.stderr, flush=True)
            rc = 1
        else:
            at = attribution
            if at["problems"]:
                for p in at["problems"][:5]:
                    print(f"[goodput_gate] FAIL: journey invariant: "
                          f"{p}", file=sys.stderr, flush=True)
                if len(at["problems"]) > 5:
                    print(f"[goodput_gate] FAIL: ... and "
                          f"{len(at['problems']) - 5} more journey "
                          f"invariant violation(s)",
                          file=sys.stderr, flush=True)
                rc = 1
            if disagg is not None:
                if at["handed_off"] != at["counter_daemon_handoffs"]:
                    print(f"[goodput_gate] FAIL: {at['handed_off']} "
                          f"journey(s) crossed the handoff edge but "
                          f"daemon_handoffs moved by "
                          f"{at['counter_daemon_handoffs']}",
                          file=sys.stderr, flush=True)
                    rc = 1
                if at["handoff_bytes_sum"] != at["counter_handoff_bytes"]:
                    print(f"[goodput_gate] FAIL: journey handoff bytes "
                          f"sum to {at['handoff_bytes_sum']} but "
                          f"handoff_bytes moved by "
                          f"{at['counter_handoff_bytes']}",
                          file=sys.stderr, flush=True)
                    rc = 1
            if at["exemplars_resolved"] < 1:
                print("[goodput_gate] FAIL: no histogram exemplar "
                      "resolves to a live journey rid (scraped "
                      f"{len(at['exemplars'])} exemplar(s))",
                      file=sys.stderr, flush=True)
                rc = 1
            dom = ", ".join(f"{k}={v}" for k, v in
                            sorted(at["dominant_by_phase"].items(),
                                   key=lambda kv: -kv[1]))
            miss = (", ".join(
                f"{k}={v}" for k, v in
                sorted(at["misses_by_phase"].items(),
                       key=lambda kv: -kv[1]))
                or "none")
            print(f"[goodput_gate] attribute: "
                  f"{len(at['requests'])} journey(s) verified, "
                  f"{at['handed_off']} handed off "
                  f"({at['handoff_bytes_sum']} bytes == counters), "
                  f"{at['exemplars_resolved']}/{len(at['exemplars'])} "
                  f"exemplar(s) resolved, dominant phases: {dom}; "
                  f"SLO misses by phase: {miss}",
                  file=sys.stderr, flush=True)
    if run["roll"] is not None:
        roll = run["roll"]
        bad_roll = roll["shed"] + roll["rebuilding"] + roll["errors"]
        if bad_roll or not roll["ok"]:
            print(f"[goodput_gate] FAIL: rolling restart was not "
                  f"zero-shed: {roll}", file=sys.stderr, flush=True)
            rc = 1
        else:
            print(f"[goodput_gate] rolling restart: {roll['ok']} "
                  f"request(s) served, zero shed", file=sys.stderr,
                  flush=True)
    att = overall["attainment"]
    if att is not None and att < args.min_attainment:
        print(f"[goodput_gate] FAIL: attainment {att} < floor "
              f"{args.min_attainment}", file=sys.stderr, flush=True)
        rc = 1
    if args.check_baselines:
        cr_spec = importlib.util.spec_from_file_location(
            "check_regression", pathlib.Path(__file__).resolve().parent
            / "check_regression.py")
        check_regression = importlib.util.module_from_spec(cr_spec)
        cr_spec.loader.exec_module(check_regression)

        with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                         delete=False) as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
            rows_path = f.name
        try:
            gate_rc = check_regression.main(
                [rows_path, "--baselines", args.baselines])
        finally:
            os.unlink(rows_path)
        rc = rc or gate_rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
