"""Generate the committed lab2 before/after showcase pair.

The reference ships a human-scale demonstration image with its processed
output (``/root/reference/lab2/test_data/``: lenna.data at 512x512 plus
the Roberts-filtered result) so a reader can SEE what the kernel does.
This tool produces tpulab's equivalent: a deterministic photo-class
512x512 RGBA scene (synthetic — no third-party image rights involved),
run through the same ``roberts_edges`` op the lab2 workload uses, both
sides committed as ``.data`` (the suite's raw format) and ``.png``.

Usage: python tools/make_showcase.py [--out data/lab2/showcase]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def photo_scene(size: int = 512, seed: int = 1973) -> np.ndarray:
    """Deterministic photo-class RGBA test scene.

    Built from the feature families edge detectors are demonstrated on:
    smooth gradients (sky), a disc with soft shading (sun), overlapping
    rectangles (buildings) with window grids, a sinusoidal ridge line
    (hills), and film-grain noise so flat regions aren't digitally flat.
    """
    rng = np.random.default_rng(seed)
    y, x = np.mgrid[0:size, 0:size].astype(np.float32) / size

    # sky: vertical gradient, slightly warm at the horizon
    r = 40 + 120 * y
    g = 60 + 110 * y
    b = 120 + 90 * y

    # sun disc with soft limb
    d_sun = np.hypot(x - 0.72, y - 0.22)
    sun = np.clip(1.0 - d_sun / 0.11, 0.0, 1.0) ** 0.5
    r = r + 180 * sun
    g = g + 150 * sun
    b = b + 60 * sun

    # hills: everything below a sinusoidal ridge darkens
    ridge = 0.55 + 0.08 * np.sin(x * 9.2) + 0.05 * np.sin(x * 23.1 + 1.7)
    hill = (y > ridge).astype(np.float32)
    r = r * (1 - hill) + hill * (30 + 40 * y)
    g = g * (1 - hill) + hill * (70 + 50 * y)
    b = b * (1 - hill) + hill * (35 + 30 * y)

    # buildings: overlapping rectangles with window grids
    for i in range(7):
        brng = np.random.default_rng(seed + 100 + i)
        w = brng.uniform(0.06, 0.16)
        h = brng.uniform(0.15, 0.38)
        cx = brng.uniform(0.05, 0.95)
        top = 1.0 - h
        mask = ((x > cx - w / 2) & (x < cx + w / 2) & (y > top)).astype(
            np.float32
        )
        shade = brng.uniform(0.15, 0.45)
        r = r * (1 - mask) + mask * 255 * shade * 0.9
        g = g * (1 - mask) + mask * 255 * shade * 0.95
        b = b * (1 - mask) + mask * 255 * shade
        # windows: lit cells on an 8px grid inside the building
        win = (
            mask
            * (np.sin(x * size * np.pi / 8) > 0.6)
            * (np.sin(y * size * np.pi / 8) > 0.6)
        ).astype(np.float32)
        lit = (brng.random() < 0.8) * win
        r = r * (1 - lit) + lit * 250
        g = g * (1 - lit) + lit * 220
        b = b * (1 - lit) + lit * 120

    # film grain
    grain = rng.normal(0.0, 3.0, (size, size)).astype(np.float32)
    rgba = np.stack(
        [
            np.clip(r + grain, 0, 255),
            np.clip(g + grain, 0, 255),
            np.clip(b + grain, 0, 255),
            np.full((size, size), 255.0, np.float32),
        ],
        axis=-1,
    )
    return rgba.astype(np.uint8)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(ROOT, "data/lab2/showcase"))
    ap.add_argument("--size", type=int, default=512)
    args = ap.parse_args(argv)

    import jax

    from tpulab.io.imagefile import save_image
    from tpulab.ops.roberts import roberts_edges

    os.makedirs(args.out, exist_ok=True)
    scene = photo_scene(args.size)
    edges = np.asarray(jax.jit(roberts_edges)(scene))

    for name, img in (("cityline_512", scene), ("cityline_512_roberts", edges)):
        for ext in (".data", ".png"):
            path = os.path.join(args.out, name + ext)
            save_image(path, img)
            print(f"wrote {path} ({os.path.getsize(path)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
