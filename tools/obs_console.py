#!/usr/bin/env python
"""Live terminal ops console for a running tpulab daemon.

One screen, refreshed in place every ``--interval`` seconds, built
entirely from the daemon's observability requests (tpulab/daemon.py):

  * ``metrics``  — the latency percentile table (p50/p90/p99 TTFT /
    ITL / e2e / queue-wait / prefill) from the Prometheus scrape;
  * ``fleet``    — the per-replica health table (or the single-engine
    gauge row on a no-fleet daemon);
  * ``history``  — windowed rates + percentiles from the
    ``--metrics-interval`` sampler ring, with unicode sparklines of
    the requested rate series (tokens/s, requests/s, ticks/s);
  * ``alerts``   — the rule-engine state table, firing first (SLO burn
    rates, recompile/occupancy tripwires, staleness);
  * ``slowlog``  — the worst-N requests by e2e, rid-linked to traces;
  * ``journey``  — the newest cross-engine request journeys (round
    21): pools crossed, dominant phase, handoff cost per request.

All rendering is the SHARED module ``tpulab/obs/render.py`` — the same
functions ``tools/obs_report.py`` uses for its one-shot summary, so the
two surfaces cannot drift.  Pure-stdlib, like the rest of the obs
layer.

Usage:
    python tools/obs_console.py [--socket /tmp/tpulab.sock]
                                [--interval 1.0] [--window 30]
                                [--frames N | --once] [--all-rules]

``--once`` prints a single frame without ANSI clearing (scripts,
captures, tests); ``--frames N`` stops after N refreshes.  A daemon
request that fails mid-session renders as an ``unavailable`` line
instead of killing the console — a dashboard must outlive the thing it
watches.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tpulab.obs import render as R  # noqa: E402

# the wire client lives in tools/obs_report.py (request /
# request_with_retry); load it the way the tests do so there is one
# copy of the frame protocol on the tools side too
_spec = importlib.util.spec_from_file_location(
    "obs_report", pathlib.Path(__file__).resolve().parent
    / "obs_report.py")
_rep = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_rep)
request = _rep.request

#: default rate series the sparklines track
DEFAULT_SERIES = ("engine_tokens_out", "engine_requests_done",
                  "engine_ticks")

_CLEAR = "\x1b[H\x1b[2J"


def fetch(sock: str, *, window_s: float = 30.0,
          series: tuple = DEFAULT_SERIES, slowlog_n: int = 5,
          journeys_n: int = 4) -> dict:
    """One round of scrapes; every surface degrades independently
    (``None`` on failure) so a daemon mid-restart still renders."""
    out: dict = {}

    def grab(key, lab, config=None, decode_json=True):
        try:
            raw = request(sock, lab, config)
            out[key] = json.loads(raw) if decode_json else raw.decode()
        except Exception as e:  # noqa: BLE001 — a dashboard must
            # outlive the daemon it watches; the frame shows the gap
            out[key] = None
            out.setdefault("errors", []).append(f"{lab}: {e}")

    grab("metrics", "metrics", decode_json=False)
    grab("fleet", "fleet")
    grab("history", "history",
         {"seconds": window_s, "series": list(series)})
    grab("alerts", "alerts")
    grab("slowlog", "slowlog", {"n": slowlog_n})
    grab("journeys", "journey", {"n": journeys_n})
    return out


def render_frame(scr: dict, *, all_rules: bool = False,
                 title: str = "") -> str:
    """One console frame from a :func:`fetch` result — pure function,
    unit-tested without a daemon (tests/test_obs_alerts.py)."""
    metrics = {}
    if scr.get("metrics"):
        try:
            metrics = R.parse_prometheus(scr["metrics"])
        except ValueError:
            metrics = {}
    parts = [
        f"tpulab ops console{'  ' + title if title else ''}  "
        f"{time.strftime('%H:%M:%S')}",
        R.format_latency_table(R.summarize(metrics))
        if metrics else "metrics: unavailable",
        R.format_fleet(scr.get("fleet"), metrics),
        R.format_history(scr.get("history")),
        R.format_alerts(scr.get("alerts"), all_rules=all_rules),
        R.format_slowlog(scr.get("slowlog")),
        R.format_journeys(scr.get("journeys")),
    ]
    if scr.get("errors"):
        parts.append("scrape errors: " + "; ".join(scr["errors"]))
    return "\n\n".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket", default="/tmp/tpulab.sock")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh cadence in seconds")
    ap.add_argument("--window", type=float, default=30.0,
                    help="history window for rates/percentiles")
    ap.add_argument("--series", default=",".join(DEFAULT_SERIES),
                    help="comma-separated rate series to sparkline")
    ap.add_argument("--slowlog", type=int, default=5, metavar="N",
                    help="worst-N slow requests per frame")
    ap.add_argument("--journeys", type=int, default=4, metavar="N",
                    help="newest-N request journeys per frame")
    ap.add_argument("--frames", type=int, default=0, metavar="N",
                    help="stop after N frames (0 = until ^C)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame, no ANSI clear, exit")
    ap.add_argument("--all-rules", action="store_true",
                    help="show every alert rule, not just non-OK ones")
    args = ap.parse_args(argv)
    if args.interval <= 0:
        ap.error("--interval must be > 0")
    series = tuple(s for s in args.series.split(",") if s)
    shown = 0
    try:
        while True:
            scr = fetch(args.socket, window_s=args.window,
                        series=series, slowlog_n=args.slowlog,
                        journeys_n=args.journeys)
            frame = render_frame(scr, all_rules=args.all_rules,
                                 title=args.socket)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write(_CLEAR + frame + "\n")
            sys.stdout.flush()
            shown += 1
            if args.frames and shown >= args.frames:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
