#!/usr/bin/env python
"""Capture ONE real cross-pool request journey from a live disagg daemon.

Round-21 evidence tool: spawns a private prefill/decode-pooled daemon
(``--pool-spec prefill=1,decode=1`` — every request hands off), drives a
single streamed generate through it, then asks the daemon's ``journey``
request for the stitched record and writes it — together with the
handoff counters it must agree with — to ``--out``
(``results/obs_journey_r21.json`` is the committed capture).

The capture is self-checking: it fails loudly unless the journey is
complete, spans both pools, carries the full 7-phase disagg waterfall
with contiguous monotonic phases, and its handoff bytes equal the
daemon's ``handoff_bytes`` counter delta for the run (exactly one
handoff, so the delta IS the payload).

Usage::

    python tools/obs_journey_capture.py --out results/obs_journey_r21.json
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tpulab.obs.journey import HANDOFF_PHASES, PHASES  # noqa: E402

import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "obs_report", pathlib.Path(__file__).resolve().parent / "obs_report.py")
obs_report = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)

TAG = "journey-r21-capture"


def _spawn(sock: str) -> subprocess.Popen:
    if os.path.exists(sock):
        os.unlink(sock)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock,
         "--replicas", "1", "--pool-spec", "prefill=1,decode=1",
         "--prefix-index", "radix", "--spill-blocks", "512"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"daemon exited rc={proc.returncode}")
        if os.path.exists(sock):
            return proc
        time.sleep(0.1)
    proc.kill()
    proc.wait()
    raise RuntimeError("daemon socket never appeared")


def _reap(proc) -> None:
    if proc is None or proc.poll() is not None:
        if proc is not None:
            proc.wait()
        return
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _counters(metrics: dict) -> dict:
    return {k: v.get("value", 0) for k, v in metrics.items()
            if v.get("type") == "counter"}


def capture(sock: str) -> dict:
    prompt = ("The observability tier stitches one causal journey per "
              "request across every engine that touched it. " * 3)
    # warm both pools first so the committed journey measures serving,
    # not prefill/decode compile (the counters below are deltas, so the
    # warmup's own handoff stays out of the evidence)
    obs_report.request(sock, "generate",
                       {"steps": 8, "stream": True}, prompt.encode())
    before = _counters(obs_report.parse_prometheus(
        obs_report.request(sock, "metrics").decode()))
    out = obs_report.request(
        sock, "generate",
        {"steps": 24, "stream": True, "tag": TAG}, prompt.encode())
    assert out, "generate returned no output"
    j = json.loads(obs_report.request(
        sock, "journey", {"tag": TAG}).decode())["journey"]
    assert j is not None, f"no journey recorded for tag {TAG!r}"
    after = _counters(obs_report.parse_prometheus(
        obs_report.request(sock, "metrics").decode()))

    # self-check: this is evidence, not a screenshot
    assert j["completed"], j
    phases = [p["phase"] for p in j["phases"]]
    assert phases == list(PHASES), phases
    for a, b in zip(j["phases"], j["phases"][1:]):
        assert a["t1_ms"] == b["t0_ms"], (a, b)
    for p in j["phases"]:
        assert p["ms"] >= 0 and p["t1_ms"] >= p["t0_ms"], p
    assert j["pools"] == ["prefill", "decode"], j["pools"]
    hsum = round(sum(p["ms"] for p in j["phases"]
                     if p["phase"] in HANDOFF_PHASES), 3)
    assert abs(hsum - j["handoff_ms"]) <= 0.01, (hsum, j["handoff_ms"])
    dh = after.get("daemon_handoffs", 0) - before.get("daemon_handoffs", 0)
    db = after.get("handoff_bytes", 0) - before.get("handoff_bytes", 0)
    assert dh == 1, f"expected exactly one handoff, counter moved {dh}"
    assert j["handoff_bytes"] == db, (j["handoff_bytes"], db)

    return {
        "round": 21,
        "tool": "tools/obs_journey_capture.py",
        "daemon": {"pool_spec": "prefill=1,decode=1", "replicas_per_pool": 1},
        "request": {"tag": TAG, "steps": 24,
                    "prompt_bytes": len(prompt.encode())},
        "counters_delta": {"daemon_handoffs": dh, "handoff_bytes": db},
        "journey": j,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--socket", default="/tmp/tpulab_journey_capture.sock")
    ap.add_argument("--out", default="results/obs_journey_r21.json")
    args = ap.parse_args(argv)

    proc = _spawn(args.socket)
    try:
        doc = capture(args.socket)
    finally:
        _reap(proc)
    pathlib.Path(args.out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(args.out).write_text(json.dumps(doc, indent=1) + "\n")
    j = doc["journey"]
    print(f"[journey_capture] rid={j['rid']} e2e={j['e2e_ms']}ms "
          f"handoff={j['handoff_ms']}ms/{j['handoff_bytes']}B "
          f"pools={'>'.join(j['pools'])} -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
