#!/usr/bin/env python
"""Scrape a running tpulab daemon and render a latency-percentile summary.

Speaks the daemon's wire protocol (tpulab/daemon.py) over its unix
socket and issues the observability requests this layer added:

  * ``metrics``    — Prometheus text exposition of the process-global
    registry (per-request ttft/itl/e2e/queue-wait/prefill histograms,
    ``engine_*`` gauges for every warm engine);
  * ``trace_dump`` — the ring-buffer tracer's retained window as Chrome
    trace-event JSON (``--trace-out FILE``; open the file directly in
    https://ui.perfetto.dev).
  * ``slowlog`` — the worst-N requests by e2e latency with their
    per-request span summaries (``--slowlog N``); each entry's ``rid``
    links it to the same request's events in the trace dump.
  * ``compile_stats`` — the process compile ledger + device peaks
    (``--roofline``: per-program compiles / compile-seconds / FLOPs /
    bytes accessed / compute- vs bandwidth-bound classification, plus
    the engine_mfu/train_mfu gauges; on the CPU proxy the bound column
    says so instead of fabricating a peak).
  * ``postmortem`` — the newest crash flight-recorder bundle
    (``--postmortem``: reason, error, engine stats at death, armed
    fault schedule, compile table, slow-log worst-N, trace-slice
    size — tpulab.obs.flightrec).
  * ``alerts`` — the round-15 rule-engine state table (``--alerts``:
    SLO burn rates, tripwires, staleness; firing first —
    tpulab.obs.alerts).
  * ``history`` — the metrics-history windowed report (``--history S``
    to print rates + windowed percentiles over the last S seconds,
    ``--history-out FILE`` to capture the raw JSON —
    tpulab.obs.history; populated by the daemon's
    ``--metrics-interval`` sampler).

For a live-refresh view of all of the above, use the ops console
(``tools/obs_console.py``) — it shares this tool's rendering through
``tpulab/obs/render.py``.

The summary table is the serving-metrics view production TPU serving
comparisons report (PAPERS.md, arXiv:2605.25645): p50/p90/p99 TTFT,
inter-token latency, and end-to-end time, estimated from the scraped
histogram buckets with the same interpolation rule the registry itself
uses (``tpulab.obs.percentile_from_buckets`` — one copy of the math).

``--drive N`` optionally sends N small ``generate`` requests first, so
a freshly started daemon has populated histograms to report — the
on-chip evidence queue (tools/onchip_queue_r10.sh) uses this to capture
a real trace + scrape in one shot.

Usage:
    python tools/obs_report.py [--socket /tmp/tpulab.sock]
                               [--drive N] [--steps M]
                               [--trace-out results/obs_trace.json]
                               [--raw] [--json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import socket
import struct
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tpulab.loadgen import SHED_RE as _SHED_RE  # noqa: E402
# the shared rendering module (tpulab/obs/render.py — the round-15
# dedup satellite): the percentile/parse/table code used to live here
# and is now ONE copy this tool and tools/obs_console.py both import.
# Re-exported under the historical names so existing consumers (tests,
# capture scripts) keep working.
from tpulab.obs.render import (LATENCY_METRICS as _LATENCY_METRICS,  # noqa: E402,F401
                               format_alerts, format_fleet,
                               format_history, format_journey,
                               format_journeys, format_latency_table,
                               format_slowlog, histogram_percentile,
                               parse_prometheus, summarize)

#: _SHED_RE (tpulab.loadgen.SHED_RE — the ONE copy of the client-side
#: shed contract): an error frame whose body matches is BACKPRESSURE,
#: not a failure — honor the retry-after and try again inside the
#: caller's deadline.  The pattern covers BOTH daemon park flavors:
#: ``shed retry_after_ms=N`` (deadline/queue shedding) and
#: ``rebuilding retry_after_ms=N`` (the fleet's whole-fleet drain/
#: rebuild park — e.g. mid rolling-restart), so a capture or drive
#: riding :func:`request_with_retry` survives a rolling restart


def request(sock_path: str, lab: str, config: dict | None = None,
            payload: bytes = b"") -> bytes:
    """One daemon round-trip; raises on an error frame.  Chunk frames
    (status 2, streaming generates) are drained — the terminal frame
    carries the full output either way."""
    header = json.dumps({"lab": lab, "config": config or {}}).encode()
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(sock_path)
    try:
        s.sendall(struct.pack("<I", len(header)) + header)
        s.sendall(struct.pack("<Q", len(payload)) + payload)

        def read_exact(n: int) -> bytes:
            buf = b""
            while len(buf) < n:
                r = s.recv(n - len(buf))
                if not r:
                    raise ConnectionError("daemon closed mid-frame")
                buf += r
            return buf

        while True:
            status = read_exact(1)[0]
            (n,) = struct.unpack("<Q", read_exact(8))
            out = read_exact(n)
            if status == 2:      # streamed chunk: keep reading
                continue
            if status != 0:
                raise RuntimeError(
                    f"daemon error for {lab!r}: "
                    f"{out.decode('utf-8', 'replace')[-500:]}")
            return out
    finally:
        s.close()


class ShedResponse(RuntimeError):
    """The daemon rejected the request with retry-after (load
    shedding).  ``retry_after_ms`` is the daemon's backoff hint."""

    def __init__(self, retry_after_ms: int, body: str):
        self.retry_after_ms = retry_after_ms
        super().__init__(body)


def request_with_retry(sock_path: str, lab: str, config: dict | None = None,
                       payload: bytes = b"", *, deadline_s: float = 30.0,
                       base_backoff_s: float = 0.05,
                       rng: "random.Random | None" = None) -> bytes:
    """:func:`request` with client-side resilience: connect/send
    failures retry on exponential backoff with full jitter, and a
    shed/rebuilding park response (``shed retry_after_ms=N`` /
    ``rebuilding retry_after_ms=N`` — the latter is the fleet's
    drain-park during a rolling restart) honors the daemon's
    retry-after hint — all bounded by an absolute ``deadline_s``.  The
    last error is re-raised once the deadline is spent, so a genuinely
    dead daemon still fails loudly instead of looping forever.

    Crash-durable daemons (round 16): a ``generate`` whose config
    carries a durable ``rid`` retries a connection-refused/reset — the
    daemon-restart analogue of the rebuilding park — by first asking
    the restarted daemon to ``resume`` that rid (the journal replays
    the request server-side), and only falls back to a fresh submission
    when the daemon answers ``resume unknown rid`` (the crash predated
    the accept record, so nothing can be duplicated)."""
    import random
    import time

    rng = rng or random.Random()
    t0 = time.monotonic()
    attempt = 0
    rid = (config or {}).get("rid") if lab == "generate" else None
    tried_conn = False
    while True:
        try:
            if rid is not None and tried_conn:
                # a connection already broke once: the request may be
                # journaled and replaying — resuming by rid returns the
                # SAME stream instead of submitting a duplicate
                try:
                    return request(sock_path, "resume",
                                   {"rid": rid, "received": 0})
                except RuntimeError as e:
                    if "resume unknown rid" not in str(e):
                        raise
            return request(sock_path, lab, config, payload)
        except (ConnectionError, OSError, RuntimeError) as e:
            shed = _SHED_RE.search(str(e))
            if shed is None and not isinstance(e, (ConnectionError, OSError)):
                raise  # a real daemon-side error: retrying cannot help
            if isinstance(e, (ConnectionError, OSError)):
                tried_conn = True
            attempt += 1
            if shed is not None:
                # either arm (shed / rebuilding park): group 2 is the
                # daemon's retry-after hint in milliseconds
                wait = int(shed.group(2)) / 1e3
            else:
                # exponential backoff, full jitter: concurrent clients
                # must not re-dogpile a recovering daemon in lockstep
                wait = rng.uniform(0, base_backoff_s * (2 ** min(attempt, 6)))
            if time.monotonic() + wait - t0 > deadline_s:
                if shed is not None:
                    raise ShedResponse(int(shed.group(2)), str(e)) from e
                raise
            time.sleep(wait)


def format_roofline(payload: dict) -> str:
    """Render a ``compile_stats`` response as the roofline table
    (pure function — unit-tested without a daemon)."""
    peaks = payload.get("peaks") or {}
    lines = [
        f"device: {peaks.get('device_kind') or 'unknown'}  "
        f"peak_tflops={peaks.get('peak_tflops')}  "
        f"peak_gbps={peaks.get('peak_gbps')}",
        f"mfu: engine={payload.get('mfu', {}).get('engine_mfu')}%  "
        f"train={payload.get('mfu', {}).get('train_mfu')}%  "
        f"steady_recompiles={payload.get('steady_recompiles')}  "
        f"compile_s_total={payload.get('total_compile_seconds')}",
    ]
    from tpulab.obs.roofline import roofline_rows

    rows = roofline_rows(payload.get("programs") or {}, peaks)
    if not rows:
        lines.append("(no programs compiled yet)")
        return "\n".join(lines)
    w = max(len(r["program"]) for r in rows)
    lines.append(f"{'program':<{w}}  {'compiles':>8}  {'compile_s':>9}  "
                 f"{'gflops':>9}  {'gbytes':>8}  {'f/byte':>7}  bound")
    for r in rows:
        gf = (f"{r['flops'] / 1e9:.3f}" if r["flops"] else "-")
        gb = (f"{r['bytes_accessed'] / 1e9:.3f}"
              if r["bytes_accessed"] else "-")
        inten = (f"{r['intensity_flops_per_byte']:.2f}"
                 if r["intensity_flops_per_byte"] is not None else "-")
        lines.append(
            f"{r['program']:<{w}}  {r['compiles']:>8}  "
            f"{r['compile_seconds']:>9.3f}  {gf:>9}  {gb:>8}  "
            f"{inten:>7}  {r['bound']}")
    return "\n".join(lines)


def format_postmortem(bundle: dict) -> str:
    """Render a ``postmortem`` response (pure function, unit-tested).
    ``{"bundles": 0}`` renders as the no-bundle message."""
    if not bundle or not bundle.get("reason"):
        return "no post-mortem bundles recorded"
    err = bundle.get("error") or {}
    eng = bundle.get("engine") or {}
    trace = bundle.get("trace") or {}
    slow = (bundle.get("slowlog") or {}).get("worst", [])
    lines = [
        f"postmortem: {bundle.get('reason')}  "
        f"(bundle {bundle.get('path', '<inline>')}, "
        f"{bundle.get('bundles', 1)} on disk)",
        f"error: {err.get('type')}: {err.get('message')}" if err
        else "error: none recorded",
        f"engine: build_key={eng.get('build_key')} "
        f"stamp={eng.get('build_stamp')} "
        f"replica={eng.get('replica_index')}",
    ]
    st = eng.get("stats") or {}
    if st:
        keys = ("ticks", "tokens_out", "requests_done", "recompiles",
                "blocks_used", "blocks_free", "preemptions")
        lines.append("stats at death: " + " ".join(
            f"{k}={st[k]}" for k in keys if k in st))
    faults_ = bundle.get("faults") or {}
    if faults_.get("rules"):
        lines.append("armed faults: " + "; ".join(
            f"{r['site']} {r['kind']} at={r['at']} fired={r['fired']}"
            for r in faults_["rules"]))
    cs = bundle.get("compile_stats") or {}
    compiled = {k: v for k, v in cs.items() if v.get("compiles")}
    if compiled:
        lines.append("compiled programs: " + " ".join(
            f"{k}x{v['compiles']}" for k, v in sorted(compiled.items())))
    lines.append(f"trace slice: {len(trace.get('events', []))} events "
                 f"({trace.get('dropped', 0)} dropped before capture)")
    for e in slow[:5]:
        lines.append(f"  slow rid={e.get('rid')} tag={e.get('tag') or '-'} "
                     f"e2e={e.get('e2e_ms')}ms tokens={e.get('tokens')} "
                     f"resubmits={e.get('resubmits')}")
    return "\n".join(lines)


def drive(sock_path: str, n: int, steps: int,
          deadline_s: float = 120.0) -> None:
    """Send ``n`` small generate requests (shared system-prompt prefix,
    so the scrape also exercises prefix hits) to populate the
    histograms on a fresh daemon.  Each request rides
    :func:`request_with_retry`, so transient connect failures and shed
    responses back off and retry instead of killing the capture."""
    prompt = (b"observability scrape warmup: " * 3)[:64]
    for i in range(n):
        request_with_retry(sock_path, "generate", {"steps": steps},
                           prompt + str(i).encode(), deadline_s=deadline_s)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--socket", default="/tmp/tpulab.sock")
    ap.add_argument("--drive", type=int, default=0, metavar="N",
                    help="send N generate requests first (populates the "
                         "histograms on a fresh daemon)")
    ap.add_argument("--steps", type=int, default=32,
                    help="tokens per --drive request")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="also request trace_dump and write the Chrome "
                         "trace JSON here (open in ui.perfetto.dev)")
    ap.add_argument("--slowlog", type=int, default=0, metavar="N",
                    help="also print the daemon's worst-N slow-log "
                         "entries (per-request span summaries; each "
                         "rid links to the trace_dump events)")
    ap.add_argument("--journey", default=None, metavar="RID|TAG",
                    help="print ONE request's stitched cross-engine "
                         "journey as a phase waterfall (queue -> "
                         "prefill -> handoff export/transfer/import -> "
                         "decode), looked up by server rid (integer) "
                         "or wire tag; rids come from slowlog entries, "
                         "trace events, and histogram exemplars")
    ap.add_argument("--journeys", type=int, default=0, metavar="N",
                    help="also print the N newest request journeys "
                         "(one line each: pools crossed, dominant "
                         "phase, handoff cost)")
    ap.add_argument("--alerts", action="store_true",
                    help="also print the daemon's alert state table "
                         "(tpulab.obs.alerts — SLO burn rates, "
                         "tripwires, staleness; firing first)")
    ap.add_argument("--history", type=float, default=0, metavar="S",
                    help="also print the metrics-history windowed "
                         "summary over the last S seconds (rates + "
                         "windowed percentiles from the daemon's "
                         "--metrics-interval sampler ring)")
    ap.add_argument("--history-out", default=None, metavar="FILE",
                    help="write the raw 'history' response JSON to "
                         "FILE (the round-15 capture artifact)")
    ap.add_argument("--roofline", action="store_true",
                    help="also print the per-program compile/roofline "
                         "table (compile counts + seconds, FLOPs, "
                         "bytes, compute- vs bandwidth-bound) and the "
                         "engine_mfu/train_mfu gauges")
    ap.add_argument("--postmortem", action="store_true",
                    help="also print the newest crash flight-recorder "
                         "bundle (reason, error, stats at death, armed "
                         "faults, compile table)")
    ap.add_argument("--raw", action="store_true",
                    help="print the raw Prometheus text instead of the "
                         "summary table")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object")
    args = ap.parse_args(argv)

    if args.drive:
        drive(args.socket, args.drive, args.steps)
    text = request(args.socket, "metrics").decode("utf-8")
    if args.raw:
        print(text, end="")
        return 0
    metrics = parse_prometheus(text)
    rows = summarize(metrics)
    # fleet state (round 13): replica count + per-replica health so a
    # scrape of a sick fleet names the replica, not just the totals.
    # Tolerant of an empty daemon (no warm fleet yet -> 0 replicas).
    try:
        fleet = json.loads(request(args.socket, "fleet"))
    except Exception:
        fleet = None
    if args.trace_out:
        trace = request(args.socket, "trace_dump")
        json.loads(trace)  # refuse to write a corrupt dump
        pathlib.Path(args.trace_out).write_bytes(trace)
        print(f"[obs_report] trace written to {args.trace_out} "
              f"(open in ui.perfetto.dev)", file=sys.stderr)
    slow = None
    if args.slowlog:
        slow = json.loads(request(args.socket, "slowlog",
                                  {"n": args.slowlog}))
    journey = None
    if args.journey is not None:
        # integer -> server rid lookup; anything else -> wire tag
        try:
            cfg = {"rid": int(args.journey)}
        except ValueError:
            cfg = {"tag": args.journey}
        journey = json.loads(request(args.socket, "journey", cfg))
    journeys = None
    if args.journeys:
        journeys = json.loads(request(args.socket, "journey",
                                      {"n": args.journeys}))
    alerts = None
    if args.alerts:
        alerts = json.loads(request(args.socket, "alerts"))
    hist = None
    if args.history or args.history_out:
        hist = json.loads(request(
            args.socket, "history",
            {"seconds": args.history or 30.0,
             "series": ["engine_tokens_out", "engine_requests_done"]}))
        if args.history_out:
            pathlib.Path(args.history_out).write_text(
                json.dumps(hist, indent=1) + "\n")
            print(f"[obs_report] history written to {args.history_out}",
                  file=sys.stderr)
    roof = None
    if args.roofline:
        roof = json.loads(request(args.socket, "compile_stats"))
    pm = None
    if args.postmortem:
        pm = json.loads(request(args.socket, "postmortem"))
    if args.json:
        out = {"latency": rows}
        if fleet is not None:
            out["fleet"] = fleet
        if slow is not None:
            out["slowlog"] = slow.get("worst", [])
        if journey is not None:
            out["journey"] = journey.get("journey")
        if journeys is not None:
            out["journeys"] = journeys
        if alerts is not None:
            out["alerts"] = alerts
        if hist is not None:
            out["history"] = hist
        if roof is not None:
            out["compile_stats"] = roof
        if pm is not None:
            out["postmortem"] = pm
        print(json.dumps(out))
        return 0
    # the shared renderers (tpulab.obs.render) — format_fleet degrades
    # gracefully on a single-engine/no-fleet daemon by synthesizing a
    # row from the engine_* gauges instead of assuming replicas exist
    print(format_latency_table(rows))
    print(format_fleet(fleet, metrics))
    if hist is not None and args.history:
        print(format_history(hist))
    if alerts is not None:
        print(format_alerts(alerts))
    if slow is not None:
        print(format_slowlog(slow))
    if journey is not None:
        print(format_journey(journey.get("journey")))
    if journeys is not None:
        print(format_journeys(journeys))
    if roof is not None:
        print(format_roofline(roof))
    if pm is not None:
        print(format_postmortem(pm))
    return 0


if __name__ == "__main__":
    sys.exit(main())
