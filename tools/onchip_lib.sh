#!/bin/bash
# Shared scaffolding for the per-round on-chip evidence queues
# (tools/onchip_queue_r*.sh).  Factored out in round 20: every queue
# since r10 hand-copied the same cd/log-dir/stage()/ratchet/re-sign
# boilerplate; one drifting copy per round is how the r14 queue lost
# its status timestamps.  This file deliberately does NOT match the
# tools/onchip_queue*.sh lint glob (tests/test_faults.py), so it may
# hold helpers while every queue script stays subject to the
# source-relay_lib/no-local-wait_relay checks.
#
# Claim discipline (docs/tpu_runs.md): TPU-claiming processes are
# WAITED on, never killed -- a killed claim wedges the relay for every
# later process.  wait_relay comes from tools/relay_lib.sh (the ONE
# copy); queue scripts get it transitively by sourcing this lib.
#
# Usage from a queue script:
#   . "$(dirname "$0")/onchip_lib.sh"    # sources relay_lib.sh
#   onchip_init                          # cd repo, L=results/logs, stamp
#   host_stage <name> <cmd...>           # ungated: host-only evidence
#   stage <name> <cmd...>                # relay-gated: on-chip evidence
#   ratchet <rows.jsonl> <date-label>    # regression verdict + ratchet
#   resign                               # re-sign mutated artifacts
#   onchip_done                          # final status stamp

cd /root/repo || exit 1
L=results/logs

. "$(dirname "$0")/relay_lib.sh"

onchip_init() {
  mkdir -p "$L"
  date > "$L/queue.status"
}

# host_stage <name> <cmd...> -- NO relay gate: host-only tiers (CPU
# backend, forced virtual devices) must land their evidence even with
# the relay down.  Same log/status shape as stage() so queue.status
# reads uniformly.
host_stage() {
  name=$1; shift
  echo "== $name start $(date)" >> "$L/queue.status"
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> "$L/queue.status"
}

# stage <name> <cmd...> -- relay-gated: waits for the TPU relay before
# claiming the chip; a skipped stage is recorded, never retried blind.
stage() {
  name=$1; shift
  echo "== $name wait-relay $(date)" >> "$L/queue.status"
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> "$L/queue.status"
    return 1
  fi
  echo "== $name start $(date)" >> "$L/queue.status"
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> "$L/queue.status"
}

# ratchet <rows.jsonl> <date-label> -- mechanical regression verdict +
# baseline ratchet in ONE pass (host-only JSON diff, never gated).
ratchet() {
  rows=$1; label=$2
  python tools/check_regression.py "$rows" --update --date "$label" \
      > "$L/regression_$(basename "$rows" .jsonl).log" 2>&1
  echo "== regression+ratchet($(basename "$rows")) rc=$? $(date)" \
      >> "$L/queue.status"
}

# resign -- stages above rewrite signed artifacts (baselines.json under
# --update; pallas_tpu_parity.json); signatures must track them or
# tests/test_signing.py reds.  Host-only, never gated.
resign() {
  python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
  echo "== resign rc=$? $(date)" >> "$L/queue.status"
}

onchip_done() {
  echo "QUEUE DONE $(date)" >> "$L/queue.status"
}
