#!/bin/bash
# Sequential on-chip evidence queue (single chip -- no contention).
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"
date > $L/queue.status
echo "== bench ==" >> $L/queue.status
python bench.py > $L/bench_r4.log 2>&1
echo "bench rc=$? $(date)" >> $L/queue.status
echo "== flash_train_proof ==" >> $L/queue.status
python tools/flash_train_proof.py > $L/flash_train.log 2>&1
echo "flash_train rc=$? $(date)" >> $L/queue.status
echo "== tune_flash ==" >> $L/queue.status
python tools/tune_flash.py > $L/tune_flash.log 2>&1
echo "tune_flash rc=$? $(date)" >> $L/queue.status
echo "== serving_tpu ==" >> $L/queue.status
python tools/serving_tpu.py > $L/serving_tpu.log 2>&1
echo "serving_tpu rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
