#!/bin/bash
# Sequential on-chip evidence queue (single chip -- no contention).
# Each stage is gated on a live compiled-matmul probe; probes are
# waited on, never killed (claim discipline).  Ordered for a LATE
# relay recovery: headline bench first, then the fast high-value
# artifacts, with the long flash tune last.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) — one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
stage bench_r4        python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines
grep '"metric"' $L/bench_r4.log > results/bench_r4.jsonl 2>/dev/null || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage train_mfu       python tools/train_mfu_probe.py
stage serving_tpu     python tools/serving_tpu.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
echo "QUEUE DONE $(date)" >> $L/queue.status
