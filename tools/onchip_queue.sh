#!/bin/bash
# Sequential on-chip evidence queue (single chip -- no contention).
# Each stage is gated on a live relay probe; probes are waited on,
# never killed (claim discipline).  Logs land in results/logs/.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

wait_relay() {
  while true; do
    if python -c "import jax, jax.numpy as jnp; x = jnp.ones((128, 128)); (x @ x).block_until_ready(); print('compile-ok')" \
        > /tmp/queue_probe.out 2>&1 && grep -q compile-ok /tmp/queue_probe.out; then
      return 0
    fi
    sleep 120
  done
}

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  wait_relay
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# do not start while the pre-wedge bench still holds/awaits chip claims
stage bench_r4        python bench.py --skip-probe
stage train_mfu       python tools/train_mfu_probe.py
stage flash_train     python tools/flash_train_proof.py
stage tune_flash      python tools/tune_flash.py
stage serving_tpu     python tools/serving_tpu.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage parity          python tools/pallas_tpu_parity.py
echo "QUEUE DONE $(date)" >> $L/queue.status
