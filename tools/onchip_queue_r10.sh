#!/bin/bash
# Round-10 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  Each stage is gated on a live compiled-matmul
# probe.  If a previous round's queue left a probe pending (its PID in
# $PRIOR_PROBE_PID, output at /tmp/queue_probe.out), that claim is REUSED
# as the relay sentinel instead of stacking a second claim behind it.
#
# Round-10 ordering: the OBSERVABILITY evidence lands FIRST and is sized
# to complete-and-commit inside a ~3-minute relay window:
#   * obs_fast: bench.py obs_overhead (steady-state ticks/s with the
#     tpulab.obs layer on vs off; the bench itself asserts the <3%
#     budget) -- committed + ratcheted immediately;
#   * obs_capture: a REAL on-chip serving capture -- daemon with a
#     sized trace buffer, generate traffic driven through the socket,
#     then a metrics scrape (Prometheus text + percentile table) and a
#     trace_dump (Chrome trace JSON, loads in ui.perfetto.dev) written
#     under results/.  This is the acceptance artifact: ttft/itl/e2e
#     histograms populated by live on-chip generates.
# The regression pass ratchets the CPU-proxy obs_overhead baseline up to
# the chip number, exactly like paged_tick (r7) / train_step (r8) /
# prefill_interleave (r9).
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) — one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture() {
  # on-chip serving observability capture: daemon (bounded lifetime via
  # --max-requests; NEVER killed -- it holds the chip claim) + driven
  # generate traffic + metrics scrape + Perfetto trace dump.  The drive
  # sends 6 generates, then obs_report issues metrics + trace_dump +
  # metrics = 9 requests total, so the daemon exits on its own.
  SOCK=/tmp/tpulab_obs_r10.sock
  python -m tpulab.daemon --socket "$SOCK" --trace-buffer 65536 \
      --max-requests 9 &
  DPID=$!
  # wait for the socket (daemon warms the backend first -- on-chip that
  # is the compile wait; bounded so a dead daemon doesn't park the queue)
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --trace-out results/obs_trace_r10.json \
      > results/logs/obs_report_r10.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r10.prom 2>>results/logs/obs_report_r10.txt
  wait $DPID
}

date > $L/queue.status
# -- the ~3-minute observability window: the obs_overhead row, committed
#    (jsonl fallback + ratchet) IMMEDIATELY so a relay drop after this
#    point still leaves the round-10 obs evidence on disk
stage obs_fast        python bench.py --skip-probe --only obs_overhead --reps 5
grep '"metric"' $L/obs_fast.log > results/bench_r10.jsonl 2>/dev/null || true
python tools/check_regression.py results/bench_r10.jsonl --update \
    --date "round 10 (onchip_queue_r10, obs window)" > "$L/regression_obs.log" 2>&1
echo "== obs-window regression+ratchet rc=$? $(date)" >> $L/queue.status
stage obs_capture     obs_capture
stage serving_int     python tools/serving_tpu.py
# -- the long tail, round-9 ordering preserved
stage bench_r10       python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines, MERGED
# with the obs-window rows (a bare overwrite here would clobber the
# already-committed obs evidence if the relay dropped mid-registry)
grep -h '"metric"' $L/bench_r10.log $L/obs_fast.log \
    2>/dev/null | awk '!seen[$0]++' > results/bench_r10.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff -- a relay gate here could hang the
# queue after the chip stages already rewrote artifacts).  --update
# refuses to move any baseline in the worse direction without an
# explicit --accept-regression note (VERDICT r5 #6 guard); on a clean
# improving run it ratchets with round-10 provenance -- including the
# obs_overhead CPU-proxy baseline up to its chip value.
python tools/check_regression.py results/bench_r10.jsonl --update \
    --date "round 10 (onchip_queue_r10)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
