#!/bin/bash
# Round-11 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh (the
# round-11 satellite factored the per-round copies into one sourced
# library with jittered backoff and an optional bounded mode).
#
# Round-11 ordering: the FAULT-TOLERANCE evidence lands FIRST and is
# sized to complete-and-commit inside a ~3-minute relay window:
#   * chaos_fast: the chaos suite's fast tier (tests/test_faults.py,
#     CPU backend -- deterministic seeded fault schedules driving
#     supervisor replay bit-equality, preempt/resume block accounting,
#     shed-under-load, and the obs counters).  Host-only: runs BEFORE
#     any relay gate, so a wedged relay cannot block the correctness
#     evidence.
#   * fault_fast: bench.py fault_overhead on-chip -- the injector
#     disabled-vs-enabled-idle A/B (the bench itself asserts the <1%
#     budget) -- committed + ratcheted immediately.
# The regression pass ratchets the CPU-proxy fault_overhead baseline up
# to the chip number, exactly like obs_overhead (r10).
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) -- one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture() {
  # r10's on-chip serving observability capture, re-run at r11 so the
  # scrape shows the NEW fault-tolerance counters
  # (daemon_engine_restarts / daemon_replays / daemon_shed_requests /
  # engine_preemptions) next to the latency histograms.  Daemon bounded
  # via --max-requests; NEVER killed -- it holds the chip claim.
  SOCK=/tmp/tpulab_obs_r11.sock
  python -m tpulab.daemon --socket "$SOCK" --trace-buffer 65536 \
      --max-requests 9 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --trace-out results/obs_trace_r11.json \
      > results/logs/obs_report_r11.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r11.prom 2>>results/logs/obs_report_r11.txt
  wait $DPID
}

date > $L/queue.status
# -- chaos suite fast tier: HOST-ONLY (CPU backend), no relay gate --
# the correctness evidence must land even with the relay down
echo "== chaos_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py -q -m 'not slow' \
    -p no:cacheprovider > "$L/chaos_fast.log" 2>&1
echo "== chaos_fast rc=$? $(date)" >> $L/queue.status
# -- the ~3-minute fault-tolerance window: the fault_overhead row,
#    committed (jsonl fallback + ratchet) IMMEDIATELY so a relay drop
#    after this point still leaves the round-11 evidence on disk
stage fault_fast      python bench.py --skip-probe --only fault_overhead --reps 5
grep '"metric"' $L/fault_fast.log > results/bench_r11.jsonl 2>/dev/null || true
python tools/check_regression.py results/bench_r11.jsonl --update \
    --date "round 11 (onchip_queue_r11, fault window)" > "$L/regression_fault.log" 2>&1
echo "== fault-window regression+ratchet rc=$? $(date)" >> $L/queue.status
stage obs_capture     obs_capture
stage serving_int     python tools/serving_tpu.py
# -- the long tail, round-10 ordering preserved
stage bench_r11       python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines, MERGED
# with the fault-window rows (a bare overwrite here would clobber the
# already-committed fault evidence if the relay dropped mid-registry)
grep -h '"metric"' $L/bench_r11.log $L/fault_fast.log \
    2>/dev/null | awk '!seen[$0]++' > results/bench_r11.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff).  --update refuses to move any
# baseline in the worse direction without an explicit
# --accept-regression note (VERDICT r5 #6 guard); on a clean improving
# run it ratchets with round-11 provenance -- including the
# fault_overhead CPU-proxy baseline up to its chip value.
python tools/check_regression.py results/bench_r11.jsonl --update \
    --date "round 11 (onchip_queue_r11)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
