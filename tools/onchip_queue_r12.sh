#!/bin/bash
# Round-12 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-12 ordering: the GOODPUT evidence lands FIRST and is HOST-ONLY
# (CPU backend, its own spawned daemon), so a wedged relay cannot block
# the round's headline correctness/goodput evidence:
#   * loadgen_fast: the load-generator + slow-log test tier
#     (tests/test_loadgen.py -- trace byte-determinism, session prefix
#     reuse, slow-log rid linkage, the live-daemon gate acceptance).
#   * goodput_fast: tools/goodput_gate.py --spec fast against a
#     spawned CPU daemon -- per-class goodput-under-SLO, the slowlog
#     worst-N, and the goodput_fast_* rows ratcheted via
#     check_regression (results/goodput_r12.json is the committed
#     report; results/goodput_trace_fast.json the exact workload).
# Only then the relay-gated tail (r11 ordering preserved), which
# re-captures the obs scrape so the round-12 slowlog surface shows up
# in the on-chip evidence too.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) -- one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture() {
  # r11's on-chip serving observability capture, re-run at r12 so the
  # scrape sits next to a slowlog dump (the round-12 surface).  Daemon
  # bounded via --max-requests; NEVER killed -- it holds the chip claim.
  # Budget is EXACT (wait $DPID hangs on an undershoot, a dead socket
  # fails the last capture on an overshoot): 9 connections for the
  # drive invocation (6 generates + metrics + trace_dump + slowlog),
  # 1 for --raw, 2 for the slowlog_r12.json capture (metrics + slowlog).
  SOCK=/tmp/tpulab_obs_r12.sock
  python -m tpulab.daemon --socket "$SOCK" --trace-buffer 65536 \
      --slowlog 64 --max-requests 12 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --trace-out results/obs_trace_r12.json --slowlog 8 \
      > results/logs/obs_report_r12.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r12.prom 2>>results/logs/obs_report_r12.txt
  python tools/obs_report.py --socket "$SOCK" --slowlog 8 --json \
      > results/slowlog_r12.json 2>>results/logs/obs_report_r12.txt
  wait $DPID
}

date > $L/queue.status
# -- goodput fast tier: HOST-ONLY (CPU backend), no relay gate --
# the round's headline evidence must land even with the relay down
echo "== loadgen_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_loadgen.py -q -m 'not slow' \
    -p no:cacheprovider > "$L/loadgen_fast.log" 2>&1
echo "== loadgen_fast rc=$? $(date)" >> $L/queue.status
echo "== goodput_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python tools/goodput_gate.py --spawn-daemon \
    --socket /tmp/tpulab_goodput_r12.sock --spec fast \
    --out results/goodput_r12.json \
    --write-trace results/goodput_trace_fast.json \
    > "$L/goodput_fast.log" 2>&1
echo "== goodput_fast rc=$? $(date)" >> $L/queue.status
grep '"metric"' $L/goodput_fast.log > results/goodput_rows_r12.jsonl 2>/dev/null || true
python tools/check_regression.py results/goodput_rows_r12.jsonl --update \
    --date "round 12 (onchip_queue_r12, goodput fast tier)" \
    > "$L/regression_goodput.log" 2>&1
echo "== goodput regression+ratchet rc=$? $(date)" >> $L/queue.status
# -- the relay-gated tail, round-11 ordering preserved
stage obs_capture     obs_capture
stage serving_int     python tools/serving_tpu.py
stage bench_r12       python bench.py --skip-probe
grep -h '"metric"' $L/bench_r12.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r12.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff).  --update refuses to move any
# baseline in the worse direction without an explicit
# --accept-regression note (VERDICT r5 #6 guard).
python tools/check_regression.py results/bench_r12.jsonl --update \
    --date "round 12 (onchip_queue_r12)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
