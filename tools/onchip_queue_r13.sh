#!/bin/bash
# Round-13 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-13 ordering: the CHAOS-FLEET evidence lands FIRST and is
# HOST-ONLY (CPU backend, private spawned daemons), so a wedged relay
# cannot block the round's headline robustness evidence:
#   * fleet_fast: the replicated-serving test tier (tests/test_fleet.py
#     -- router scoring/health units, cross-replica migration
#     bit-equality, replay budget across migrations, cancel-during-
#     migration, drain/rolling restart, hedged retries, per-replica
#     metrics + counter/docs lints).
#   * goodput_chaos: tools/goodput_gate.py --spec chaos --replicas 3
#     --chaos --rolling-restart -- replays the seeded chaos trace
#     fault-free for reference outputs, then with a replica crash +
#     wedge armed, gating completion / stream reassembly / bit-equality
#     / zero-shed rolling restart, and ratchets the goodput_chaos_*
#     rows via check_regression (results/goodput_chaos_r13.json is the
#     committed report; results/goodput_trace_chaos.json the workload).
# Only then the relay-gated tail (r12 ordering preserved), which
# re-captures the obs scrape so the per-replica gauge breakdown shows
# up in the on-chip evidence too.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) -- one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture() {
  # r12's on-chip serving observability capture, re-run at r13 with a
  # 2-replica fleet so the scrape shows the engine_*_replica<i>
  # breakdown + fleet table.  Daemon bounded via --max-requests; NEVER
  # killed -- it holds the chip claim.  Budget is EXACT: 10 connections
  # for the drive invocation (6 generates + metrics + fleet + trace_dump
  # + slowlog), 2 for --raw (metrics + fleet), 3 for the slowlog_r13
  # capture (metrics + fleet + slowlog).
  SOCK=/tmp/tpulab_obs_r13.sock
  python -m tpulab.daemon --socket "$SOCK" --replicas 2 \
      --trace-buffer 65536 --slowlog 64 --max-requests 15 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --trace-out results/obs_trace_r13.json --slowlog 8 \
      > results/logs/obs_report_r13.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r13.prom 2>>results/logs/obs_report_r13.txt
  python tools/obs_report.py --socket "$SOCK" --slowlog 8 --json \
      > results/slowlog_r13.json 2>>results/logs/obs_report_r13.txt
  wait $DPID
}

date > $L/queue.status
# -- chaos fleet tier: HOST-ONLY (CPU backend), no relay gate --
# the round's headline evidence must land even with the relay down
echo "== fleet_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m 'not slow' \
    -p no:cacheprovider > "$L/fleet_fast.log" 2>&1
echo "== fleet_fast rc=$? $(date)" >> $L/queue.status
echo "== goodput_chaos start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python tools/goodput_gate.py --spawn-daemon \
    --socket /tmp/tpulab_goodput_r13.sock --spec chaos \
    --replicas 3 --chaos --rolling-restart \
    --out results/goodput_chaos_r13.json \
    --write-trace results/goodput_trace_chaos.json \
    > "$L/goodput_chaos.log" 2>&1
echo "== goodput_chaos rc=$? $(date)" >> $L/queue.status
grep '"metric"' $L/goodput_chaos.log > results/goodput_rows_r13.jsonl 2>/dev/null || true
python tools/check_regression.py results/goodput_rows_r13.jsonl --update \
    --date "round 13 (onchip_queue_r13, chaos fleet tier)" \
    > "$L/regression_goodput.log" 2>&1
echo "== goodput regression+ratchet rc=$? $(date)" >> $L/queue.status
# -- the relay-gated tail, round-12 ordering preserved
stage obs_capture     obs_capture
stage serving_int     python tools/serving_tpu.py
stage bench_r13       python bench.py --skip-probe
grep -h '"metric"' $L/bench_r13.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r13.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff).  --update refuses to move any
# baseline in the worse direction without an explicit
# --accept-regression note (VERDICT r5 #6 guard).
python tools/check_regression.py results/bench_r13.jsonl --update \
    --date "round 13 (onchip_queue_r13)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
