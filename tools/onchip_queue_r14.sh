#!/bin/bash
# Round-14 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-14 ordering: the COMPILER/DEVICE-OBSERVABILITY evidence lands
# FIRST and is HOST-ONLY (CPU backend, private spawned daemon), so a
# wedged relay cannot block the round's headline evidence:
#   * obs_compile_fast: tests/test_obs_compile.py -- the compile
#     ledger, the recompile tripwire both ways (steady window 0 /
#     bucket-busting nonzero), MFU/roofline math + gauges, HBM/KV
#     occupancy, the flight-recorder end-to-end chaos test, and the
#     runtime/device info paths.
#   * decode_recompiles: bench.py decode_recompiles certifies a full
#     serving window (spec + interleave + overlap ON) records ZERO
#     steady-state recompiles, ratcheting the signed
#     decode_steady_recompiles baselines row (expected 0, tol 0) via
#     check_regression (which since r14 treats 0-vs-0 as ok).
#   * obs_capture_host: a live CPU-daemon scrape proving the NEW gauges
#     flow end to end -- engine_recompiles / engine_compile_buckets_* /
#     engine_mfu / train_mfu / engine_hbm_bytes_* in the Prometheus
#     text, the compile_stats roofline table, and the postmortem
#     request (after the goodput chaos tier below has produced one).
# Only then the relay-gated tail (r13 ordering preserved), which
# re-captures the obs scrape ON-CHIP so the MFU gauges and
# memory_stats-backed HBM numbers land with real peaks.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture_host() {
  # HOST-ONLY live capture of the round-14 surfaces: drive a private
  # CPU daemon, then scrape metrics (must carry the new gauges),
  # the roofline table, and the slowlog.  Budget is EXACT: 10
  # connections for the drive invocation (6 generates + metrics +
  # fleet + trace_dump + slowlog), 4 for the roofline/raw pass
  # (metrics + fleet + compile_stats + postmortem), 1 platform probe.
  SOCK=/tmp/tpulab_obs_r14.sock
  env JAX_PLATFORMS=cpu python -m tpulab.daemon --socket "$SOCK" \
      --trace-buffer 65536 --slowlog 64 --max-requests 15 &
  DPID=$!
  for _ in $(seq 60); do [ -S "$SOCK" ] && break; sleep 2; done
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --drive 6 --steps 48 --trace-out results/obs_trace_r14_host.json \
      --slowlog 8 > results/logs/obs_report_r14_host.txt 2>&1
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --raw > results/obs_metrics_r14_host.prom \
      2>>results/logs/obs_report_r14_host.txt
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --json --roofline > results/obs_roofline_r14_host.json \
      2>>results/logs/obs_report_r14_host.txt
  wait $DPID
  # the capture is only evidence if the new gauges actually flowed
  for g in engine_recompiles engine_compile_buckets_dense engine_mfu \
           train_mfu engine_hbm_bytes_in_use engine_kv_pool_bytes \
           engine_blocks_used engine_cache_bytes; do
    grep -q "^$g " results/obs_metrics_r14_host.prom \
      || echo "MISSING GAUGE $g" >> $L/queue.status
  done
}

obs_capture_chip() {
  # the on-chip re-capture (r13 shape): 2-replica fleet, real device
  # peaks behind engine_mfu and memory_stats behind engine_hbm_*
  SOCK=/tmp/tpulab_obs_r14.sock
  python -m tpulab.daemon --socket "$SOCK" --replicas 2 \
      --trace-buffer 65536 --slowlog 64 --max-requests 15 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --trace-out results/obs_trace_r14.json --slowlog 8 --roofline \
      > results/logs/obs_report_r14.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r14.prom 2>>results/logs/obs_report_r14.txt
  python tools/obs_report.py --socket "$SOCK" --json --roofline \
      > results/obs_roofline_r14.json 2>>results/logs/obs_report_r14.txt
  wait $DPID
}

date > $L/queue.status
# -- compiler/device-observability tier: HOST-ONLY, no relay gate --
echo "== obs_compile_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_obs_compile.py -q \
    -m 'not slow' -p no:cacheprovider > "$L/obs_compile_fast.log" 2>&1
echo "== obs_compile_fast rc=$? $(date)" >> $L/queue.status
echo "== decode_recompiles start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_decode_recompiles
print(json.dumps(bench_decode_recompiles()))" \
    > "$L/decode_recompiles.log" 2>&1
echo "== decode_recompiles rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/decode_recompiles.log" \
    > results/recompile_rows_r14.jsonl 2>/dev/null || true
python tools/check_regression.py results/recompile_rows_r14.jsonl --update \
    --date "round 14 (onchip_queue_r14, host compile tier)" \
    > "$L/regression_recompiles.log" 2>&1
echo "== recompile regression+ratchet rc=$? $(date)" >> $L/queue.status
echo "== obs_capture_host start $(date)" >> $L/queue.status
obs_capture_host
echo "== obs_capture_host rc=$? $(date)" >> $L/queue.status
# -- the relay-gated tail, round-13 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r14      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r14.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r14.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r14.jsonl --update \
    --date "round 14 (onchip_queue_r14)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
