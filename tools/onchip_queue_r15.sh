#!/bin/bash
# Round-15 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-15 ordering: the TELEMETRY-OVER-TIME evidence lands FIRST and is
# HOST-ONLY (CPU backend, private spawned daemons), so a wedged relay
# cannot block the round's headline evidence:
#   * obs_time_fast: tests/test_obs_history.py + tests/test_obs_alerts.py
#     -- the history ring's windowed bucket differencing (counter resets
#     included), burn-rate window arithmetic, the alert state machine
#     with flap hysteresis, the alert-wired fleet health chaos
#     acceptance (alert fires BEFORE the crash path, placement steers
#     off, resolve after recovery), retention pruning, and the
#     rule-catalog docs lint.
#   * obs_history_overhead: bench.py obs_history_overhead re-certifies
#     the <3% obs budget with the history sampler (50 ms cadence, 20x
#     production) + full alert-catalog evaluation ON, ratcheting the
#     signed obs_history_overhead_4slots_ticks_per_s baselines row.
#   * obs_capture_host: a live CPU-daemon capture of the new surfaces
#     -- the `history` request (windowed rates/percentiles + rate
#     series) committed as results/obs_history_r15.json, and a
#     FIRING-ALERT DEMO under a scoped fault (paged.tick@replica0
#     slow_ms wedges the engine; the replica_degraded / burn-rate
#     rules must show "firing" in the captured alerts table,
#     results/obs_alerts_r15.json).
# Only then the relay-gated tail (r14 ordering preserved), which
# re-captures the obs scrape ON-CHIP.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

obs_capture_host() {
  # HOST-ONLY live capture of the round-15 surfaces.  Daemon 1 (clean):
  # sampler at 200 ms, driven traffic, then the history report with
  # rate series -> results/obs_history_r15.json.  Connection budget is
  # EXACT: 6 drives + metrics + fleet + alerts + history = 10, then a
  # raw metrics pass (1) that must carry the obs_alerts_* gauges.
  SOCK=/tmp/tpulab_obs_r15.sock
  rm -f "$SOCK"
  env JAX_PLATFORMS=cpu python -m tpulab.daemon --socket "$SOCK" \
      --metrics-interval 0.2 --slowlog 64 --max-requests 11 &
  DPID=$!
  for _ in $(seq 60); do [ -S "$SOCK" ] && break; sleep 2; done
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --drive 6 --steps 32 --alerts --history 30 \
      --history-out results/obs_history_r15.json \
      > results/logs/obs_report_r15_host.txt 2>&1
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --raw > results/obs_metrics_r15_host.prom \
      2>>results/logs/obs_report_r15_host.txt
  wait $DPID
  for g in obs_alerts_firing obs_alerts_pending obs_alerts_evals \
           fleet0_replica0_ticks; do
    grep -q "^$g " results/obs_metrics_r15_host.prom \
      || echo "MISSING METRIC $g" >> $L/queue.status
  done
  python - <<'EOF' >> $L/queue.status
import json
h = json.load(open("results/obs_history_r15.json"))
ok = (h.get("samples", 0) >= 2 and h.get("window")
      and h["window"].get("histograms", {}).get("ttft_seconds", {})
      .get("count", 0) > 0)
print("history capture:", "ok" if ok else "MISSING WINDOW DATA")
EOF
  # Daemon 2 (the firing-alert demo): a scoped fault wedges the one
  # replica's engine ticks at 300 ms; the windowed replica_degraded
  # rule (and the TTFT burn ladder, cold compile included) must be
  # FIRING in the captured alerts table.  3 drives + metrics + fleet
  # + alerts = 6 connections.
  rm -f "$SOCK"
  env JAX_PLATFORMS=cpu \
      TPULAB_FAULTS='[{"site":"paged.tick@replica0","kind":"slow_ms","at":1,"count":64,"arg":300.0}]' \
      python -m tpulab.daemon --socket "$SOCK" \
      --metrics-interval 0.2 --max-requests 6 &
  DPID=$!
  for _ in $(seq 60); do [ -S "$SOCK" ] && break; sleep 2; done
  env JAX_PLATFORMS=cpu python tools/obs_report.py --socket "$SOCK" \
      --drive 3 --steps 16 --alerts --json \
      > results/obs_alerts_r15.json \
      2>>results/logs/obs_report_r15_host.txt
  wait $DPID
  python - <<'EOF' >> $L/queue.status
import json
a = json.load(open("results/obs_alerts_r15.json")).get("alerts", {})
firing = [r["rule"] for r in a.get("alerts", []) if r["state"] == "firing"]
print("alert demo firing:", firing if firing else "NO ALERT FIRED")
assert firing, "firing-alert demo captured no firing alert"
EOF
  echo "== alert demo rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- telemetry-over-time tier: HOST-ONLY, no relay gate --
echo "== obs_time_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_obs_history.py \
    tests/test_obs_alerts.py -q -m 'not slow' -p no:cacheprovider \
    > "$L/obs_time_fast.log" 2>&1
echo "== obs_time_fast rc=$? $(date)" >> $L/queue.status
echo "== obs_history_overhead start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_obs_history_overhead
print(json.dumps(bench_obs_history_overhead()))" \
    > "$L/obs_history_overhead.log" 2>&1
echo "== obs_history_overhead rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/obs_history_overhead.log" \
    > results/obs_overhead_rows_r15.jsonl 2>/dev/null || true
python tools/check_regression.py results/obs_overhead_rows_r15.jsonl \
    --update --date "round 15 (onchip_queue_r15, host telemetry tier)" \
    > "$L/regression_obs_history.log" 2>&1
echo "== obs_history regression+ratchet rc=$? $(date)" >> $L/queue.status
echo "== obs_capture_host start $(date)" >> $L/queue.status
obs_capture_host
echo "== obs_capture_host rc=$? $(date)" >> $L/queue.status
obs_capture_chip() {
  # the on-chip re-capture (r14 shape + the round-15 surfaces): a
  # 2-replica fleet with the sampler at the production 1 s cadence;
  # history/alerts land with real device timings behind them
  SOCK=/tmp/tpulab_obs_r15.sock
  rm -f "$SOCK"
  python -m tpulab.daemon --socket "$SOCK" --replicas 2 \
      --metrics-interval 1.0 --trace-buffer 65536 --slowlog 64 \
      --max-requests 11 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --alerts --history 30 \
      --history-out results/obs_history_r15_chip.json \
      > results/logs/obs_report_r15.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r15.prom 2>>results/logs/obs_report_r15.txt
  wait $DPID
}

# -- the relay-gated tail, round-14 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r15      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r15.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r15.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r15.jsonl --update \
    --date "round 15 (onchip_queue_r15)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
