#!/bin/bash
# Round-16 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-16 ordering: the CRASH-DURABILITY evidence lands FIRST and is
# HOST-ONLY (CPU backend, private spawned daemons), so a wedged relay
# cannot block the round's headline evidence:
#   * durability_fast: tests/test_durability.py -- the write-ahead
#     journal units (torn final record, incremental ckpt chain
#     stitching, completion-record compaction, group-commit accepts),
#     the in-process resume/recovery bit-equality paths, the live
#     daemon.kill crash + restart + resume-by-rid acceptance, and the
#     counter/docs lints.
#   * goodput_kill: tools/goodput_gate.py --spec chaos --kill-daemon
#     -- SIGKILLs a journal-armed daemon mid-trace, restarts it on the
#     same socket + journal, and gates: >=1 journal recovery, >=1
#     resumed stream, every non-cancelled request completes, zero
#     lost/duplicated client bytes, completions BIT-IDENTICAL to a
#     fault-free journal-armed reference; ratchets the signed
#     goodput_kill_* baselines rows.
#   * journal_overhead: bench.py bench_journal_overhead re-certifies
#     the <1% steady-state decode budget for the armed journal
#     (buffered appends + incremental delta ckpts), ratcheting the
#     signed journal_overhead_4slots_ticks_per_s baselines row.
# Only then the relay-gated tail (r15 ordering preserved), which
# re-captures the obs scrape ON-CHIP.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- crash-durability tier: HOST-ONLY (CPU backend), no relay gate --
# the round's headline evidence must land even with the relay down
echo "== durability_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_durability.py -q \
    -m 'not slow' -p no:cacheprovider > "$L/durability_fast.log" 2>&1
echo "== durability_fast rc=$? $(date)" >> $L/queue.status
echo "== goodput_kill start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python tools/goodput_gate.py --spawn-daemon \
    --socket /tmp/tpulab_goodput_r16.sock --spec chaos \
    --kill-daemon --out results/goodput_kill_r16.json \
    > "$L/goodput_kill.log" 2>&1
echo "== goodput_kill rc=$? $(date)" >> $L/queue.status
grep '"metric"' $L/goodput_kill.log > results/goodput_rows_r16.jsonl 2>/dev/null || true
echo "== journal_overhead start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_journal_overhead
print(json.dumps(bench_journal_overhead()))" \
    > "$L/journal_overhead.log" 2>&1
echo "== journal_overhead rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/journal_overhead.log" \
    >> results/goodput_rows_r16.jsonl 2>/dev/null || true
python tools/check_regression.py results/goodput_rows_r16.jsonl --update \
    --date "round 16 (onchip_queue_r16, crash-durability tier)" \
    > "$L/regression_durability.log" 2>&1
echo "== durability regression+ratchet rc=$? $(date)" >> $L/queue.status

obs_capture_chip() {
  # the on-chip re-capture (r15 shape, now with a JOURNAL-ARMED fleet):
  # real device timings behind the history/alert surfaces, and the
  # journal counters visible in the committed scrape
  SOCK=/tmp/tpulab_obs_r16.sock
  JRN=/tmp/tpulab_obs_r16.journal.jsonl
  rm -f "$SOCK" "$JRN"
  python -m tpulab.daemon --socket "$SOCK" --replicas 2 \
      --journal "$JRN" --metrics-interval 1.0 --trace-buffer 65536 \
      --slowlog 64 --max-requests 11 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --alerts --history 30 \
      --history-out results/obs_history_r16_chip.json \
      > results/logs/obs_report_r16.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r16.prom 2>>results/logs/obs_report_r16.txt
  wait $DPID
  rm -f "$JRN"
  for g in daemon_journal_records daemon_resumed_streams \
           daemon_recoveries; do
    grep -q "^$g " results/obs_metrics_r16.prom \
      || echo "MISSING METRIC $g" >> $L/queue.status
  done
}

# -- the relay-gated tail, round-15 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r16      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r16.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r16.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r16.jsonl --update \
    --date "round 16 (onchip_queue_r16)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
