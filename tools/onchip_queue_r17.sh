#!/bin/bash
# Round-17 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-17 ordering: the ELASTIC-FLEET evidence lands FIRST and is
# HOST-ONLY (CPU backend, private spawned daemons), so a wedged relay
# cannot block the round's headline evidence:
#   * elastic_fast: tests/test_autoscale.py -- the AutoscalePolicy
#     streak/cooldown/hysteresis units, the BrownoutLadder
#     engage/release ordering + rung-effect units, scale-in under load
#     (drain-migrate-retire, greedy streams bit-identical, zero leaked
#     blocks), the spot-preemption drills (peer migration AND the
#     no-peer park-then-revival replay), the startup bounds
#     validation, and the counter/docs lints.
#   * goodput_ramp: tools/goodput_gate.py --spec ramp --autoscale --
#     replays the ~10x arrival ramp with one injected spot preemption
#     against an armed daemon (--autoscale-min 1 --autoscale-max 3)
#     vs a disarmed fixed reference, and gates: >=1 scale-out, >=1
#     scale-in, the preemption honored, >=1 brownout step with
#     steps == reversals (fully unwound), fleet settled back at the
#     floor, attainment 1.0, zero torn streams, surviving streams
#     BIT-IDENTICAL to the reference; ratchets the signed
#     goodput_ramp_* baselines rows.
#   * autoscale_overhead: bench.py bench_autoscale_overhead
#     re-certifies the <1% enabled-idle control-loop budget at ~100x
#     the production sampler cadence, ratcheting the signed
#     autoscale_overhead_4slots_ticks_per_s baselines row.
# Only then the relay-gated tail (r16 ordering preserved), which
# re-captures the obs scrape ON-CHIP.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- elastic-fleet tier: HOST-ONLY (CPU backend), no relay gate --
# the round's headline evidence must land even with the relay down
echo "== elastic_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_autoscale.py -q \
    -m 'not slow' -p no:cacheprovider > "$L/elastic_fast.log" 2>&1
echo "== elastic_fast rc=$? $(date)" >> $L/queue.status
echo "== goodput_ramp start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python tools/goodput_gate.py --spawn-daemon \
    --socket /tmp/tpulab_goodput_r17.sock --spec ramp \
    --autoscale --check-baselines --out results/goodput_ramp_r17.json \
    > "$L/goodput_ramp.log" 2>&1
echo "== goodput_ramp rc=$? $(date)" >> $L/queue.status
grep '"metric"' $L/goodput_ramp.log > results/goodput_rows_r17.jsonl 2>/dev/null || true
echo "== autoscale_overhead start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_autoscale_overhead
print(json.dumps(bench_autoscale_overhead()))" \
    > "$L/autoscale_overhead.log" 2>&1
echo "== autoscale_overhead rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/autoscale_overhead.log" \
    >> results/goodput_rows_r17.jsonl 2>/dev/null || true
python tools/check_regression.py results/goodput_rows_r17.jsonl --update \
    --date "round 17 (onchip_queue_r17, elastic-fleet tier)" \
    > "$L/regression_elastic.log" 2>&1
echo "== elastic regression+ratchet rc=$? $(date)" >> $L/queue.status

obs_capture_chip() {
  # the on-chip re-capture (r16 shape, now with an AUTOSCALE-ARMED
  # fleet): real device timings behind the history/alert surfaces, and
  # the elastic counters/gauges visible in the committed scrape
  SOCK=/tmp/tpulab_obs_r17.sock
  JRN=/tmp/tpulab_obs_r17.journal.jsonl
  rm -f "$SOCK" "$JRN"
  python -m tpulab.daemon --socket "$SOCK" --replicas 1 \
      --autoscale-min 1 --autoscale-max 2 \
      --journal "$JRN" --metrics-interval 1.0 --trace-buffer 65536 \
      --slowlog 64 --max-requests 11 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --alerts --history 30 \
      --history-out results/obs_history_r17_chip.json \
      > results/logs/obs_report_r17.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r17.prom 2>>results/logs/obs_report_r17.txt
  wait $DPID
  rm -f "$JRN"
  for g in fleet_target_replicas daemon_brownout_level \
           daemon_scale_outs daemon_scale_ins daemon_spot_preemptions; do
    grep -q "^$g " results/obs_metrics_r17.prom \
      || echo "MISSING METRIC $g" >> $L/queue.status
  done
}

# -- the relay-gated tail, round-16 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r17      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r17.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r17.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r17.jsonl --update \
    --date "round 17 (onchip_queue_r17)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
