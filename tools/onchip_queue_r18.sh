#!/bin/bash
# Round-18 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-18 ordering: the HIERARCHICAL-CACHE evidence lands FIRST and is
# HOST-ONLY (CPU backend, private spawned daemons), so a wedged relay
# cannot block the round's headline evidence:
#   * kvcache_fast: tests/test_kvcache.py -- the radix index
#     property-tested against the brute-force oracle, dict-vs-radix
#     bit-equality on exact-hit traces, the host spill tier's lossless
#     native round-trips + LRU drops, int4 pack/unpack, the full
#     spill->prefetch cycle bit-identical to a spill-disabled engine,
#     live-slot-safe prefix eviction, and the flat-h2d + zero-recompile
#     standing contracts re-certified with the tier armed.
#   * goodput_prefix: tools/goodput_gate.py --spec prefix
#     --prefix-cache -- replays the heavy-shared-prefix trace (working
#     set >= 4x the 128-block HBM pool) against a radix+spill daemon
#     (--prefix-index radix --spill-blocks 512) vs an HBM-only dict
#     reference, and gates: blocks spilled AND prefetched, hit rate
#     STRICTLY above the HBM-only floor, attainment >= the reference,
#     every stream BIT-IDENTICAL to the spill-disabled reference;
#     ratchets the signed goodput_prefix_* + prefix_cache_hit_rate
#     baselines rows.
#   * spill_overhead: bench.py bench_spill_overhead re-certifies the
#     <1% armed-but-cold steady-decode budget (and bench_prefix_lookup
#     asserts the O(L) admission-path lookup scaling), ratcheting the
#     signed spill_overhead_4slots_ticks_per_s baselines row.
# Only then the relay-gated tail (r17 ordering preserved), which
# re-captures the obs scrape ON-CHIP.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- hierarchical-cache tier: HOST-ONLY (CPU backend), no relay gate --
# the round's headline evidence must land even with the relay down
echo "== kvcache_fast start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -m pytest tests/test_kvcache.py -q \
    -m 'not slow' -p no:cacheprovider > "$L/kvcache_fast.log" 2>&1
echo "== kvcache_fast rc=$? $(date)" >> $L/queue.status
echo "== goodput_prefix start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python tools/goodput_gate.py --spawn-daemon \
    --socket /tmp/tpulab_goodput_r18.sock --spec prefix \
    --prefix-cache --check-baselines \
    --out results/goodput_prefix_r18.json \
    > "$L/goodput_prefix.log" 2>&1
echo "== goodput_prefix rc=$? $(date)" >> $L/queue.status
grep '"metric"' $L/goodput_prefix.log > results/goodput_rows_r18.jsonl 2>/dev/null || true
echo "== spill_overhead start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_spill_overhead, bench_prefix_lookup
print(json.dumps(bench_spill_overhead()))
print(json.dumps(bench_prefix_lookup()))" \
    > "$L/spill_overhead.log" 2>&1
echo "== spill_overhead rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/spill_overhead.log" \
    >> results/goodput_rows_r18.jsonl 2>/dev/null || true
python tools/check_regression.py results/goodput_rows_r18.jsonl --update \
    --date "round 18 (onchip_queue_r18, hierarchical-cache tier)" \
    > "$L/regression_kvcache.log" 2>&1
echo "== kvcache regression+ratchet rc=$? $(date)" >> $L/queue.status

obs_capture_chip() {
  # the on-chip re-capture (r17 shape, now with a RADIX+SPILL-ARMED
  # daemon): real device timings behind the history/alert surfaces,
  # and the round-18 spill counters/gauges visible in the committed
  # scrape
  SOCK=/tmp/tpulab_obs_r18.sock
  JRN=/tmp/tpulab_obs_r18.journal.jsonl
  rm -f "$SOCK" "$JRN"
  python -m tpulab.daemon --socket "$SOCK" --replicas 1 \
      --prefix-index radix --spill-blocks 512 \
      --journal "$JRN" --metrics-interval 1.0 --trace-buffer 65536 \
      --slowlog 64 --max-requests 11 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --alerts --history 30 \
      --history-out results/obs_history_r18_chip.json \
      > results/logs/obs_report_r18.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r18.prom 2>>results/logs/obs_report_r18.txt
  wait $DPID
  rm -f "$JRN"
  for g in engine_spill_capacity_blocks engine_spill_spilled \
           engine_spill_prefetched engine_prefix_hits; do
    grep -q "^$g " results/obs_metrics_r18.prom \
      || echo "MISSING METRIC $g" >> $L/queue.status
  done
}

# -- the relay-gated tail, round-17 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r18      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r18.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r18.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r18.jsonl --update \
    --date "round 18 (onchip_queue_r18)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
