#!/bin/bash
# Round-19 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  wait_relay comes from tools/relay_lib.sh.
#
# Round-19 ordering: the MESH-SHARDED-ENGINE evidence lands FIRST and is
# HOST-ONLY (CPU backend, 8 forced virtual devices), so a wedged relay
# cannot block the round's headline evidence:
#   * mesh_serving: tests/test_mesh_serving.py + the serving-mesh helper
#     unit tests -- greedy streams bit-identical mesh(1,1) vs mesh(2,4)
#     for plain/sampled/penalized/spec/prefix-hit slots, flat-h2d +
#     zero-recompile + obs on/off contracts re-certified on-mesh, the
#     spill tier certified on sharded pools (native + int8 payloads,
#     counters advancing), every EngineConfigError arm, and the
#     per-shard byte-accounting/gauge surface.
#   * mesh_tick: bench.py bench_mesh_tick_overhead -- the
#     serving_mesh(2,4)-vs-(1,1) CPU-proxy A/B (GSPMD partitioning
#     overhead on virtual devices; the same A/B is the tp scaling
#     probe on a real slice), ratcheting the signed
#     mesh_tick_8dev_ticks_per_s baselines row.
# Only then the relay-gated tail (r18 ordering preserved), which
# re-captures the obs scrape ON-CHIP -- now with a --mesh daemon once
# the relay-attached slice has >= 8 chips (mesh_spec auto-degrades to
# the device count; see the tail stage below).
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- mesh-sharded-engine tier: HOST-ONLY (CPU backend, 8 virtual
# devices), no relay gate -- the round's headline evidence must land
# even with the relay down
echo "== mesh_serving start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_mesh_serving.py \
    "tests/test_parallel.py::TestServingMeshHelpers" -q \
    -m 'not slow' -p no:cacheprovider > "$L/mesh_serving.log" 2>&1
echo "== mesh_serving rc=$? $(date)" >> $L/queue.status
echo "== mesh_tick start $(date)" >> $L/queue.status
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" python -c "
import json
from tpulab.bench import bench_mesh_tick_overhead
print(json.dumps(bench_mesh_tick_overhead()))" \
    > "$L/mesh_tick.log" 2>&1
echo "== mesh_tick rc=$? $(date)" >> $L/queue.status
grep '"metric"' "$L/mesh_tick.log" \
    > results/mesh_rows_r19.jsonl 2>/dev/null || true
python tools/check_regression.py results/mesh_rows_r19.jsonl --update \
    --date "round 19 (onchip_queue_r19, mesh-sharded-engine tier)" \
    > "$L/regression_mesh.log" 2>&1
echo "== mesh regression+ratchet rc=$? $(date)" >> $L/queue.status

obs_capture_chip() {
  # the on-chip re-capture (r18 shape, now with a MESH daemon when the
  # attached slice has the chips): real device timings behind the
  # history/alert surfaces, and the round-19 per-shard gauges visible
  # in the committed scrape
  SOCK=/tmp/tpulab_obs_r19.sock
  JRN=/tmp/tpulab_obs_r19.journal.jsonl
  rm -f "$SOCK" "$JRN"
  NDEV=$(python -c "import jax; print(len(jax.devices()))")
  MESH=""
  if [ "$NDEV" -ge 8 ]; then MESH="--mesh 2x4";
  elif [ "$NDEV" -ge 2 ]; then MESH="--mesh 1x2"; fi
  python -m tpulab.daemon --socket "$SOCK" --replicas 1 $MESH \
      --prefix-index radix --spill-blocks 512 \
      --journal "$JRN" --metrics-interval 1.0 --trace-buffer 65536 \
      --slowlog 64 --max-requests 11 &
  DPID=$!
  for _ in $(seq 120); do [ -S "$SOCK" ] && break; sleep 5; done
  python tools/obs_report.py --socket "$SOCK" --drive 6 --steps 48 \
      --alerts --history 30 \
      --history-out results/obs_history_r19_chip.json \
      > results/logs/obs_report_r19.txt 2>&1
  python tools/obs_report.py --socket "$SOCK" --raw \
      > results/obs_metrics_r19.prom 2>>results/logs/obs_report_r19.txt
  wait $DPID
  rm -f "$JRN"
  for g in engine_mesh_devices engine_kv_pool_device_bytes \
           engine_kv_pool_bytes_per_shard engine_spill_capacity_blocks; do
    grep -q "^$g " results/obs_metrics_r19.prom \
      || echo "MISSING METRIC $g" >> $L/queue.status
  done
  if [ -n "$MESH" ]; then
    grep -q "^engine_hbm_bytes_in_use_shard0 " results/obs_metrics_r19.prom \
      || echo "MISSING METRIC engine_hbm_bytes_in_use_shard0" >> $L/queue.status
  fi
}

# -- the relay-gated tail, round-18 ordering preserved
stage obs_capture    obs_capture_chip
stage serving_int    python tools/serving_tpu.py
stage bench_r19      python bench.py --skip-probe
grep -h '"metric"' $L/bench_r19.log 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r19.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff)
python tools/check_regression.py results/bench_r19.jsonl --update \
    --date "round 19 (onchip_queue_r19)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: stages above rewrite signed artifacts (baselines.json under
# the --update; pallas_tpu_parity.json) -- signatures must track them
# or tests/test_signing.py reds.  No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
