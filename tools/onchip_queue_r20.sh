#!/bin/bash
# Round-20 sequential on-chip evidence queue (single chip -- no
# contention).  First round built on tools/onchip_lib.sh (which sources
# relay_lib.sh -- the one wait_relay copy; claim discipline per
# docs/tpu_runs.md: TPU-claiming processes are WAITED on, never
# killed).
#
# Round-20 ordering: the DISAGGREGATED-FLEET evidence lands FIRST and
# is HOST-ONLY (CPU backend), so a wedged relay cannot block the
# round's headline evidence:
#   * disagg_gate: tools/goodput_gate.py --disagg -- unified vs
#     prefill/decode-pooled daemon A/B over the heavy-tail disagg
#     trace: bit-identical streams, ITL p99 within the noise band,
#     attainment 1.0, daemon_handoffs/handoff_bytes advancing, >= 1
#     prefill-pool scale event with the decode pool untouched, zero
#     leaked KV blocks from the per-replica block census.
#   * disagg_tests: tests/test_disagg.py + the handoff chaos drill in
#     tests/test_faults.py + the mesh(2,4)-both-ends handoff recert.
#   * handoff_bench: bench.py bench_handoff_overhead -- the
#     export/import/resubmit A/B against a unified engine, ratcheting
#     the signed handoff_overhead_e2e_tokens_per_s row (< 3% budget).
# Only then the relay-gated tail (r19 ordering preserved).

. "$(dirname "$0")/onchip_lib.sh"   # sources relay_lib.sh
onchip_init

# -- disaggregated-fleet tier: HOST-ONLY, no relay gate
host_stage disagg_gate env JAX_PLATFORMS=cpu \
    python tools/goodput_gate.py --spawn-daemon --spec disagg --disagg \
    --replicas 1 --spill-blocks 512 \
    --out results/goodput_disagg_r20.json
host_stage disagg_tests env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_disagg.py \
    "tests/test_faults.py::test_handoff_crash_replays_from_journaled_prompt" \
    tests/test_mesh_serving.py -q -m 'not slow' -p no:cacheprovider
host_stage handoff_bench env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_handoff_overhead
print(json.dumps(bench_handoff_overhead()))"
# the gate prints its baselines rows to stdout (the stage log); the
# bench prints its single row the same way -- merge, newest-unique
grep -h '"metric"' "$L/disagg_gate.log" "$L/handoff_bench.log" \
    2>/dev/null | awk '!seen[$0]++' > results/disagg_rows_r20.jsonl || true
ratchet results/disagg_rows_r20.jsonl \
    "round 20 (onchip_queue_r20, disaggregated-fleet tier)"

# -- the relay-gated tail, round-19 ordering preserved
stage serving_int    python tools/serving_tpu.py
stage bench_r20      python bench.py --skip-probe
grep -h '"metric"' "$L/bench_r20.log" 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r20.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
ratchet results/bench_r20.jsonl "round 20 (onchip_queue_r20)"
resign
onchip_done
