#!/bin/bash
# Round-21 sequential on-chip evidence queue (single chip -- no
# contention).  Built on tools/onchip_lib.sh (which sources
# relay_lib.sh -- the one wait_relay copy; claim discipline per
# docs/tpu_runs.md: TPU-claiming processes are WAITED on, never
# killed).
#
# Round-21 ordering: the JOURNEY/ATTRIBUTION evidence lands FIRST and
# is HOST-ONLY (CPU backend), so a wedged relay cannot block the
# round's headline evidence:
#   * journey_gate: tools/goodput_gate.py --disagg --attribute -- the
#     r20 disagg A/B plus per-request journey acceptance: every
#     completed request one stitched journey with a contiguous
#     monotonic phase waterfall across both pools, handoff phases
#     summing to handoff_ms, journey bytes == the
#     daemon_handoffs/handoff_bytes counter deltas EXACTLY, >= 1
#     histogram exemplar resolving to a live journey, SLO misses
#     attributed by dominant phase.
#   * journey_capture: tools/obs_journey_capture.py -- drives ONE
#     real handed-off request through a live disagg daemon and
#     commits its stitched journey (results/obs_journey_r21.json).
#   * journey_tests: tests/test_journey.py + the exemplar lint in
#     tests/test_obs.py + the mesh(2,4)-both-ends journey recert.
#   * journey_bench: bench.py bench_journey_overhead -- tracer +
#     journey store + exemplars armed vs fully dark, ratcheting the
#     signed journey_overhead_4slots_ticks_per_s row (< 3% budget).
# Only then the relay-gated tail (r20 ordering preserved).

. "$(dirname "$0")/onchip_lib.sh"   # sources relay_lib.sh
onchip_init

# -- journey/attribution tier: HOST-ONLY, no relay gate
host_stage journey_gate env JAX_PLATFORMS=cpu \
    python tools/goodput_gate.py --spawn-daemon --spec disagg --disagg \
    --attribute --replicas 1 --spill-blocks 512 \
    --out results/goodput_disagg_attr_r21.json
host_stage journey_capture env JAX_PLATFORMS=cpu \
    python tools/obs_journey_capture.py --out results/obs_journey_r21.json
host_stage journey_tests env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_journey.py tests/test_obs.py \
    "tests/test_mesh_serving.py::test_handoff_journey_stitched_across_mesh_engines" \
    -q -m 'not slow' -p no:cacheprovider
host_stage journey_bench env JAX_PLATFORMS=cpu python -c "
import json
from tpulab.bench import bench_journey_overhead
print(json.dumps(bench_journey_overhead()))"
# the gate prints its baselines rows to stdout (the stage log); the
# bench prints its single row the same way -- merge, newest-unique
grep -h '"metric"' "$L/journey_gate.log" "$L/journey_bench.log" \
    2>/dev/null | awk '!seen[$0]++' > results/journey_rows_r21.jsonl || true
ratchet results/journey_rows_r21.jsonl \
    "round 21 (onchip_queue_r21, journey/attribution tier)"

# -- the relay-gated tail, round-20 ordering preserved
stage serving_int    python tools/serving_tpu.py
stage bench_r21      python bench.py --skip-probe
grep -h '"metric"' "$L/bench_r21.log" 2>/dev/null \
    | awk '!seen[$0]++' > results/bench_r21.jsonl || true
stage parity         python tools/pallas_tpu_parity.py
stage flash_train    python tools/flash_train_proof.py
stage mfu_probe      python tools/train_mfu_probe.py
stage ref_harness2   python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3   python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
ratchet results/bench_r21.jsonl "round 21 (onchip_queue_r21)"
resign
onchip_done
