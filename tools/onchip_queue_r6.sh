#!/bin/bash
# Round-6 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  Each stage is gated on a live compiled-matmul
# probe.  If a previous round's queue left a probe pending (its PID in
# $PRIOR_PROBE_PID, output at /tmp/queue_probe.out), that claim is REUSED
# as the relay sentinel instead of stacking a second claim behind it.
#
# Round-6 addition: the serving stage now emits the SPECULATIVE-decode
# row (spec_lookup_batch4_k4: accepted-length mean, verify passes per
# token, tok/s, speedup vs plain ticks — tools/serving_tpu.py), so the
# batched paged_verify speedup lands automatically when the relay heals.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) — one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
stage bench_r6        python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines
grep '"metric"' $L/bench_r6.log > results/bench_r6.jsonl 2>/dev/null || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage train_mfu       python tools/train_mfu_probe.py
stage serving_tpu     python tools/serving_tpu.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff — a relay gate here could hang the
# queue after the chip stages already rewrote artifacts).  --update
# refuses to move any baseline in the worse direction without an
# explicit --accept-regression note (VERDICT r5 #6 guard), so a
# half-broken relay window can never launder a regression into the
# table; on a clean improving run it ratchets with round-6 provenance.
python tools/check_regression.py results/bench_r6.jsonl --update \
    --date "round 6 (onchip_queue_r6)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under a later --update) — signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
