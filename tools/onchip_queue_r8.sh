#!/bin/bash
# Round-8 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  Each stage is gated on a live compiled-matmul
# probe.  If a previous round's queue left a probe pending (its PID in
# $PRIOR_PROBE_PID, output at /tmp/queue_probe.out), that claim is REUSED
# as the relay sentinel instead of stacking a second claim behind it.
#
# Round-8 addition: the TRAIN evidence lands FIRST and is sized to
# complete-and-commit inside a ~3-minute relay window -- the relay has
# been dropping between stages all round, so the highest-value rows
# (the device-resident train step this round exists to prove) go
# before the long tails:
#   * train_fast: bench.py train_step_overhead (steady-state steps/s,
#     donated state + K-step fused dispatch vs the pre-change loop) +
#     the b8 x s2048 labformer_train throughput scenario at low reps --
#     together well under the window on chip;
#   * train_mfu: tools/train_mfu_probe.py now also emits the
#     train_s2048_flash_fused_k4 / train_s256_dense_fused_k4 cases, so
#     the fused-dispatch MFU delta is measured on the same shapes as
#     the round-4 21.7%-MFU reading.
# The regression pass ratchets the CPU-proxy train_step baseline up to
# the chip number, exactly like paged_tick in round 7.
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) — one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- the ~3-minute train window: overhead row + throughput row, committed
#    (jsonl fallback + ratchet) IMMEDIATELY so a relay drop after this
#    point still leaves the round-8 train evidence on disk
stage train_fast      python bench.py --skip-probe --only train_step --reps 5
grep '"metric"' $L/train_fast.log > results/bench_r8.jsonl 2>/dev/null || true
stage train_tput      python bench.py --skip-probe --only labformer_train --reps 5
grep '"metric"' $L/train_tput.log >> results/bench_r8.jsonl 2>/dev/null || true
python tools/check_regression.py results/bench_r8.jsonl --update \
    --date "round 8 (onchip_queue_r8, train window)" > "$L/regression_train.log" 2>&1
echo "== train-window regression+ratchet rc=$? $(date)" >> $L/queue.status
stage train_mfu       python tools/train_mfu_probe.py
# -- the long tail, round-7 ordering preserved
stage bench_r8        python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines, MERGED
# with the train-window rows (a bare overwrite here would clobber the
# already-committed train evidence if the relay dropped mid-registry)
grep -h '"metric"' $L/bench_r8.log $L/train_fast.log $L/train_tput.log \
    2>/dev/null | awk '!seen[$0]++' > results/bench_r8.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage serving_tpu     python tools/serving_tpu.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff -- a relay gate here could hang the
# queue after the chip stages already rewrote artifacts).  --update
# refuses to move any baseline in the worse direction without an
# explicit --accept-regression note (VERDICT r5 #6 guard); on a clean
# improving run it ratchets with round-8 provenance -- including the
# train_step CPU-proxy baseline up to its chip value.
python tools/check_regression.py results/bench_r8.jsonl --update \
    --date "round 8 (onchip_queue_r8)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
