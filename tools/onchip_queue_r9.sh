#!/bin/bash
# Round-9 sequential on-chip evidence queue (single chip -- no contention).
#
# Claim discipline (docs/tpu_runs.md + .claude/skills/verify): TPU-claiming
# processes are WAITED on, never killed -- a killed claim wedges the relay
# for every later process.  Each stage is gated on a live compiled-matmul
# probe.  If a previous round's queue left a probe pending (its PID in
# $PRIOR_PROBE_PID, output at /tmp/queue_probe.out), that claim is REUSED
# as the relay sentinel instead of stacking a second claim behind it.
#
# Round-9 ordering: the INTERLEAVE evidence lands FIRST and is sized to
# complete-and-commit inside a ~3-minute relay window -- the relay has
# been dropping between stages for several rounds, so the highest-value
# rows (stall-free interleaved chunked prefill, this round's change) go
# before the long tails:
#   * prefill_fast: bench.py prefill_interleave (mixed-workload aggregate
#     tokens/s, default interleaved+chunked path vs the pre-change
#     synchronous dense admission, stall_ticks 26 -> 0 on the CPU proxy);
#   * serving_int: tools/serving_tpu.py, whose decode_prefill_interleave
#     scenario measures the same contrast at serving size on chip (plus
#     the pre-existing scenario set).
# The regression pass ratchets the CPU-proxy prefill_interleave baseline
# up to the chip number, exactly like paged_tick (r7) and train_step (r8).
cd /root/repo || exit 1
L=results/logs
mkdir -p "$L"

# wait_relay comes from the shared relay library (bounded/jittered probe
# loop, claim discipline) — one copy instead of a per-round paste
. "$(dirname "$0")/relay_lib.sh"

stage() {  # stage <name> <cmd...>
  name=$1; shift
  echo "== $name wait-relay $(date)" >> $L/queue.status
  if ! wait_relay; then
    # bounded mode (WAIT_RELAY_MAX_S) gave up: skip the stage instead
    # of launching a TPU claim against a known-down relay
    echo "== $name SKIPPED (relay unreachable) $(date)" >> $L/queue.status
    return 1
  fi
  echo "== $name start $(date)" >> $L/queue.status
  "$@" > "$L/$name.log" 2>&1
  echo "== $name rc=$? $(date)" >> $L/queue.status
}

date > $L/queue.status
# -- the ~3-minute interleave window: the prefill_interleave row,
#    committed (jsonl fallback + ratchet) IMMEDIATELY so a relay drop
#    after this point still leaves the round-9 interleave evidence on disk
stage prefill_fast    python bench.py --skip-probe --only prefill_interleave --reps 5
grep '"metric"' $L/prefill_fast.log > results/bench_r9.jsonl 2>/dev/null || true
python tools/check_regression.py results/bench_r9.jsonl --update \
    --date "round 9 (onchip_queue_r9, interleave window)" > "$L/regression_prefill.log" 2>&1
echo "== interleave-window regression+ratchet rc=$? $(date)" >> $L/queue.status
stage serving_int     python tools/serving_tpu.py
# -- the long tail, round-8 ordering preserved
stage bench_r9        python bench.py --skip-probe
# committed fallback for the driver's round-end bench (see
# bench.py::_last_good_headline): the freshest on-chip lines, MERGED
# with the interleave-window rows (a bare overwrite here would clobber
# the already-committed interleave evidence if the relay dropped
# mid-registry)
grep -h '"metric"' $L/bench_r9.log $L/prefill_fast.log \
    2>/dev/null | awk '!seen[$0]++' > results/bench_r9.jsonl || true
stage parity          python tools/pallas_tpu_parity.py
stage flash_train     python tools/flash_train_proof.py
stage ref_harness2    python tools/run_reference_harness.py --backend tpu --lab lab2 --k-times 5
stage ref_harness3    python tools/run_reference_harness.py --backend tpu --lab lab3 --k-times 5
stage tune_flash      python tools/tune_flash.py
# mechanical regression verdict + ratchet in ONE pass, ungated like the
# re-sign below (host-only JSON diff -- a relay gate here could hang the
# queue after the chip stages already rewrote artifacts).  --update
# refuses to move any baseline in the worse direction without an
# explicit --accept-regression note (VERDICT r5 #6 guard); on a clean
# improving run it ratchets with round-9 provenance -- including the
# prefill_interleave CPU-proxy baseline up to its chip value.
python tools/check_regression.py results/bench_r9.jsonl --update \
    --date "round 9 (onchip_queue_r9)" > "$L/regression.log" 2>&1
echo "== regression+ratchet rc=$? $(date)" >> $L/queue.status
# re-sign: the stages above rewrite signed artifacts (pallas_tpu_parity
# .json; baselines.json under the --update) -- signatures must track
# them or tests/test_signing.py::test_committed_signatures_verify reds.
# No relay gate: signing is host-only.
python tools/sign_artifacts.py sign > "$L/resign.log" 2>&1
echo "== resign rc=$? $(date)" >> $L/queue.status
echo "QUEUE DONE $(date)" >> $L/queue.status
