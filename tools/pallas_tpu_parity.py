"""Real-TPU Pallas parity artifact: compiled Mosaic kernels vs XLA twins.

Every Pallas kernel in the unit suite runs ``interpret=True`` on the CPU
mesh; this script is the committed proof that the *compiled* (Mosaic)
lowering of each kernel is correct on actual TPU hardware.  The kernels
ARE the product (reference lab2/src/main.cu:15-52, lab3/src/main.cu:40-76,
lab1/src/main.cu:22-29), so their hardware lowering gets its own pinned
artifact: ``results/pallas_tpu_parity.json``.

Checks (all compiled, interpret=False, on the real chip):
  - elementwise subtract (lab1 kernel) vs fused-XLA subtract: bit-exact
  - Roberts halo-DMA stencil (lab2) vs XLA roberts_edges: bit-exact
  - Mahalanobis classify (lab3) vs XLA classify_labels: bit-exact labels
  - flash attention (fwd + custom_vjp bwd) vs naive XLA attention
  - paged-attention decode kernel (scalar-prefetch block tables, GQA,
    ragged lengths, sliding window) vs the XLA gather path

Usage: python tools/pallas_tpu_parity.py [--out results/pallas_tpu_parity.json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _naive_attention(q, k, v, causal: bool):
    """O(s^2) reference attention in f32 over (b, s, h, d).

    ``precision=HIGHEST``: TPU einsum default routes f32 matmuls through
    bf16 passes (~1e-2 error at these shapes) — the *reference* would be
    the noisy side of the comparison, dominating the parity bound."""
    import jax
    import jax.numpy as jnp

    hi = jax.lax.Precision.HIGHEST
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf, precision=hi) * scale
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    w = jnp.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", w, vf, precision=hi)


def run_checks() -> list:
    import jax
    import jax.numpy as jnp

    from tpulab.ops.elementwise import make_binary_fn
    from tpulab.ops.mahalanobis import class_statistics, classify_labels
    from tpulab.ops.pallas.attention import flash_attention
    from tpulab.ops.pallas.classify import classify_labels_pallas
    from tpulab.ops.pallas.elementwise import pallas_binary
    from tpulab.ops.pallas.stencil import roberts_pallas
    from tpulab.ops.roberts import roberts_edges

    rng = np.random.default_rng(2026)
    checks = []

    # lab1: f32 subtract, awkward (non-aligned) length
    n = 123_457
    a = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    b = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    got = np.asarray(pallas_binary(a, b, interpret=False))
    want = np.asarray(make_binary_fn("subtract", jnp.float32)(a, b))
    checks.append({
        "kernel": "pallas_elementwise_subtract",
        "shape": [n],
        "dtype": "float32",
        "bit_exact": bool(np.array_equal(got, want)),
        "max_abs_err": float(np.max(np.abs(got - want))),
    })

    # lab2: Roberts stencil, non-multiple-of-tile image with alpha variety
    h, w = 1021, 1531
    img = rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)
    imgj = jnp.asarray(img)
    got = np.asarray(roberts_pallas(imgj, interpret=False))
    want = np.asarray(roberts_edges(imgj))
    checks.append({
        "kernel": "pallas_roberts_stencil",
        "shape": [h, w, 4],
        "dtype": "uint8",
        "bit_exact": bool(np.array_equal(got, want)),
        "mismatch_px": int((got != want).any(-1).sum()),
    })

    # lab3: Mahalanobis classify, 5 classes incl. a 2-point (near-degenerate
    # covariance) class, odd image size
    h, w = 777, 513
    img = rng.integers(0, 256, size=(h, w, 4), dtype=np.uint8)
    classes = [
        np.stack([rng.integers(0, w, size=k), rng.integers(0, h, size=k)], axis=1)
        for k in (4, 7, 2, 16, 9)
    ]
    stats = class_statistics(img, classes)
    imgj = jnp.asarray(img)
    mu = jnp.asarray(stats.mean)
    ic = jnp.asarray(stats.inv_cov)
    got = np.asarray(classify_labels_pallas(imgj, mu, ic, interpret=False))
    want = np.asarray(classify_labels(imgj, mu, ic))
    checks.append({
        "kernel": "pallas_mahalanobis_classify",
        "shape": [h, w],
        "n_classes": len(classes),
        "bit_exact": bool(np.array_equal(got, want)),
        "mismatch_px": int((got != want).sum()),
    })

    # flash attention: causal, seq not a block multiple, bf16 inputs
    b_, s, nh, d = 2, 1536, 4, 64
    q = jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32) * 0.5,
                    jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32) * 0.5,
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32),
                    jnp.bfloat16)
    got = np.asarray(
        flash_attention(q, k, v, causal=True, block_q=512, block_k=512,
                        interpret=False).astype(jnp.float32)
    )
    want = np.asarray(_naive_attention(q, k, v, causal=True))
    err = np.max(np.abs(got - want))
    checks.append({
        "kernel": "pallas_flash_attention",
        "shape": [b_, s, nh, d],
        "dtype": "bfloat16",
        "max_abs_err": float(err),
        "tol": 2e-2,  # bf16 inputs, f32 accumulation
        "within_tol": bool(err < 2e-2),
    })

    # flash backward (custom_vjp dq/dkv kernels), f32 for a tight bound
    b_, s, nh, d = 1, 1024, 2, 64
    q, k, v = (
        jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32) * 0.5)
        for _ in range(3)
    )
    tgt = jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32))
    loss_f = lambda q, k, v: jnp.sum(
        (flash_attention(q, k, v, causal=True, block_q=512, block_k=512,
                         interpret=False) - tgt) ** 2
    )
    loss_n = lambda q, k, v: jnp.sum((_naive_attention(q, k, v, True) - tgt) ** 2)
    gf = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.jit(jax.grad(loss_n, argnums=(0, 1, 2)))(q, k, v)
    gerr = max(float(jnp.abs(a - b).max()) for a, b in zip(gf, gn))
    checks.append({
        "kernel": "pallas_flash_attention_backward",
        "shape": [b_, s, nh, d],
        "dtype": "float32",
        "max_abs_err": gerr,
        "tol": 5e-3,  # f32 grads, large-magnitude sum-of-squares loss
        "within_tol": bool(gerr < 5e-3),
    })

    # windowed ring building block: flash with a static q_offset (query
    # row i at global position offset+i) + window — the masks ride
    # iota/compare/select paths that only real Mosaic exercises, and
    # offset rows with no visible key must return o=0 / lse=-inf
    from tpulab.ops.pallas.attention import flash_attention_with_lse

    b_, s, nh, d = 1, 512, 2, 64
    q, k, v = (
        jnp.asarray(rng.standard_normal((b_, s, nh, d)).astype(np.float32) * 0.5)
        for _ in range(3)
    )
    w, off = 200, 512  # offset = one shard; window spans a partial block
    got_o, got_lse = flash_attention_with_lse(
        q, k, v, causal=True, window=w, q_offset=off,
        block_q=128, block_k=128, interpret=False)
    qp = off + np.arange(s)[:, None]
    kp_pos = np.arange(s)[None, :]
    keep = (kp_pos <= qp) & (kp_pos > qp - w)
    sc = np.einsum("bqhd,bkhd->bhqk",
                   np.asarray(q) / np.sqrt(d), np.asarray(k))
    sc = np.where(keep[None, None], sc, -np.inf)
    with np.errstate(over="ignore", invalid="ignore"):
        m = sc.max(-1, keepdims=True)
        p = np.where(np.isfinite(sc), np.exp(sc - np.where(np.isfinite(m), m, 0)), 0.0)
        l = p.sum(-1, keepdims=True)
        want_o = np.einsum("bhqk,bkhd->bqhd", p / np.where(l > 0, l, 1.0),
                           np.asarray(v))
    alive = keep.any(-1)
    oerr = float(np.max(np.abs(np.asarray(got_o) - want_o)))
    dead_ok = bool(
        (np.asarray(got_o)[:, ~alive] == 0).all()
        and np.all(np.asarray(got_lse)[:, ~alive] == -np.inf)
    ) if (~alive).any() else True
    checks.append({
        "kernel": "pallas_flash_attention_q_offset",
        "shape": [b_, s, nh, d],
        "dtype": "float32",
        "window": w,
        "q_offset": off,
        "max_abs_err": oerr,
        "dead_rows_clean": dead_ok,
        "tol": 1e-4,
        "within_tol": bool(oerr < 1e-4 and dead_ok),
    })

    # paged-attention decode kernel (scalar-prefetch block tables) vs
    # the XLA gather path — GQA grouping + ragged lengths + window
    from tpulab.models.paged import _paged_attend
    from tpulab.ops.pallas.paged import paged_attend_pallas

    S, M, BS, P, h, kvh, d = 4, 6, 16, 48, 8, 2, 64
    q = jnp.asarray(rng.standard_normal((S, 1, h, d)).astype(np.float32) * 0.5,
                    jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((P, BS, kvh, d)).astype(np.float32) * 0.5,
                     jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((P, BS, kvh, d)).astype(np.float32),
                     jnp.bfloat16)
    tables = jnp.asarray(
        rng.choice(P, (S, M), replace=False).reshape(S, M), jnp.int32)
    lengths = jnp.asarray([1, 30, 64, 96], jnp.int32)
    for window, name in ((0, "pallas_paged_attention"),
                         (11, "pallas_paged_attention_window")):
        got = np.asarray(paged_attend_pallas(
            q, kp, vp, tables, lengths, BS, window, interpret=False
        ).astype(jnp.float32))
        want = np.asarray(_paged_attend(
            q, kp, vp, tables, lengths, BS, window).astype(jnp.float32))
        perr = float(np.max(np.abs(got - want)))
        checks.append({
            "kernel": name,
            "shape": [S, M, BS, h, kvh, d],
            "dtype": "bfloat16",
            "max_abs_err": perr,
            "tol": 2e-2,  # bf16 inputs, f32 softmax/acc both sides
            "within_tol": bool(perr < 2e-2),
        })

    # int8 KV pools read IN-KERNEL (dequant via the gather recipe): the
    # scale operands ride trailing-singleton lane blocks and the int8
    # data rides (1, BS, 1, d) blocks — both layouts only real Mosaic
    # tiling rules can certify
    from tpulab.models.paged import _kv_quant

    kq = tuple(jnp.asarray(a) for a in _kv_quant(kp.astype(jnp.float32)))
    vq = tuple(jnp.asarray(a) for a in _kv_quant(vp.astype(jnp.float32)))
    got = np.asarray(paged_attend_pallas(
        q, kq, vq, tables, lengths, BS, 0, interpret=False
    ).astype(jnp.float32))
    want = np.asarray(_paged_attend(
        q, kq, vq, tables, lengths, BS, 0).astype(jnp.float32))
    qerr = float(np.max(np.abs(got - want)))
    checks.append({
        "kernel": "pallas_paged_attention_int8",
        "shape": [S, M, BS, h, kvh, d],
        "dtype": "int8+f32scale",
        "max_abs_err": qerr,
        "tol": 2e-2,  # identical dequant recipe both sides
        "within_tol": bool(qerr < 2e-2),
    })
    return checks


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(ROOT / "results" / "pallas_tpu_parity.json"))
    args = ap.parse_args(argv)

    import jax

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(f"refusing to run: default device is {dev.platform}, not tpu "
              "(this artifact pins the Mosaic lowering on real hardware)",
              file=sys.stderr)
        return 2

    checks = run_checks()
    ok = all(c.get("bit_exact", c.get("within_tol", False)) for c in checks)
    report = {
        "device_kind": dev.device_kind,
        "jax_version": jax.__version__,
        "compiled": True,
        "interpret": False,
        "ok": ok,
        "checks": checks,
    }
    out = pathlib.Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
