#!/bin/bash
# relay_lib.sh — THE one wait_relay used by every on-chip evidence queue
# (tools/onchip_queue*.sh source this; the copy-pasted per-round
# variants drifted for five rounds before being factored here).
#
# Claim discipline (docs/tpu_runs.md): TPU-claiming processes are
# WAITED on, never killed — a killed claim wedges the relay for every
# later process.  If a previous round's queue left a probe pending (its
# PID in $PRIOR_PROBE_PID, output at /tmp/queue_probe.out), that claim
# is REUSED as the relay sentinel instead of stacking a second claim
# behind it.
#
# wait_relay blocks until a compiled-matmul probe succeeds.  Retries
# back off with JITTER (base sleep +/- up to 25%) so several queues or
# a queue racing the bench probe don't re-claim in lockstep the moment
# the relay recovers.  Optionally bounded: set WAIT_RELAY_MAX_S > 0 and
# wait_relay returns 1 after that many seconds, appending a clean
# "RELAY UNREACHABLE" record to $RELAY_STATUS_LOG (default
# results/logs/queue.status) instead of parking the queue forever —
# the caller decides whether to skip the stage or abort.
#
# Usage:   . "$(dirname "$0")/relay_lib.sh"   # then: wait_relay || ...

_relay_jitter_sleep() {  # _relay_jitter_sleep BASE_SECONDS REMAINING_S
  local base=$1 remaining=${2:-0}
  # +/- up to 25% of base, from $RANDOM (0..32767)
  local span=$((base / 2)) off=0
  [ "$span" -gt 0 ] && off=$((RANDOM % (span + 1)))
  local s=$((base - span / 2 + off))
  # a bounded wait never oversleeps its own deadline (the bound is
  # re-checked at the top of the loop, so cap at remaining + 1)
  if [ "$remaining" -gt 0 ] && [ "$s" -gt $((remaining + 1)) ]; then
    s=$((remaining + 1))
  fi
  sleep "$s"
}

wait_relay() {
  local t0=$(date +%s) max="${WAIT_RELAY_MAX_S:-0}" status_log remaining=0
  status_log="${RELAY_STATUS_LOG:-results/logs/queue.status}"
  while true; do
    if [ "$max" -gt 0 ]; then
      remaining=$((max - ($(date +%s) - t0)))
      if [ "$remaining" -le 0 ]; then
        echo "== RELAY UNREACHABLE after ${max}s $(date)" >> "$status_log"
        return 1
      fi
    fi
    if [ -n "$PRIOR_PROBE_PID" ] && kill -0 "$PRIOR_PROBE_PID" 2>/dev/null; then
      _relay_jitter_sleep 60 "$remaining"
      continue
    fi
    if grep -q compile-ok /tmp/queue_probe.out 2>/dev/null; then
      # consume the sentinel so every LATER stage re-probes (the relay
      # can drop again between stages)
      PRIOR_PROBE_PID=""
      rm -f /tmp/queue_probe.out
      return 0
    fi
    PRIOR_PROBE_PID=""
    python -c "import jax, jax.numpy as jnp; x = jnp.ones((128, 128)); (x @ x).block_until_ready(); print('compile-ok')" \
        > /tmp/queue_probe.out 2>&1
    # loop re-checks the probe output; a failed probe (relay down but
    # fast-failing) falls through to another attempt after the check
    grep -q compile-ok /tmp/queue_probe.out 2>/dev/null \
        || _relay_jitter_sleep 120 "$remaining"
  done
}
