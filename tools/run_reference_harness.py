"""Drive the reference suite's OWN unmodified harness against tpulab.

Proof of the SURVEY section-7 design promise: the reference's
``run_test.py``/``tester.py`` (reference ``run_test.py:58-60`` lab-from-
path convention, ``tester.py:16`` timing regex, ``tester.py:126-132``
subprocess stdin contract) drive a tpulab "binary" with zero edits.

The "binary" is the native thin client (``native/bin/tpulab_client``)
behind a warm daemon — the framework's answer to subprocess-per-run vs
JAX startup cost (SURVEY section 7 "hard parts").  The reference harness
is executed from a scratch workdir holding copies of the reference's
tiny lab2 fixtures (the reference ImgData materializes sibling formats
next to its sources, and /root/reference is read-only), with the shim at
``<workdir>/lab2/src/`` so the harness resolves ``lab_name="lab2"``.

Usage:
    python tools/run_reference_harness.py [--k-times 5] [--out results/reference_harness]
"""

from __future__ import annotations

import argparse
import os
import pathlib
import shutil
import socket
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
REFERENCE = pathlib.Path("/root/reference")

# tiny fixtures only: the whitelists in the reference processors skip
# missing files, and the multi-MB PNGs would spend minutes in the
# reference's per-pixel pack loops (converter.py:100-115) for no extra
# compatibility signal (the goldens cover the .txt fixtures)
TINY_FIXTURES = {
    "lab2": (
        "02.data", "57.data", "95.data", "96.data", "97.data", "98.data",
        "99.data", "test_01.txt", "test_02.txt",
    ),
    # the reference Lab3Processor pins every image's class-definition
    # points to MAP_TO_INIT_POINTS["test_01_lab3.txt"] (reference
    # lab3_processor.py:117) whose coordinates live in a 3x3 box, so any
    # staged image works; the golden covers test_01_lab3
    "lab3": ("04.data", "09.data", "test_01_lab3.txt", "test_02_lab3.txt"),
}

# reference kernel_sizes grammar per lab (reference tester.py:113-121):
# lab2 = [[bx,by],[gx,gy]] pairs; lab3 = [blocks, threads] ints
DEFAULT_KERNEL_SIZES = {
    "lab2": "[[[32, 32], [16, 16]], [[16, 16], [32, 32]], [[8, 8], [64, 64]]]",
    "lab3": "[[256, 256], [1024, 256], [32, 32]]",
}


def stage_workdir(workdir: pathlib.Path, lab: str) -> pathlib.Path:
    data = workdir / lab / "data"
    data.mkdir(parents=True, exist_ok=True)  # --workdir may be reused
    for fn in TINY_FIXTURES[lab]:
        src = REFERENCE / lab / "data" / fn
        if src.exists():
            shutil.copy(src, data / fn)
    shutil.copytree(
        REFERENCE / lab / "data_out_gt",
        workdir / lab / "data_out_gt",
        dirs_exist_ok=True,
    )
    srcdir = workdir / lab / "src"
    srcdir.mkdir(exist_ok=True)
    client = ROOT / "native" / "bin" / "tpulab_client"
    if not client.exists():
        raise SystemExit("native client missing; run tools/build_native.py first")
    shim = srcdir / "to_plot_tpu"
    shim.write_text(f"#!/bin/sh\nexec {client} {lab} --to-plot\n")
    shim.chmod(0o755)
    shim_cpu = srcdir / "main_tpu_cpu"
    shim_cpu.write_text(f"#!/bin/sh\nexec {client} {lab} --backend cpu\n")
    shim_cpu.chmod(0o755)
    return srcdir


def start_daemon(workdir: pathlib.Path, env: dict) -> tuple:
    sock = str(workdir / "daemon.sock")
    env = dict(env, TPULAB_DAEMON_SOCKET=sock, PYTHONPATH=str(ROOT))
    # log to a file, not a PIPE: nobody drains a pipe during the harness
    # run, and a full pipe buffer would block the daemon's writes
    log = open(workdir / "daemon.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpulab.daemon", "--socket", sock],
        cwd=workdir,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(
                f"daemon died: {(workdir / 'daemon.log').read_text()[-2000:]}"
            )
        try:
            s = socket.socket(socket.AF_UNIX)
            s.connect(sock)
            s.close()
            return proc, sock
        except OSError:
            time.sleep(0.2)
    raise SystemExit("daemon socket never appeared")


def daemon_platform(sock_path: str) -> str:
    """Ask the daemon which backend it computes on (wire protocol of
    tpulab/daemon.py; 'platform' pseudo-lab)."""
    import json as _json
    import struct

    header = _json.dumps({"lab": "platform"}).encode()
    s = socket.socket(socket.AF_UNIX)
    s.connect(sock_path)
    s.sendall(struct.pack("<I", len(header)) + header + struct.pack("<Q", 0))
    def recv_exact(n):
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("daemon closed during platform probe")
            buf += chunk
        return buf
    status, ln = struct.unpack("<BQ", recv_exact(9))
    out = recv_exact(ln).decode()
    s.close()
    if status != 0:
        raise SystemExit(f"platform probe failed: {out[-500:]}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lab", default="lab2", choices=sorted(TINY_FIXTURES))
    ap.add_argument("--k-times", type=int, default=5)
    ap.add_argument(
        "--kernel-sizes",
        default=None,
        help="per-lab JSON (reference tester.py:113-121); defaults per lab",
    )
    ap.add_argument("--out", default=None)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--backend", default="cpu", choices=("cpu", "tpu"),
                    help="daemon compute backend: cpu (hermetic) or tpu — "
                         "the daemon claims the real chip and the "
                         "reference harness verifies CHIP output bit-"
                         "exactly (round-2 verdict missing #3)")
    args = ap.parse_args(argv)
    kernel_sizes = args.kernel_sizes or DEFAULT_KERNEL_SIZES[args.lab]
    suffix = ("" if args.lab == "lab2" else f"_{args.lab}") + (
        "_tpu" if args.backend == "tpu" else ""
    )
    out_default = ROOT / "results" / f"reference_harness{suffix}"

    workdir = pathlib.Path(args.workdir or tempfile.mkdtemp(prefix="refharness_"))
    srcdir = stage_workdir(workdir, args.lab)

    if args.backend == "tpu":
        # leave the container's JAX_PLATFORMS=axon for the daemon (it
        # claims the one chip; "cpu" stays registered for the f64/oracle
        # paths).  Everything else in this tool must NOT claim: the
        # reference harness subprocess gets CPU pins below.
        env = dict(os.environ)
    else:
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    daemon, sock = start_daemon(workdir, env)
    if args.backend == "tpu":
        # refuse to produce a *_tpu artifact computed anywhere else —
        # outside the container (or with the relay down) the daemon
        # could silently fall back to CPU and the harness would "pass"
        plat = daemon_platform(sock)
        if plat != "tpu":
            daemon.terminate()
            daemon.wait(timeout=10)
            raise SystemExit(
                f"--backend tpu requested but the daemon computes on "
                f"{plat!r}; aborting before writing a _tpu artifact"
            )
    try:
        # the harness itself is numpy/pandas only — pin it to CPU so it
        # can never contend for the daemon's chip claim
        run_env = dict(env, TPULAB_DAEMON_SOCKET=sock,
                       JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        cmd = [
            sys.executable,
            str(REFERENCE / "run_test.py"),
            "--binary_path_cuda", str(srcdir / "to_plot_tpu"),
            "--binary_path_cpu", str(srcdir / "main_tpu_cpu"),
            "--k_times", str(args.k_times),
            "--kernel_sizes", kernel_sizes,
            "--metadata_columns2plot", '["filename"]',
        ]
        print("+", " ".join(cmd), flush=True)
        r = subprocess.run(
            cmd, cwd=workdir, env=run_env, capture_output=True, text=True, timeout=1800
        )
        (workdir / "run_test_stdout.log").write_text(r.stdout)
        (workdir / "run_test_stderr.log").write_text(r.stderr)
        print(r.stdout[-3000:])
        if r.returncode != 0:
            print(r.stderr[-3000:], file=sys.stderr)
            return r.returncode
    finally:
        daemon.terminate()
        daemon.wait(timeout=10)

    out = pathlib.Path(args.out) if args.out else out_default
    out.mkdir(parents=True, exist_ok=True)
    copied = []
    for pat in ("stats_*.csv", "failed_*.csv", "*.png"):
        for f in srcdir.glob(pat):
            shutil.copy(f, out / f.name)
            copied.append(f.name)
    shutil.copy(workdir / "run_test_stdout.log", out / "run_test_stdout.log")
    print(f"artifacts -> {out}: {copied}")
    # the harness only writes stats when every run verified
    # (reference tester.py:260-285); a failed_*.csv means a verify broke
    if not any(c.startswith("stats_") for c in copied):
        print("NO STATS CSV — verification must have failed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
