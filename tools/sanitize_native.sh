#!/bin/bash
# One sanitizer pass over the native tier (VERDICT round-5 weak #7).
#
#   1. ASan+UBSan: native/fastcodec (CPython extension) and
#      native/loader (ctypes .so) rebuilt instrumented IN PLACE (the
#      production .so's are stashed and restored), then exercised by
#      the real python tests — tests/test_native.py, tests/test_loader.py,
#      tests/test_io.py — under LD_PRELOAD=libasan.
#      detect_leaks=0: CPython's arena allocator reports thousands of
#      intentional "leaks"; the pass is for heap corruption and UB,
#      which abort loudly (-fno-sanitize-recover=undefined).
#      Tests that COMPILE jax programs (the daemon fixture, the train
#      driver integrations) are deselected: jaxlib 0.4.36's XLA
#      compiler aborts under an ASan-preloaded interpreter before any
#      native code runs — an environment limit, not a native finding.
#      Every deselected loader path stays covered functionally by the
#      regular suite and concurrently by the TSan driver below.
#   2. TSan: the loader's worker/consumer choreography cannot run under
#      a preloaded libtsan with an uninstrumented CPython, so the
#      thread pass compiles native/loader/tpulab_loader.cpp TOGETHER
#      with tools/tsan_loader_driver.cpp (everything instrumented) and
#      hammers claim/publish, resume cursors, the relaxed short_reads
#      counter, and mid-stream shutdown across 2/4/8 worker threads.
#
# The combined log is committed at results/logs/native_sanitizers.log;
# exit is nonzero if any stage fails.  Host-only (no TPU claim): safe
# to run outside the relay queue.
set -u
cd "$(dirname "$0")/.." || exit 1
L=results/logs
mkdir -p "$L"
LOG=$L/native_sanitizers.log
: > "$LOG"
note() { echo "$@" | tee -a "$LOG"; }
note "== native sanitizer pass: $(gcc --version | head -1)"

rc=0
SAN="-fsanitize=address,undefined -fno-sanitize-recover=undefined -g -O1 -fno-omit-frame-pointer"
PYINC=$(python -c "import sysconfig; print(sysconfig.get_paths()['include'])")
EXT=$(python -c "import sysconfig; print(sysconfig.get_config_var('EXT_SUFFIX'))")

# stash the production artifacts; instrumented builds go IN PLACE so the
# ctypes path (io/loader.py) and the sys.path extension hook
# (io/imagefile.py) pick them up without any code changes
cp -a native/lib native/lib.pre-sanitize
restore() { rm -rf native/lib; mv native/lib.pre-sanitize native/lib; }
trap restore EXIT

note "== build: fastcodec + loader under ASan/UBSan"
# PIPESTATUS, not the pipeline exit: `| tee` would otherwise mask a
# compiler failure and the pass would run GREEN against the stashed
# uninstrumented production .so's
gcc -shared -fPIC $SAN -Wall -I"$PYINC" \
    -o "native/lib/_tpulab_fastcodec$EXT" native/fastcodec/fastcodecmodule.c \
    2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1
g++ -std=c++17 -shared -fPIC $SAN -Wall -pthread \
    -o native/lib/libtpulab_loader.so native/loader/tpulab_loader.cpp \
    2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1

ASAN_LIB=$(gcc -print-file-name=libasan.so)
note "== pytest under ASan/UBSan (preload $ASAN_LIB)"
env LD_PRELOAD="$ASAN_LIB" \
    ASAN_OPTIONS="detect_leaks=0" \
    UBSAN_OPTIONS="print_stacktrace=1,halt_on_error=1" \
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m pytest tests/test_io.py tests/test_loader.py \
        -q -p no:cacheprovider -k "not train" 2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1
env LD_PRELOAD="$ASAN_LIB" \
    ASAN_OPTIONS="detect_leaks=0" \
    UBSAN_OPTIONS="print_stacktrace=1,halt_on_error=1" \
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m pytest tests/test_native.py \
        -q -p no:cacheprovider -k "Fastcodec or rejects_bad_usage" \
        2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1

note "== build + run: loader under TSan (dedicated threaded driver)"
TSAN_BIN=$(mktemp -t tsan_loader.XXXXXX)
g++ -std=c++17 -fsanitize=thread -g -O1 -Wall -pthread \
    -o "$TSAN_BIN" tools/tsan_loader_driver.cpp native/loader/tpulab_loader.cpp \
    2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1
TSAN_OPTIONS="halt_on_error=1" "$TSAN_BIN" 2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || rc=1
rm -f "$TSAN_BIN"

note "== sanitizer pass rc=$rc"
exit $rc
