"""Operational-behavior artifact for the serving engine.

Drives PagedEngine through a chatbot-shaped workload — many requests
sharing a system prompt, mixed tails, more requests than slots — and
records the engine's own counters: prefix hit rate, dense-prefill
skips, block recycling, batched ticks vs serial.  These properties are
platform-independent (counters, not timings), so the artifact is valid
evidence from any backend; perf numbers live in bench.py.

Usage: python tools/serving_behavior.py [--out results/serving_behavior.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# counters are backend-independent; pin CPU BEFORE jax initializes so
# the tool runs anywhere (incl. containers whose default platform is a
# tunneled accelerator that may be unavailable) — same override as
# tools/gen_fixtures.py and tests/conftest.py.  The env var alone is
# not enough: the container's sitecustomize updates jax_platforms at
# interpreter startup, which takes precedence — override the config too.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(ROOT / "results" / "serving_behavior.json"))
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    import tpulab.models.paged as paged_mod
    from tpulab.models.labformer import LabformerConfig, init_params
    from tpulab.models.paged import PagedEngine

    cfg = LabformerConfig(d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
                          d_ff=64, max_seq=256)
    # random init is sufficient: every recorded counter is
    # weight-independent (hits depend on prompt bytes, ticks on max_new
    # and slot scheduling) — no token values are compared
    params = init_params(cfg, seed=0)

    system = (np.arange(24) % 7).astype(np.int32)  # 3 full blocks at BS=8
    rng = np.random.default_rng(0)
    jobs = [
        (np.concatenate([system, rng.integers(0, 7, rng.integers(1, 6))
                         .astype(np.int32)]), int(rng.integers(4, 12)))
        for _ in range(12)
    ]

    dense_prefills = {"n": 0}
    real_prefill = paged_mod._prefill

    def counting(*a, **kw):
        dense_prefills["n"] += 1
        return real_prefill(*a, **kw)

    paged_mod._prefill = counting
    try:
        eng = PagedEngine(params, cfg, slots=4, n_blocks=48, block_size=8,
                          max_seq=128)
        for prompt, n in jobs:
            eng.submit(prompt, max_new=n)
        out = eng.run()
    finally:
        paged_mod._prefill = real_prefill

    stats = eng.stats()
    total_tokens = int(sum(len(v) for v in out.values()))
    serial_ticks = int(sum(n for _, n in jobs))
    report = {
        "workload": {
            "requests": len(jobs),
            "slots": 4,
            "shared_system_prompt_tokens": int(len(system)),
            "total_generated_tokens": total_tokens,
        },
        "engine": stats,
        "derived": {
            "prefix_hit_rate": round(
                stats["prefix_hits"]
                / max(stats["prefix_hits"] + stats["prefix_misses"], 1), 3),
            "dense_prefills_run": dense_prefills["n"],
            "dense_prefills_skipped_by_cache": len(jobs) - dense_prefills["n"],
            "batched_ticks": stats["ticks"],
            "serial_ticks_would_be": serial_ticks,
            "tick_ratio": round(stats["ticks"] / serial_ticks, 3),
        },
        "device": jax.devices()[0].platform,
        "note": "counters, not timings: valid from any backend",
    }
    out_path = pathlib.Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
